import time, numpy as np
t0 = time.time()
def log(m): print(f"[{time.time()-t0:6.1f}s] {m}", flush=True)
import jax
log(f"devices: {jax.devices()}")
from spark_rapids_tpu.engine import TpuSession
from spark_rapids_tpu.plan.logical import col, functions as F
s = TpuSession({})
df = s.from_pydict({"a": [1, 2]}).select(col("a"),
                                         F.explode([1.5, 2.5]).alias("x"))
assert sorted(df.collect()) == [(1, 1.5), (1, 2.5), (2, 1.5), (2, 2.5)]
log("explode OK")
rng = np.random.RandomState(0)
n, m = 20000, 64
left = {"k": rng.randint(0, m, n).tolist(), "v": rng.uniform(0, 1, n).tolist()}
right = {"k": list(range(m)), "w": [float(i) * 2 for i in range(m)]}
j = s.from_pydict(left).join(s.from_pydict(right).hint("broadcast"), on="k")
assert "TpuBroadcastHashJoinExec" in j.physical_plan().tree_string()
out = dict(j.group_by(col("k")).agg(F.sum(col("w")).alias("sw")).collect())
ka = np.array(left["k"])
for kk in range(0, m, 7):
    want = (ka == kk).sum() * kk * 2.0
    assert abs(out[kk] - want) < 1e-6, (kk, out[kk], want)
log("broadcast join OK")
# TPC-H Q1 and Q6 on the chip
import sys; sys.path.insert(0, "/root/repo")
from benchmarks.tpch import QUERIES, load_tables
tables = load_tables(s, sf=0.002)
r6 = QUERIES[6](tables).collect()
log(f"tpch q6 on TPU OK: revenue={r6[0][0]:.2f}")
r1 = QUERIES[1](tables).collect()
assert len(r1) == 6, r1
log(f"tpch q1 on TPU OK: {len(r1)} groups")
