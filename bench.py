"""Flagship benchmark: TPC-H Q6/Q1 + scan-included Q6 + TPC-DS q5 on the
device engine vs this framework's own CPU (pyarrow) executors.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
  metric/value = device-engine steady-state throughput on the HEADLINE
                query (TPC-H Q6 over a device-resident cached table, the
                same metric as rounds 1-3 so the series stays comparable)
  vs_baseline  = speedup over the CPU oracle on the same query (the
                 stand-in for the reference's CPU-Spark-vs-GPU headline,
                 19.8x, reference README.md:7-15)
  extra        = per-query breakdown (Q6 cached, Q6 scan-included from
                 parquet on disk, Q1 grouped agg, TPC-DS q5 joins),
                 tunnel/transfer microbench (H2D/D2H MB/s, dispatch
                 latency), effective GB/s vs an HBM roofline, and
                 vs_ref_headline = vs_baseline / 19.8 (the
                 engine-vs-reference-target ratio; VERDICT r3 item 10).

Robustness (round-2 postmortem: a hung device run must not erase the
evidence; round-3 postmortem: SIGKILLing a TPU-attached child can poison
the machine-wide tunnel lease for 30+ min):
  * ALL device work runs in a CHILD that streams one JSON line per
    completed stage; the parent mirrors every line into BENCH_partial.json;
  * the child enforces ITS OWN deadline: after every stage/run it checks
    the clock, emits {"stage":"abort"} and exits CLEANLY (sys.exit(0));
  * the parent NEVER kills a TPU-mode child. On budget overrun it
    ABANDONS the child (stops reading; the child finishes or aborts on
    its own deadline and exits cleanly whenever the lease lets it);
    TPU children are started in their own session (setsid) so a driver
    process-group kill cannot SIGKILL them either;
  * the CPU oracle runs first in its own forced-CPU child, so a device
    hang can never erase the baseline;
  * if the chip is unavailable, the device engine is measured on the CPU
    backend instead and the unit carries the platform tag ([cpu]).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
N_ROWS = int(os.environ.get("BENCH_ROWS", 6_000_000))  # ~SF1 lineitem
TPCDS_SF = float(os.environ.get("BENCH_TPCDS_SF", 0.1))
N_RUNS = 3
# The driver's own benchmark timeout killed rounds 1-2 at ~450s; everything
# must finish (or be abandoned) inside this global budget.
GLOBAL_BUDGET_S = float(os.environ.get("BENCH_GLOBAL_S", 400))
TPU_PROBE_S = float(os.environ.get("BENCH_TPU_PROBE_S", 240))
T0 = time.time()

# 1994-01-01 / 1995-01-01 / 1998-09-02 as days since epoch
D_1994, D_1995, D_19980902 = 8766, 9131, 10471


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


# --------------------------------------------------------------------------
# child: executes the workload on one backend, emits a JSON line per stage
# --------------------------------------------------------------------------

_SILENT = False
_DEADLINE = [float("inf")]


def emit(stage: str, **kw):
    global _SILENT
    if _SILENT:
        return
    try:
        print(json.dumps({"stage": stage, **kw}), flush=True)
    except (BrokenPipeError, OSError):
        # parent abandoned us; keep running to a clean exit, silently
        _SILENT = True


def checkpoint(label: str) -> None:
    """Clean in-process deadline: abort BETWEEN units of work, never via a
    signal — a SIGKILLed TPU-attached process poisons the tunnel lease."""
    if time.time() > _DEADLINE[0]:
        emit("abort", reason="deadline", at=label)
        sys.exit(0)


def make_lineitem(n: int):
    """Q6+Q1 lineitem: the 4 Q6 columns (same distributions as rounds 1-3,
    keeping the headline comparable) plus Q1's returnflag/linestatus/tax."""
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(42)
    price = rng.uniform(900.0, 105000.0, n)
    discount = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    quantity = rng.randint(1, 51, n).astype(np.int64)
    shipdate = rng.randint(8035, 10592, n).astype(np.int64)
    returnflag = np.array(["A", "N", "R"])[rng.randint(0, 3, n)]
    linestatus = np.array(["F", "O"])[rng.randint(0, 2, n)]
    tax = np.round(rng.uniform(0.0, 0.08, n), 2)
    return pa.table({
        "l_extendedprice": price,
        "l_discount": discount,
        "l_quantity": quantity.astype(np.float64),
        "l_shipdate": shipdate,
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_tax": tax,
    })


def q6(df):
    from spark_rapids_tpu.plan.logical import col, functions as F
    return (df.filter((col("l_shipdate") >= D_1994)
                      & (col("l_shipdate") < D_1995)
                      & (col("l_discount") >= 0.05)
                      & (col("l_discount") <= 0.07)
                      & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q1(df):
    from spark_rapids_tpu.plan.logical import col, functions as F, lit
    li = df.filter(col("l_shipdate") <= D_19980902)
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (li.group_by(col("l_returnflag"), col("l_linestatus"))
            .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                 F.sum(col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(disc).alias("sum_disc_price"),
                 F.sum(disc * (lit(1.0) + col("l_tax"))).alias("sum_charge"),
                 F.avg(col("l_quantity")).alias("avg_qty"),
                 F.avg(col("l_extendedprice")).alias("avg_price"),
                 F.avg(col("l_discount")).alias("avg_disc"),
                 F.count(lit(1)).alias("count_order"))
            .order_by("l_returnflag", "l_linestatus"))


def checksum(rows) -> float:
    """Stable scalar over a collected result for the oracle cross-check."""
    acc = 0.0
    for r in rows:
        for v in r:
            if isinstance(v, bool) or v is None:
                acc += 1.0 if v else 0.0
            elif isinstance(v, (int, float)):
                acc += float(v)
            else:
                acc += float(sum(str(v).encode()) % 1000)
    return acc


def timed(name: str, fn, n_runs: int) -> None:
    t0 = time.time()
    val = fn()
    emit("warmup", q=name, t=time.time() - t0, value=val)
    checkpoint(name)
    for i in range(n_runs):
        t0 = time.time()
        val = fn()
        emit("run", q=name, i=i, t=time.time() - t0, value=val)
        checkpoint(name)


def transfer_microbench():
    """Tunnel/link microbench: H2D and D2H MB/s, per-dispatch latency.
    Context for the roofline numbers (tunneled dev TPUs: D2H ~26 MB/s)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    h = np.empty(16 << 20, np.uint8)  # 16 MiB
    t = []
    for _ in range(2):
        t0 = time.time()
        d = jax.device_put(h)
        d.block_until_ready()
        t.append(time.time() - t0)
    h2d = (16 / min(t)) if min(t) > 0 else 0.0
    small = jax.device_put(np.empty(2 << 20, np.uint8))
    small.block_until_ready()
    t0 = time.time()
    np.asarray(small)
    d2h_t = time.time() - t0
    d2h = (2 / d2h_t) if d2h_t > 0 else 0.0
    x = jnp.ones(1024, jnp.float32)
    f = jax.jit(lambda a: a + 1)
    f(x).block_until_ready()
    t0 = time.time()
    for _ in range(20):
        y = f(x)
    y.block_until_ready()
    disp_ms = (time.time() - t0) / 20 * 1e3
    emit("transfer", h2d_mb_s=round(h2d, 1), d2h_mb_s=round(d2h, 1),
         dispatch_ms=round(disp_ms, 3))


def integrity_microbench(session) -> dict:
    """Checksum on/off wire-throughput delta (the ISSUE-4 acceptance
    number): an in-process socket pair streams a buffer with reader-side
    verification enabled then disabled; the delta is the integrity tax.
    On a multi-core host the AsyncLeafVerifier overlaps hashing with the
    recv loop (expected <=5% with crc32c); on a single-core container the
    hash cannot hide behind the wire and costs ~wire_rate/hash_rate
    (~10% at 1 GB/s) — `single_core` labels the number accordingly.
    Session-cumulative integrity counters ride along so a perf number is
    never read without knowing whether corruption recovery fired."""
    import numpy as np
    from spark_rapids_tpu.mem.integrity import ChecksumPolicy
    from spark_rapids_tpu.metrics import names as MN
    from spark_rapids_tpu.shuffle.net import (ShuffleSocketServer,
                                              SocketTransport)

    nbytes = 32 << 20
    data = np.arange(nbytes, dtype=np.uint8)
    policy = ChecksumPolicy(True, "crc32c")
    digest = policy.checksum_one(data)

    class OneBufferServer:
        def buffer_layout(self, bid):
            return [((nbytes,), "uint8", nbytes)], {"bid": bid}

        def buffer_checksums(self, bid):
            return (policy.algorithm, (digest,))

        def copy_leaf_chunk(self, bid, li, off, length, view):
            view[:length] = data[off:off + length]

        def done_serving(self, bid):
            pass

    srv = SocketTransport(pool_size=16 << 20, chunk_size=4 << 20,
                          max_inflight_bytes=1 << 40)
    server = ShuffleSocketServer(srv, OneBufferServer())
    cli = SocketTransport(pool_size=16 << 20, chunk_size=4 << 20,
                          max_inflight_bytes=1 << 40)
    cli.set_peers({"peer": server.address})
    client = cli.make_client("peer")
    try:
        client.fetch_buffer(1)  # warm (connect + allocations)

        def measure(n=3):
            best = 0.0
            for _ in range(n):
                t0 = time.time()
                out, _meta = client.fetch_buffer(2)
                assert out[0].nbytes == nbytes
                best = max(best, nbytes / (time.time() - t0) / 1e6)
            return best

        results = {}
        for label, pol in (("on", ChecksumPolicy(True, "crc32c")),
                           ("off", ChecksumPolicy(False, "crc32c"))):
            cli.integrity = pol
            results[label] = measure()
    finally:
        server.close()
        srv.shutdown()
        cli.shutdown()
    overhead = (results["off"] - results["on"]) / results["off"] * 100 \
        if results["off"] > 0 else 0.0
    totals = dict(getattr(session, "query_metrics_total", {}) or {})
    pool = session.runtime.pool_stats() if session._runtime is not None \
        else {}
    return {
        "algorithm": policy.algorithm,
        "wire_mb_s_checksum_on": round(results["on"], 1),
        "wire_mb_s_checksum_off": round(results["off"], 1),
        "overhead_pct": round(overhead, 2),
        "single_core": (os.cpu_count() or 1) <= 1,
        "numChecksumMismatches": int(
            totals.get(MN.NUM_CHECKSUM_MISMATCHES, 0)
            + pool.get(MN.NUM_CHECKSUM_MISMATCHES, 0)),
        "numCorruptionRefetches": int(
            totals.get(MN.NUM_CORRUPTION_REFETCHES, 0)
            + pool.get(MN.NUM_CORRUPTION_REFETCHES, 0)),
        "numLostMapOutputs": int(
            totals.get(MN.NUM_LOST_MAP_OUTPUTS, 0)
            + pool.get(MN.NUM_LOST_MAP_OUTPUTS, 0)),
        "checksumTime_s": round(float(
            pool.get(MN.CHECKSUM_TIME, 0.0)), 4),
    }


def compress_microbench() -> dict:
    """Spill write/read delta per codec (the ISSUE-5 acceptance number):
    the host->disk spill path timed with compression off and on, same
    leaves, same disk.  `none` is the current raw path — the on/off delta
    is the codec tax (or win) at the spill tier; the wire-side per-codec
    numbers live in BENCH_WIRE.json (tests/test_wire_throughput.py)."""
    import tempfile

    import numpy as np
    from spark_rapids_tpu.compress import (CompressionPolicy,
                                           available_codecs, resolve_codec)
    from spark_rapids_tpu.mem.buffer import read_leaves, write_leaves
    from spark_rapids_tpu.mem.buffer import BatchMeta, ColumnLeafMeta

    rng = np.random.RandomState(42)
    n = 2_000_000  # ~48MB of typical columnar leaves
    leaves = [
        np.cumsum(rng.randint(0, 10, n)).astype(np.int64),  # sorted-ish
        rng.uniform(900.0, 105000.0, n),                    # prices
        np.ones(n, dtype=np.bool_),                          # validity
    ]
    meta = BatchMeta(
        schema=None, capacity=n,
        leaf_meta=[ColumnLeafMeta(str(a.dtype), [a.shape], [a.dtype.str])
                   for a in leaves[:-1]],
        sel_shape=leaves[-1].shape,
        size_bytes=sum(a.nbytes for a in leaves))
    raw_total = sum(a.nbytes for a in leaves)
    out = {"nbytes": raw_total, "codecs": {}}
    with tempfile.TemporaryDirectory(prefix="bench_spill_") as d:
        for codec_name in ["none"] + [c for c in ("lz4", "zstd")
                                      if c in available_codecs()]:
            pol = CompressionPolicy(codec_name, min_size=0)
            path = os.path.join(d, f"spill_{codec_name}.bin")
            t0 = time.time()
            if pol.enabled:
                frames = pol.compress_leaves(leaves)
                write_leaves(path, frames)
                disk_bytes = sum(f.nbytes for f in frames)
            else:
                write_leaves(path, leaves)
                disk_bytes = raw_total
            w_t = time.time() - t0
            t0 = time.time()
            if pol.enabled:
                from spark_rapids_tpu.native import spill_read
                raw = spill_read(path, disk_bytes)
                codec = resolve_codec(codec_name)
                off = 0
                back = []
                for f in frames:
                    frame = np.frombuffer(raw, np.uint8, count=f.nbytes,
                                          offset=off)
                    back.append(pol.decompress_one(frame, codec))
                    off += f.nbytes
                assert sum(b.nbytes for b in back) == raw_total
            else:
                back = read_leaves(path, meta)
            r_t = time.time() - t0
            out["codecs"][codec_name] = {
                "write_mb_s": round(raw_total / w_t / 1e6, 1),
                "read_mb_s": round(raw_total / r_t / 1e6, 1),
                "disk_bytes": disk_bytes,
                "ratio": round(raw_total / disk_bytes, 2),
            }
    base = out["codecs"].get("none", {})
    for name, rec in out["codecs"].items():
        if name != "none" and base.get("write_mb_s"):
            rec["write_delta_pct"] = round(
                (rec["write_mb_s"] - base["write_mb_s"])
                / base["write_mb_s"] * 100, 1)
            rec["read_delta_pct"] = round(
                (rec["read_mb_s"] - base["read_mb_s"])
                / base["read_mb_s"] * 100, 1)
    out["host_cpus"] = os.cpu_count() or 1
    out["available_codecs"] = available_codecs()
    return out


def fusion_microbench() -> dict:
    """Whole-stage fusion on/off deltas (the ISSUE-6 acceptance numbers):
    a q1-shaped pipeline (scan -> filter -> project -> partial agg) and an
    exchange-bucketing pipeline, each run on a fresh session with cleared
    kernel caches, recording per-query jit-compile count, per-batch
    dispatch count, and warmup seconds — so the compile-count claim
    (>= 2x fewer programs with fusion ON) is measured, not asserted."""
    import jax
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.plan.logical import col, functions as F, lit
    from spark_rapids_tpu.utils import kernel_cache as KC

    # ground truth for compile counts: jax fires one
    # /jax/compilation_cache/compile_requests_use_cache per compiled
    # computation, EAGER primitives included — so the count also sees the
    # per-op dispatch programs fusion eliminates (our kernel_cache
    # counters only see whole programs built through the exec layer)
    xla_compiles = [0]
    try:
        jax.monitoring.register_event_listener(
            lambda name, **kw: xla_compiles.__setitem__(
                0, xla_compiles[0]
                + (name == "/jax/compilation_cache/"
                           "compile_requests_use_cache")))
    except Exception:
        pass

    n = 200_000
    base_conf = {
        "spark.rapids.sql.variableFloatAgg.enabled": "true",
        # several reader batches so per-batch dispatch counts mean
        # something (one giant batch would make every mode look fused)
        "spark.rapids.sql.reader.batchSizeRows": str(n // 4),
        "spark.rapids.sql.tpu.memoryScanCache.enabled": "false",
    }

    def q1_shape(s, df):
        return (df.filter(col("l_shipdate") <= D_19980902)
                .select(col("l_returnflag"), col("l_linestatus"),
                        (col("l_extendedprice")
                         * (lit(1.0) - col("l_discount"))).alias("disc"))
                .group_by(col("l_returnflag"), col("l_linestatus"))
                .agg(F.sum(col("disc")).alias("s"),
                     F.count(lit(1)).alias("c")))

    def exchange_shape(s, df):
        return (df.filter(col("l_discount") >= 0.02)
                .select(col("l_shipdate"), col("l_quantity"))
                .repartition(4, col("l_shipdate")))

    table = make_lineitem(n)
    out = {"rows": n, "queries": {}}
    for qname, build in (("q1_shape", q1_shape),
                         ("exchange_shape", exchange_shape)):
        rec = {}
        for label, fusion in (("fusion_off", "false"), ("fusion_on", "true")):
            conf = dict(base_conf)
            conf["spark.rapids.sql.tpu.fusion.enabled"] = fusion
            KC.clear()
            jax.clear_caches()
            before = KC.stats()
            xla0 = xla_compiles[0]
            s = TpuSession(conf)
            df = s.from_arrow(table)
            t0 = time.time()
            r1 = checksum(build(s, df).collect())
            warmup_s = time.time() - t0
            after_compile = KC.stats()
            xla1 = xla_compiles[0]
            t0 = time.time()
            r2 = checksum(build(s, df).collect())
            steady_s = time.time() - t0
            after = KC.stats()
            rec[label] = {
                "jit_compiles": (after_compile["builds"]
                                 - before["builds"]
                                 + after_compile["stage_compiles"]
                                 - before["stage_compiles"]),
                "xla_compiles": xla1 - xla0,
                "dispatches_warm_run": (after["dispatches"]
                                        - after_compile["dispatches"]),
                # input buffers donated to compiled programs during the
                # warm run (ISSUE 11): each one is an HBM copy the warm
                # dispatch did NOT pay; 0 with fusion off (no stage
                # programs) or donation disabled
                "donated_copies_warm_run": (after["donated_buffers"]
                                            - after_compile[
                                                "donated_buffers"]),
                "warmup_s": round(warmup_s, 3),
                "steady_s": round(steady_s, 4),
                "value": r1,
            }
            assert abs(r1 - r2) <= 1e-6 * max(1.0, abs(r1))
        off, on = rec["fusion_off"], rec["fusion_on"]
        rec["match"] = bool(abs(off["value"] - on["value"])
                            <= 1e-4 * max(1.0, abs(off["value"])))
        # xla_compiles is the ground truth, but if the monitoring event
        # never fired (older jax without the hook) fall back to the
        # exec-layer program count rather than reporting 0/0 = no change
        src = ("xla_compiles" if off["xla_compiles"] or on["xla_compiles"]
               else "jit_compiles")
        rec["compile_reduction"] = round(
            off[src] / max(1, on[src]), 2)
        out["queries"][qname] = rec
    return out


def tracing_microbench() -> dict:
    """Distributed-tracing overhead (the ISSUE-7 <5% acceptance gate):
    the q1 pipeline on fresh sessions with tracing + a file journal ON
    vs OFF (same table, kernels warm after each session's own warmup
    run), plus the live-heartbeat rpc cost measured against a real
    worker process — so 'tracing is cheap' is a recorded artifact, not
    an assertion."""
    import tempfile

    from spark_rapids_tpu.engine import TpuSession

    n = 200_000
    table = make_lineitem(n)

    def measure(conf):
        s = TpuSession({"spark.rapids.sql.variableFloatAgg.enabled":
                        "true", **conf})
        df = s.from_arrow(table)
        checksum(q1(df).collect())          # warmup: compile + caches
        runs = []
        for _ in range(5):
            t0 = time.perf_counter()
            checksum(q1(df).collect())
            runs.append(time.perf_counter() - t0)
        return min(runs)

    off_s = measure({"spark.rapids.sql.tpu.trace.enabled": "false"})
    jdir = tempfile.mkdtemp(prefix="bench_trace_")
    on_s = measure({"spark.rapids.sql.tpu.trace.enabled": "true",
                    "spark.rapids.sql.tpu.metrics.journal.dir": jdir})
    overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s > 0 else 0.0
    out = {"rows": n, "q1_trace_off_s": round(off_s, 4),
           "q1_trace_on_s": round(on_s, 4),
           "overhead_pct": round(overhead_pct, 2),
           # the acceptance gate: tracing must cost <5% on q1
           "gate_ok": bool(overhead_pct < 5.0)}

    # heartbeat cost: round-trip latency of rpc_heartbeat against a live
    # worker process (the monitor polls on DEDICATED connections, so this
    # latency is the whole cost — it never blocks the query path)
    try:
        from spark_rapids_tpu.cluster import ProcCluster
        cluster = ProcCluster(
            1, conf={"spark.rapids.sql.tpu.trace."
                     "heartbeatIntervalMs": "0"}, cpu=True)
        try:
            w = cluster.workers[0]
            w.rpc("heartbeat")              # connection warmup
            t0 = time.perf_counter()
            n_polls = 20
            for _ in range(n_polls):
                hb = w.rpc("heartbeat")
            out["heartbeat_rpc_ms"] = round(
                (time.perf_counter() - t0) / n_polls * 1e3, 3)
            out["heartbeat_fields"] = sorted(hb.keys())
        finally:
            cluster.shutdown()
    except Exception as e:  # the worker probe must never sink the bench
        out["heartbeat_error"] = repr(e)[:200]
    return out


def pressure_microbench(write_artifact: bool = True) -> dict:
    """Memory-budget sweep (the ISSUE-8 acceptance artifact, and the
    BENCH_PRESSURE stage ROADMAP item 4 asks for): the spill-cascade
    slice (partitioned join -> grouped agg -> sort) run at accounted-pool
    budgets of 100/75/50/25% of its measured working set, with the
    memory ledger's breakdown (spill bytes, churn ratio, victim quality,
    retry counts, headroom) recorded per budget — so the data-movement
    scheduler PR has a reproducible baseline to beat.  Also measures the
    ledger's own cost: q1 with the ledger (and a file journal) on vs off
    at MODERATE level, gated <5% like the tracing stage."""
    import shutil
    import tempfile

    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.metrics import names as MN
    from spark_rapids_tpu.metrics.memledger import analyze_shards
    from spark_rapids_tpu.metrics.timeline import load_journal_dir
    from spark_rapids_tpu.plan.logical import col, functions as F, lit

    n = int(os.environ.get("BENCH_PRESSURE_ROWS", 120_000))
    base_conf = {
        "spark.rapids.sql.variableFloatAgg.enabled": "true",
        "spark.rapids.memory.host.spillStorageSize": str(1 << 20),
        "spark.rapids.sql.batchSizeBytes": str(512 << 10),
        "spark.rapids.sql.reader.batchSizeRows": "16384",
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.sql.tpu.join.partitioned.threshold": "1",
        "spark.rapids.sql.tpu.shuffle.partitions": "8",
        "spark.rapids.sql.tpu.memoryScanCache.enabled": "false",
    }

    def slice_query(s):
        fact = s.from_pydict({
            "k": [i % 7 for i in range(n)],
            "v": [float(i) for i in range(n)],
            "q": [i % 3 for i in range(n)]})
        dim = s.from_pydict({"k": list(range(7)),
                             "name": [f"g{j}" for j in range(7)]})
        return checksum(
            fact.join(dim, on="k").filter(col("q") < 2)
            .group_by(col("name"))
            .agg(F.sum(col("v")).alias("sv"), F.count(lit(1)).alias("c"))
            .order_by(col("name")).collect())

    def run(pool_bytes=0, jdir=None, extra=None):
        """One measured slice run.  The warmup query shares the session
        (compiles + H2D), so everything reported is a DELTA over the
        timed run only: counter movement, and only the journal files the
        timed query opened — otherwise every breakdown would double-count
        the warmup's spills against one run's time_s."""
        conf = dict(base_conf, **(extra or {}))
        if pool_bytes:
            conf["spark.rapids.memory.tpu.poolSizeBytes"] = str(pool_bytes)
        if jdir:
            conf["spark.rapids.sql.tpu.metrics.journal.dir"] = jdir
        s = TpuSession(conf)
        slice_query(s)                     # warmup: compiles + H2D
        warm_files = set(os.listdir(jdir)) if jdir else set()
        ps_before = dict(s.runtime.pool_stats())
        tot_before = dict(getattr(s, "query_metrics_total", {}) or {})
        t0 = time.perf_counter()
        val = slice_query(s)
        elapsed = time.perf_counter() - t0
        ps_after = s.runtime.pool_stats()
        counters = {k: int(ps_after.get(k, 0)) - int(ps_before.get(k, 0))
                    for k in (MN.OOM_SPILL_RETRIES, MN.OOM_ALLOC_FAILURES,
                              MN.NUM_POLICY_VICTIM_PICKS,
                              MN.NUM_POLICY_VICTIM_OVERRIDES,
                              MN.NUM_POLICY_EARLY_RELEASES,
                              MN.NUM_PROACTIVE_UNSPILLS)}
        tot_after = dict(getattr(s, "query_metrics_total", {}) or {})
        totals = {k: tot_after.get(k, 0) - tot_before.get(k, 0)
                  for k in tot_after}
        new_shards = []
        if jdir:
            fresh = set(os.listdir(jdir)) - warm_files

            def shard_files(label):
                # invert load_journal_dir's labeling: 'driver/query-N'
                # came from query-N.jsonl, a worker label 'exec-K' from
                # shard-exec-K.jsonl (process-lifetime: only counted
                # when the file itself is fresh)
                base = label.rsplit("/", 1)[-1]
                return {base + ".jsonl", "shard-" + base + ".jsonl"}

            new_shards = [sh for sh in load_journal_dir(jdir)
                          if shard_files(sh["label"]) & fresh]
        return elapsed, val, ps_after, counters, totals, new_shards

    # 1. unconstrained run: the measured working set is the 100% budget.
    # The baseline gets a journal dir too, so slowdown_vs_unconstrained
    # isolates BUDGET pressure rather than folding in journal-write cost
    jdir0 = tempfile.mkdtemp(prefix="bench_pressure_base_")
    try:
        el0, val0, ps0, _c0, _t0, _sh0 = run(jdir=jdir0)
    finally:
        shutil.rmtree(jdir0, ignore_errors=True)
    working_set = int(ps0.get("device_peak", 0)) or 1

    def budget_row(pool, prefix, extra=None):
        jdir = tempfile.mkdtemp(prefix=prefix)
        try:
            el, val, _ps, counters, totals, shards = run(pool, jdir,
                                                         extra)
            rep = analyze_shards(shards)
        finally:
            shutil.rmtree(jdir, ignore_errors=True)
        t = rep["totals"]
        row = {
            "pool_bytes": pool,
            "time_s": round(el, 4),
            "slowdown_vs_unconstrained": round(el / el0, 3) if el0 else None,
            "match": bool(abs(val - val0) <= 1e-6 * max(1.0, abs(val0))),
            # ledger-derived breakdown (metrics/memledger.py)
            "spill_bytes": t["spilled_bytes"],
            "respill_bytes": t["respill_bytes"],
            "churn_ratio": rep["churn"]["churn_ratio"],
            "victim_quality": rep["victim_quality"]["quality"],
            "headroom_bytes": rep["headroom"]["bytes"],
            "cascades": len(rep["cascades"]),
            "oom_spills": t["oom_spills"],
            "oom_fails": t["oom_fails"],
            "ledger_events": t["events"],
            # runtime/retry view of the same run (timed-run deltas)
            "oomSpillRetries": counters[MN.OOM_SPILL_RETRIES],
            "oomAllocFailures": counters[MN.OOM_ALLOC_FAILURES],
            "numPolicyVictimPicks": counters[MN.NUM_POLICY_VICTIM_PICKS],
            "numPolicyVictimOverrides":
                counters[MN.NUM_POLICY_VICTIM_OVERRIDES],
            "numPolicyEarlyReleases":
                counters[MN.NUM_POLICY_EARLY_RELEASES],
            "numProactiveUnspills": counters[MN.NUM_PROACTIVE_UNSPILLS],
            "retries": int(sum(totals.get(f"{b}Retries", 0)
                               for b in MN.RETRY_BLOCKS)),
            "splits": int(sum(totals.get(f"{b}Splits", 0)
                              for b in MN.RETRY_BLOCKS)),
        }
        return row, val

    # each budget runs twice — data-movement policy engine ON (the
    # default) and OFF — so the artifact carries the ISSUE-18 acceptance
    # comparison (churn/slowdown deltas, and bit-for-bit row checksums)
    policy_off_conf = {"spark.rapids.sql.tpu.policy.enabled": "false"}
    budgets = {}
    for pct in (100, 75, 50, 25):
        pool = max(1 << 16, working_set * pct // 100)
        row, val_on = budget_row(pool, f"bench_pressure_{pct}_")
        off, val_off = budget_row(pool, f"bench_pressure_{pct}off_",
                                  policy_off_conf)
        row["policy_off"] = {k: off[k] for k in (
            "time_s", "slowdown_vs_unconstrained", "match",
            "spill_bytes", "respill_bytes", "churn_ratio",
            "victim_quality", "cascades", "oomSpillRetries")}
        row["policy_bit_for_bit"] = bool(val_on == val_off)
        budgets[str(pct)] = row

    # 2. ledger overhead gate (<5% on q1 at MODERATE, journal on — the
    # ISSUE-8 twin of the tracing stage's gate)
    table = make_lineitem(200_000)

    def measure_q1(ledger_on):
        jdir = tempfile.mkdtemp(prefix="bench_pressure_ovh_")
        try:
            s = TpuSession({
                "spark.rapids.sql.variableFloatAgg.enabled": "true",
                "spark.rapids.sql.tpu.metrics.journal.dir": jdir,
                "spark.rapids.sql.tpu.memory.ledger.enabled":
                    "true" if ledger_on else "false"})
            df = s.from_arrow(table)
            checksum(q1(df).collect())      # warmup
            runs = []
            for _ in range(5):
                t0 = time.perf_counter()
                checksum(q1(df).collect())
                runs.append(time.perf_counter() - t0)
            return min(runs)
        finally:
            shutil.rmtree(jdir, ignore_errors=True)

    off_s = measure_q1(False)
    on_s = measure_q1(True)
    overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s > 0 else 0.0

    rec = {
        "recorded_unix": int(time.time()),
        "rows": n,
        "working_set_bytes": working_set,
        "unconstrained_time_s": round(el0, 4),
        "conf": {k: v for k, v in base_conf.items()
                 if "variableFloat" not in k},
        "budgets": budgets,
        "ledger_overhead": {
            "q1_ledger_off_s": round(off_s, 4),
            "q1_ledger_on_s": round(on_s, 4),
            "overhead_pct": round(overhead_pct, 2),
            "gate_ok": bool(overhead_pct < 5.0)},
        "note": ("join->agg->sort spill-cascade slice at 25/50/75/100% "
                 "of measured working set; breakdowns reconstructed "
                 "offline from the memory ledger journal "
                 "(python -m spark_rapids_tpu.metrics --memory)"),
    }
    try:
        import jax
        rec["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        rec["platform"] = "unknown"
    if write_artifact:
        try:
            with open(os.path.join(REPO, "BENCH_PRESSURE.json"), "w") as f:
                json.dump(rec, f, indent=1)
        except OSError:
            pass
    return rec


def serve_microbench(write_artifact: bool = True) -> dict:
    """Serving-tier bench (ISSUE 10 acceptance; also BENCH_SERVE.json).

    Part 1 — parameterized plan cache: a q1-shaped query is submitted
    cold (cleared kernel caches), then re-submitted with CHANGED literals
    (date cutoff, price scale).  The variant must ride the plan cache
    (hit counters prove the path) and compile >= 5x fewer XLA programs
    than the cold run — values re-bind into the cached compiled stages.

    Part 2 — mixed workload: 12 short selective queries (literal
    variants, priority 5) race 2 long parquet-scan queries (priority 0)
    through the scheduler at concurrency 1/4/16, all on warm compile
    caches (one untimed warmup round first, so the concurrency deltas
    measure OVERLAP, not compile luck).  Records wall time, throughput,
    p50/p95 latency, p95 queue time, admission stats — plus an OOM-
    injection round at concurrency 4 whose per-query checksums must be
    bit-for-bit identical to the serial round's."""
    import jax
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.plan.logical import col, functions as F, lit
    from spark_rapids_tpu.utils import kernel_cache as KC

    xla_compiles = [0]
    try:
        jax.monitoring.register_event_listener(
            lambda name, **kw: xla_compiles.__setitem__(
                0, xla_compiles[0]
                + (name == "/jax/compilation_cache/"
                           "compile_requests_use_cache")))
    except Exception:
        pass

    n = 300_000
    table = make_lineitem(n)
    base_conf = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}

    def q1_param(df, cutoff, scale):
        disc = col("l_extendedprice") * (lit(scale) - col("l_discount"))
        return (df.filter(col("l_shipdate") <= cutoff)
                .group_by(col("l_returnflag"), col("l_linestatus"))
                .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                     F.sum(disc).alias("sum_disc"),
                     F.avg(col("l_discount")).alias("avg_disc"),
                     F.count(lit(1)).alias("n"))
                .order_by("l_returnflag", "l_linestatus"))

    out = {"rows": n, "single_core": (os.cpu_count() or 1) == 1}

    # ---- part 1: parameterized plan cache ---------------------------------
    KC.clear()
    jax.clear_caches()
    s = TpuSession(base_conf)
    df = s.from_arrow(table)
    variants = [(D_19980902, 1.0), (D_1995, 1.02), (D_1994, 0.98)]
    runs = []
    for i, (cutoff, scale) in enumerate(variants):
        b0, x0, t0 = KC.stats(), xla_compiles[0], time.time()
        val = checksum(s.submit(q1_param(df, cutoff, scale)).collect(300))
        b1, x1 = KC.stats(), xla_compiles[0]
        runs.append({
            "label": "cold" if i == 0 else f"variant{i}",
            "seconds": round(time.time() - t0, 3),
            "xla_compiles": x1 - x0,
            "jit_compiles": (b1["builds"] - b0["builds"]
                             + b1["stage_compiles"] - b0["stage_compiles"]),
            "value": val,
        })
    sched = s.scheduler.stats()
    s.shutdown_serving()
    cold, var1 = runs[0], runs[1]
    src = ("xla_compiles" if cold["xla_compiles"] or var1["xla_compiles"]
           else "jit_compiles")
    out["plan_cache"] = {
        "runs": runs,
        "hits": sched["plan_cache"]["hits"],
        "misses": sched["plan_cache"]["misses"],
        "params_lifted": sched["plan_cache"]["params_lifted"],
        "compile_reduction": round(
            cold[src] / max(1, max(r[src] for r in runs[1:])), 2),
        "warmup_reduction": round(
            cold["seconds"] / max(1e-9, max(r["seconds"]
                                            for r in runs[1:])), 2),
    }

    # ---- part 2: mixed workload at concurrency 1/4/16 ---------------------
    pq_dir = os.path.join("/tmp", f"bench_serve_{n}")
    pq_path = os.path.join(pq_dir, "lineitem.parquet")
    if not os.path.exists(pq_path):
        import pyarrow.parquet as papq
        os.makedirs(pq_dir, exist_ok=True)
        tmp = f"{pq_path}.{os.getpid()}.tmp"
        papq.write_table(table, tmp, compression="snappy")
        os.replace(tmp, pq_path)

    short_variants = [(8300 + 137 * i, 0.01 + 0.005 * (i % 8), 25 + i % 20)
                      for i in range(12)]

    def q_short(df, lo, dmin, qmax):
        return (df.filter((col("l_shipdate") >= lo)
                          & (col("l_discount") >= dmin)
                          & (col("l_quantity") < qmax))
                .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                     .alias("revenue")))

    def run_round(concurrency, inject=None):
        conf = dict(base_conf)
        conf["spark.rapids.sql.tpu.serve.maxConcurrentQueries"] = \
            str(concurrency)
        conf["spark.rapids.sql.concurrentTpuTasks"] = str(concurrency)
        if inject:
            conf["spark.rapids.tpu.test.injectOom"] = inject
        rs = TpuSession(conf)
        rdf = rs.from_arrow(table)
        t0 = time.time()
        futs = [(f"short{i}", rs.submit(q_short(rdf, *v), priority=5))
                for i, v in enumerate(short_variants)]
        futs += [(f"long{j}", rs.submit(q6(rs.read.parquet(pq_path)),
                                        priority=0))
                 for j in range(2)]
        values = {name: checksum(f.collect(600)) for name, f in futs}
        wall = time.time() - t0
        lats = sorted(f.latency_seconds for _n2, f in futs)
        queues = sorted(f.queue_seconds for _n2, f in futs)

        def pct(xs, p):
            return round(xs[min(len(xs) - 1, int(p * len(xs)))], 4)
        stats = rs.scheduler.stats()
        rs.shutdown_serving()
        return {
            "concurrency": concurrency,
            "queries": len(futs),
            "wall_s": round(wall, 3),
            "throughput_qps": round(len(futs) / wall, 3),
            "p50_latency_s": pct(lats, 0.50),
            "p95_latency_s": pct(lats, 0.95),
            "p95_queue_s": pct(queues, 0.95),
            "plan_cache_hits": stats["plan_cache"]["hits"],
            "admitted": stats["admitted"],
            "failed": stats["failed"],
        }, values

    # serial BLOCKING baseline: the same mix through collect() loops on a
    # fresh session with cleared caches — what "one query owns the
    # runtime" costs a second user: every literal variant pays its own
    # baked-literal trace+compile, and nothing overlaps.  This is the
    # "serial execution of the same query mix" the acceptance criterion
    # compares concurrency-4 against.
    KC.clear()
    jax.clear_caches()
    sb = TpuSession(base_conf)
    sdf = sb.from_arrow(table)
    t0 = time.time()
    serial_values = {}
    for i, v in enumerate(short_variants):
        serial_values[f"short{i}"] = checksum(q_short(sdf, *v).collect())
    for j in range(2):
        serial_values[f"long{j}"] = checksum(
            q6(sb.read.parquet(pq_path)).collect())
    serial_wall = time.time() - t0
    n_mix = len(serial_values)
    serial_blocking = {"wall_s": round(serial_wall, 3),
                       "queries": n_mix,
                       "throughput_qps": round(n_mix / serial_wall, 3)}

    run_round(4)  # warm the parameterized programs, untimed
    rounds = {"serial_blocking": serial_blocking}
    baseline_values = None
    mismatches = 0
    for c in (1, 4, 16):
        rec, values = run_round(c)
        if baseline_values is None:
            baseline_values = values
        else:
            for k, v in values.items():
                if abs(v - baseline_values[k]) > 1e-6 * max(1.0, abs(v)):
                    mismatches += 1
        rounds[f"c{c}"] = rec
    rec, values = run_round(4, inject="5x2,17x2,29x2,41x2")
    for k, v in values.items():
        if abs(v - baseline_values[k]) > 1e-6 * max(1.0, abs(v)):
            mismatches += 1
    rec["injectOom"] = "5x2,17x2,29x2,41x2"
    rounds["c4_oom"] = rec
    # the scheduler rounds must agree with the BLOCKING run too (same
    # queries, parameterized vs baked execution paths)
    for k, v in baseline_values.items():
        if abs(v - serial_values[k]) > 1e-6 * max(1.0, abs(v)):
            mismatches += 1
    out["mixed_workload"] = rounds
    out["mismatches"] = mismatches

    # ---- part 3: SLO-aware preemption (ISSUE 19) --------------------------
    # A latency class (priority 10, selective short queries) arrives
    # while a long priority-0 background scan holds the single device
    # semaphore slot.  Preemption OFF: each short query waits for the
    # whole remaining background run.  Preemption ON: the background
    # query suspends at its next stage boundary (parks its buffers,
    # releases the semaphore) and resumes afterwards — the latency-class
    # p99 is the headline, the preempt SLO phase (suspend->resume
    # seconds the victim paid) is the cost side, and every background
    # checksum must stay bit-for-bit identical to the unpreempted run.
    def q_bg(df):
        return (df.filter(col("l_quantity") > lit(0.0))
                .select((col("l_extendedprice")
                         * (lit(1.0) - col("l_discount"))).alias("v"),
                        (col("l_quantity") * lit(3.0)).alias("w"),
                        col("l_shipdate")))

    def preempt_round(enabled: bool):
        conf = dict(base_conf)
        conf.update({
            "spark.rapids.sql.tpu.serve.maxConcurrentQueries": "2",
            "spark.rapids.sql.concurrentTpuTasks": "1",
            "spark.rapids.sql.reader.batchSizeRows": "4000",
            "spark.rapids.sql.tpu.serve.preemption.enabled":
                "true" if enabled else "false",
        })
        ps = TpuSession(conf)
        pdf = ps.from_arrow(table)
        # warm both shapes (untimed): the round measures CONTENTION, not
        # compile luck
        checksum(ps.submit(q_bg(pdf)).collect(600))
        checksum(ps.submit(q_short(pdf, *short_variants[0])).collect(600))
        bg_vals = []
        f_bg = ps.submit(q_bg(pdf), priority=0)
        lats = []
        for i in range(10):
            if f_bg.done():
                bg_vals.append(checksum(f_bg.collect(600)))
                f_bg = ps.submit(q_bg(pdf), priority=0)
            f = ps.submit(q_short(pdf, *short_variants[i % 12]),
                          priority=10)
            f.result(600)
            lats.append(f.latency_seconds)
            time.sleep(0.02)
        bg_vals.append(checksum(f_bg.collect(600)))
        lats.sort()

        def pct(p):
            return round(lats[min(len(lats) - 1, int(p * len(lats)))], 4)
        st = ps.scheduler.stats()
        slo = ps.scheduler.slo.report()
        ps.shutdown_serving()
        rec = {
            "enabled": enabled,
            "latency_queries": len(lats),
            "p50_latency_s": pct(0.50),
            "p95_latency_s": pct(0.95),
            "p99_latency_s": pct(0.99),
            "bg_runs": len(bg_vals),
            "preemptions": st["lifecycle"]["preemptions"],
            "preemption_resumes": st["lifecycle"]["preemption_resumes"],
        }
        pre = slo.get("preempt", {}).get("10", None) \
            or slo.get("preempt", {}).get("0", None)
        if pre:
            # suspend->resume latency the victims paid (SLO phase)
            rec["preempt_p50_s"] = pre["p50_s"]
            rec["preempt_p99_s"] = pre["p99_s"]
        return rec, bg_vals

    try:
        rec_off, bg_off = preempt_round(False)
        rec_on, bg_on = preempt_round(True)
        bg_mismatch = sum(1 for v in bg_on + bg_off
                          if abs(v - bg_on[0]) > 1e-6 * max(1.0, abs(v)))
        # shed/cancel accounting round: expired deadlines shed at
        # admission, a cancel of the queued second query resolves it
        # without it ever costing a worker (maxConcurrentQueries=1 keeps
        # it deterministically queued behind the first)
        cconf = dict(base_conf)
        cconf["spark.rapids.sql.tpu.serve.maxConcurrentQueries"] = "1"
        cconf["spark.rapids.sql.reader.batchSizeRows"] = "4000"
        cs = TpuSession(cconf)
        cdf = cs.from_arrow(table)
        f1 = cs.submit(q_bg(cdf))
        fc = cs.submit(q_bg(cdf))
        fc.cancel("bench accounting round")
        fc.exception(600)
        f1.result(600)
        shed_futs = [cs.submit(q_short(cdf, *short_variants[i]),
                               deadline_ms=0.001) for i in range(4)]
        for f in shed_futs:
            f.exception(600)
        acct = cs.scheduler.stats()["lifecycle"]
        cs.shutdown_serving()
        out["preemption"] = {
            "off": rec_off,
            "on": rec_on,
            "p99_improvement": round(
                rec_off["p99_latency_s"]
                / max(1e-9, rec_on["p99_latency_s"]), 3),
            "bg_checksum_mismatches": bg_mismatch,
            "sheds": acct["deadline_sheds"],
            "cancels": acct["cancelled"],
        }
    except Exception as e:  # noqa: BLE001 — bench stage must not abort
        out["preemption"] = {"error": repr(e)[:200]}
    out["speedup_c4_vs_serial"] = round(
        rounds["c4"]["throughput_qps"]
        / max(1e-9, serial_blocking["throughput_qps"]), 3)
    out["speedup_c16_vs_serial"] = round(
        rounds["c16"]["throughput_qps"]
        / max(1e-9, serial_blocking["throughput_qps"]), 3)
    # isolated concurrency effect on warm caches (on a single-core host
    # expect ~1.0: there is no second core for overlapped work)
    out["speedup_c4_vs_c1_warm"] = round(
        rounds["c4"]["throughput_qps"]
        / max(1e-9, rounds["c1"]["throughput_qps"]), 3)
    try:
        out["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        out["platform"] = "unknown"
    if write_artifact:
        try:
            with open(os.path.join(REPO, "BENCH_SERVE.json"), "w") as f:
                json.dump(out, f, indent=1)
        except OSError:
            pass
    return out


def streaming_microbench(write_artifact: bool = True) -> dict:
    """Streaming micro-batch bench (ISSUE 20 acceptance artifact:
    BENCH_STREAM.json).

    For several epoch batch sizes: a grouped sum/avg/count query runs
    incrementally over an in-memory append stream (reader batch rows
    pinned to the epoch size — the bit-for-bit alignment contract).
    After a 3-epoch warm-up, the sweep records epochs/s, p50/p95 epoch
    latency, and the warm-epoch compile count, which must be ZERO (every
    epoch after the first is a plan-cache hit replaying compiled
    stages).  At the largest stream length it also times one full batch
    re-query over everything seen so far: the incremental epoch must
    beat it >= 3x (the speedup grows with stream length — that is the
    point of keeping state resident), and the incremental result's
    checksum must match the batch oracle's."""
    import jax
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.engine import DataFrame, TpuSession
    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.plan.logical import col, functions as F, lit
    from spark_rapids_tpu.streaming import MemoryStream, StreamingQuery
    from spark_rapids_tpu.types import LongType, DoubleType, Schema, \
        StructField
    from spark_rapids_tpu.utils import kernel_cache as KC

    xla_compiles = [0]
    try:
        jax.monitoring.register_event_listener(
            lambda name, **kw: xla_compiles.__setitem__(
                0, xla_compiles[0]
                + (name == "/jax/compilation_cache/"
                           "compile_requests_use_cache")))
    except Exception:
        pass

    schema = Schema([StructField("k", LongType),
                     StructField("v", DoubleType)])
    rng = np.random.default_rng(42)

    def make_chunk(rows):
        return pa.table({
            "k": pa.array(rng.integers(0, 64, rows), type=pa.int64()),
            "v": pa.array(rng.random(rows) * 100.0, type=pa.float64())})

    def build(df):
        return df.group_by(col("k")).agg(
            F.sum(col("v")).alias("sv"), F.avg(col("v")).alias("av"),
            F.count(lit(1)).alias("c"))

    WARMUP = 3
    out = {"single_core": (os.cpu_count() or 1) == 1, "batch_sizes": []}
    for batch_rows, n_epochs in ((2_000, 24), (8_000, 24), (32_000, 24)):
        conf = {
            "spark.rapids.sql.variableFloatAgg.enabled": "true",
            "spark.rapids.sql.reader.batchSizeRows": str(batch_rows),
            "spark.rapids.sql.tpu.streaming.maxBatchRows": str(batch_rows),
        }
        s = TpuSession(conf)
        src = MemoryStream(schema, name=f"bench{batch_rows}")
        q = StreamingQuery(s, src, build, name=f"bench{batch_rows}")
        for _ in range(WARMUP):
            src.append(make_chunk(batch_rows))
            q.trigger_once()
        b0, x0 = KC.stats(), xla_compiles[0]
        times = []
        for _ in range(n_epochs - WARMUP):
            src.append(make_chunk(batch_rows))
            t0 = time.time()
            q.trigger_once()
            times.append(time.time() - t0)
        b1, x1 = KC.stats(), xla_compiles[0]
        times.sort()

        def pct(p):
            return round(times[min(len(times) - 1,
                                   int(p * len(times)))], 5)

        rec = {
            "epoch_rows": batch_rows,
            "epochs": n_epochs,
            "warm_epochs": len(times),
            "epochs_per_s": round(len(times) / max(1e-9, sum(times)), 2),
            "p50_epoch_s": pct(0.50),
            "p95_epoch_s": pct(0.95),
            "rows_per_s": round(batch_rows * len(times)
                                / max(1e-9, sum(times)), 1),
            "warm_compiles": (b1["builds"] - b0["builds"]
                              + b1["stage_compiles"]
                              - b0["stage_compiles"]),
            "warm_xla_compiles": x1 - x0,
        }
        if batch_rows == 32_000:
            # incremental-vs-full-requery at the longest stream: one
            # more epoch incrementally vs the whole history from scratch
            src.append(make_chunk(batch_rows))
            t0 = time.time()
            q.trigger_once()
            t_inc = time.time() - t0
            full_df = build(DataFrame(s, L.LogicalScan(
                src.rows_between(0, src.latest_offset()), schema,
                "memory")))
            t_full = None
            for _ in range(2):  # first run may compile the final concat
                t1 = time.time()
                full = full_df.to_arrow()
                t_full = time.time() - t1
            inc = q.result()
            cks = {
                "incremental": round(checksum(
                    sorted(zip(*(inc.column(i).to_pylist()
                                 for i in range(inc.num_columns))))), 4),
                "batch_oracle": round(checksum(
                    sorted(zip(*(full.column(i).to_pylist()
                                 for i in range(full.num_columns))))), 4),
            }
            rec["requery"] = {
                "stream_rows": src.latest_offset(),
                "incremental_epoch_s": round(t_inc, 5),
                "full_requery_s": round(t_full, 5),
                "speedup": round(t_full / max(1e-9, t_inc), 2),
                "checksum_match": abs(cks["incremental"]
                                      - cks["batch_oracle"])
                <= 1e-6 * max(1.0, abs(cks["batch_oracle"])),
                **cks,
            }
        out["batch_sizes"].append(rec)
        q.stop()
        s.shutdown_serving()
    out["warm_compiles_total"] = sum(r["warm_compiles"]
                                     for r in out["batch_sizes"])
    out["zero_warm_compiles"] = out["warm_compiles_total"] == 0
    last = out["batch_sizes"][-1].get("requery", {})
    out["incremental_speedup"] = last.get("speedup")
    out["checksum_match"] = last.get("checksum_match")
    try:
        out["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        out["platform"] = "unknown"
    if write_artifact:
        try:
            with open(os.path.join(REPO, "BENCH_STREAM.json"), "w") as f:
                json.dump(out, f, indent=1)
        except OSError:
            pass
    return out


def chaos_microbench(write_artifact: bool = True) -> dict:
    """Chaos/recovery bench (ISSUE 15 acceptance artifact:
    BENCH_CHAOS.json).  On a 3-worker CPU ProcCluster running the
    representative grouped-aggregation slice:

      * recovery-latency rows at 0 / 1 / 2 injected mid-task kills per
        query (injectCrash armed per round over rpc_inject_faults, so
        replacements spawn healthy), each round verified EXACTLY equal
        to the fault-free result (int64 aggregation: order-invariant);
      * a measured speculation win on an injected-delay straggler: the
        speculative copy finishes first (wall clock well under the
        injected delay), the result is identical, and
        numSpeculationWins moves.

    Workers are always forced-CPU subprocesses, so this stage never
    touches a leased chip from a TPU-mode child (driver side only plans
    and compares)."""
    from spark_rapids_tpu.cluster import ProcCluster
    from spark_rapids_tpu.engine import DataFrame, TpuSession
    from spark_rapids_tpu.plan import logical as PL
    from spark_rapids_tpu.plan.logical import col, functions as F

    import pyarrow as pa

    rows = int(os.environ.get("BENCH_CHAOS_ROWS", 6000))
    n_workers = 3
    delay_ms = 8000
    session = TpuSession()
    table = pa.table({"k": pa.array([i % 32 for i in range(rows)],
                                    pa.int64()),
                      "v": pa.array([5 * i + 3 for i in range(rows)],
                                    pa.int64())})
    step = (rows + n_workers - 1) // n_workers
    map_plans = [session.from_arrow(table.slice(i * step, step)).plan
                 for i in range(n_workers)]
    map_schema = DataFrame(session, map_plans[0]).schema
    reduce_plan = (DataFrame(session, PL.LogicalPlaceholder(map_schema))
                   .group_by(col("k"))
                   .agg(F.sum(col("v")).alias("sv"),
                        F.count(col("v")).alias("c"))).plan
    out = {"rows": rows, "workers": n_workers, "kill_rounds": []}
    cluster = ProcCluster(
        n_workers,
        conf={"spark.rapids.sql.tpu.task.timeoutMs": "30000",
              "spark.rapids.sql.tpu.task.retryBackoffMs": "50",
              "spark.rapids.sql.tpu.task.maxBackoffMs": "500",
              "spark.rapids.shuffle.retry.backoffBaseMs": "5",
              "spark.rapids.sql.tpu.trace.heartbeatIntervalMs": "200"},
        cpu=True, max_task_retries=3)
    try:
        def run_once():
            t0 = time.perf_counter()
            res, _stats = cluster.run_map_reduce(map_plans, ["k"],
                                                 2 * n_workers,
                                                 reduce_plan)
            dt = time.perf_counter() - t0
            return {k: (sv, c) for k, sv, c in
                    zip(res["k"].to_pylist(), res["sv"].to_pylist(),
                        res["c"].to_pylist())}, dt

        oracle, _warm = run_once()   # warm compile caches
        _, clean_s = run_once()      # steady-state fault-free latency
        out["clean_s"] = round(clean_s, 3)
        for kills in (0, 1, 2):
            for w in cluster.workers:
                w.rpc("inject_faults")  # disarm
            for w in cluster.workers[:kills]:
                w.rpc("inject_faults", crash="map@1")
            retries0 = cluster.task_retries
            got, dt = run_once()
            out["kill_rounds"].append({
                "kills": kills,
                "seconds": round(dt, 3),
                "recovery_latency_s": round(max(0.0, dt - clean_s), 3),
                "replacements": cluster.task_retries - retries0,
                "bit_for_bit": got == oracle})
        # speculation win on an injected-delay straggler
        for w in cluster.workers:
            w.rpc("inject_faults")
        cluster.workers[1].rpc("inject_faults",
                               delay=f"reduce:{delay_ms}")
        wins0, spec0 = cluster.speculation_wins, cluster.speculative_tasks
        got, dt = run_once()
        out["speculation"] = {
            "injected_delay_s": delay_ms / 1e3,
            "seconds": round(dt, 3),
            "beat_the_straggler": bool(dt < delay_ms / 1e3),
            "speculative_tasks": cluster.speculative_tasks - spec0,
            "numSpeculationWins": cluster.speculation_wins - wins0,
            "bit_for_bit": got == oracle}
        out["recovery"] = {
            "task_retries": cluster.task_retries,
            "evicted_workers": cluster.evicted_workers,
            "abandoned_tasks": cluster.abandoned_tasks,
            "worker_shrinks": cluster.worker_shrinks,
            "driver_counters": {
                k: v for k, v in sorted(
                    cluster._transport.counters.items())
                if k.startswith("task_retries_")
                or k == "worker_shrinks"}}
        out["ok"] = bool(
            all(r["bit_for_bit"] for r in out["kill_rounds"])
            and out["speculation"]["bit_for_bit"]
            and out["speculation"]["numSpeculationWins"] >= 1)
    finally:
        cluster.shutdown()
    if write_artifact:
        try:
            with open(os.path.join(REPO, "BENCH_CHAOS.json"), "w") as f:
                json.dump(out, f, indent=1)
        except OSError:
            pass
    return out


def profile_microbench(write_artifact: bool = True) -> dict:
    """Roofline-attribution capture (ISSUE 13 acceptance artifact:
    BENCH_PROFILE.json).  Runs the representative query set (q1 grouped
    agg, q6 selective agg) with a journal, captures each query's
    roofline ledger — per-operator declared bytes per resource,
    estimated/HLO flops, measured span seconds, the named bottleneck
    resource, achieved-vs-peak utilization — plus a serving-tier round
    that populates the per-priority SLO phase histograms, and measures
    the profiler's own overhead (cost accounting + ledger build ON vs
    the costAccounting kill switch, same MODERATE level, <5% gate).
    scripts/profile_regression.py diffs this artifact against the
    checked-in BASELINE_PROFILE.json in CI."""
    import shutil
    import tempfile

    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.metrics import roofline as RL
    from spark_rapids_tpu.plan.logical import col, functions as F

    n = int(os.environ.get("BENCH_PROFILE_ROWS", 200_000))
    table = make_lineitem(n)
    base_conf = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
    peaks = None
    out = {"rows": n, "queries": {}}

    def run_q1(s):
        return checksum(q1(s.from_arrow(table)).collect())

    def run_q6(s):
        return checksum(q6(s.from_arrow(table)).collect())

    nj = n // 4

    def run_join(s):
        # exchange + partitioned join + grouped agg + sort: the shape
        # that exercises the wire/d2h/link declarations q1/q6 cannot
        fact = s.from_pydict({
            "k": [i % 7 for i in range(nj)],
            "v": [float(i) for i in range(nj)],
            "q": [i % 3 for i in range(nj)]})
        dim = s.from_pydict({"k": list(range(7)),
                             "name": [f"g{j}" for j in range(7)]})
        return checksum(
            fact.join(dim, on="k").filter(col("q") < 2)
            .group_by(col("name"))
            .agg(F.sum(col("v")).alias("sv"))
            .order_by(col("name")).collect())

    join_conf = {
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.sql.tpu.join.partitioned.threshold": "1",
        "spark.rapids.sql.tpu.shuffle.partitions": "4",
    }

    # ---- per-query roofline ledgers ---------------------------------------
    for qname, run_fn, extra in (("q1", run_q1, {}), ("q6", run_q6, {}),
                                 ("join_slice", run_join, join_conf)):
        jdir = tempfile.mkdtemp(prefix=f"bench_profile_{qname}_")
        try:
            s = TpuSession({**base_conf, **extra,
                            "spark.rapids.sql.tpu.metrics.journal.dir":
                            jdir})
            run_fn(s)                               # warm: compiles + H2D
            t0 = time.perf_counter()
            val = run_fn(s)
            elapsed = time.perf_counter() - t0
            qe = s.last_execution
            if peaks is None:
                peaks = RL.platform_peaks(conf=s.conf)
            ledger = qe.roofline_ledger(peaks)
            out["queries"][qname] = {
                "time_s": round(elapsed, 4),
                "value": val,
                "nodes": len(ledger),
                # the acceptance criterion: every plan node names a
                # bottleneck resource ('host' = declared orchestration-
                # bound, still a named attribution)
                "all_nodes_attributed": all(
                    r["bottleneck"] for r in ledger),
                "summary": RL.summarize(ledger),
                "ledger": ledger,
            }
        finally:
            shutil.rmtree(jdir, ignore_errors=True)
    out["peaks"] = peaks

    # ---- profiler overhead gate (<5% on q1, min-of-5, same level) ---------
    def measure_q1(conf):
        s = TpuSession({**base_conf, **conf})
        df = s.from_arrow(table)
        checksum(q1(df).collect())
        runs = []
        for _ in range(5):
            t0 = time.perf_counter()
            checksum(q1(df).collect())
            runs.append(time.perf_counter() - t0)
        return min(runs)

    off_s = measure_q1({
        "spark.rapids.sql.tpu.roofline.costAccounting.enabled": "false",
        "spark.rapids.sql.tpu.roofline.enabled": "false"})
    on_s = measure_q1({})
    overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s > 0 else 0.0
    out["profiler_overhead"] = {
        "q1_cost_off_s": round(off_s, 4),
        "q1_cost_on_s": round(on_s, 4),
        "overhead_pct": round(overhead_pct, 2),
        "gate_ok": bool(overhead_pct < 5.0),
    }

    # ---- serving SLO phase histograms (per priority class) ----------------
    s = TpuSession(base_conf)
    df = s.from_arrow(table)
    futs = []
    for i in range(6):
        qv = q6(df) if i % 2 else \
            df.filter(col("l_discount") >= 0.01 * (i + 1)).agg(
                F.sum(col("l_extendedprice")).alias("r"))
        futs.append(s.submit(qv, priority=5 if i % 2 else 0))
    for f in futs:
        f.result(300)
    sched = s.scheduler
    out["slo"] = sched.stats()["slo"]
    out["fairness"] = sched.fairness_snapshot()
    s.shutdown_serving()
    try:
        import jax
        out["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        out["platform"] = "unknown"
    out["recorded_unix"] = int(time.time())
    if write_artifact:
        try:
            with open(os.path.join(REPO, "BENCH_PROFILE.json"), "w") as f:
                json.dump(out, f, indent=1)
        except OSError:
            pass
    return out


# --------------------------------------------------------------------------
# multichip: mesh-vs-socket exchange tiers per device count (ISSUE 14)
# --------------------------------------------------------------------------

MULTICHIP_DEVICE_COUNTS = (2, 4, 8)


def multichip_measure(n_devices: int, rows: int = 1 << 17,
                      runs: int = 4, parity: bool = True) -> dict:
    """In-process mesh-vs-socket exchange measurement (the
    --multichip-child entry calls this AFTER provisioning `n_devices`
    virtual CPU devices; scripts/ci.sh's dryrun reuses it at a smaller
    size).  One generic hash exchange over the same table on both tiers:

      * MESH tier: `TpuShuffleExchangeExec` lowered to jitted shard_map
        collectives (shuffle/mesh_exchange.py) — materialize + full
        per-partition read, everything device-resident;
      * SOCKET tier: the kill-switched exchange (device catalog write)
        plus the production cross-host read — every partition's buffers
        served by the env's real ShuffleServer over a REAL TCP loopback
        socket (shuffle/net.py bounce/chunk path, the BENCH_WIRE wire)
        and re-adopted H2D.  This is the D2H -> wire -> H2D tax the
        mesh tier exists to eliminate.

    Reports per-tier effective throughput over the exchange's LOGICAL
    bytes (the codec-invariant map-statistics figure, identical across
    tiers by construction — asserted), warm-run compiled-program
    dispatch/compile counts for the mesh tier, checksum mismatches
    between the tiers' partition contents, and (parity=True) q1/join
    -slice bit-for-bit checks across mesh / kill-switch / mesh-less
    sessions."""
    import jax

    from spark_rapids_tpu import config as C  # noqa: F401 (conf keys)
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.exec.base import ExecContext
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.mem.buffer import host_to_batch
    from spark_rapids_tpu.mem.runtime import TpuRuntime
    from spark_rapids_tpu.plan.logical import col
    from spark_rapids_tpu.shuffle.manager import get_shuffle_env
    from spark_rapids_tpu.shuffle.net import (ShuffleSocketServer,
                                              SocketTransport)
    from spark_rapids_tpu.utils import kernel_cache as KC

    # wide rows (one int64 key + 12 float64 payload columns, ~118
    # logical B/row): the exchange tiers differ in how they MOVE bytes,
    # and narrow rows would let the shared per-row partition-id compute
    # dominate both tiers on a small host
    table = {"k": [(i * 2654435761) % (1 << 31) for i in range(rows)]}
    for j in range(12):
        table[f"v{j}"] = [float(i + j) * 0.5 for i in range(rows)]

    def find_exchange(node):
        if isinstance(node, TpuShuffleExchangeExec):
            return node
        for c in node.children:
            r = find_exchange(c)
            if r is not None:
                return r
        return None

    def tier_setup(ici: bool):
        conf = {"spark.rapids.sql.tpu.mesh.devices": str(n_devices),
                "spark.rapids.sql.tpu.shuffle.ici.enabled":
                    "true" if ici else "false"}
        s = TpuSession(conf)
        return s, TpuRuntime(s.conf)

    def fresh_exchange(s, rt):
        # fresh plan instance per run (an exchange caches its handle),
        # SAME session/runtime so the scan cache and kernel caches warm
        # across runs and the measurement is the exchange, not warmup
        df = s.from_pydict(table).repartition(n_devices, col("k"))
        ex = find_exchange(df.physical_plan())
        return ex, ExecContext(conf=s.conf, runtime=rt)

    def drain_seconds(s, rt):
        ex, ctx = fresh_exchange(s, rt)
        t0 = time.time()
        batches = [b for b in ex.children[0].execute(ctx)]
        jax.block_until_ready([c.data for b in batches
                               for c in b.columns])
        return time.time() - t0

    def checksum_parts(parts_by_p):
        total_rows = 0
        acc = 0.0
        for p in sorted(parts_by_p):
            for tb in parts_by_p[p]:
                total_rows += tb.num_rows
                for j in range(tb.num_columns):
                    acc += float((p + 1)) * sum(
                        v for v in tb.column(j).to_pylist()
                        if v is not None)
        return total_rows, round(acc, 3)

    # ---- mesh tier ----------------------------------------------------
    mesh_sums = None
    logical_bytes = 0
    mesh_t = []
    dispatches_warm = compiles_warm = 0
    s, rt = tier_setup(True)
    for r in range(runs):
        ex, ctx = fresh_exchange(s, rt)
        before = KC.stats()
        t0 = time.time()
        h = ex.materialize(ctx)
        parts = {}
        for p in range(h.num_partitions):
            subs = h.fetch(p)
            jax.block_until_ready([c.data for b in subs
                                   for c in b.columns])
            parts[p] = subs
        mesh_t.append(time.time() - t0)
        after = KC.stats()
        if r == runs - 1:  # warm run: caches populated by earlier runs
            dispatches_warm = after["dispatches"] - before["dispatches"]
            compiles_warm = (after["stage_compiles"]
                             - before["stage_compiles"])
            logical_bytes = h.stats().total_bytes
            mesh_sums = checksum_parts(
                {p: [b.to_arrow() for b in subs]
                 for p, subs in parts.items()})
        assert getattr(h, "is_mesh", False), "mesh tier never lowered"
        h.release()
    mesh_drain = min(drain_seconds(s, rt) for _ in range(2))

    # ---- socket tier --------------------------------------------------
    sock_t = []
    sock_sums = None
    sock_bytes = 0
    s, rt = tier_setup(False)
    for r in range(runs):
        ex, ctx = fresh_exchange(s, rt)
        env = get_shuffle_env(ctx.runtime, ctx.conf)
        # PRODUCTION-default transport geometry (8MB bounce pool, 1MB
        # chunks, conf-registry defaults) over a real TCP loopback —
        # the same wire BENCH_WIRE measures
        server_tp = SocketTransport()
        server = ShuffleSocketServer(server_tp, env.server)
        client_tp = SocketTransport()
        client_tp.set_peers({"peer": ("127.0.0.1", server.address[1])})
        client = client_tp.make_client("peer")
        try:
            t0 = time.time()
            h = ex.materialize(ctx)
            parts = {}
            for p in range(h.num_partitions):
                got = []
                for block in env.catalog.blocks_for_reduce(h.sid, p):
                    for bid in env.catalog.buffers_for(block):
                        leaves, meta = client.fetch_buffer(bid)
                        batch = host_to_batch(list(leaves), meta)
                        jax.block_until_ready(
                            [c.data for c in batch.columns])
                        got.append(batch)
                parts[p] = got
            sock_t.append(time.time() - t0)
            if r == runs - 1:
                sock_bytes = h.stats().total_bytes
                sock_sums = checksum_parts(
                    {p: [b.to_arrow() for b in subs]
                     for p, subs in parts.items()})
            h.release()
        finally:
            server.close()
            client_tp.shutdown()
            server_tp.shutdown()
    sock_drain = min(drain_seconds(s, rt) for _ in range(2))

    assert logical_bytes == sock_bytes, (logical_bytes, sock_bytes)
    mismatches = 0 if mesh_sums == sock_sums else 1

    # ---- q1/join-slice parity across tiers ----------------------------
    q1_match = join_match = None
    if parity:
        def q1_like(s):
            from spark_rapids_tpu.plan.logical import functions as F
            n = 20000
            df = s.from_pydict(
                {"k": [i % 5 for i in range(n)],
                 "q": [float(i % 50) for i in range(n)],
                 "p": [float(i % 90) * 0.01 for i in range(n)]})
            return (df.repartition(4, col("k"))
                    .filter(col("p") < 0.7)
                    .group_by("k")
                    .agg(F.sum(col("q")).alias("sq"),
                         F.count(col("q")).alias("c"))
                    .order_by(col("k")))

        def join_slice(s):
            from spark_rapids_tpu.plan.logical import functions as F
            n = 12000
            left = s.from_pydict(
                {"k": [i % 40 for i in range(n)],
                 "v": [float(i % 17) for i in range(n)]})
            dim = s.from_pydict(
                {"k": list(range(40)),
                 "name": [f"g{i}" for i in range(40)]})
            return (left.repartition(4)
                    .join(dim, on="k")
                    .group_by("name")
                    .agg(F.sum(col("v")).alias("sv"))
                    .order_by(col("name")))

        def across_tiers(q):
            base = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
            mesh_conf = {**base, "spark.rapids.sql.tpu.mesh.devices":
                         str(n_devices)}
            got = [q(TpuSession(c)).collect() for c in (
                mesh_conf,
                {**mesh_conf,
                 "spark.rapids.sql.tpu.shuffle.ici.enabled": "false"},
                base)]
            return got[0] == got[1] == got[2]

        q1_match = across_tiers(q1_like)
        join_match = across_tiers(join_slice)

    # effective EXCHANGE throughput: both tiers consume the identical
    # child (drained from the same warm scan cache) — subtracting the
    # separately-measured drain isolates what the tiers actually differ
    # on (partition + move + serve).  Raw end-to-end times reported too.
    mesh_best = min(mesh_t)
    sock_best = min(sock_t)
    mesh_ex = max(mesh_best - mesh_drain, 1e-6)
    sock_ex = max(sock_best - sock_drain, 1e-6)
    return {"n_devices": n_devices, "rows": rows,
            "logical_mb": round(logical_bytes / 1e6, 2),
            "mesh_s": round(mesh_best, 4),
            "socket_s": round(sock_best, 4),
            "drain_s": round(min(mesh_drain, sock_drain), 4),
            "mesh_exchange_gb_s": round(logical_bytes / mesh_ex / 1e9,
                                        3),
            "socket_exchange_gb_s": round(logical_bytes / sock_ex / 1e9,
                                          3),
            "ratio": round(sock_ex / mesh_ex, 2),
            "ratio_end_to_end": round(sock_best / mesh_best, 2),
            "dispatches_per_exchange_warm": dispatches_warm,
            "compiles_warm_run": compiles_warm,
            "checksum_mismatches": mismatches,
            "q1_match": q1_match, "join_match": join_match}


def multichip_child(n_devices: int) -> None:
    """`bench.py --multichip-child=N`: self-provision N virtual CPU
    devices (device count latches at backend init, hence one process per
    count) and print ONE JSON row."""
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
    force_cpu_backend(n_devices=n_devices)
    import jax
    os.environ.setdefault("JAX_ENABLE_X64", "1")
    jax.config.update("jax_enable_x64", True)
    # parity queries are compile-heavy: run them once, in the widest
    # (8-device) child — the ratio rows stay cheap for every count
    row = multichip_measure(n_devices, parity=(n_devices == 8))
    print(json.dumps(row), flush=True)


def multichip_microbench(write_artifact: bool = True) -> dict:
    """Per-device-count exchange tiers (also `python bench.py
    --multichip`): one forced-CPU child per device count in
    MULTICHIP_DEVICE_COUNTS (XLA's host-platform device count latches at
    backend init), rows collected into MULTICHIP.json — REAL rows
    (throughput, ratio, warm dispatch/compile counts, checksum parity)
    replacing the ok-flag-only MULTICHIP_r*.json records."""
    rows = []
    for n in MULTICHIP_DEVICE_COUNTS:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # the child sets its own device count
        try:
            out = subprocess.run(
                [sys.executable, "-u", os.path.abspath(__file__),
                 f"--multichip-child={n}"],
                capture_output=True, text=True, timeout=280, env=env)
            line = out.stdout.strip().splitlines()[-1] if \
                out.stdout.strip() else ""
            rows.append(json.loads(line) if line.startswith("{") else
                        {"n_devices": n, "error":
                         (out.stderr or "no output")[-300:]})
        except (subprocess.TimeoutExpired, ValueError) as e:
            rows.append({"n_devices": n, "error": repr(e)[:300]})
    ok_rows = [r for r in rows if "error" not in r]
    result = {
        "rows": rows,
        "ratio_max_devices": (ok_rows[-1]["ratio"] if ok_rows else None),
        "checksum_mismatches": sum(r.get("checksum_mismatches", 0)
                                   for r in ok_rows),
        "q1_match": next((r["q1_match"] for r in ok_rows
                          if r.get("q1_match") is not None), None),
        "join_match": next((r["join_match"] for r in ok_rows
                            if r.get("join_match") is not None), None),
        "ok": bool(ok_rows) and all(
            r.get("checksum_mismatches", 1) == 0 for r in ok_rows),
    }
    if write_artifact:
        artifact = {
            "metric": "mesh_vs_socket_exchange_throughput",
            "value": result["ratio_max_devices"],
            "unit": "x(socket->mesh)",
            "note": "generic hash exchange per device count: mesh tier "
                    "= jitted shard_map all-to-all (data stays in "
                    "device memory), socket tier = device catalog "
                    "write + real TCP-loopback serve + H2D re-adopt "
                    "(the production cross-host path).  Throughput is "
                    "over LOGICAL (map-statistics) bytes; "
                    "dispatches/compiles are the warm run's "
                    "compiled-program counts",
            **result,
        }
        try:
            with open(os.path.join(REPO, "MULTICHIP.json"), "w") as f:
                json.dump(artifact, f, indent=1)
        except OSError:
            pass
    return result


def child_main(mode: str) -> None:
    _DEADLINE[0] = time.time() + float(
        os.environ.get("BENCH_CHILD_DEADLINE_S", "1e9"))
    sys.path.insert(0, REPO)
    t0 = time.time()
    if mode in ("cpu", "oracle"):
        # env JAX_PLATFORMS=cpu alone is NOT sufficient: the container's
        # sitecustomize imports jax and registers the axon plugin in every
        # interpreter, and backend enumeration can block on the machine-wide
        # TPU lease — the factories must be dropped before first use
        from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
        force_cpu_backend()
    import jax
    # persistent compilation cache: the q1/q5 whole-stage programs cost
    # 40s+ to compile on the tunneled chip; caching them on disk makes
    # every bench rerun (including the driver's end-of-round run) start
    # from warm compiles.  Same idempotent helper the engine and the
    # executor worker bootstrap use (utils/compile_cache.py), forced on
    # because the bench wants warm compiles on every backend it measures.
    from spark_rapids_tpu.utils.compile_cache import enable_compilation_cache
    enable_compilation_cache(
        os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_bench_cache"),
        force=True)
    try:
        platform = jax.devices()[0].platform
    except Exception as e:
        # round-4 postmortem: this exact failure (axon backend UNAVAILABLE)
        # escaped as a traceback on the SHARED stderr and, because TPU
        # children are abandoned, landed in the driver's combined capture
        # AFTER the parent's headline line — erasing the round artifact.
        # A backend that cannot init is a reportable stage, not a crash.
        emit("backend_error", error=repr(e)[:300], t=time.time() - t0)
        sys.exit(0)
    emit("backend", platform=platform, t=time.time() - t0)
    checkpoint("backend")

    t0 = time.time()
    table = make_lineitem(N_ROWS)
    emit("datagen", rows=N_ROWS, t=time.time() - t0)
    checkpoint("datagen")

    from spark_rapids_tpu.engine import TpuSession
    if mode == "oracle":
        conf = {"spark.rapids.sql.enabled": "false"}
    else:
        # variableFloatAgg: sums/avgs over doubles; without it the aggregate
        # falls back to CPU and the bench degenerates into a D2H-bound CPU
        # query (round-2 postmortem).  The reference enables the same conf
        # for its TPC-H/TPCxBB runs (docs/configs.md variableFloatAgg).
        conf = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
    session = TpuSession(conf)
    li = session.from_arrow(table)

    # the oracle has no compile/H2D warmup effects, so one run suffices
    # (the parent takes min over warmup+runs for the CPU child); device
    # children take 3 steady runs — the FIRST post-warmup run still
    # absorbs async tails (r4: tpcds_q5 runs [1.24s, 0.26s]), so min()
    # over 3 is the honest steady state
    heavy_runs = 1 if mode == "oracle" else 3
    # headline first: if the deadline lands mid-suite, Q6-cached survives
    timed("q6", lambda: checksum(q6(li).collect()),
          N_RUNS if mode != "oracle" else 1)
    timed("q1", lambda: checksum(q1(li).collect()), heavy_runs)

    try:
        transfer_microbench()
    except Exception as e:  # microbench must never sink the bench
        emit("transfer", error=repr(e)[:200])
    checkpoint("transfer")

    # scan-included Q6: parquet from disk through the device decode path
    # (file scans are NOT in the memory scan cache — every run re-decodes)
    pq_dir = os.path.join("/tmp", f"bench_lineitem_{N_ROWS}")
    pq_path = os.path.join(pq_dir, "lineitem.parquet")
    if not os.path.exists(pq_path):
        import pyarrow.parquet as papq
        os.makedirs(pq_dir, exist_ok=True)
        # per-pid temp name: the oracle and device children run
        # CONCURRENTLY and may both lose the exists() race; the atomic
        # replace makes last-writer-wins safe
        tmp = f"{pq_path}.{os.getpid()}.tmp"
        papq.write_table(table, tmp, compression="snappy")
        os.replace(tmp, pq_path)
    emit("parquet_ready", path=pq_path,
         bytes=os.path.getsize(pq_path))
    checkpoint("parquet_ready")
    timed("q6_scan",
          lambda: checksum(q6(session.read.parquet(pq_path)).collect()),
          heavy_runs)

    # TPC-DS q5 (3-channel union + dim joins + ROLLUP) — BASELINE config 3
    t0 = time.time()
    from benchmarks.tpcds.datagen import load_tables as ds_load
    from benchmarks.tpcds.queries import q5 as ds_q5
    ds = ds_load(session, sf=TPCDS_SF)
    emit("tpcds_datagen", sf=TPCDS_SF, t=time.time() - t0)
    checkpoint("tpcds_datagen")
    timed("tpcds_q5", lambda: checksum(ds_q5(ds).collect()), heavy_runs)

    # the reference's HEADLINE query: TPCxBB-like Q5 (19.8x on the chart,
    # reference README.md:7-15) — clickstream x item join + per-user
    # conditional-sum pivot + demographics join
    t0 = time.time()
    from benchmarks.tpcxbb.datagen import load_tables as xbb_load
    from benchmarks.tpcxbb.queries import q5 as xbb_q5
    xbb = xbb_load(session, sf=TPCDS_SF)
    emit("tpcxbb_datagen", sf=TPCDS_SF, t=time.time() - t0)
    checkpoint("tpcxbb_datagen")
    timed("tpcxbb_q5", lambda: checksum(xbb_q5(xbb).collect()), heavy_runs)

    # SF1 scale tier (opt-in: BENCH_SF1=1): ~2.88M-row store_sales
    # (1.2GB of tables), streamed through the multi-batch path.  The
    # capture loop enables this so lease windows record on-chip SF1
    # numbers (VERDICT r4 item 6; the reference's chart is SF10k on a
    # cluster, README.md:7-15 — this is the one-chip scale point).
    if os.environ.get("BENCH_SF1") == "1":
        from benchmarks.tpcds.queries import QUERIES as DSQ
        t0 = time.time()
        ds1 = ds_load(session, sf=1.0)
        emit("tpcds_sf1_datagen", t=time.time() - t0)
        checkpoint("tpcds_sf1_datagen")
        for name, qn in (("sf1_q5", 5), ("sf1_q3", 3), ("sf1_q7", 7),
                         ("sf1_q19", 19)):
            timed(name,
                  lambda qn=qn: checksum(DSQ[qn](ds1).collect()),
                  heavy_runs)

    # observability rollup: the session-cumulative retry/spill/fallback/
    # wire counters ride along in the BENCH_* artifacts so a perf number
    # is never read without knowing how hard the memory/retry machinery
    # worked to produce it (docs/monitoring.md)
    try:
        from spark_rapids_tpu.metrics.export import session_observability
        emit("observability", **session_observability(session))
    except Exception as e:  # the rollup must never sink the bench
        emit("observability", error=repr(e)[:200])
    # telemetry-plane rollup (ISSUE 17): flight-recorder/sampler state
    # of the driving process, so an artifact records whether the
    # always-on plane was live for the numbers above (its overhead is
    # gated separately: scripts/obs_overhead.py -> BENCH_OBS.json)
    try:
        from spark_rapids_tpu.metrics.ring import get_telemetry
        t = get_telemetry()
        if t is None:
            emit("telemetry", enabled=False)
        else:
            emit("telemetry", enabled=True, role=t.role,
                 sampler_ticks=t.sampler.ticks,
                 series=sorted(t.sampler.latest()),
                 **t.recorder.stats())
    except Exception as e:
        emit("telemetry", error=repr(e)[:200])
    # adaptive-execution rollup (PR-3): coalesce/skew/strategy-change
    # counts and stage re-plan latency next to the observability block,
    # so a perf number is never read without knowing whether AQE rewrote
    # the plan that produced it
    try:
        from spark_rapids_tpu.metrics.export import session_adaptive
        emit("adaptive", **session_adaptive(session))
    except Exception as e:
        emit("adaptive", error=repr(e)[:200])
    # integrity rollup (ISSUE 4): checksum on/off wire-throughput delta
    # plus the session's corruption-recovery counters, so the BENCH_*
    # artifacts track the verification tax and any recoveries that fired
    try:
        emit("integrity", **integrity_microbench(session))
    except Exception as e:
        emit("integrity", error=repr(e)[:200])
    # compression rollup (ISSUE 5): spill write/read delta per codec
    # (codec none == the pre-compression raw path; the deltas say what a
    # codec costs/buys at the spill tier on THIS host), next to the wire
    # per-codec numbers BENCH_WIRE.json carries
    try:
        emit("compress", **compress_microbench())
    except Exception as e:
        emit("compress", error=repr(e)[:200])
    # fusion rollup (ISSUE 6): per-query jit-compile count, per-batch
    # dispatch count and warmup seconds with whole-stage fusion on vs
    # off, so the >= 2x compile-count acceptance is a measured artifact
    try:
        emit("fusion", **fusion_microbench())
    except Exception as e:
        emit("fusion", error=repr(e)[:200])
    # tracing rollup (ISSUE 7): q1 with distributed tracing + journal on
    # vs off (<5% acceptance gate) and the heartbeat rpc round-trip cost,
    # so the observability tax is a measured BENCH_* artifact
    try:
        emit("tracing", **tracing_microbench())
    except Exception as e:
        emit("tracing", error=repr(e)[:200])
    # pressure rollup (ISSUE 8): the memory-budget sweep at 25/50/75/100%
    # of measured working set with ledger-derived breakdowns, plus the
    # ledger's own <5% overhead gate; also writes BENCH_PRESSURE.json
    try:
        emit("pressure", **pressure_microbench())
    except Exception as e:
        emit("pressure", error=repr(e)[:200])
    # profile rollup (ISSUE 13): per-operator roofline ledgers for the
    # representative query set (declared bytes/flops joined against
    # measured spans, bottleneck resource per plan node), serving SLO
    # phase histograms, and the profiler's own <5% overhead gate; also
    # writes BENCH_PROFILE.json — the capture scripts/
    # profile_regression.py diffs against the checked-in baseline
    try:
        emit("profile", **profile_microbench())
    except Exception as e:
        emit("profile", error=repr(e)[:200])
    # serving rollup (ISSUE 10): parameterized plan-cache compile
    # reduction on a q1-shaped literal variant, and the mixed-workload
    # scheduler sweep at concurrency 1/4/16 (throughput, p95 latency and
    # queue time, OOM-injection bit-for-bit check); also writes
    # BENCH_SERVE.json
    try:
        emit("serve", **serve_microbench())
    except Exception as e:
        emit("serve", error=repr(e)[:200])
    # streaming rollup (ISSUE 20): incremental micro-batch epochs/s per
    # batch size, p50/p95 epoch latency, the zero-warm-compile gate
    # (every epoch after the first replays compiled stages), and the
    # incremental-vs-full-requery speedup with a batch-oracle checksum
    # cross-check; also writes BENCH_STREAM.json
    try:
        emit("streaming", **streaming_microbench())
    except Exception as e:
        emit("streaming", error=repr(e)[:200])
    # chaos rollup (ISSUE 15): recovery latency at 0/1/2 injected
    # mid-task kills on a 3-worker ProcCluster plus a measured
    # speculation win on an injected-delay straggler, every round
    # verified bit-for-bit; also writes BENCH_CHAOS.json.  CPU worker
    # subprocesses only — a TPU-mode child never risks the lease here.
    # The stage costs ~60s of cluster spawns; when the deadline cannot
    # afford it, it rides the standing artifact (refresh standalone:
    # `python bench.py --chaos`)
    try:
        if _DEADLINE[0] - time.time() >= 90:
            emit("chaos", **chaos_microbench())
        else:
            with open(os.path.join(REPO, "BENCH_CHAOS.json")) as f:
                art = json.load(f)
            emit("chaos", from_artifact=True, ok=art.get("ok"),
                 clean_s=art.get("clean_s"),
                 kill_rounds=art.get("kill_rounds"),
                 speculation=art.get("speculation"))
    except Exception as e:
        emit("chaos", error=repr(e)[:200])
    # multichip rollup (ISSUE 14): per-device-count mesh-vs-socket
    # exchange throughput (forced-CPU children, so a TPU-mode run never
    # risks the lease on this stage), warm dispatch/compile counts, and
    # the cross-tier checksum/q1/join parity flags; also writes
    # MULTICHIP.json — real rows where the ok-flag dryrun record was.
    # The sweep spawns one fresh-backend child per device count (~200s):
    # when the bench deadline cannot afford that, the stage rides the
    # standing artifact (refresh standalone: `python bench.py
    # --multichip`) instead of silently vanishing into an abort
    try:
        if _DEADLINE[0] - time.time() >= 260:
            emit("multichip", **multichip_microbench())
        else:
            with open(os.path.join(REPO, "MULTICHIP.json")) as f:
                art = json.load(f)
            emit("multichip", from_artifact=True,
                 recorded_note=art.get("note"),
                 rows=art.get("rows"),
                 ratio_max_devices=art.get("ratio_max_devices"),
                 checksum_mismatches=art.get("checksum_mismatches"),
                 q1_match=art.get("q1_match"),
                 join_match=art.get("join_match"),
                 ok=art.get("ok"))
    except Exception as e:
        emit("multichip", error=repr(e)[:200])
    emit("done", t=time.time() - (_DEADLINE[0] - float(
        os.environ.get("BENCH_CHILD_DEADLINE_S", "1e9"))))


# --------------------------------------------------------------------------
# parent: budget-enforced orchestration (never kills a TPU child)
# --------------------------------------------------------------------------

class StageReader:
    """Reads JSON stage lines from a child under per-read budgets."""

    def __init__(self, label: str, mode: str, deadline_s: float):
        self.label = label
        self.tpu = mode == "tpu"
        env = dict(os.environ)
        if mode in ("cpu", "oracle"):
            env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_CHILD_DEADLINE_S"] = str(max(deadline_s, 5.0))
        # per-child stderr LOG FILE, never the shared stderr: an abandoned
        # TPU child that dies after the parent exits must not be able to
        # append anything to the driver's combined capture (round-4
        # postmortem: a late child traceback after the headline line made
        # the artifact unparseable)
        self._errlog = open(f"/tmp/bench_{label}.stderr.log", "a")
        self.proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__),
             f"--child={mode}"],
            stdout=subprocess.PIPE, stderr=self._errlog, text=True, env=env,
            # own session: a driver-level process-group SIGKILL must not
            # hit a TPU-attached child (lease poisoning, round-3 memory)
            start_new_session=self.tpu)
        self._lines: list = []
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._lock = threading.Condition()
        self._eof = False
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._lock:
                self._lines.append(line)
                self._lock.notify()
        with self._lock:
            self._eof = True
            self._lock.notify()

    def next_stage(self, budget_s: float):
        """Next parsed stage line, or None on timeout/eof.  On timeout the
        child is ABANDONED (TPU mode) or killed (CPU mode) — never a signal
        at a TPU-attached process."""
        deadline = time.time() + budget_s
        while True:
            with self._lock:
                while not self._lines:
                    if self._eof:
                        return None
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        if self.tpu:
                            log(f"{self.label}: budget exceeded "
                                f"({budget_s:.0f}s) — ABANDONING child "
                                f"(it exits on its own deadline)")
                        else:
                            log(f"{self.label}: budget exceeded "
                                f"({budget_s:.0f}s) — killing CPU child")
                            self.proc.kill()
                        return None
                    self._lock.wait(timeout=min(remaining, 5))
                line = self._lines.pop(0)
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = None
            if not isinstance(rec, dict) or "stage" not in rec:
                log(f"{self.label}: ignoring non-stage stdout: "
                    f"{line.strip()[:120]}")
                continue
            log(f"{self.label}: {rec}")
            _write_partial(self.label, rec)
            return rec

    def close(self):
        if self.tpu:
            return  # abandoned, exits on its own clean deadline
        try:
            self.proc.kill()
        except OSError:
            pass
        try:
            self._errlog.close()
        except OSError:
            pass


_PARTIAL: dict = {"stages": []}


def _write_partial(label: str, rec: dict) -> None:
    _PARTIAL["stages"].append({"child": label, **rec})
    try:
        with open(os.path.join(REPO, "BENCH_partial.json"), "w") as f:
            json.dump(_PARTIAL, f, indent=1)
    except OSError:
        pass


def collect(r: "StageReader", end_at: float,
            reserve_s: float = 0.0) -> dict:
    """Read a child's stages until eof/abort/deadline.  Returns
    {platform, runs: {q: [t..]}, warmup: {q: t}, values: {q: v},
    transfer: {...}}.  reserve_s caps the FIRST read (backend init) so an
    unavailable chip is abandoned with enough budget left for a fallback
    child."""
    out = {"platform": None, "runs": {}, "warmup": {}, "values": {},
           "transfer": None, "aborted": False, "backend_error": None,
           "observability": None, "adaptive": None, "integrity": None,
           "compress": None, "fusion": None, "tracing": None,
           "pressure": None, "serve": None, "streaming": None,
           "profile": None, "chaos": None, "multichip": None}
    first = True
    try:
        while True:
            budget = min(TPU_PROBE_S if first else 240.0,
                         end_at - time.time())
            if first and reserve_s:
                budget = min(budget,
                             max(30.0, end_at - reserve_s - time.time()))
            if budget <= 0:
                break
            rec = r.next_stage(budget)
            if rec is None:
                break
            first = False
            st = rec.get("stage")
            if st == "backend_error":
                out["backend_error"] = rec.get("error")
                break
            if st == "backend":
                out["platform"] = rec.get("platform")
            elif st == "warmup":
                out["warmup"][rec["q"]] = rec["t"]
                out["values"][rec["q"]] = rec.get("value")
            elif st == "run":
                out["runs"].setdefault(rec["q"], []).append(rec["t"])
                out["values"][rec["q"]] = rec.get("value", None)
            elif st == "transfer":
                out["transfer"] = {k: v for k, v in rec.items()
                                   if k != "stage"}
            elif st == "observability":
                out["observability"] = {k: v for k, v in rec.items()
                                        if k != "stage"}
            elif st == "adaptive":
                out["adaptive"] = {k: v for k, v in rec.items()
                                   if k != "stage"}
            elif st == "integrity":
                out["integrity"] = {k: v for k, v in rec.items()
                                    if k != "stage"}
            elif st == "compress":
                out["compress"] = {k: v for k, v in rec.items()
                                   if k != "stage"}
            elif st == "fusion":
                out["fusion"] = {k: v for k, v in rec.items()
                                 if k != "stage"}
            elif st == "tracing":
                out["tracing"] = {k: v for k, v in rec.items()
                                  if k != "stage"}
            elif st == "pressure":
                out["pressure"] = {k: v for k, v in rec.items()
                                   if k != "stage"}
            elif st == "serve":
                out["serve"] = {k: v for k, v in rec.items()
                                if k != "stage"}
            elif st == "streaming":
                out["streaming"] = {k: v for k, v in rec.items()
                                    if k != "stage"}
            elif st == "profile":
                out["profile"] = {k: v for k, v in rec.items()
                                  if k != "stage"}
            elif st == "chaos":
                out["chaos"] = {k: v for k, v in rec.items()
                                if k != "stage"}
            elif st == "multichip":
                out["multichip"] = {k: v for k, v in rec.items()
                                    if k != "stage"}
            elif st == "abort":
                out["aborted"] = True
                break
            elif st == "done":
                break
        return out
    finally:
        r.close()


def main():
    if len(sys.argv) > 1 and sys.argv[1].startswith("--child="):
        child_main(sys.argv[1].split("=", 1)[1])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--pressure":
        # standalone memory-budget sweep: regenerate BENCH_PRESSURE.json
        # without the full suite (runs on whatever backend is available;
        # set JAX_PLATFORMS=cpu to keep it off a leased chip)
        print(json.dumps(pressure_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--profile":
        # standalone roofline-attribution capture: regenerate
        # BENCH_PROFILE.json (per-operator ledgers + SLO histograms +
        # profiler overhead gate) without the full suite
        print(json.dumps(profile_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--serve":
        # standalone serving-tier sweep: regenerate BENCH_SERVE.json
        # (plan-cache compile reduction + concurrency 1/4/16 mixed
        # workload) without the full suite
        print(json.dumps(serve_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--streaming":
        # standalone streaming micro-batch sweep: regenerate
        # BENCH_STREAM.json (epochs/s per batch size, p50/p95 epoch
        # latency, zero-warm-compile gate, incremental-vs-full-requery
        # speedup + checksum) without the full suite
        print(json.dumps(streaming_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        # standalone chaos/recovery sweep: regenerate BENCH_CHAOS.json
        # (kill-recovery latency at 0/1/2 kills + the speculation win)
        # without the full suite; worker subprocesses are forced-CPU
        print(json.dumps(chaos_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1].startswith("--multichip-child="):
        multichip_child(int(sys.argv[1].split("=", 1)[1]))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        # standalone per-device-count mesh-vs-socket exchange sweep:
        # regenerate MULTICHIP.json (real rows) without the full suite
        print(json.dumps(multichip_microbench(), indent=1))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--fusion":
        # standalone whole-stage fusion/donation sweep (CPU backend:
        # the stage is a CPU child in the full run too) — compile and
        # dispatch counts plus donated_copies_warm_run per query shape
        from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
        force_cpu_backend()
        print(json.dumps(fusion_microbench(), indent=1))
        return

    # The headline line is emitted UNCONDITIONALLY (round-4 postmortem:
    # parsed=null after a 554-turn round).  Whatever _run() manages — or
    # doesn't — the last stdout act of this process is one JSON line, also
    # mirrored to BENCH_HEADLINE.json.
    result = {"metric": "tpch_q6_like_device_throughput", "value": 0.0,
              "unit": "Mrows/s[none]", "vs_baseline": 0.0}
    try:
        result = _run() or result
    except SystemExit:
        pass
    except BaseException as e:  # noqa: BLE001 — report, never crash out
        import traceback
        traceback.print_exc(file=sys.stderr)
        result.setdefault("extra", {})["fatal"] = repr(e)[:500]
    finally:
        line = json.dumps(result)
        try:
            with open(os.path.join(REPO, "BENCH_HEADLINE.json"), "w") as f:
                f.write(line + "\n")
        except OSError:
            pass
        print(line, flush=True)


def _run():
    end_at = T0 + GLOBAL_BUDGET_S
    want_tpu = os.environ.get("JAX_PLATFORMS", "axon") != "cpu"

    # 1. start the TPU child FIRST: it spends its opening minutes blocked in
    # backend init (tunnel lease), which overlaps for free with the oracle;
    # its stage lines buffer in the reader thread until we consume them
    tpu_reader = None
    if want_tpu:
        tpu_reader = StageReader("device", "tpu",
                                 end_at - time.time() - 5)

    # 2. CPU oracle (forced-CPU child, drops the axon plugin factories, so
    # it cannot block on the device lease).  The oracle is deterministic
    # in (N_ROWS, TPCDS_SF); BENCH_ORACLE_CACHE=1 lets capture loops that
    # rerun the bench for TPU lease windows skip the ~3min oracle replay.
    cache_path = f"/tmp/bench_oracle_{N_ROWS}_{TPCDS_SF}.json"
    cpu = None
    if os.environ.get("BENCH_ORACLE_CACHE") == "1" \
            and os.path.exists(cache_path):
        try:
            with open(cache_path) as f:
                cpu = json.load(f)
            log(f"oracle loaded from {cache_path}")
        except (OSError, ValueError):
            cpu = None
    if cpu is None or not cpu.get("runs", {}).get("q6"):
        # SF1 adds ~40s datagen + 4 scale queries to the oracle's budget
        oracle_cap = 600 if os.environ.get("BENCH_SF1") == "1" else 210
        cpu = collect(StageReader("cpu-oracle", "oracle",
                                  min(end_at, T0 + oracle_cap)
                                  - time.time()),
                      min(end_at, T0 + oracle_cap))
        if not cpu["runs"].get("q6") and not cpu["warmup"].get("q6"):
            log("FATAL: CPU oracle produced no q6 runs")
            return {"metric": "tpch_q6_like_device_throughput",
                    "value": 0.0, "unit": "Mrows/s[none]",
                    "vs_baseline": 0.0,
                    "extra": {"fatal": "cpu oracle produced no q6 runs"}}
        # the oracle has no warmup effects: fold warmup times in as runs
        for q, t in cpu["warmup"].items():
            cpu["runs"].setdefault(q, []).append(t)
        if os.environ.get("BENCH_ORACLE_CACHE") == "1" \
                and len(cpu["runs"]) >= 5 and not cpu.get("aborted"):
            try:
                with open(cache_path, "w") as f:
                    json.dump(cpu, f)
            except OSError:
                pass

    # 3. consume the device child (already running); if the chip reported
    # UNAVAILABLE quickly, the lease may free up — retry while the budget
    # still leaves room for the CPU-engine fallback child
    dev = (collect(tpu_reader, end_at, reserve_s=130.0)
           if tpu_reader else {"runs": {}, "warmup": {}})
    while (want_tpu and not dev["runs"].get("q6")
           and not dev.get("warmup", {}).get("q6")
           and dev.get("backend_error")
           and end_at - time.time() > 200.0):
        log(f"TPU backend error ({dev['backend_error'][:80]}); "
            f"retrying in 20s")
        time.sleep(20)
        dev = collect(StageReader("device", "tpu",
                                  end_at - time.time() - 5),
                      end_at, reserve_s=130.0)
    unit_note = ""
    if not dev["runs"].get("q6") and dev.get("warmup", {}).get("q6"):
        # deadline landed between warmup and run 1: the warmup time
        # (compile+H2D inclusive) is still device evidence — report it
        # with an explicit unit marker instead of discarding it
        log("device runs missing; falling back to warmup time")
        dev["runs"]["q6"] = [dev["warmup"]["q6"]]
        unit_note = ":warmup-only"
    if not dev["runs"].get("q6"):
        if want_tpu:
            log("TPU unavailable; measuring the device engine on the CPU "
                "backend instead")
        dev = collect(StageReader("device-cpu", "cpu",
                                  end_at - time.time()), end_at)
    if not dev["runs"].get("q6"):
        log("device child produced nothing; reporting CPU numbers")
        dev = cpu

    platform = (dev["platform"] or "unknown") + unit_note
    per_query = {}
    mismatch = False
    for q in sorted(set(dev["runs"]) | set(cpu["runs"])):
        d = min(dev["runs"][q]) if dev["runs"].get(q) else None
        c = min(cpu["runs"][q]) if cpu["runs"].get(q) else None
        entry = {"dev_s": round(d, 4) if d else None,
                 "cpu_s": round(c, 4) if c else None,
                 "vs_oracle": round(c / d, 3) if d and c else None,
                 "warmup_s": round(dev["warmup"].get(q, 0), 2)}
        dv, cv = dev["values"].get(q), cpu["values"].get(q)
        if dv is not None and cv is not None:
            entry["match"] = bool(abs(dv - cv) <= 1e-4 * max(1.0, abs(cv)))
            if not entry["match"]:
                mismatch = True
                log(f"ORACLE MISMATCH {q}: dev={dv} cpu={cv}")
        per_query[q] = entry

    q6_t = min(dev["runs"]["q6"])
    cpu_t = min(cpu["runs"]["q6"])
    vs = cpu_t / q6_t
    if mismatch:
        platform += ":MISMATCH"
    # Q6 touches 4 float64/int64 columns -> 32 B/row per pass
    eff_gb_s = N_ROWS * 32 / q6_t / 1e9
    extra = {
        "per_query": per_query,
        "transfer": dev.get("transfer"),
        "observability": dev.get("observability"),
        "adaptive": dev.get("adaptive"),
        "integrity": dev.get("integrity"),
        "compress": dev.get("compress"),
        "fusion": dev.get("fusion"),
        "tracing": dev.get("tracing"),
        "pressure": dev.get("pressure"),
        "serve": dev.get("serve"),
        "streaming": dev.get("streaming"),
        "profile": dev.get("profile"),
        "chaos": dev.get("chaos"),
        "multichip": dev.get("multichip"),
        "q6_effective_gb_s": round(eff_gb_s, 2),
        "hbm_roofline_note": "v5e HBM ~819 GB/s; q6 reads 32 B/row",
        "vs_ref_headline": round(vs / 19.8, 4),
        "tpcds_sf": TPCDS_SF,
        "aborted": dev.get("aborted", False),
    }
    result = {
        "metric": f"tpch_q6_like_{N_ROWS // 1_000_000}M_rows_device_throughput",
        "value": round(N_ROWS / q6_t / 1e6, 3),
        "unit": f"Mrows/s[{platform}]",
        "vs_baseline": round(vs, 3),
        "extra": extra,
    }
    onchip_path = os.path.join(REPO, "BENCH_ONCHIP.json")
    if platform.startswith("tpu") and not mismatch:
        # persist real-chip evidence: the lease can be down for hours
        # (three rounds lost to it), so a later fallback run must not be
        # the only record.  MERGE with the previous on-chip record: a
        # partial suite (deadline mid-run) must never erase queries an
        # earlier lease window did capture — stale entries are marked.
        now = int(time.time())
        for e in extra["per_query"].values():
            if e.get("dev_s") is not None:
                e["recorded_unix"] = now
        try:
            with open(onchip_path) as f:
                oldpq = json.load(f).get("extra", {}).get("per_query", {})
            for q, e in oldpq.items():
                cur = extra["per_query"].get(q, {})
                if cur.get("dev_s") is None and e.get("dev_s") is not None:
                    # carry the earlier window's number (with its own
                    # recorded_unix) so partial windows accumulate
                    extra["per_query"][q] = {**e, "stale": True}
        except (OSError, ValueError):
            pass
        try:
            with open(onchip_path, "w") as f:
                json.dump({"recorded_unix": int(time.time()), **result}, f,
                          indent=1)
        except OSError:
            pass
    elif os.path.exists(onchip_path):
        # chip unavailable THIS run: point at the last real on-chip
        # record (clearly labeled; the headline metric stays this run's)
        try:
            with open(onchip_path) as f:
                extra["last_onchip"] = json.load(f)
        except (OSError, ValueError):
            pass
    try:
        with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
            json.dump({"dev": dev, "cpu": cpu, "extra": extra}, f, indent=1)
    except OSError:
        pass
    return result


if __name__ == "__main__":
    main()
