"""Flagship benchmark: TPC-H Q6 shape on the device engine vs the CPU path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value       = device-engine throughput (million rows/sec through the
                filter->project->aggregate pipeline, steady-state)
  vs_baseline = speedup over this framework's own CPU (pyarrow) executors,
                the stand-in for the reference's CPU-Spark-vs-GPU oracle
                (reference headline: TPCxBB-like Q5 19.8x, README.md:7-15).
"""
from __future__ import annotations

import json
import time

import numpy as np

N_ROWS = 6_000_000  # ~SF1 lineitem row count


def make_lineitem(n: int):
    import pyarrow as pa
    rng = np.random.RandomState(42)
    price = rng.uniform(900.0, 105000.0, n)
    discount = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    quantity = rng.randint(1, 51, n).astype(np.int64)
    # days since epoch across 1992-1998 (TPC-H date range)
    shipdate = rng.randint(8035, 10592, n).astype(np.int64)
    return pa.table({
        "l_extendedprice": price,
        "l_discount": discount,
        "l_quantity": quantity,
        "l_shipdate": shipdate,
    })


def q6(session, table):
    from spark_rapids_tpu.plan.logical import col, functions as F
    df = session.from_arrow(table)
    # 1994-01-01 = day 8766, 1995-01-01 = day 9131
    return (df.filter((col("l_shipdate") >= 8766)
                      & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= 0.05)
                      & (col("l_discount") <= 0.07)
                      & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def timed_run(session, table):
    """One full run: plan + execute + materialize.  Kernels compiled on a
    previous run are reused via the process-wide kernel cache."""
    t0 = time.perf_counter()
    rows = q6(session, table).collect()
    return time.perf_counter() - t0, rows


def main():
    from spark_rapids_tpu.engine import TpuSession
    table = make_lineitem(N_ROWS)

    tpu = TpuSession()
    timed_run(tpu, table)  # warmup: compile + caches
    tpu_runs = [timed_run(tpu, table) for _ in range(3)]
    tpu_t = min(t for t, _ in tpu_runs)
    tpu_rows = tpu_runs[-1][1]

    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    cpu_t, cpu_rows = timed_run(cpu, table)

    assert abs(tpu_rows[0][0] - cpu_rows[0][0]) < 1e-4 * abs(cpu_rows[0][0]), \
        (tpu_rows, cpu_rows)

    mrows_s = N_ROWS / tpu_t / 1e6
    print(json.dumps({
        "metric": "tpch_q6_like_6M_rows_device_throughput",
        "value": round(mrows_s, 3),
        "unit": "Mrows/s",
        "vs_baseline": round(cpu_t / tpu_t, 3),
    }))


if __name__ == "__main__":
    main()
