"""Flagship benchmark: TPC-H Q6 shape on the device engine vs the CPU path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value       = device-engine steady-state throughput (million rows/sec
                through the filter->project->aggregate pipeline, over
                device-resident data — the scan cache keeps the table in
                HBM across runs, the TPU-native analogue of Spark's storage
                layer keeping hot tables in cluster memory)
  vs_baseline = speedup over this framework's own CPU (pyarrow) executors,
                the stand-in for the reference's CPU-Spark-vs-GPU oracle
                (reference headline: TPCxBB-like Q5 19.8x, README.md:7-15).

Robustness (round-2 postmortem: BENCH_r02 rc=124 — run 1 hung on the
tunneled device and the buffered result died with the process):
  * ALL device work runs in a CHILD process that streams one JSON line per
    completed stage; the parent enforces a budget per stage and SIGKILLs a
    hung child — evidence gathered so far survives;
  * the parent mirrors every stage into BENCH_partial.json as it arrives;
  * the CPU oracle runs first in its own forced-CPU child, so a device
    hang can never erase the baseline;
  * if the device child dies with zero completed runs, the CPU numbers are
    reported (unit carries the platform) instead of nothing.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

N_ROWS = int(os.environ.get("BENCH_ROWS", 6_000_000))  # ~SF1 lineitem
# Budgets are sized so the WORST chain (probe succeeds late + one later
# stage hangs at budget) still prints the final JSON line inside ~430s —
# the driver's own benchmark timeout killed rounds 1 and 2 at ~450s and a
# driver kill loses the line (BENCH_partial.json survives either way).
STAGE_BUDGET = {  # seconds, per stage, enforced by the parent
    "backend": int(os.environ.get("BENCH_TPU_PROBE_S", "240")),
    "datagen": 60,
    "warmup": 150,
    "run": 60,
}
N_RUNS = 3

T0 = time.time()


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


# --------------------------------------------------------------------------
# child: executes the pipeline on one backend, emits a JSON line per stage
# --------------------------------------------------------------------------

def make_lineitem(n: int):
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(42)
    price = rng.uniform(900.0, 105000.0, n)
    discount = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    quantity = rng.randint(1, 51, n).astype(np.int64)
    # days since epoch across 1992-1998 (TPC-H date range)
    shipdate = rng.randint(8035, 10592, n).astype(np.int64)
    return pa.table({
        "l_extendedprice": price,
        "l_discount": discount,
        "l_quantity": quantity,
        "l_shipdate": shipdate,
    })


def q6(session, table):
    from spark_rapids_tpu.plan.logical import col, functions as F
    df = session.from_arrow(table)
    # 1994-01-01 = day 8766, 1995-01-01 = day 9131
    return (df.filter((col("l_shipdate") >= 8766)
                      & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= 0.05)
                      & (col("l_discount") <= 0.07)
                      & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def child_main(mode: str) -> None:
    def emit(stage: str, **kw):
        print(json.dumps({"stage": stage, **kw}), flush=True)

    t0 = time.time()
    if mode in ("cpu", "oracle"):
        # env JAX_PLATFORMS=cpu alone is NOT sufficient: the container's
        # sitecustomize imports jax and registers the axon plugin in every
        # interpreter, and backend enumeration can block on the machine-wide
        # TPU lease — the factories must be dropped before first use
        from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
        force_cpu_backend()
    import jax
    platform = jax.devices()[0].platform
    emit("backend", platform=platform, t=time.time() - t0)

    t0 = time.time()
    table = make_lineitem(N_ROWS)
    emit("datagen", rows=N_ROWS, t=time.time() - t0)

    from spark_rapids_tpu.engine import TpuSession
    if mode == "oracle":
        conf = {"spark.rapids.sql.enabled": "false"}
    else:
        # variableFloatAgg: Q6's sum() is over doubles; without this the
        # aggregate falls back to CPU (and the bench degenerates into a
        # D2H-bound CPU query).  The reference enables the same conf for
        # its TPC-H/TPCxBB runs (docs/configs.md variableFloatAgg; its
        # default is also off for bit-exact Spark parity).
        conf = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
    session = TpuSession(conf)

    # warmup: compile + H2D (populates the device scan cache + kernel cache)
    t0 = time.time()
    rows = q6(session, table).collect()
    emit("warmup", t=time.time() - t0, value=rows[0][0])

    for i in range(N_RUNS):
        t0 = time.time()
        rows = q6(session, table).collect()
        emit("run", i=i, t=time.time() - t0, value=rows[0][0])


# --------------------------------------------------------------------------
# parent: budget-enforced orchestration
# --------------------------------------------------------------------------

class StageReader:
    """Reads JSON stage lines from a child under per-stage budgets."""

    def __init__(self, label: str, mode: str):
        self.label = label
        env = dict(os.environ)
        if mode == "cpu" or mode == "oracle":
            env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__),
             f"--child={mode}"],
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env)
        self.stages: list = []
        self._lines: list = []
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._lock = threading.Condition()
        self._eof = False
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._lock:
                self._lines.append(line)
                self._lock.notify()
        with self._lock:
            self._eof = True
            self._lock.notify()

    def next_stage(self, budget_s: float):
        """Next parsed stage line, or None on timeout/eof (child killed on
        timeout)."""
        deadline = time.time() + budget_s
        while True:
            with self._lock:
                while not self._lines:
                    if self._eof:
                        return None
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        log(f"{self.label}: stage budget exceeded "
                            f"({budget_s:.0f}s) — killing child")
                        self.proc.kill()
                        return None
                    self._lock.wait(timeout=min(remaining, 5))
                line = self._lines.pop(0)
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = None
            if not isinstance(rec, dict) or "stage" not in rec:
                # stray stdout from a library (plugin banner, warning):
                # skip it, don't treat the child as dead
                log(f"{self.label}: ignoring non-stage stdout: "
                    f"{line.strip()[:120]}")
                continue
            self.stages.append(rec)
            log(f"{self.label}: {rec}")
            _write_partial(self.label, rec)
            return rec

    def close(self):
        try:
            self.proc.kill()
        except OSError:
            pass


_PARTIAL: dict = {"stages": []}


def _write_partial(label: str, rec: dict) -> None:
    _PARTIAL["stages"].append({"child": label, **rec})
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_partial.json"), "w") as f:
            json.dump(_PARTIAL, f, indent=1)
    except OSError:
        pass


def drive(label: str, mode: str) -> dict:
    """Run one child through its stages; returns {platform, warmup, runs,
    value}."""
    r = StageReader(label, mode)
    out = {"platform": None, "warmup": None, "runs": [], "value": None}
    try:
        rec = r.next_stage(STAGE_BUDGET["backend"])
        if not rec or rec.get("stage") != "backend":
            return out
        out["platform"] = rec["platform"]
        rec = r.next_stage(STAGE_BUDGET["datagen"])
        if not rec or rec.get("stage") != "datagen":
            return out
        rec = r.next_stage(STAGE_BUDGET["warmup"])
        if not rec or rec.get("stage") != "warmup":
            return out
        out["warmup"] = rec["t"]
        out["value"] = rec.get("value")
        for _ in range(N_RUNS):
            rec = r.next_stage(STAGE_BUDGET["run"])
            if not rec or rec.get("stage") != "run":
                break
            out["runs"].append(rec["t"])
            out["value"] = rec.get("value", out["value"])
        return out
    finally:
        r.close()


def main():
    if len(sys.argv) > 1 and sys.argv[1].startswith("--child="):
        child_main(sys.argv[1].split("=", 1)[1])
        return

    # 1. CPU oracle first: a later device hang cannot erase the baseline
    cpu = drive("cpu-oracle", "oracle")
    if not cpu["runs"]:
        log("FATAL: CPU oracle produced no runs")
        print(json.dumps({"metric": "tpch_q6_like_device_throughput",
                          "value": 0.0, "unit": "Mrows/s[none]",
                          "vs_baseline": 0.0}))
        return
    cpu_t = min(cpu["runs"])
    log(f"cpu oracle steady-state: {cpu_t:.3f}s")

    # 2. device child under per-stage budgets
    want_tpu = os.environ.get("JAX_PLATFORMS", "") not in ("cpu", "")
    dev = drive("device", "tpu" if want_tpu else "cpu")
    unit_note = ""
    if not dev["runs"]:
        if dev["warmup"] is not None:
            # warmup completed but runs hung/died: report warmup time
            # (compile+H2D inclusive) with an explicit unit marker
            dev["runs"] = [dev["warmup"]]
            unit_note = ":warmup-only"
            log("device runs missing; falling back to warmup time")
        elif want_tpu:
            # chip unavailable (lease outage): run the DEVICE ENGINE on the
            # CPU backend so the artifact still measures this engine against
            # its pyarrow oracle — the unit's [cpu] tag marks the platform
            log("TPU unavailable; measuring the device engine on the CPU "
                "backend instead")
            dev = drive("device-cpu", "cpu")
            if not dev["runs"]:
                log("device child produced nothing; reporting CPU numbers")
                dev = cpu
        else:
            log("device child produced nothing; reporting CPU numbers")
            dev = cpu

    tpu_t = min(dev["runs"])
    platform = (dev["platform"] or "unknown") + unit_note

    # oracle cross-check (tolerate missing values from a killed child)
    if dev.get("value") is not None and cpu.get("value") is not None:
        ok = abs(dev["value"] - cpu["value"]) < 1e-4 * abs(cpu["value"])
        log(f"oracle check: device={dev['value']} cpu={cpu['value']} "
            f"match={ok}")
        if not ok:
            platform += ":MISMATCH"

    mrows_s = N_ROWS / tpu_t / 1e6
    print(json.dumps({
        "metric": f"tpch_q6_like_{N_ROWS // 1_000_000}M_rows_device_throughput",
        "value": round(mrows_s, 3),
        "unit": f"Mrows/s[{platform}]",
        "vs_baseline": round(cpu_t / tpu_t, 3),
    }))


if __name__ == "__main__":
    main()
