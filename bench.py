"""Flagship benchmark: TPC-H Q6 shape on the device engine vs the CPU path.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
  value       = device-engine throughput (million rows/sec through the
                filter->project->aggregate pipeline, steady-state)
  vs_baseline = speedup over this framework's own CPU (pyarrow) executors,
                the stand-in for the reference's CPU-Spark-vs-GPU oracle
                (reference headline: TPCxBB-like Q5 19.8x, README.md:7-15).

Robustness (round-1 postmortem: BENCH_r01 rc=124 with no output — the axon
TPU lease acquisition can block forever in a sleep-retry loop):
  * every stage logs to stderr with a timestamp so a hang is diagnosable
    from the tail;
  * TPU device acquisition is probed in a SUBPROCESS with a bounded budget
    (BENCH_TPU_PROBE_S, default 420s); on timeout the benchmark falls back
    to the virtual-CPU backend so a number is always recorded (the platform
    used is logged to stderr and carried in the "unit" field).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 6_000_000))  # ~SF1 lineitem
PROBE_BUDGET_S = int(os.environ.get("BENCH_TPU_PROBE_S", "420"))

T0 = time.time()


def log(msg: str) -> None:
    print(f"[bench +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def tpu_lease_available(budget_s: int) -> bool:
    """Try acquiring the axon TPU in a child process under a hard timeout.

    The child claims and releases the lease; if it succeeds, the parent's
    own initialization is expected to be fast.  A hung child is killed, and
    the benchmark proceeds on CPU instead of dying with no output."""
    if os.environ.get("JAX_PLATFORMS", "") in ("cpu", ""):
        return False
    log(f"probing TPU lease (budget {budget_s}s)...")
    code = "import jax; print(jax.devices(), flush=True)"
    try:
        r = subprocess.run([sys.executable, "-u", "-c", code],
                           timeout=budget_s, capture_output=True, text=True)
        ok = r.returncode == 0
        log(f"TPU probe rc={r.returncode} out={r.stdout.strip()[:200]}")
        return ok
    except subprocess.TimeoutExpired:
        log("TPU probe TIMED OUT — lease unavailable; falling back to CPU")
        return False


def force_cpu_backend() -> None:
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend as f
    f()


def make_lineitem(n: int):
    import pyarrow as pa
    rng = np.random.RandomState(42)
    price = rng.uniform(900.0, 105000.0, n)
    discount = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    quantity = rng.randint(1, 51, n).astype(np.int64)
    # days since epoch across 1992-1998 (TPC-H date range)
    shipdate = rng.randint(8035, 10592, n).astype(np.int64)
    return pa.table({
        "l_extendedprice": price,
        "l_discount": discount,
        "l_quantity": quantity,
        "l_shipdate": shipdate,
    })


def q6(session, table):
    from spark_rapids_tpu.plan.logical import col, functions as F
    df = session.from_arrow(table)
    # 1994-01-01 = day 8766, 1995-01-01 = day 9131
    return (df.filter((col("l_shipdate") >= 8766)
                      & (col("l_shipdate") < 9131)
                      & (col("l_discount") >= 0.05)
                      & (col("l_discount") <= 0.07)
                      & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def timed_run(session, table):
    """One full run: plan + execute + materialize.  Kernels compiled on a
    previous run are reused via the process-wide kernel cache."""
    t0 = time.perf_counter()
    rows = q6(session, table).collect()
    return time.perf_counter() - t0, rows


def main():
    on_tpu = tpu_lease_available(PROBE_BUDGET_S)
    if not on_tpu:
        force_cpu_backend()
    import jax
    platform = jax.devices()[0].platform
    log(f"backend ready: platform={platform} devices={jax.devices()}")

    from spark_rapids_tpu.engine import TpuSession
    table = make_lineitem(N_ROWS)
    log(f"data gen done: {N_ROWS} rows")

    tpu = TpuSession()
    t, _ = timed_run(tpu, table)
    log(f"warmup (compile) done in {t:.2f}s")
    tpu_runs = []
    for i in range(3):
        t, rows = timed_run(tpu, table)
        log(f"device run {i} done in {t:.3f}s")
        tpu_runs.append((t, rows))
    tpu_t = min(t for t, _ in tpu_runs)
    tpu_rows = tpu_runs[-1][1]

    cpu = TpuSession({"spark.rapids.sql.enabled": "false"})
    cpu_t, cpu_rows = timed_run(cpu, table)
    log(f"cpu oracle run done in {cpu_t:.3f}s")

    assert abs(tpu_rows[0][0] - cpu_rows[0][0]) < 1e-4 * abs(cpu_rows[0][0]), \
        (tpu_rows, cpu_rows)
    log("oracle check passed")

    mrows_s = N_ROWS / tpu_t / 1e6
    print(json.dumps({
        "metric": f"tpch_q6_like_{N_ROWS // 1_000_000}M_rows_device_throughput",
        "value": round(mrows_s, 3),
        "unit": f"Mrows/s[{platform}]",
        "vs_baseline": round(cpu_t / tpu_t, 3),
    }))


if __name__ == "__main__":
    main()
