"""Benchmark / workload applications (SURVEY.md §4 tier 3).

Mirrors the reference's integration_tests benchmark apps: a TPC-H-like
suite (reference: integration_tests/src/main/scala/.../tpch/
TpchLikeSpark.scala:49-290+) with schema, data generator and all 22
queries, runnable against the TPU engine or the CPU fallback engine for
comparison.
"""
