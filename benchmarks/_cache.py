"""Shared arrow-table cache for the benchmark datagens.

`generate(sf, seed)` is deterministic, so the expensive python-list ->
arrow conversion happens ONCE per (suite, sf, seed); every session then
wraps the same immutable arrow tables.  The TPC-DS oracle tier alone
builds its dataset ~200 times (99 queries x cpu+tpu sessions) — this
cache is what keeps the fast test tier inside a CI budget (VERDICT r4
item 10)."""
from __future__ import annotations

_CACHE: dict = {}
_MAX_ENTRIES = 4


def cached_load(suite: str, generate, schemas, session, sf: float,
                seed: int):
    """{name: DataFrame} on `session`, from cached arrow tables."""
    key = (suite, sf, seed)
    tables = _CACHE.get(key)
    if tables is None:
        import pyarrow as pa

        from spark_rapids_tpu.types import to_arrow
        data = generate(sf, seed)
        tables = {
            name: pa.table(
                {k: pa.array(v, type=to_arrow(schemas[name].field(k).dtype))
                 for k, v in data[name].items()})
            for name in schemas}
        while len(_CACHE) >= _MAX_ENTRIES:
            _CACHE.pop(next(iter(_CACHE)))
        _CACHE[key] = tables
    return {name: session.from_arrow(t) for name, t in tables.items()}
