from .datagen import generate, load_tables  # noqa: F401
from .queries import QUERIES  # noqa: F401
