"""Mortgage-like data generator (structure-faithful to the reference's
ETL benchmark inputs: Fannie-Mae-style performance + acquisition files).

Reference counterpart: mortgage/MortgageSpark.scala ReadPerformanceCsv
(:34-79) / ReadAcquisitionCsv (:81-118).  Each loan gets a monthly
performance history whose delinquency status evolves (so the
delinquency-window ETL selects meaningful ever_30/90/180 cohorts), plus
one acquisition row."""
from __future__ import annotations

import datetime

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)

SERVICERS = ["BANK A", "BANK B", "CREDIT UNION C", "LENDER D", "OTHER"]
CHANNELS = ["R", "C", "B"]


def _days(y, m):
    return (datetime.date(y, m, 1) - _EPOCH).days


def generate(sf: float = 0.001, seed: int = 29):
    """Returns {table_name: dict of column -> python list}."""
    rng = np.random.RandomState(seed)
    n_loans = max(60, int(50_000 * sf))
    months = 24  # two years of reporting history per loan

    loan_ids = np.arange(1, n_loans + 1)
    quarters = [f"200{1 + i % 4}Q{1 + (i // 4) % 4}" for i in range(n_loans)]
    start_year = rng.randint(2001, 2004, n_loans)

    perf = {k: [] for k in
            ("loan_id", "quarter", "monthly_reporting_period",
             "servicer", "interest_rate", "current_actual_upb",
             "current_loan_delinquency_status")}
    for li in range(n_loans):
        status = 0
        upb = float(rng.uniform(50_000, 500_000))
        rate = float(np.round(rng.uniform(2.5, 8.0), 3))
        y0 = int(start_year[li])
        for m in range(months):
            y, mo = y0 + m // 12, 1 + m % 12
            # delinquency random walk: mostly current, occasional spirals
            if status == 0:
                status = int(rng.rand() < 0.06)
            else:
                status = 0 if rng.rand() < 0.4 else status + 1
            upb = max(0.0, upb - float(rng.uniform(200, 2000)))
            perf["loan_id"].append(int(loan_ids[li]))
            perf["quarter"].append(quarters[li])
            perf["monthly_reporting_period"].append(_days(y, mo))
            perf["servicer"].append(SERVICERS[li % len(SERVICERS)])
            perf["interest_rate"].append(rate)
            perf["current_actual_upb"].append(round(upb, 2))
            perf["current_loan_delinquency_status"].append(status)

    acq = {
        "loan_id": loan_ids.tolist(),
        "quarter": quarters,
        "orig_channel": [CHANNELS[i % 3] for i in range(n_loans)],
        "seller_name": [SERVICERS[i % len(SERVICERS)]
                        for i in range(n_loans)],
        "orig_interest_rate": np.round(rng.uniform(2.5, 8.0, n_loans),
                                       3).tolist(),
        "orig_upb": rng.randint(50_000, 500_000, n_loans).tolist(),
        "orig_loan_term": rng.choice([180, 240, 360], n_loans).tolist(),
        "dti": np.round(rng.uniform(5, 60, n_loans), 1).tolist(),
        "borrower_credit_score": rng.randint(550, 830, n_loans).tolist(),
        "zip": rng.randint(100, 999, n_loans).tolist(),
    }
    return {"performance": perf, "acquisition": acq}


def load_tables(session, sf: float = 0.001, seed: int = 29):
    """{name: DataFrame} on the given session (cached arrow tables)."""
    from .schema import SCHEMAS
    from .._cache import cached_load
    return cached_load("mortgage", generate, SCHEMAS, session, sf, seed)
