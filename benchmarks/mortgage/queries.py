"""The reference's Mortgage ETL + aggregate drivers in this repo's DSL.

Behavior from mortgage/MortgageSpark.scala:
  * performance_delinquency — CreatePerformanceDelinquency (:213-299):
    per-loan ever_30/90/180 cohorts (conditional min/max aggregation),
    a 12-month EXPLODE fan-out with the floor/mod month-bucket
    arithmetic ("josh_mody"), re-aggregation, and a multi-key left join
    back onto the monthly history;
  * simple_aggregates — SimpleAggregates (:349-365);
  * aggregates_with_percentiles — AggregatesWithPercentiles (:367-389)
    (grouping on loan_id directly; the reference's hex(hash(...))
    anonymization wrapper is orthogonal to the aggregate shape).  The
    percentile aggregate falls back to the CPU executors on both sides,
    matching the reference, which ships no GPU Percentile rule;
  * aggregates_with_join — AggregatesWithJoin (:391-421).

Each `qname(t)` takes {table_name: DataFrame} and returns a DataFrame.
"""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit
from spark_rapids_tpu.types import IntegerType, LongType


def performance_delinquency(t):
    df = (t["performance"]
          .with_column("timestamp_month",
                       F.month(col("monthly_reporting_period")))
          .with_column("timestamp_year",
                       F.year(col("monthly_reporting_period"))))

    status = col("current_loan_delinquency_status")
    agg_df = (df.select(
        col("quarter"), col("loan_id"), status,
        F.when(status >= 1, col("monthly_reporting_period"))
        .alias("d30"),
        F.when(status >= 3, col("monthly_reporting_period"))
        .alias("d90"),
        F.when(status >= 6, col("monthly_reporting_period"))
        .alias("d180"))
        .group_by(col("quarter"), col("loan_id"))
        .agg(F.max(status).alias("d12"),
             F.min(col("d30")).alias("delinquency_30"),
             F.min(col("d90")).alias("delinquency_90"),
             F.min(col("d180")).alias("delinquency_180"))
        .select(col("quarter"), col("loan_id"),
                (col("d12") >= 1).alias("ever_30"),
                (col("d12") >= 3).alias("ever_90"),
                (col("d12") >= 6).alias("ever_180"),
                col("delinquency_30"), col("delinquency_90"),
                col("delinquency_180")))

    joined = (df.select(col("quarter"), col("loan_id"),
                        col("current_loan_delinquency_status")
                        .alias("delinquency_12"),
                        col("current_actual_upb").alias("upb_12"),
                        col("timestamp_month"), col("timestamp_year"))
              .join(agg_df, on=["loan_id", "quarter"], how="left"))

    months = 12
    mody = F.floor(((col("timestamp_year") * 12 + col("timestamp_month"))
                    - 24000 - col("month_y")) / months)
    test_df = (joined
               .with_column("month_y", F.explode(list(range(12))))
               .select(col("quarter"), col("loan_id"),
                       mody.cast(LongType).alias("josh_mody_n"),
                       col("ever_30"), col("ever_90"), col("ever_180"),
                       col("month_y"), col("delinquency_12"),
                       col("upb_12"))
               .group_by(col("quarter"), col("loan_id"),
                         col("josh_mody_n"), col("ever_30"),
                         col("ever_90"), col("ever_180"), col("month_y"))
               .agg(F.max(col("delinquency_12")).alias("delinquency_12"),
                    F.min(col("upb_12")).alias("upb_12")))
    mseq = lit(24000) + (col("josh_mody_n") * months) + col("month_y")
    test_df = (test_df
               .with_column("timestamp_year",
                            F.floor((mseq - 1) / 12).cast(LongType))
               .with_column("timestamp_month_tmp",
                            (mseq % 12).cast(LongType))
               .with_column("timestamp_month",
                            F.when(col("timestamp_month_tmp") == 0, 12)
                            .otherwise(col("timestamp_month_tmp")))
               .with_column("delinquency_12",
                            (col("delinquency_12") > 3).cast(IntegerType)
                            + (col("upb_12") == 0.0).cast(IntegerType))
               .select(col("quarter"), col("loan_id"),
                       col("timestamp_year"), col("timestamp_month"),
                       col("delinquency_12"), col("upb_12")))

    return (t["performance"]
            .with_column("timestamp_month",
                         F.month(col("monthly_reporting_period")))
            .with_column("timestamp_year",
                         F.year(col("monthly_reporting_period")))
            .join(test_df,
                  on=["quarter", "loan_id", "timestamp_year",
                      "timestamp_month"], how="left"))


def simple_aggregates(t):
    max_rate = (t["performance"]
                .with_column("monthval",
                             F.month(col("monthly_reporting_period")))
                .group_by(col("monthval"), col("loan_id"))
                .agg(F.max(col("interest_rate"))
                     .alias("max_monthly_rate")))
    return (max_rate
            .join(t["acquisition"], on=["loan_id"])
            .group_by(col("zip"), col("monthval"))
            .agg(F.min(col("max_monthly_rate"))
                 .alias("min_max_monthly_rate")))


def aggregates_with_percentiles(t):
    rate = col("interest_rate")
    return (t["performance"]
            .group_by(col("loan_id"))
            .agg(F.min(rate).alias("interest_rate_min"),
                 F.max(rate).alias("interest_rate_max"),
                 F.avg(rate).alias("interest_rate_avg"),
                 F.percentile(rate, 0.5).alias("interest_rate_50p"),
                 F.percentile(rate, 0.75).alias("interest_rate_75p"),
                 F.percentile(rate, 0.90).alias("interest_rate_90p"),
                 F.percentile(rate, 0.99).alias("interest_rate_99p")))


def aggregates_with_join(t):
    perf = (t["performance"]
            .group_by(col("loan_id"))
            .agg(F.min(col("interest_rate")).alias("min_int_rate")))
    acq = (t["acquisition"]
           .group_by(col("loan_id"))
           .agg(F.first(col("orig_interest_rate")).alias("first_int_rate"),
                F.coalesce(F.max(col("dti")), lit(0.0)).alias("max_dti")))
    return perf.join(acq, on=["loan_id"], how="left")


QUERIES = {"delinquency": performance_delinquency,
           "simple_aggregates": simple_aggregates,
           "aggregates_with_percentiles": aggregates_with_percentiles,
           "aggregates_with_join": aggregates_with_join}
