"""Mortgage-like table schemas (reference: MortgageSpark.scala
performanceSchema :37-69 / acquisitionSchema :84-117, trimmed to the
columns the ETL and aggregate drivers touch)."""
from spark_rapids_tpu.types import (DateType, DoubleType, LongType, Schema,
                                    StringType, StructField as F)

PERFORMANCE = Schema([
    F("loan_id", LongType), F("quarter", StringType),
    F("monthly_reporting_period", DateType), F("servicer", StringType),
    F("interest_rate", DoubleType), F("current_actual_upb", DoubleType),
    F("current_loan_delinquency_status", LongType)])

ACQUISITION = Schema([
    F("loan_id", LongType), F("quarter", StringType),
    F("orig_channel", StringType), F("seller_name", StringType),
    F("orig_interest_rate", DoubleType), F("orig_upb", LongType),
    F("orig_loan_term", LongType), F("dti", DoubleType),
    F("borrower_credit_score", LongType), F("zip", LongType)])

SCHEMAS = {"performance": PERFORMANCE, "acquisition": ACQUISITION}
