"""Pallas-vs-XLA microbench: settle `spark.rapids.sql.tpu.pallas.enabled`
with measured data (VERDICT r4 item 7).

Benchmarks, on the ambient backend (meant for the real chip; prints the
platform so CPU-backend runs are self-labeling):
  1. cumsum        — ops/pallas_kernels.cumsum_1d vs jnp.cumsum (the
                     segmented-aggregation inner primitive, _masked_cumsum)
  2. seg_sum       — exec/aggregate._seg_sum (cumsum + 2 searchsorted
                     gathers) with the pallas cumsum vs the XLA cumsum
  3. bit_unpack    — io/parquet_device._bitpacked_unpack (XLA gather/
                     shift/mask), timed in GB/s to decide whether a
                     pallas rival is worth writing at all
  4. sort_encode   — exec/sort key-encode + argsort (XLA), same question

Writes BENCH_PALLAS.json at the repo root:
  {platform, results: [{name, n, dtype, xla_ms, pallas_ms, speedup}...],
   verdict: "..."}

Run: timeout 900 python benchmarks/pallas_micro.py   (ambient env; one
jax process at a time — this touches the TPU lease)."""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu" in sys.argv:
    # CPU self-test: the ambient env pins the axon plugin in every
    # process, so the factories must drop BEFORE first backend use
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
    force_cpu_backend()

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def timeit(fn, *args, n_runs: int = 10) -> float:
    """Median ms of a jitted fn (blocked)."""
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n_runs):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    try:
        platform = jax.devices()[0].platform
    except Exception as e:  # lease down: report and leave evidence
        print(json.dumps({"platform": None, "error": repr(e)[:200]}))
        return
    results = []
    rng = np.random.RandomState(7)

    # 1/2. cumsum + seg_sum
    from spark_rapids_tpu.exec import aggregate as agg
    from spark_rapids_tpu.ops.pallas_kernels import cumsum_1d
    for n in (1 << 20, 1 << 23):
        for dt in (jnp.int32, jnp.float32):
            v = jnp.asarray(rng.randint(0, 100, n), dtype=dt)
            xla_ms = timeit(jax.jit(jnp.cumsum), v)
            try:
                pal_ms = timeit(jax.jit(cumsum_1d), v)
            except Exception as e:
                pal_ms = None
                print(f"pallas cumsum failed n={n} {dt.__name__}: "
                      f"{e!r}"[:160], file=sys.stderr)
            results.append({
                "name": "cumsum", "n": n, "dtype": dt.__name__,
                "xla_ms": round(xla_ms, 3),
                "pallas_ms": round(pal_ms, 3) if pal_ms else None,
                "speedup": round(xla_ms / pal_ms, 2) if pal_ms else None})

    n = 1 << 22
    gid = jnp.asarray(np.sort(rng.randint(0, 1024, n)).astype(np.int32))
    vals = jnp.asarray(rng.randint(0, 1000, n).astype(np.int32))
    contribute = jnp.asarray(rng.rand(n) < 0.9)

    def seg(v, g, c):
        return agg._seg_sum(v, g, c, 1024)
    for mode in ("xla", "pallas"):
        agg.set_pallas_cumsum(mode == "pallas")
        try:
            ms = timeit(jax.jit(seg), vals, gid, contribute)
        except Exception as e:
            ms = None
            print(f"seg_sum {mode} failed: {e!r}"[:160], file=sys.stderr)
        results.append({"name": f"seg_sum[{mode}]", "n": n,
                        "dtype": "int32",
                        "ms": round(ms, 3) if ms else None})
    agg.set_pallas_cumsum(False)

    # 3. parquet bit-unpack (XLA): GB/s of unpacked output
    from spark_rapids_tpu.io.parquet_device import _bitpacked_unpack
    for bw in (3, 11, 20):
        count = 1 << 21
        packed = rng.randint(0, 256, (count * bw + 7) // 8 + 8,
                             dtype=np.uint8).tobytes()

        def unpack(bw=bw, count=count, packed=packed):
            return _bitpacked_unpack(packed, bw, count, count)
        ms = timeit(lambda: unpack())
        results.append({"name": "bit_unpack_xla", "n": count,
                        "bit_width": bw, "ms": round(ms, 3),
                        "out_gb_s": round(count * 4 / ms / 1e6, 2)})

    # 4. sort key-encode + argsort (XLA)
    keys = jnp.asarray(rng.randint(-10**9, 10**9, 1 << 21)
                       .astype(np.int64))
    ms = timeit(jax.jit(jnp.argsort), keys)
    results.append({"name": "argsort_xla", "n": 1 << 21,
                    "dtype": "int64", "ms": round(ms, 3)})

    cs = [r for r in results if r["name"] == "cumsum"
          and r.get("speedup") is not None]
    wins = [r for r in cs if r["speedup"] > 1.1]
    verdict = (
        f"pallas cumsum wins {len(wins)}/{len(cs)} shapes on {platform}"
        if cs else f"pallas cumsum unmeasurable on {platform}")
    out = {"platform": platform, "recorded_unix": int(time.time()),
           "results": results, "verdict": verdict}
    with open(os.path.join(REPO, "BENCH_PALLAS.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"platform": platform, "verdict": verdict,
                      "n_results": len(results)}))


if __name__ == "__main__":
    main()
