"""Pallas-vs-XLA microbench: settle `spark.rapids.sql.tpu.pallas.enabled`
with measured data (VERDICT r4 item 7).

Benchmarks, on the ambient backend (meant for the real chip; prints the
platform so CPU-backend runs are self-labeling):
  1. cumsum        — ops/pallas_kernels.cumsum_1d vs jnp.cumsum (the
                     segmented-aggregation inner primitive, _masked_cumsum)
  2. seg_sum       — exec/aggregate._seg_sum (cumsum + 2 searchsorted
                     gathers) with the pallas cumsum vs the XLA cumsum
  3. bit_unpack    — io/parquet_device._bitpacked_unpack (XLA gather/
                     shift/mask), timed in GB/s to decide whether a
                     pallas rival is worth writing at all
  4. sort_encode   — exec/sort key-encode + argsort (XLA), same question

Writes BENCH_PALLAS.json at the repo root:
  {platform, results: [{name, n, dtype, xla_ms, pallas_ms, speedup}...],
   verdict: "..."}

Run: timeout 900 python benchmarks/pallas_micro.py   (ambient env; one
jax process at a time — this touches the TPU lease)."""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu" in sys.argv:
    # CPU self-test: the ambient env pins the axon plugin in every
    # process, so the factories must drop BEFORE first backend use
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
    force_cpu_backend()

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def timeit(fn, *args, n_runs: int = 10) -> float:
    """Median ms of a jitted fn (blocked)."""
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n_runs):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    try:
        platform = jax.devices()[0].platform
    except Exception as e:  # lease down: report and leave evidence
        print(json.dumps({"platform": None, "error": repr(e)[:200]}))
        return
    results = []
    rng = np.random.RandomState(7)

    # 1/2. cumsum + seg_sum
    from spark_rapids_tpu.exec import aggregate as agg
    from spark_rapids_tpu.ops.pallas_kernels import cumsum_1d
    for n in (1 << 20, 1 << 23):
        for dt in (jnp.int32, jnp.float32):
            v = jnp.asarray(rng.randint(0, 100, n), dtype=dt)
            xla_ms = timeit(jax.jit(jnp.cumsum), v)
            mode = "compiled"
            try:
                pal_ms = timeit(jax.jit(cumsum_1d), v)
            except Exception as e:
                # compiled pallas unavailable on this backend: measure
                # the INTERPRET-mode kernel so the row is filled, and
                # LABEL it — interpreter timings are functional checks,
                # not chip numbers (no speedup reported)
                print(f"pallas cumsum failed n={n} {dt.__name__}: "
                      f"{e!r}"[:160], file=sys.stderr)
                mode = "interpret"
                try:
                    pal_ms = timeit(
                        jax.jit(lambda x: cumsum_1d(x, interpret=True)),
                        v, n_runs=2)
                except Exception as e2:
                    pal_ms = None
                    mode = "unavailable"
                    print(f"interpret cumsum failed too: {e2!r}"[:160],
                          file=sys.stderr)
            results.append({
                "name": "cumsum", "n": n, "dtype": dt.__name__,
                "xla_ms": round(xla_ms, 3),
                "pallas_ms": round(pal_ms, 3) if pal_ms else None,
                "pallas_mode": mode,
                "speedup": (round(xla_ms / pal_ms, 2)
                            if pal_ms and mode == "compiled" else None)})

    n = 1 << 22
    gid = jnp.asarray(np.sort(rng.randint(0, 1024, n)).astype(np.int32))
    vals = jnp.asarray(rng.randint(0, 1000, n).astype(np.int32))
    contribute = jnp.asarray(rng.rand(n) < 0.9)

    def seg(v, g, c):
        return agg._seg_sum(v, g, c, 1024)
    for mode in ("xla", "pallas"):
        agg.set_pallas_cumsum(mode == "pallas")
        # the dispatcher is BACKEND-gated (TPU -> pallas, CPU -> XLA):
        # record which path actually ran, not which flag was set
        path = agg._pallas_seg_mode() or "xla"
        try:
            ms = timeit(jax.jit(seg), vals, gid, contribute)
        except Exception as e:
            ms = None
            print(f"seg_sum {mode} failed: {e!r}"[:160], file=sys.stderr)
        results.append({"name": f"seg_sum[{mode}]", "n": n,
                        "dtype": "int32", "path": path,
                        "ms": round(ms, 3) if ms else None})
    agg.set_pallas_cumsum(False)

    # 2b. fused multi-aggregate segmented reduction: the scatter path
    # (one jax.ops.segment_* per aggregate — the pre-ISSUE-11 shape)
    # vs the fused dispatcher (shared searchsorted + prefix sums on
    # CPU; ONE pallas pass on TPU).  sum+count+min+max of one column.
    def seg_scatter(v, g, c):
        vz = jnp.where(c, v, 0)
        return (jax.ops.segment_sum(vz, g, num_segments=1024,
                                    indices_are_sorted=True),
                jax.ops.segment_sum(c.astype(jnp.int64), g,
                                    num_segments=1024,
                                    indices_are_sorted=True),
                jax.ops.segment_min(jnp.where(c, v, 2**31 - 1), g,
                                    num_segments=1024,
                                    indices_are_sorted=True),
                jax.ops.segment_max(jnp.where(c, v, -2**31), g,
                                    num_segments=1024,
                                    indices_are_sorted=True))

    def seg_fused(v, g, c):
        return tuple(agg._seg_multi(
            [("sum", v, c, 0),
             ("sum", c.astype(jnp.int64), jnp.ones_like(c), 0, True),
             ("min", v, c, jnp.int32(2**31 - 1)),
             ("max", v, c, jnp.int32(-2**31))], g, 1024))
    sc_ms = timeit(jax.jit(seg_scatter), vals, gid, contribute)
    # flag ON for the fused measurement so a TPU backend actually runs
    # the pallas kernel (the dispatcher stays backend-gated: CPU still
    # records path=xla by design)
    agg.set_pallas_cumsum(True)
    fu_path = agg._pallas_seg_mode() or "xla"
    fu_ms = timeit(jax.jit(seg_fused), vals, gid, contribute)
    agg.set_pallas_cumsum(False)
    results.append({"name": "seg_agg_scatter", "n": n, "aggs": 4,
                    "ms": round(sc_ms, 3)})
    results.append({"name": "seg_agg_fused", "n": n, "aggs": 4,
                    "path": fu_path, "ms": round(fu_ms, 3),
                    "speedup": round(sc_ms / fu_ms, 2)})

    # 3. parquet bit-unpack (XLA): GB/s of unpacked output
    from spark_rapids_tpu.io.parquet_device import _bitpacked_unpack
    for bw in (3, 11, 20):
        count = 1 << 21
        packed = rng.randint(0, 256, (count * bw + 7) // 8 + 8,
                             dtype=np.uint8).tobytes()

        def unpack(bw=bw, count=count, packed=packed):
            return _bitpacked_unpack(packed, bw, count, count)
        ms = timeit(lambda: unpack())
        results.append({"name": "bit_unpack_xla", "n": count,
                        "bit_width": bw, "ms": round(ms, 3),
                        "out_gb_s": round(count * 4 / ms / 1e6, 2)})

    # 4. sort key-encode + argsort (XLA)
    keys = jnp.asarray(rng.randint(-10**9, 10**9, 1 << 21)
                       .astype(np.int64))
    ms = timeit(jax.jit(jnp.argsort), keys)
    results.append({"name": "argsort_xla", "n": 1 << 21,
                    "dtype": "int64", "ms": round(ms, 3)})

    # 4b. packed-key multi-column sort (ISSUE 11): the full sort_order
    # path — lexsort (variadic sort HLO) vs the packed path (components
    # fused into 64-bit words + embedded row ids, single-operand sort
    # passes).  One-shot spec (everything fits one word), a 2-pass and
    # a 3-pass spec; permutations are verified identical.
    from spark_rapids_tpu import types as RT
    from spark_rapids_tpu.columnar import Column, ColumnarBatch
    from spark_rapids_tpu.exec.sort import sort_order
    from spark_rapids_tpu.ops.expressions import BoundReference
    from spark_rapids_tpu.utils import packed_sort as PS
    ns = 1 << 21
    sort_specs = {
        "int32+byte": (
            [RT.IntegerType, RT.ByteType],
            [rng.randint(-10**9, 10**9, ns).astype(np.int32),
             rng.randint(-100, 100, ns).astype(np.int8)]),
        "int32+int32": (
            [RT.IntegerType, RT.IntegerType],
            [rng.randint(-10**9, 10**9, ns).astype(np.int32),
             rng.randint(-10**9, 10**9, ns).astype(np.int32)]),
        "int32+int64": (
            [RT.IntegerType, RT.LongType],
            [rng.randint(-10**9, 10**9, ns).astype(np.int32),
             rng.randint(-10**17, 10**17, ns).astype(np.int64)]),
    }
    for spec_name, (dts, arrs) in sort_specs.items():
        schema = RT.Schema([RT.StructField(f"c{i}", dt)
                            for i, dt in enumerate(dts)])
        cols = [Column(jnp.asarray(a), jnp.ones(ns, jnp.bool_), dt)
                for a, dt in zip(arrs, dts)]
        batch = ColumnarBatch(cols, jnp.ones(ns, jnp.bool_), schema)
        exprs = [BoundReference(i, dt, f"c{i}")
                 for i, dt in enumerate(dts)]
        asc = [True] * len(dts)
        nf = [True] * len(dts)
        st = {}

        def order_fn(b, _e=exprs, _a=asc, _n=nf, _st=st):
            return sort_order(b, _e, _a, _n, stats=_st)
        PS.set_packed_enabled(False)
        lex_fn = jax.jit(order_fn)
        lex_ms = timeit(lex_fn, batch, n_runs=5)
        o_lex = np.asarray(lex_fn(batch))
        PS.set_packed_enabled(True)
        pk_fn = jax.jit(lambda b, _e=exprs, _a=asc, _n=nf, _st=st:
                        sort_order(b, _e, _a, _n, stats=_st))
        pk_ms = timeit(pk_fn, batch, n_runs=5)
        o_pk = np.asarray(pk_fn(batch))
        results.append({"name": "argsort_lexsort", "spec": spec_name,
                        "n": ns, "ms": round(lex_ms, 3)})
        results.append({"name": "argsort_packed", "spec": spec_name,
                        "n": ns, "ms": round(pk_ms, 3),
                        "passes": st.get("passes"),
                        "identical_perm": bool(np.array_equal(o_lex,
                                                              o_pk)),
                        "speedup": round(lex_ms / pk_ms, 2)})

    cs = [r for r in results if r["name"] == "cumsum"
          and r.get("speedup") is not None]
    wins = [r for r in cs if r["speedup"] > 1.1]
    packed = [r for r in results if r["name"] == "argsort_packed"]
    best = max((r["speedup"] for r in packed), default=0)
    verdict = (
        (f"pallas cumsum wins {len(wins)}/{len(cs)} shapes on {platform}"
         if cs else f"pallas cumsum interpret-only on {platform}")
        + f"; packed-key sort up to {best}x vs lexsort")
    out = {"platform": platform, "recorded_unix": int(time.time()),
           "results": results, "verdict": verdict}
    with open(os.path.join(REPO, "BENCH_PALLAS.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"platform": platform, "verdict": verdict,
                      "n_results": len(results)}))


if __name__ == "__main__":
    main()
