"""Per-stage TPU profiling harness (VERDICT r4 item 3: where does the
roofline gap go — H2D? dispatch? f64 emulation? compile?).

Measures, each under its own timer, and writes PROFILE_ONCHIP.json:
  1. H2D bandwidth: device_put of numpy arrays, various sizes/dtypes
  2. dispatch+sync latency: tiny jitted op round trip
  3. compile time: Q6-shaped kernel
  4. steady-state kernel time on device-resident data (f64, f32/i32,
     bf16 variants — the emulated-f64 cost shows up as the f64/f32 gap)
  5. D2H scalar fetch

Run: timeout 1200 python benchmarks/profile_device.py   (ambient env;
one jax process at a time).  --cpu forces the CPU backend (self-test)."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
T0 = time.time()

if "--cpu" in sys.argv:
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
    force_cpu_backend()


def log(msg):
    print(f"[prof +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def main():
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_enable_x64", True)
    try:
        dev = jax.devices()[0]
    except Exception as e:
        print(json.dumps({"platform": None, "error": repr(e)[:200]}))
        return
    log(f"platform={dev.platform} device={dev}")
    out = {"platform": dev.platform, "recorded_unix": int(time.time()),
           "h2d": [], "kernels": {}}

    # 1. H2D bandwidth
    for mb, dtype in [(1, np.float32), (8, np.float64), (48, np.float64),
                      (48, np.float32), (48, np.int32)]:
        n = mb * (1 << 20) // np.dtype(dtype).itemsize
        host = np.arange(n, dtype=dtype)
        t = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        dt = time.perf_counter() - t
        log(f"H2D {mb}MB {np.dtype(dtype).name}: {dt:.3f}s "
            f"({mb / dt:.1f} MB/s)")
        out["h2d"].append({"mb": mb, "dtype": np.dtype(dtype).name,
                           "s": round(dt, 4),
                           "mb_s": round(mb / dt, 1)})

    # 2. dispatch+sync latency
    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.float32(1.0), dev)
    f(x).block_until_ready()
    ts = []
    for _ in range(10):
        t = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t)
    log(f"dispatch+sync latency: min={min(ts)*1e3:.1f}ms "
        f"median={sorted(ts)[5]*1e3:.1f}ms")
    out["dispatch_ms"] = {"min": round(min(ts) * 1e3, 2),
                          "median": round(sorted(ts)[5] * 1e3, 2)}

    # 3+4. Q6-shaped kernel: filter + project + masked sum over 6M rows
    n = 6_000_000
    cap = 1 << 23
    rng = np.random.RandomState(42)
    cols = {
        "price": np.zeros(cap), "disc": np.zeros(cap),
        "qty": np.zeros(cap, np.int64), "ship": np.zeros(cap, np.int64),
    }
    cols["price"][:n] = rng.uniform(900.0, 105000.0, n)
    cols["disc"][:n] = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    cols["qty"][:n] = rng.randint(1, 51, n)
    cols["ship"][:n] = rng.randint(8035, 10592, n)
    sel = np.arange(cap) < n

    t = time.perf_counter()
    dcols = {k: jax.device_put(v, dev) for k, v in cols.items()}
    dsel = jax.device_put(sel, dev)
    for v in dcols.values():
        v.block_until_ready()
    table_s = time.perf_counter() - t
    table_mb = sum(v.nbytes for v in cols.values()) / 2**20
    log(f"H2D 6M-row 4-col table ({table_mb:.0f}MB): {table_s:.3f}s")
    out["h2d_table"] = {"mb": round(table_mb), "s": round(table_s, 3),
                        "mb_s": round(table_mb / table_s, 1)}

    def q6(c, s):
        keep = (s & (c["ship"] >= 8766) & (c["ship"] < 9131)
                & (c["disc"] >= 0.05) & (c["disc"] <= 0.07)
                & (c["qty"] < 24))
        return jnp.sum(jnp.where(keep, c["price"] * c["disc"], 0.0))

    def steady(name, fn, *args, bytes_per_row=32):
        jfn = jax.jit(fn)
        t = time.perf_counter()
        r = jfn(*args)
        jax.block_until_ready(r)
        compile_s = time.perf_counter() - t
        ts = []
        for _ in range(5):
            t = time.perf_counter()
            jax.block_until_ready(jfn(*args))
            ts.append(time.perf_counter() - t)
        ms = min(ts) * 1e3
        gb_s = n * bytes_per_row / (ms / 1e3) / 1e9
        log(f"{name}: compile {compile_s:.2f}s steady {ms:.1f}ms -> "
            f"{n / (ms / 1e3) / 1e6:.0f} Mrows/s, {gb_s:.1f} GB/s eff")
        out["kernels"][name] = {"compile_s": round(compile_s, 2),
                                "steady_ms": round(ms, 2),
                                "mrows_s": round(n / (ms / 1e3) / 1e6, 1),
                                "eff_gb_s": round(gb_s, 2)}
        return r

    r = steady("q6_f64", q6, dcols, dsel)

    dcols32 = {k: (v.astype(jnp.float32) if v.dtype == jnp.float64
                   else v.astype(jnp.int32)) for k, v in dcols.items()}
    for v in dcols32.values():
        v.block_until_ready()
    steady("q6_f32_i32", q6, dcols32, dsel, bytes_per_row=16)

    dcols16 = {k: (v.astype(jnp.bfloat16) if v.dtype == jnp.float64
                   else v.astype(jnp.int32)) for k, v in dcols.items()}
    for v in dcols16.values():
        v.block_until_ready()
    steady("q6_bf16_i32", q6, dcols16, dsel, bytes_per_row=12)

    # 5. D2H scalar
    t = time.perf_counter()
    float(r)
    d2h_ms = (time.perf_counter() - t) * 1e3
    log(f"D2H scalar: {d2h_ms:.1f}ms")
    out["d2h_scalar_ms"] = round(d2h_ms, 2)

    with open(os.path.join(REPO, "PROFILE_ONCHIP.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"platform": dev.platform,
                      "kernels": out["kernels"]}))


if __name__ == "__main__":
    main()
