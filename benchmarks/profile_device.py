"""Per-stage TPU profiling harness (round-3 diagnosis of the 16s/run Q6).

Measures, each under its own stderr-logged timer:
  1. H2D bandwidth: device_put of numpy arrays, various sizes/dtypes
  2. dispatch+sync latency: tiny jitted op round trip
  3. compile time: Q6-shaped kernel
  4. steady-state kernel time on device-resident data
  5. D2H scalar fetch

Run: JAX_PLATFORMS=<tpu|cpu> python benchmarks/profile_device.py
"""
import sys
import time

import numpy as np

T0 = time.time()


def log(msg):
    print(f"[prof +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_enable_x64", True)
    dev = jax.devices()[0]
    log(f"platform={dev.platform} device={dev}")

    # 1. H2D bandwidth
    for mb, dtype in [(1, np.float32), (8, np.float64), (48, np.float64),
                      (48, np.float32), (48, np.int32)]:
        n = mb * (1 << 20) // np.dtype(dtype).itemsize
        host = np.arange(n, dtype=dtype)
        t = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        dt = time.perf_counter() - t
        log(f"H2D {mb}MB {np.dtype(dtype).name}: {dt:.3f}s "
            f"({mb / dt:.1f} MB/s)")

    # 2. dispatch+sync latency
    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.float32(1.0), dev)
    f(x).block_until_ready()
    ts = []
    for _ in range(10):
        t = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t)
    log(f"dispatch+sync latency: min={min(ts)*1e3:.1f}ms "
        f"median={sorted(ts)[5]*1e3:.1f}ms")

    # 3+4. Q6-shaped kernel: filter + project + masked sum over 6M f64 rows
    n = 6_000_000
    cap = 1 << 23
    rng = np.random.RandomState(42)
    cols = {
        "price": np.zeros(cap), "disc": np.zeros(cap),
        "qty": np.zeros(cap, np.int64), "ship": np.zeros(cap, np.int64),
    }
    cols["price"][:n] = rng.uniform(900.0, 105000.0, n)
    cols["disc"][:n] = rng.choice(np.arange(0.0, 0.11, 0.01), n)
    cols["qty"][:n] = rng.randint(1, 51, n)
    cols["ship"][:n] = rng.randint(8035, 10592, n)
    sel = np.arange(cap) < n

    t = time.perf_counter()
    dcols = {k: jax.device_put(v, dev) for k, v in cols.items()}
    dsel = jax.device_put(sel, dev)
    for v in dcols.values():
        v.block_until_ready()
    log(f"H2D 6M-row 4-col table ({sum(v.nbytes for v in cols.values())/2**20:.0f}MB): "
        f"{time.perf_counter() - t:.3f}s")

    def q6(c, s):
        keep = (s & (c["ship"] >= 8766) & (c["ship"] < 9131)
                & (c["disc"] >= 0.05) & (c["disc"] <= 0.07) & (c["qty"] < 24))
        return jnp.sum(jnp.where(keep, c["price"] * c["disc"], 0.0))

    jq6 = jax.jit(q6)
    t = time.perf_counter()
    r = jq6(dcols, dsel).block_until_ready()
    log(f"Q6 kernel compile+run: {time.perf_counter() - t:.3f}s")
    ts = []
    for _ in range(5):
        t = time.perf_counter()
        jq6(dcols, dsel).block_until_ready()
        ts.append(time.perf_counter() - t)
    log(f"Q6 kernel steady-state: min={min(ts)*1e3:.1f}ms -> "
        f"{n / min(ts) / 1e6:.0f} Mrows/s")

    # f32 variant (TPU-native dtype)
    dcols32 = {k: v.astype(jnp.float32) if v.dtype == jnp.float64 else
               v.astype(jnp.int32) for k, v in dcols.items()}
    jq6_32 = jax.jit(q6)
    jq6_32(dcols32, dsel).block_until_ready()
    ts = []
    for _ in range(5):
        t = time.perf_counter()
        jq6_32(dcols32, dsel).block_until_ready()
        ts.append(time.perf_counter() - t)
    log(f"Q6 kernel f32/i32: min={min(ts)*1e3:.1f}ms -> "
        f"{n / min(ts) / 1e6:.0f} Mrows/s")

    # 5. D2H scalar
    t = time.perf_counter()
    float(r)
    log(f"D2H scalar: {(time.perf_counter() - t)*1e3:.1f}ms")


if __name__ == "__main__":
    main()
