"""TPC-DS-like benchmark subset (BASELINE.md staged config 3)."""
from .datagen import generate, load_tables
from .queries import QUERIES
from .schema import SCHEMAS

__all__ = ["generate", "load_tables", "QUERIES", "SCHEMAS"]
