"""TPC-DS-like data generator (structure-faithful, not dsdgen-exact).

Row counts scale with `sf` like the spec (store_sales ~ 2.88M * sf); the
foreign keys (store_sales -> every dimension) and the value domains the
star-join queries filter on (manufacturer/manager ids, month/year windows,
demographics tuples, promo channel flags, store names, zip prefixes,
hour/minute buckets) are generated so each query selects a meaningful,
non-empty subset at tiny scale factors."""
from __future__ import annotations

import datetime

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)

GENDERS = ["M", "F"]
MARITAL = ["S", "M", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
STORE_NAMES = ["ought", "able", "ese", "anti", "cally", "ation", "eing"]


def generate(sf: float = 0.001, seed: int = 7):
    """Returns {table_name: dict of column -> python list}."""
    rng = np.random.RandomState(seed)
    out = {}

    # date_dim: one row per day, 1998-01-01 .. 2003-12-31 (the window the
    # query templates' d_year in {1998..2002} filters land in)
    start = datetime.date(1998, 1, 1)
    end = datetime.date(2003, 12, 31)
    n_days = (end - start).days + 1
    dates = [start + datetime.timedelta(days=i) for i in range(n_days)]
    first_sk = 2_450_815  # spec-like offset; value only needs consistency
    out["date_dim"] = {
        "d_date_sk": [first_sk + i for i in range(n_days)],
        "d_date": [(d - _EPOCH).days for d in dates],
        "d_year": [d.year for d in dates],
        "d_moy": [d.month for d in dates],
        "d_dom": [d.day for d in dates],
        "d_qoy": [(d.month - 1) // 3 + 1 for d in dates],
        # weekday() is Monday=0; DAY_NAMES is Sunday-first
        "d_day_name": [DAY_NAMES[(d.weekday() + 1) % 7] for d in dates],
        # consecutive month counter (spec's d_month_seq, offset-free here:
        # only equality/range against values from the same column is used)
        "d_month_seq": [(d.year - 1998) * 12 + d.month - 1 for d in dates],
    }

    # time_dim at minute granularity (86400-second spec table folded x60)
    out["time_dim"] = {
        "t_time_sk": list(range(1440)),
        "t_hour": [m // 60 for m in range(1440)],
        "t_minute": [m % 60 for m in range(1440)],
    }

    n_item = max(40, int(18_000 * sf))
    brand_id = (rng.randint(1, 11, n_item) * 1_000_000
                + rng.randint(1, 17, n_item))
    cat_id = rng.randint(1, len(CATEGORIES) + 1, n_item)
    out["item"] = {
        "i_item_sk": list(range(1, n_item + 1)),
        "i_item_id": [f"AAAAAAAA{i:08d}" for i in range(1, n_item + 1)],
        "i_brand_id": brand_id.tolist(),
        "i_brand": [f"brand#{b % 97}" for b in brand_id],
        "i_category_id": cat_id.tolist(),
        "i_category": [CATEGORIES[c - 1] for c in cat_id],
        # ids cycle so every query parameter selects a non-empty subset at
        # tiny scale factors (the spec's substitution parameters are drawn
        # from the populated domain the same way)
        "i_manufact_id": [(i * 13) % 20 + 1 for i in range(n_item)],
        "i_manufact": [f"manufact#{(i * 13) % 20 + 1}"
                       for i in range(n_item)],
        "i_manager_id": [(i * 7) % 40 + 1 for i in range(n_item)],
        "i_current_price": np.round(rng.uniform(0.5, 100.0, n_item),
                                    2).tolist(),
        "i_class_id": [(i * 3) % 16 + 1 for i in range(n_item)],
        "i_color": [["red", "blue", "green", "amber", "slate", "navy"]
                    [i % 6] for i in range(n_item)],
        "i_class": [f"class#{(i * 3) % 16 + 1}" for i in range(n_item)],
        "i_item_desc": [f"item description {i}" for i in range(n_item)],
    }

    # demographics is a CROSS PRODUCT in the spec (1,920,800 rows = every
    # combination repeated): cycle the 2x5x7 tuple space so every queried
    # tuple exists at any scale
    n_cd = max(70, int(1_920_800 * sf * 0.01))
    combos = [(g, m, e) for g in GENDERS for m in MARITAL
              for e in EDUCATION]
    out["customer_demographics"] = {
        "cd_demo_sk": list(range(1, n_cd + 1)),
        "cd_gender": [combos[i % 70][0] for i in range(n_cd)],
        "cd_marital_status": [combos[i % 70][1] for i in range(n_cd)],
        "cd_education_status": [combos[i % 70][2] for i in range(n_cd)],
        "cd_dep_count": rng.randint(0, 7, n_cd).tolist(),
        "cd_dep_employed_count": rng.randint(0, 7, n_cd).tolist(),
        "cd_dep_college_count": rng.randint(0, 7, n_cd).tolist(),
    }

    n_hd = max(10, int(7_200 * sf * 10))
    buy_potentials = [">10000", "5001-10000", "1001-5000", "501-1000",
                      "0-500", "Unknown"]
    out["household_demographics"] = {
        "hd_demo_sk": list(range(1, n_hd + 1)),
        "hd_dep_count": rng.randint(0, 10, n_hd).tolist(),
        "hd_vehicle_count": rng.randint(0, 5, n_hd).tolist(),
        "hd_buy_potential": [buy_potentials[i % 6] for i in range(n_hd)],
    }

    n_promo = max(5, int(300 * sf * 10))
    out["promotion"] = {
        "p_promo_sk": list(range(1, n_promo + 1)),
        "p_channel_email": ["Y" if r < 0.5 else "N"
                            for r in rng.rand(n_promo)],
        "p_channel_event": ["Y" if r < 0.3 else "N"
                            for r in rng.rand(n_promo)],
    }

    n_store = max(4, int(1_002 * sf * 2))
    states = ["TN", "SD", "AL", "GA", "MI", "OH", "TX", "CA"]
    counties = ["Williamson County", "Ziebach County", "Walker County",
                "Daviess County", "Barrow County", "Franklin Parish",
                "Luce County", "Richland County"]
    cities = ["Midway", "Fairview", "Oakland", "Springdale", "Union",
              "Salem", "Plainview", "Glendale"]
    out["store"] = {
        "s_store_sk": list(range(1, n_store + 1)),
        "s_store_name": [STORE_NAMES[i % len(STORE_NAMES)]
                         for i in range(n_store)],
        "s_zip": [f"{rng.randint(10000, 99999)}" for _ in range(n_store)],
        "s_number_employees": rng.randint(200, 301, n_store).tolist(),
        "s_company_name": [f"Unknown#{i % 3}" for i in range(n_store)],
        "s_state": [states[i % len(states)] for i in range(n_store)],
        "s_county": [counties[i % len(counties)] for i in range(n_store)],
        "s_city": [cities[i % len(cities)] for i in range(n_store)],
        "s_gmt_offset": [-5.0 if i % 2 else -6.0 for i in range(n_store)],
    }

    n_ca = max(20, int(50_000 * sf))
    out["customer_address"] = {
        "ca_address_sk": list(range(1, n_ca + 1)),
        "ca_zip": [f"{rng.randint(10000, 99999)}" for _ in range(n_ca)],
        "ca_gmt_offset": rng.choice([-10.0, -9.0, -8.0, -7.0, -6.0, -5.0],
                                    n_ca).tolist(),
        "ca_state": [states[i % len(states)] for i in range(n_ca)],
        "ca_county": [counties[i % len(counties)] for i in range(n_ca)],
        "ca_city": [cities[i % len(cities)] for i in range(n_ca)],
        "ca_country": ["United States"] * n_ca,
    }

    n_cust = max(30, int(100_000 * sf))
    out["customer"] = {
        "c_customer_sk": list(range(1, n_cust + 1)),
        "c_customer_id": [f"CUST{i:011d}" for i in range(1, n_cust + 1)],
        "c_current_addr_sk": rng.randint(1, n_ca + 1, n_cust).tolist(),
        "c_birth_month": rng.randint(1, 13, n_cust).tolist(),
        "c_current_cdemo_sk": rng.randint(1, n_cd + 1, n_cust).tolist(),
        "c_current_hdemo_sk": rng.randint(1, n_hd + 1, n_cust).tolist(),
        "c_first_name": [f"First{i % 997}" for i in range(n_cust)],
        "c_last_name": [f"Last{i % 991}" for i in range(n_cust)],
        "c_salutation": [["Mr.", "Mrs.", "Ms.", "Dr."][i % 4]
                         for i in range(n_cust)],
        "c_preferred_cust_flag": [["Y", "N"][i % 2] for i in range(n_cust)],
    }

    n_cc = max(2, int(6 * sf * 10))
    out["call_center"] = {
        "cc_call_center_sk": list(range(1, n_cc + 1)),
        "cc_name": [f"call center {i}" for i in range(1, n_cc + 1)],
    }

    n_ss = max(300, int(2_880_000 * sf))
    date_sks = np.array(out["date_dim"]["d_date_sk"])
    # a ticket covers ~4 line items sharing customer/demographics/address/
    # store/date (the spec generates baskets the same way) — the per-ticket
    # count queries (q34/q73) and ticket-grouped sums (q68) need real
    # multi-row tickets
    n_tick = (n_ss + 3) // 4
    per_tick = np.minimum(4, n_ss - 4 * np.arange(n_tick))

    def per_ticket(vals):
        return np.repeat(np.asarray(vals), per_tick)[:n_ss]
    # items are DISTINCT within a ticket so (ss_item_sk,
    # ss_ticket_number) is a key, like the spec's store_sales PK —
    # q93's sale->return join depends on it
    within = np.arange(n_ss) - np.repeat(4 * np.arange(n_tick),
                                         per_tick)[:n_ss]
    ss_items = ((per_ticket(rng.randint(0, n_item, n_tick)) + within)
                % n_item) + 1
    out["store_sales"] = {
        "ss_sold_date_sk": per_ticket(
            rng.choice(date_sks, n_tick)).tolist(),
        "ss_sold_time_sk": rng.randint(0, 1440, n_ss).tolist(),
        "ss_item_sk": ss_items.tolist(),
        "ss_customer_sk": per_ticket(
            rng.randint(1, n_cust + 1, n_tick)).tolist(),
        "ss_cdemo_sk": per_ticket(
            rng.randint(1, n_cd + 1, n_tick)).tolist(),
        "ss_hdemo_sk": per_ticket(
            rng.randint(1, n_hd + 1, n_tick)).tolist(),
        "ss_addr_sk": per_ticket(
            rng.randint(1, n_ca + 1, n_tick)).tolist(),
        "ss_store_sk": per_ticket(
            rng.randint(1, n_store + 1, n_tick)).tolist(),
        "ss_promo_sk": rng.randint(1, n_promo + 1, n_ss).tolist(),
        "ss_ticket_number": per_ticket(
            np.arange(1, n_tick + 1)).tolist(),
        "ss_quantity": rng.randint(1, 101, n_ss).tolist(),
        "ss_list_price": np.round(rng.uniform(1.0, 200.0, n_ss),
                                  2).tolist(),
        "ss_sales_price": np.round(rng.uniform(0.5, 180.0, n_ss),
                                   2).tolist(),
        "ss_ext_discount_amt": np.round(rng.uniform(0.0, 500.0, n_ss),
                                        2).tolist(),
        "ss_ext_sales_price": np.round(rng.uniform(1.0, 2000.0, n_ss),
                                       2).tolist(),
        "ss_ext_wholesale_cost": np.round(rng.uniform(1.0, 1000.0, n_ss),
                                          2).tolist(),
        "ss_coupon_amt": np.round(rng.uniform(0.0, 100.0, n_ss),
                                  2).tolist(),
        "ss_net_profit": np.round(rng.uniform(-500.0, 500.0, n_ss),
                                  2).tolist(),
    }
    # returns + catalog/web channels (q5's three-channel union).  Store
    # returns reference a sold ticket (customer, item, ticket_number) so
    # the multi-fact chains (q25/q29: sale -> return -> catalog re-purchase)
    # resolve at tiny scale factors.
    n_sr = max(60, int(287_000 * sf))
    # sample sale ROWS without replacement: with the per-ticket distinct
    # items above, (sr_item_sk, sr_ticket_number) is then a key, so the
    # q93-style left join can never fan out
    sr_pick = rng.choice(n_ss, size=min(n_sr, n_ss), replace=False)
    n_sr = len(sr_pick)
    out["store_returns"] = {
        "sr_returned_date_sk": rng.choice(date_sks, n_sr).tolist(),
        "sr_store_sk": rng.randint(1, n_store + 1, n_sr).tolist(),
        "sr_return_amt": np.round(rng.uniform(1.0, 800.0, n_sr),
                                  2).tolist(),
        "sr_net_loss": np.round(rng.uniform(0.5, 300.0, n_sr), 2).tolist(),
        "sr_item_sk": [out["store_sales"]["ss_item_sk"][i]
                       for i in sr_pick],
        "sr_customer_sk": [out["store_sales"]["ss_customer_sk"][i]
                           for i in sr_pick],
        "sr_ticket_number": [out["store_sales"]["ss_ticket_number"][i]
                             for i in sr_pick],
        "sr_return_quantity": rng.randint(1, 51, n_sr).tolist(),
    }

    n_cp = max(6, int(11_718 * sf))
    out["catalog_page"] = {
        "cp_catalog_page_sk": list(range(1, n_cp + 1)),
        "cp_catalog_page_id": [f"CPAG{i:012d}" for i in range(1, n_cp + 1)],
    }

    n_cs = max(150, int(1_440_000 * sf))
    out["catalog_sales"] = {
        "cs_sold_date_sk": rng.choice(date_sks, n_cs).tolist(),
        "cs_catalog_page_sk": rng.randint(1, n_cp + 1, n_cs).tolist(),
        "cs_item_sk": rng.randint(1, n_item + 1, n_cs).tolist(),
        "cs_order_number": list(range(1, n_cs + 1)),
        "cs_ext_sales_price": np.round(rng.uniform(1.0, 2000.0, n_cs),
                                       2).tolist(),
        "cs_net_profit": np.round(rng.uniform(-400.0, 600.0, n_cs),
                                  2).tolist(),
        "cs_bill_customer_sk": rng.randint(1, n_cust + 1, n_cs).tolist(),
        "cs_ship_customer_sk": rng.randint(1, n_cust + 1, n_cs).tolist(),
        "cs_bill_cdemo_sk": rng.randint(1, n_cd + 1, n_cs).tolist(),
        "cs_call_center_sk": rng.randint(1, n_cc + 1, n_cs).tolist(),
        "cs_promo_sk": rng.randint(1, n_promo + 1, n_cs).tolist(),
        "cs_quantity": rng.randint(1, 101, n_cs).tolist(),
        "cs_list_price": np.round(rng.uniform(1.0, 200.0, n_cs),
                                  2).tolist(),
        "cs_sales_price": np.round(rng.uniform(0.5, 180.0, n_cs),
                                   2).tolist(),
        "cs_coupon_amt": np.round(rng.uniform(0.0, 100.0, n_cs),
                                  2).tolist(),
        "cs_bill_addr_sk": rng.randint(1, n_ca + 1, n_cs).tolist(),
    }

    n_cr = max(30, int(144_000 * sf))
    out["catalog_returns"] = {
        "cr_returned_date_sk": rng.choice(date_sks, n_cr).tolist(),
        "cr_catalog_page_sk": rng.randint(1, n_cp + 1, n_cr).tolist(),
        "cr_return_amount": np.round(rng.uniform(1.0, 900.0, n_cr),
                                     2).tolist(),
        "cr_net_loss": np.round(rng.uniform(0.5, 400.0, n_cr), 2).tolist(),
    }

    n_web = max(3, int(30 * sf * 10))
    out["web_site"] = {
        "web_site_sk": list(range(1, n_web + 1)),
        "web_site_id": [f"WSIT{i:012d}" for i in range(1, n_web + 1)],
    }

    n_ws = max(100, int(720_000 * sf))
    out["web_sales"] = {
        "ws_sold_date_sk": rng.choice(date_sks, n_ws).tolist(),
        "ws_web_site_sk": rng.randint(1, n_web + 1, n_ws).tolist(),
        "ws_item_sk": rng.randint(1, n_item + 1, n_ws).tolist(),
        "ws_order_number": list(range(1, n_ws + 1)),
        "ws_ext_sales_price": np.round(rng.uniform(1.0, 1500.0, n_ws),
                                       2).tolist(),
        "ws_net_profit": np.round(rng.uniform(-300.0, 500.0, n_ws),
                                  2).tolist(),
        "ws_bill_customer_sk": rng.randint(1, n_cust + 1, n_ws).tolist(),
        "ws_bill_addr_sk": rng.randint(1, n_ca + 1, n_ws).tolist(),
        "ws_ext_discount_amt": np.round(rng.uniform(0.0, 500.0, n_ws),
                                        2).tolist(),
    }

    # catalog/web orders span ~3 line items (dsdgen baskets), so the
    # multi-warehouse-order EXISTS queries (q16/q94/q95) have real
    # multi-row orders to find.  A reassignment, not a draw, placed
    # BEFORE web_returns/catalog_returns copy order numbers from their
    # sales rows, so returns stay consistent with their orders.
    out["catalog_sales"]["cs_order_number"] = \
        [i // 3 + 1 for i in range(n_cs)]
    out["web_sales"]["ws_order_number"] = \
        [i // 3 + 1 for i in range(n_ws)]

    # omni-channel overlap: the set-operation queries (q38 INTERSECT /
    # q87 EXCEPT) compare (customer, date) sets ACROSS channels, and at
    # tiny scale factors independent uniform draws never collide — pin
    # the first rows of each channel to the same customers on the same
    # day so the intersect is provably non-empty at any sf
    k_omni = min(25, n_cust, n_ss, n_cs, n_ws)
    d_omni = int(date_sks[800])  # a 2000 date inside the q38/q87 window
    for i in range(k_omni):
        out["store_sales"]["ss_sold_date_sk"][i] = d_omni
        out["store_sales"]["ss_customer_sk"][i] = i + 1
        out["catalog_sales"]["cs_sold_date_sk"][i] = d_omni
        out["catalog_sales"]["cs_bill_customer_sk"][i] = i + 1
        out["web_sales"]["ws_sold_date_sk"][i] = d_omni
        out["web_sales"]["ws_bill_customer_sk"][i] = i + 1

    # ...and STORE-ONLY customers for the EXCEPT/anti queries (q69/q87):
    # the last k_solo customers get store activity in 2000 but every
    # web/catalog row of theirs is remapped to an omni customer, and
    # their address pins to ca 1 (state TN) so state filters keep them
    k_solo = min(12, n_cust // 4)
    solo = set(range(n_cust - k_solo + 1, n_cust + 1))
    for i, c in enumerate(out["web_sales"]["ws_bill_customer_sk"]):
        if c in solo:
            out["web_sales"]["ws_bill_customer_sk"][i] = 1 + i % k_omni
    for key in ("cs_bill_customer_sk", "cs_ship_customer_sk"):
        for i, c in enumerate(out["catalog_sales"][key]):
            if c in solo:
                out["catalog_sales"][key][i] = 1 + i % k_omni
    for j, c in enumerate(sorted(solo)):
        out["store_sales"]["ss_sold_date_sk"][k_omni + j] = d_omni
        out["store_sales"]["ss_customer_sk"][k_omni + j] = c
        out["customer"]["c_current_addr_sk"][c - 1] = 1  # TN address

    # web returns reference a sold web order (item, order) so the q5 left
    # join resolves a site for most returns
    n_wr = max(20, int(72_000 * sf))
    wr_pick = rng.randint(0, n_ws, n_wr)
    out["web_returns"] = {
        "wr_returned_date_sk": rng.choice(date_sks, n_wr).tolist(),
        "wr_item_sk": [out["web_sales"]["ws_item_sk"][i] for i in wr_pick],
        "wr_order_number": [out["web_sales"]["ws_order_number"][i]
                            for i in wr_pick],
        "wr_return_amt": np.round(rng.uniform(1.0, 700.0, n_wr),
                                  2).tolist(),
        "wr_net_loss": np.round(rng.uniform(0.5, 350.0, n_wr), 2).tolist(),
    }
    # inventory snapshots (spec: weekly per item x warehouse; sampled)
    n_wh = max(3, int(20 * sf * 5))
    out["warehouse"] = {
        "w_warehouse_sk": list(range(1, n_wh + 1)),
        "w_warehouse_name": [f"warehouse {i}"
                             for i in range(1, n_wh + 1)],
    }
    # weekly snapshots for every (item, warehouse) pair, like dsdgen's
    # inventory (items capped so the row count stays bounded at bench
    # scale factors; the variability queries q39/q21 need every pair
    # present in every month, not a sparse random sample)
    inv_items = min(n_item, 400)
    weekly = date_sks[::7]
    wk, it_, wh_ = np.meshgrid(weekly, np.arange(1, inv_items + 1),
                               np.arange(1, n_wh + 1), indexing="ij")
    n_inv = wk.size
    out["inventory"] = {
        "inv_date_sk": wk.ravel().tolist(),
        "inv_item_sk": it_.ravel().tolist(),
        "inv_warehouse_sk": wh_.ravel().tolist(),
        "inv_quantity_on_hand": rng.randint(0, 1000, n_inv).tolist(),
    }

    out["reason"] = {
        "r_reason_sk": list(range(1, 10)),
        "r_reason_desc": [f"reason {i}" for i in range(1, 10)],
    }
    # store returns carry a reason for q93's per-reason adjustment
    out["store_returns"]["sr_reason_sk"] = \
        rng.randint(1, 10, n_sr).tolist()

    # ---------------------------------------------------------------
    # Columns and tables for the shipping/returns/demographic queries
    # (q16/q24/q30/q32/q40/q49/q62/q66/q71/q72/q75-q78/q80/q81/q83-q85/
    # q90/q91/q94/q95/q99).  ALL new draws happen after every original
    # draw so the original columns' rng stream — and therefore every
    # already-anchored query result — is unchanged.
    # ---------------------------------------------------------------
    sm_types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
    sm_carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS"]
    out["ship_mode"] = {
        "sm_ship_mode_sk": list(range(1, 21)),
        "sm_type": [sm_types[i % 5] for i in range(20)],
        "sm_carrier": [sm_carriers[i % 4] for i in range(20)],
    }
    n_wp = max(5, int(60 * sf * 10))
    out["web_page"] = {
        "wp_web_page_sk": list(range(1, n_wp + 1)),
        "wp_char_count": rng.randint(2500, 7500, n_wp).tolist(),
    }
    out["income_band"] = {
        "ib_income_band_sk": list(range(1, 21)),
        "ib_lower_bound": [i * 10_000 for i in range(20)],
        "ib_upper_bound": [(i + 1) * 10_000 for i in range(20)],
    }
    out["household_demographics"]["hd_income_band_sk"] = \
        rng.randint(1, 21, n_hd).tolist()
    # deterministic cycle: every market id up to n_store exists, so
    # q24's single-market cut is never empty
    out["store"]["s_market_id"] = \
        [(i % 10) + 1 for i in range(n_store)]
    countries = ["UNITED STATES", "CANADA", "MEXICO", "BRAZIL", "JAPAN",
                 "GERMANY"]
    out["customer"]["c_birth_country"] = \
        [countries[i % 6] for i in range(n_cust)]
    out["store_returns"]["sr_cdemo_sk"] = \
        rng.randint(1, n_cd + 1, n_sr).tolist()

    # web_sales: quantities/prices, shipping control plane, promo/time/
    # page keys.  Ship date = sold date + a 1..120-day lag (date_sks are
    # consecutive, so sk arithmetic IS date arithmetic), clipped to the
    # calendar.
    last_sk = int(date_sks[-1])
    ws_sold = np.asarray(out["web_sales"]["ws_sold_date_sk"])
    out["web_sales"].update({
        "ws_quantity": rng.randint(1, 101, n_ws).tolist(),
        "ws_list_price": np.round(rng.uniform(1.0, 200.0, n_ws),
                                  2).tolist(),
        "ws_sales_price": np.round(rng.uniform(0.5, 180.0, n_ws),
                                   2).tolist(),
        "ws_ship_date_sk": np.minimum(
            ws_sold + rng.randint(1, 121, n_ws), last_sk).tolist(),
        "ws_warehouse_sk": rng.randint(1, n_wh + 1, n_ws).tolist(),
        "ws_ship_mode_sk": rng.randint(1, 21, n_ws).tolist(),
        "ws_promo_sk": rng.randint(1, n_promo + 1, n_ws).tolist(),
        "ws_sold_time_sk": rng.randint(0, 1440, n_ws).tolist(),
        "ws_web_page_sk": rng.randint(1, n_wp + 1, n_ws).tolist(),
        "ws_ship_customer_sk": rng.randint(1, n_cust + 1, n_ws).tolist(),
        "ws_ship_addr_sk": rng.randint(1, n_ca + 1, n_ws).tolist(),
        "ws_ship_hdemo_sk": rng.randint(1, n_hd + 1, n_ws).tolist(),
    })
    cs_sold = np.asarray(out["catalog_sales"]["cs_sold_date_sk"])
    out["catalog_sales"].update({
        "cs_ship_date_sk": np.minimum(
            cs_sold + rng.randint(1, 121, n_cs), last_sk).tolist(),
        "cs_ship_mode_sk": rng.randint(1, 21, n_cs).tolist(),
        "cs_warehouse_sk": rng.randint(1, n_wh + 1, n_cs).tolist(),
        "cs_ship_addr_sk": rng.randint(1, n_ca + 1, n_cs).tolist(),
        "cs_ext_discount_amt": np.round(rng.uniform(0.0, 500.0, n_cs),
                                        2).tolist(),
        "cs_sold_time_sk": rng.randint(0, 1440, n_cs).tolist(),
        "cs_ship_hdemo_sk": rng.randint(1, n_hd + 1, n_cs).tolist(),
    })
    # catalog returns reference a sold catalog order (item, order) the
    # way web_returns reference web orders, so return-aware catalog
    # queries (q16/q49/q78/q83) resolve
    cr_pick = rng.randint(0, n_cs, n_cr)
    out["catalog_returns"].update({
        "cr_item_sk": [out["catalog_sales"]["cs_item_sk"][i]
                       for i in cr_pick],
        "cr_order_number": [out["catalog_sales"]["cs_order_number"][i]
                            for i in cr_pick],
        "cr_call_center_sk": rng.randint(1, n_cc + 1, n_cr).tolist(),
        "cr_returning_customer_sk":
            rng.randint(1, n_cust + 1, n_cr).tolist(),
        "cr_return_quantity": rng.randint(1, 51, n_cr).tolist(),
    })
    out["web_returns"].update({
        "wr_returning_customer_sk":
            rng.randint(1, n_cust + 1, n_wr).tolist(),
        "wr_reason_sk": rng.randint(1, 10, n_wr).tolist(),
        "wr_return_quantity": rng.randint(1, 51, n_wr).tolist(),
        "wr_refunded_cdemo_sk": rng.randint(1, n_cd + 1, n_wr).tolist(),
        "wr_refunded_addr_sk": rng.randint(1, n_ca + 1, n_wr).tolist(),
        "wr_web_page_sk": rng.randint(1, n_wp + 1, n_wr).tolist(),
    })
    # the refunding and returning person are the same household (as in
    # dsdgen), so q85's paired-demographics equality can match
    out["web_returns"]["wr_returning_cdemo_sk"] = \
        list(out["web_returns"]["wr_refunded_cdemo_sk"])
    # stores share the customer-address zip space so q24's zip equi-join
    # resolves (an override, not a draw: the rng stream is untouched)
    out["store"]["s_zip"] = [out["customer_address"]["ca_zip"][i % n_ca]
                             for i in range(n_store)]

    # q76's NULL-key channel rows (dsdgen leaves these fks null for a
    # fraction of rows; every other query inner-joins them away on both
    # engines).  The nulled slice starts past the pinned omni/solo rows.
    null_n = max(6, n_ss // 200)
    lo = k_omni + k_solo + 2
    for i in range(lo, min(lo + null_n, n_ss)):
        out["store_sales"]["ss_store_sk"][i] = None
    for i in range(min(null_n, n_ws)):
        out["web_sales"]["ws_ship_customer_sk"][i] = None
    for i in range(min(null_n, n_cs)):
        out["catalog_sales"]["cs_ship_addr_sk"][i] = None
    return out


def load_tables(session, sf: float = 0.001, seed: int = 7):
    """{name: DataFrame} on the given session (cached arrow tables)."""
    from .schema import SCHEMAS
    from .._cache import cached_load
    return cached_load("tpcds", generate, SCHEMAS, session, sf, seed)
