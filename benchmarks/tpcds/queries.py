"""TPC-DS star-join queries in the DataFrame API (public TPC-DS spec
templates, expressed in this repo's own DSL — BASELINE.md staged config 3).

Each `qN(t)` takes {table_name: DataFrame} and returns a DataFrame.  The
shapes exercised: dimension broadcast joins into the store_sales fact,
multi-dimension chains, string-prefix anti-conditions (q19), and the
pure-count multi-way join (q96)."""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit


def q3(t):
    """Brand revenue by year for one manufacturer in November."""
    dd = t["date_dim"].filter(col("d_moy") == 11)
    it = t["item"].filter(col("i_manufact_id") == 12)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_discount_amt")).alias("sum_agg"))
            .order_by(col("d_year"), col("sum_agg").desc(),
                      col("i_brand_id"))
            .limit(100))


def q5(t):
    """Sales/returns/profit per channel over a 14-day window, rolled up by
    (channel, id) — the reference's headline TPCxBB-era shape: three
    union'd sales+returns channels, a dimension join each, and a ROLLUP
    aggregate (BASELINE staged config 3)."""
    dd = t["date_dim"].filter((col("d_date") >= "2000-08-23")
                              & (col("d_date") <= "2000-09-06"))

    def channel(sales, returns, sales_cols, ret_cols, dim, dim_key,
                dim_id, label):
        """One channel: union sales rows (returns zeroed) with return rows
        (sales zeroed), join the date window and the channel dimension,
        aggregate per dimension id."""
        s_key, s_date, s_price, s_profit = sales_cols
        r_key, r_date, r_amt, r_loss = ret_cols
        s_part = sales.select(
            col(s_key).alias("page_sk"), col(s_date).alias("date_sk"),
            col(s_price).alias("sales_price"),
            col(s_profit).alias("profit"),
            (col(s_price) * 0.0).alias("return_amt"),
            (col(s_price) * 0.0).alias("net_loss"))
        r_part = returns.select(
            col(r_key).alias("page_sk"), col(r_date).alias("date_sk"),
            (col(r_amt) * 0.0).alias("sales_price"),
            (col(r_amt) * 0.0).alias("profit"),
            col(r_amt).alias("return_amt"), col(r_loss).alias("net_loss"))
        return (s_part.union(r_part)
                .join(dd, on=col("date_sk") == col("d_date_sk"))
                .join(dim, on=col("page_sk") == col(dim_key))
                .group_by(col(dim_id))
                .agg(F.sum(col("sales_price")).alias("sales"),
                     F.sum(col("return_amt")).alias("returns"),
                     F.sum(col("profit") - col("net_loss")).alias("profit"))
                .select(lit(label).alias("channel"),
                        col(dim_id).alias("id"), col("sales"),
                        col("returns"), col("profit")))

    ssr = channel(
        t["store_sales"], t["store_returns"],
        ("ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
         "ss_net_profit"),
        ("sr_store_sk", "sr_returned_date_sk", "sr_return_amt",
         "sr_net_loss"),
        t["store"], "s_store_sk", "s_store_name", "store channel")
    csr = channel(
        t["catalog_sales"], t["catalog_returns"],
        ("cs_catalog_page_sk", "cs_sold_date_sk", "cs_ext_sales_price",
         "cs_net_profit"),
        ("cr_catalog_page_sk", "cr_returned_date_sk", "cr_return_amount",
         "cr_net_loss"),
        t["catalog_page"], "cp_catalog_page_sk", "cp_catalog_page_id",
        "catalog channel")
    # web returns resolve their site through the originating sale
    # (left outer on item+order, the spec's join)
    wr = (t["web_returns"]
          .join(t["web_sales"]
                .select(col("ws_item_sk").alias("wsi"),
                        col("ws_order_number").alias("wso"),
                        col("ws_web_site_sk").alias("site_sk")),
                on=(col("wr_item_sk") == col("wsi"))
                & (col("wr_order_number") == col("wso")), how="left")
          .select(col("site_sk").alias("wr_site_sk"),
                  col("wr_returned_date_sk"), col("wr_return_amt"),
                  col("wr_net_loss")))
    wsr = channel(
        t["web_sales"], wr,
        ("ws_web_site_sk", "ws_sold_date_sk", "ws_ext_sales_price",
         "ws_net_profit"),
        ("wr_site_sk", "wr_returned_date_sk", "wr_return_amt",
         "wr_net_loss"),
        t["web_site"], "web_site_sk", "web_site_id", "web channel")

    return (ssr.union(csr).union(wsr)
            .rollup(col("channel"), col("id"))
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns")).alias("returns"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel"), col("id"))
            .limit(100))


def q7(t):
    """Average sales metrics per item for one demographics tuple with a
    non-event/non-email promotion."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(pr, on=col("ss_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q19(t):
    """Brand revenue where the customer's zip prefix differs from the
    store's (out-of-neighborhood purchases)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1998))
    it = t["item"].filter(col("i_manager_id") == 8)
    joined = (dd.join(t["store_sales"],
                      on=col("d_date_sk") == col("ss_sold_date_sk"))
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(t["customer"],
                    on=col("ss_customer_sk") == col("c_customer_sk"))
              .join(t["customer_address"],
                    on=col("c_current_addr_sk") == col("ca_address_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .filter(F.substring(col("ca_zip"), 1, 5)
                      != F.substring(col("s_zip"), 1, 5)))
    return (joined
            .group_by(col("i_brand_id"), col("i_brand"),
                      col("i_manufact_id"), col("i_manufact"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand"),
                      col("i_brand_id"), col("i_manufact_id"),
                      col("i_manufact"))
            .limit(100))


def q42(t):
    """Category revenue for one manager's items in November."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_category_id"),
                      col("i_category"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("d_year"),
                      col("i_category_id"), col("i_category"))
            .limit(100))


def q52(t):
    """Brand revenue for one manager's items in November (brand cut of
    q42)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand"), col("i_brand_id"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year"), col("ext_price").desc(),
                      col("i_brand_id"))
            .limit(100))


def q55(t):
    """Brand revenue for one manager in one month."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1999))
    it = t["item"].filter(col("i_manager_id") == 28)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id"))
            .limit(100))


def q96(t):
    """Count of evening purchases by high-dependent-count households at
    one store."""
    td = t["time_dim"].filter((col("t_hour") == 20)
                              & (col("t_minute") >= 30))
    hd = t["household_demographics"].filter(col("hd_dep_count") == 7)
    st = t["store"].filter(col("s_store_name") == "ese")
    return (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count(lit(1)).alias("cnt")))


QUERIES = {3: q3, 5: q5, 7: q7, 19: q19, 42: q42, 52: q52, 55: q55,
           96: q96}
