"""The FULL TPC-DS query suite, q1-q99, in the DataFrame API (public
TPC-DS spec templates, expressed in this repo's own DSL — BASELINE.md
staged config 3; breadth model: the reference's TPC-DS/TPCxBB drivers
under integration_tests).

Each `qN(t)` takes {table_name: DataFrame} and returns a DataFrame.
Every query shape in the spec is exercised: star joins, multi-fact
chains, EXISTS/NOT-EXISTS rewrites (semi/anti joins), INTERSECT/EXCEPT
(semi/anti chains), year-over-year self joins, rank/cumulative windows
over aggregates, ROLLUPs, FULL OUTER channel joins, and scalar-subquery
composition (driver-side, the tpch q11/q15/q22 convention).

Tiny-scale-factor conventions, applied consistently and documented per
query: substitution parameters are chosen from the generator's populated
domains (the spec draws them from the data the same way); a handful of
1-in-N single-bin predicates are widened to a band of bins when one bin
of a tiny table selects nothing; monthly granularity stands in for the
spec's week_seq, which the tiny date_dim does not carry; and columns the
tiny tables do not carry use the closest generated stand-in (noted in
each docstring)."""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit


def q3(t):
    """Brand revenue by year for one manufacturer in November."""
    dd = t["date_dim"].filter(col("d_moy") == 11)
    it = t["item"].filter(col("i_manufact_id") == 12)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_discount_amt")).alias("sum_agg"))
            .order_by(col("d_year"), col("sum_agg").desc(),
                      col("i_brand_id"))
            .limit(100))


def q5(t):
    """Sales/returns/profit per channel over a 14-day window, rolled up by
    (channel, id) — the reference's headline TPCxBB-era shape: three
    union'd sales+returns channels, a dimension join each, and a ROLLUP
    aggregate (BASELINE staged config 3)."""
    dd = t["date_dim"].filter((col("d_date") >= "2000-08-23")
                              & (col("d_date") <= "2000-09-06"))

    def channel(sales, returns, sales_cols, ret_cols, dim, dim_key,
                dim_id, label):
        """One channel: union sales rows (returns zeroed) with return rows
        (sales zeroed), join the date window and the channel dimension,
        aggregate per dimension id."""
        s_key, s_date, s_price, s_profit = sales_cols
        r_key, r_date, r_amt, r_loss = ret_cols
        s_part = sales.select(
            col(s_key).alias("page_sk"), col(s_date).alias("date_sk"),
            col(s_price).alias("sales_price"),
            col(s_profit).alias("profit"),
            (col(s_price) * 0.0).alias("return_amt"),
            (col(s_price) * 0.0).alias("net_loss"))
        r_part = returns.select(
            col(r_key).alias("page_sk"), col(r_date).alias("date_sk"),
            (col(r_amt) * 0.0).alias("sales_price"),
            (col(r_amt) * 0.0).alias("profit"),
            col(r_amt).alias("return_amt"), col(r_loss).alias("net_loss"))
        return (s_part.union(r_part)
                .join(dd, on=col("date_sk") == col("d_date_sk"))
                .join(dim, on=col("page_sk") == col(dim_key))
                .group_by(col(dim_id))
                .agg(F.sum(col("sales_price")).alias("sales"),
                     F.sum(col("return_amt")).alias("returns"),
                     F.sum(col("profit") - col("net_loss")).alias("profit"))
                .select(lit(label).alias("channel"),
                        col(dim_id).alias("id"), col("sales"),
                        col("returns"), col("profit")))

    ssr = channel(
        t["store_sales"], t["store_returns"],
        ("ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
         "ss_net_profit"),
        ("sr_store_sk", "sr_returned_date_sk", "sr_return_amt",
         "sr_net_loss"),
        t["store"], "s_store_sk", "s_store_name", "store channel")
    csr = channel(
        t["catalog_sales"], t["catalog_returns"],
        ("cs_catalog_page_sk", "cs_sold_date_sk", "cs_ext_sales_price",
         "cs_net_profit"),
        ("cr_catalog_page_sk", "cr_returned_date_sk", "cr_return_amount",
         "cr_net_loss"),
        t["catalog_page"], "cp_catalog_page_sk", "cp_catalog_page_id",
        "catalog channel")
    # web returns resolve their site through the originating sale
    # (left outer on item+order, the spec's join)
    wr = (t["web_returns"]
          .join(t["web_sales"]
                .select(col("ws_item_sk").alias("wsi"),
                        col("ws_order_number").alias("wso"),
                        col("ws_web_site_sk").alias("site_sk")),
                on=(col("wr_item_sk") == col("wsi"))
                & (col("wr_order_number") == col("wso")), how="left")
          .select(col("site_sk").alias("wr_site_sk"),
                  col("wr_returned_date_sk"), col("wr_return_amt"),
                  col("wr_net_loss")))
    wsr = channel(
        t["web_sales"], wr,
        ("ws_web_site_sk", "ws_sold_date_sk", "ws_ext_sales_price",
         "ws_net_profit"),
        ("wr_site_sk", "wr_returned_date_sk", "wr_return_amt",
         "wr_net_loss"),
        t["web_site"], "web_site_sk", "web_site_id", "web channel")

    return (ssr.union(csr).union(wsr)
            .rollup(col("channel"), col("id"))
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns")).alias("returns"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel"), col("id"))
            .limit(100))


def q7(t):
    """Average sales metrics per item for one demographics tuple with a
    non-event/non-email promotion."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(pr, on=col("ss_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q19(t):
    """Brand revenue where the customer's zip prefix differs from the
    store's (out-of-neighborhood purchases)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1998))
    it = t["item"].filter(col("i_manager_id") == 8)
    joined = (dd.join(t["store_sales"],
                      on=col("d_date_sk") == col("ss_sold_date_sk"))
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(t["customer"],
                    on=col("ss_customer_sk") == col("c_customer_sk"))
              .join(t["customer_address"],
                    on=col("c_current_addr_sk") == col("ca_address_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .filter(F.substring(col("ca_zip"), 1, 5)
                      != F.substring(col("s_zip"), 1, 5)))
    return (joined
            .group_by(col("i_brand_id"), col("i_brand"),
                      col("i_manufact_id"), col("i_manufact"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand"),
                      col("i_brand_id"), col("i_manufact_id"),
                      col("i_manufact"))
            .limit(100))


def q42(t):
    """Category revenue for one manager's items in November."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_category_id"),
                      col("i_category"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("d_year"),
                      col("i_category_id"), col("i_category"))
            .limit(100))


def q52(t):
    """Brand revenue for one manager's items in November (brand cut of
    q42)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand"), col("i_brand_id"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year"), col("ext_price").desc(),
                      col("i_brand_id"))
            .limit(100))


def q55(t):
    """Brand revenue for one manager in one month."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1999))
    it = t["item"].filter(col("i_manager_id") == 28)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id"))
            .limit(100))


def q96(t):
    """Count of evening purchases by high-dependent-count households at
    one store."""
    td = t["time_dim"].filter((col("t_hour") == 20)
                              & (col("t_minute") >= 30))
    hd = t["household_demographics"].filter(col("hd_dep_count") == 7)
    st = t["store"].filter(col("s_store_name") == "ese")
    return (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count(lit(1)).alias("cnt")))


# --------------------------------------------------------------------------
# round-4 breadth tier: the operator shapes the first 8 queries miss —
# EXISTS/IN rewrites (q10/q35), windows over joins (q47/q57/q89), multi-
# fact chains (q25/q29), scalar subqueries (q6/q65), ticket-grouped counts
# (q34/q73/q68), day-of-week pivots (q43), OR-branch demographic filters
# (q13/q48).  Public TPC-DS spec templates in this repo's DSL; parameter
# windows widened where the tiny-sf generator would otherwise select empty
# sets (each docstring notes it).  Reference breadth model:
# integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala.
# --------------------------------------------------------------------------


def q6(t):
    """States whose customers bought items priced >= 1.2x their category
    average in one month (scalar subquery for the month_seq + per-category
    average join)."""
    month_seq = t["date_dim"].filter((col("d_year") == 2001)
                                     & (col("d_moy") == 1)) \
        .agg(F.min(col("d_month_seq")).alias("m")).collect()[0][0]
    dd = t["date_dim"].filter(col("d_month_seq") == month_seq)
    cat_avg = (t["item"].group_by(col("i_category"))
               .agg(F.avg(col("i_current_price")).alias("cat_price"))
               .select(col("i_category").alias("avg_cat"),
                       col("cat_price")))
    it = (t["item"].join(cat_avg, on=col("i_category") == col("avg_cat"))
          .filter(col("i_current_price") > 1.2 * col("cat_price")))
    return (t["customer_address"]
            .join(t["customer"],
                  on=col("ca_address_sk") == col("c_current_addr_sk"))
            .join(t["store_sales"],
                  on=col("c_customer_sk") == col("ss_customer_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("ca_state"))
            .agg(F.count(lit(1)).alias("cnt"))
            .filter(col("cnt") >= 1)  # spec: >= 10 (SF1000 scale)
            .order_by(col("cnt"), col("ca_state"))
            .limit(100))


_DATE_KEY = {"ss_cust": "ss_sold_date_sk", "ws_cust": "ws_sold_date_sk",
             "cs_cust": "cs_sold_date_sk"}


def _active_customers(t, sales, cust_key, alias):
    """Distinct customers with activity in 2000 (the EXISTS rewrite:
    aggregate-then-join, how Spark plans the subquery)."""
    dd = t["date_dim"].filter(col("d_year") == 2000)
    return (sales.join(dd, on=col(_DATE_KEY[alias]) == col("d_date_sk"))
            .group_by(col(cust_key))
            .agg(F.count(lit(1)).alias("_c"))
            .select(col(cust_key).alias(alias)))


def _channel_activity(t):
    """Distinct active-customer sets per channel in the year-2000 window
    (shared by the q10/q35/q69 EXISTS rewrites)."""
    return (_active_customers(t, t["store_sales"], "ss_customer_sk",
                              "ss_cust"),
            _active_customers(t, t["web_sales"], "ws_bill_customer_sk",
                              "ws_cust"),
            _active_customers(t, t["catalog_sales"],
                              "cs_ship_customer_sk", "cs_cust"))


def q10(t):
    """Demographics counts for customers in selected counties with a store
    purchase AND (a web OR a catalog purchase) in the year — the
    EXISTS/left-semi + existence-flag rewrite."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    ca = t["customer_address"].filter(col("ca_county").isin(
        "Williamson County", "Walker County", "Ziebach County"))
    return (t["customer"]
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left")
            .filter(~(col("ws_cust").is_null()
                      & col("cs_cust").is_null()))
            .group_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.min(col("cd_dep_count")).alias("min_dep"),
                 F.max(col("cd_dep_count")).alias("max_dep"),
                 F.avg(col("cd_dep_count")).alias("avg_dep"))
            .order_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .limit(100))


def _revenue_ratio(sales_joined, revenue_col):
    """Shared q12/q20/q98 tail: per-item revenue + class-partitioned
    revenue ratio window."""
    from spark_rapids_tpu.plan.logical import Window
    grouped = (sales_joined
               .group_by(col("i_item_id"), col("i_item_desc"),
                         col("i_category"), col("i_class"),
                         col("i_current_price"))
               .agg(F.sum(col(revenue_col)).alias("itemrevenue")))
    w = Window.partition_by(col("i_class"))
    return (grouped
            .with_column("revenueratio",
                         col("itemrevenue") * lit(100.0)
                         / F.sum(col("itemrevenue")).over(w))
            .order_by(col("i_category"), col("i_class"), col("i_item_id"),
                      col("i_item_desc"), col("revenueratio"))
            .limit(100))


def q12(t):
    """Web revenue ratio by item within class (window over join).  Date
    window widened to the year (spec: 30 days) for tiny-sf population."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["web_sales"]
              .join(it, on=col("ws_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "ws_ext_sales_price")


def q13(t):
    """Averages under OR'd demographic x household x address branches."""
    cd, hd, ca = (t["customer_demographics"], t["household_demographics"],
                  t["customer_address"])
    dd = t["date_dim"].filter(col("d_year") == 2001)
    demo_ok = (
        ((col("cd_marital_status") == "M")
         & (col("cd_education_status") == "Advanced Degree")
         & col("ss_sales_price").between(100.0, 150.0)
         & (col("hd_dep_count") == 3))
        | ((col("cd_marital_status") == "S")
           & (col("cd_education_status") == "College")
           & col("ss_sales_price").between(50.0, 100.0)
           & (col("hd_dep_count") == 1))
        | ((col("cd_marital_status") == "W")
           & (col("cd_education_status") == "2 yr Degree")
           & col("ss_sales_price").between(150.0, 200.0)
           & (col("hd_dep_count") == 1)))
    addr_ok = (
        (col("ca_state").isin("TX", "OH", "TN")
         & col("ss_net_profit").between(100.0, 200.0))
        | (col("ca_state").isin("OR", "NM", "KY")
           & col("ss_net_profit").between(150.0, 300.0))
        | (col("ca_state").isin("VA", "TX", "MS")
           & col("ss_net_profit").between(50.0, 250.0)))
    return (t["store_sales"]
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(ca, on=col("ss_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(demo_ok & addr_ok
                    & (col("ca_country") == "United States"))
            .agg(F.avg(col("ss_quantity")).alias("avg_qty"),
                 F.avg(col("ss_ext_sales_price")).alias("avg_price"),
                 F.avg(col("ss_ext_wholesale_cost")).alias("avg_cost"),
                 F.sum(col("ss_ext_wholesale_cost")).alias("sum_cost")))


def q15(t):
    """Catalog revenue per customer zip for select zips/states or big
    tickets."""
    dd = t["date_dim"].filter((col("d_qoy") == 2)
                              & (col("d_year") == 2001))
    return (t["catalog_sales"]
            .join(t["customer"],
                  on=col("cs_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5).isin(
                "85669", "86197", "88274", "83405", "86475")
                | col("ca_state").isin("CA", "GA", "TX")
                | (col("cs_sales_price") > 500.0))
            .group_by(col("ca_zip"))
            .agg(F.sum(col("cs_sales_price")).alias("total"))
            .order_by(col("ca_zip"))
            .limit(100))


def q20(t):
    """Catalog revenue ratio by item within class (q12's catalog twin)."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["catalog_sales"]
              .join(it, on=col("cs_item_sk") == col("i_item_sk"))
              .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "cs_ext_sales_price")


def _sale_return_catalog(t, d1_filter, d2_filter, d3_filter):
    """q25/q29 chain: store sale -> its return -> catalog re-purchase by
    the same customer of the same item, each leg date-filtered."""
    d1 = t["date_dim"].filter(d1_filter).select(col("d_date_sk")
                                                .alias("d1_sk"))
    d2 = t["date_dim"].filter(d2_filter).select(col("d_date_sk")
                                                .alias("d2_sk"))
    d3 = t["date_dim"].filter(d3_filter).select(col("d_date_sk")
                                                .alias("d3_sk"))
    return (t["store_sales"]
            .join(t["store_returns"],
                  on=(col("ss_customer_sk") == col("sr_customer_sk"))
                  & (col("ss_item_sk") == col("sr_item_sk"))
                  & (col("ss_ticket_number") == col("sr_ticket_number")))
            .join(t["catalog_sales"],
                  on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("sr_item_sk") == col("cs_item_sk")))
            .join(d1, on=col("ss_sold_date_sk") == col("d1_sk"))
            .join(d2, on=col("sr_returned_date_sk") == col("d2_sk"))
            .join(d3, on=col("cs_sold_date_sk") == col("d3_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk")))


def q25(t):
    """Profit across the sale->return->catalog chain per item x store.
    Date legs widened to the full year (spec: month windows) so the tiny-sf
    chain stays populated."""
    joined = _sale_return_catalog(
        t, col("d_year") == 2000, col("d_year") == 2000,
        col("d_year") == 2000)
    return (joined
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .agg(F.sum(col("ss_net_profit")).alias("store_sales_profit"),
                 F.sum(col("sr_net_loss")).alias("store_returns_loss"),
                 F.sum(col("cs_net_profit")).alias("catalog_sales_profit"))
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .limit(100))


def q26(t):
    """Catalog averages per item for one demographics tuple (q7's catalog
    twin)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["catalog_sales"]
            .join(cd, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
            .join(pr, on=col("cs_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q27(t):
    """ROLLUP(item, state) averages for one demographics tuple."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "F") & (col("cd_marital_status") == "D")
        & (col("cd_education_status") == "Primary"))
    dd = t["date_dim"].filter(col("d_year") == 1999)
    st = t["store"].filter(col("s_state").isin("TN", "SD", "AL", "GA"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .rollup(col("i_item_id"), col("s_state"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"), col("s_state"))
            .limit(100))


def q29(t):
    """Quantities across the sale->return->catalog chain (q25's quantity
    cut)."""
    joined = _sale_return_catalog(
        t, col("d_year") == 2000, col("d_year") == 2000,
        col("d_year").isin(2000, 2001, 2002))
    return (joined
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .agg(F.sum(col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(col("sr_return_quantity"))
                 .alias("store_returns_quantity"),
                 F.sum(col("cs_quantity")).alias("catalog_sales_quantity"))
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .limit(100))


def _ticket_counts(t, date_filter, hd_filter, county_filter, lo, hi):
    """q34/q73 core: per-ticket line counts within bounds, joined back to
    the customer."""
    dd = t["date_dim"].filter(date_filter)
    hd = t["household_demographics"].filter(hd_filter)
    st = t["store"].filter(county_filter)
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"))
               .agg(F.count(lit(1)).alias("cnt"))
               .filter(col("cnt").between(lo, hi)))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("c_salutation"), col("c_preferred_cust_flag"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("c_salutation"), col("c_preferred_cust_flag")
                      .desc(), col("ss_ticket_number"))
            .limit(1000))


def q34(t):
    """Big-basket customers (count bounds scaled to the ~4-line tickets
    the tiny-sf generator produces; spec: 15..20)."""
    return _ticket_counts(
        t,
        (col("d_dom").between(1, 3) | col("d_dom").between(25, 28))
        & col("d_year").isin(1999, 2000, 2001),
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)
        & (col("hd_dep_count") > 0.2 * col("hd_vehicle_count")),
        col("s_county").isin("Williamson County", "Ziebach County",
                             "Walker County", "Daviess County"),
        2, 4)


def q35(t):
    """Demographics x state stats for customers with a store purchase AND
    (web OR catalog) activity (q10 with address grouping)."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    return (t["customer"]
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left")
            .filter(~(col("ws_cust").is_null()
                      & col("cs_cust").is_null()))
            .group_by(col("ca_state"), col("cd_gender"),
                      col("cd_marital_status"), col("cd_dep_count"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.min(col("cd_dep_employed_count")).alias("min_emp"),
                 F.max(col("cd_dep_employed_count")).alias("max_emp"),
                 F.avg(col("cd_dep_college_count")).alias("avg_col"))
            .order_by(col("ca_state"), col("cd_gender"),
                      col("cd_marital_status"), col("cd_dep_count"))
            .limit(100))


def q36(t):
    """Gross-margin ROLLUP by category/class with an in-category margin
    rank (window over a rollup)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_year") == 2001)
    st = t["store"].filter(col("s_state").isin("TN", "SD", "AL", "GA",
                                               "MI", "OH", "TX", "CA"))
    rolled = (t["store_sales"]
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
              .join(st, on=col("ss_store_sk") == col("s_store_sk"))
              .rollup(col("i_category"), col("i_class"))
              .agg(F.sum(col("ss_net_profit")).alias("profit"),
                   F.sum(col("ss_ext_sales_price")).alias("sales"))
              .with_column("gross_margin",
                           col("profit") / col("sales")))
    w = Window.partition_by(col("i_category")) \
        .order_by(col("gross_margin"))
    return (rolled
            .with_column("rank_within_parent", F.rank().over(w))
            .order_by(col("i_category"), col("rank_within_parent"))
            .limit(100))


def q43(t):
    """Per-store day-of-week sales pivot (conditional-sum pivot)."""
    dd = t["date_dim"].filter(col("d_year") == 2000)
    st = t["store"].filter(col("s_gmt_offset") == -5.0)
    day_sum = [
        F.sum(F.when(col("d_day_name") == day, col("ss_sales_price"))
              .otherwise(0.0)).alias(f"{day[:3].lower()}_sales")
        for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                    "Thursday", "Friday", "Saturday"]]
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .group_by(col("s_store_name"), col("s_store_sk"))
            .agg(*day_sum)
            .order_by(col("s_store_name"), col("s_store_sk"))
            .limit(100))


def q45(t):
    """Web revenue by customer zip/city for select zips or select items."""
    dd = t["date_dim"].filter((col("d_qoy") == 2)
                              & (col("d_year") == 2001))
    return (t["web_sales"]
            .join(t["customer"],
                  on=col("ws_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5).isin(
                "85669", "86197", "88274", "83405", "86475")
                | col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19, 23,
                                        29))
            .group_by(col("ca_zip"), col("ca_city"))
            .agg(F.sum(col("ws_ext_sales_price")).alias("total"))
            .order_by(col("ca_zip"), col("ca_city"))
            .limit(100))


def _monthly_deviation(joined, group_cols, order_cols):
    """q47/q57 core: monthly sums, year-partition average, lag/lead
    neighbors, >10% deviation filter."""
    from spark_rapids_tpu.plan.logical import Window
    monthly = (joined
               .group_by(*[col(c) for c in group_cols + ["d_year",
                                                         "d_moy"]])
               .agg(F.sum(col("sales_col")).alias("sum_sales")))
    w_avg = Window.partition_by(*[col(c) for c in group_cols + ["d_year"]])
    w_seq = Window.partition_by(*[col(c) for c in group_cols]) \
        .order_by(col("d_year"), col("d_moy"))
    flagged = (monthly
               .with_column("avg_monthly_sales",
                            F.avg(col("sum_sales")).over(w_avg))
               .with_column("psum", F.lag(col("sum_sales"), 1).over(w_seq))
               .with_column("nsum", F.lead(col("sum_sales"), 1)
                            .over(w_seq))
               .filter((col("avg_monthly_sales") > 0)
                       & (F.abs(col("sum_sales")
                                - col("avg_monthly_sales"))
                          / col("avg_monthly_sales") > 0.1)
                       & (col("d_year") == 1999)))
    return (flagged
            .order_by(*([col("avg_monthly_sales").desc(),
                         col("sum_sales")]
                        + [col(c) for c in order_cols]))
            .limit(100))


def q47(t):
    """Store monthly sales deviating >10% from the yearly average, with
    neighboring months (windows over a 3-way join)."""
    dd = t["date_dim"].filter(col("d_year").isin(1998, 1999, 2000))
    joined = (t["store_sales"]
              .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .with_column("sales_col", col("ss_sales_price")))
    return _monthly_deviation(
        joined, ["i_category", "i_brand", "s_store_name",
                 "s_company_name"],
        ["i_category", "i_brand", "s_store_name", "s_company_name",
         "d_year", "d_moy"])


def q48(t):
    """Store quantity sum under OR'd demographic/address branches (q13's
    quantity cut)."""
    dd = t["date_dim"].filter(col("d_year") == 2001)
    demo_ok = (
        ((col("cd_marital_status") == "M")
         & (col("cd_education_status") == "4 yr Degree")
         & col("ss_sales_price").between(100.0, 150.0))
        | ((col("cd_marital_status") == "D")
           & (col("cd_education_status") == "2 yr Degree")
           & col("ss_sales_price").between(50.0, 100.0))
        | ((col("cd_marital_status") == "S")
           & (col("cd_education_status") == "College")
           & col("ss_sales_price").between(150.0, 200.0)))
    addr_ok = (
        (col("ca_state").isin("CO", "OH", "TX")
         & col("ss_net_profit").between(0.0, 2000.0))
        | (col("ca_state").isin("OR", "MN", "KY")
           & col("ss_net_profit").between(150.0, 3000.0))
        | (col("ca_state").isin("VA", "CA", "MS")
           & col("ss_net_profit").between(50.0, 25000.0)))
    return (t["store_sales"]
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer_demographics"],
                  on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["customer_address"],
                  on=col("ss_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(demo_ok & addr_ok
                    & (col("ca_country") == "United States"))
            .agg(F.sum(col("ss_quantity")).alias("total_quantity")))


def q57(t):
    """Catalog monthly sales deviation by call center (q47's catalog
    twin)."""
    dd = t["date_dim"].filter(col("d_year").isin(1998, 1999, 2000))
    joined = (t["catalog_sales"]
              .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
              .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
              .join(t["call_center"],
                    on=col("cs_call_center_sk") == col("cc_call_center_sk"))
              .with_column("sales_col", col("cs_sales_price")))
    return _monthly_deviation(
        joined, ["i_category", "i_brand", "cc_name"],
        ["i_category", "i_brand", "cc_name", "d_year", "d_moy"])


def q65(t):
    """Store/item pairs whose revenue is below the store's average
    (aggregate-of-aggregate self join; spec threshold 0.1x scaled to 1.0x
    for tiny-sf row counts)."""
    month_lo = t["date_dim"].filter((col("d_year") == 2000)
                                    & (col("d_moy") == 1)) \
        .agg(F.min(col("d_month_seq")).alias("m")).collect()[0][0]
    dd = t["date_dim"].filter(col("d_month_seq").between(
        month_lo, month_lo + 11))
    revenue = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .group_by(col("ss_store_sk"), col("ss_item_sk"))
               .agg(F.sum(col("ss_sales_price")).alias("revenue")))
    store_avg = (revenue.group_by(col("ss_store_sk"))
                 .agg(F.avg(col("revenue")).alias("ave"))
                 .select(col("ss_store_sk").alias("avg_store"),
                         col("ave")))
    return (revenue
            .join(store_avg, on=col("ss_store_sk") == col("avg_store"))
            .filter(col("revenue") <= col("ave"))
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .select(col("s_store_name"), col("i_item_desc"),
                    col("revenue"), col("i_current_price"))
            .order_by(col("s_store_name"), col("i_item_desc"),
                      col("revenue"))
            .limit(100))


def q68(t):
    """Ticket-grouped city sums where the purchase city differs from the
    customer's current city."""
    dd = t["date_dim"].filter(col("d_dom").between(1, 2)
                              & col("d_year").isin(1998, 1999, 2000))
    st = t["store"].filter(col("s_city").isin("Midway", "Fairview"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(t["customer_address"],
                     on=col("ss_addr_sk") == col("ca_address_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("ca_city"))
               .agg(F.sum(col("ss_ext_sales_price")).alias("extended_price"),
                    F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit"))
               .select(col("ss_ticket_number"), col("ss_customer_sk"),
                       col("ca_city").alias("bought_city"),
                       col("extended_price"), col("amt"), col("profit")))
    cur = t["customer_address"].select(col("ca_address_sk").alias("cur_sk"),
                                       col("ca_city").alias("cur_city"))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .join(cur, on=col("c_current_addr_sk") == col("cur_sk"))
            .filter(col("cur_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("cur_city"), col("bought_city"),
                    col("ss_ticket_number"), col("extended_price"),
                    col("amt"), col("profit"))
            .order_by(col("c_last_name"), col("ss_ticket_number"))
            .limit(100))


def q73(t):
    """Frequent-shopper baskets (q34's narrow cut; count bounds scaled to
    the ~4-line tickets; spec: 1..5)."""
    return _ticket_counts(
        t,
        col("d_dom").between(1, 2) & col("d_year").isin(1999, 2000, 2001),
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)
        & (col("hd_dep_count") > 0.5 * col("hd_vehicle_count")),
        col("s_county").isin("Williamson County", "Ziebach County",
                             "Walker County", "Daviess County"),
        1, 5)


def q89(t):
    """Monthly class/brand/store sales deviating from the yearly average
    (window over join, no lag/lead)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(
        (col("i_category").isin("Books", "Electronics", "Sports")
         & col("i_class").isin("class#1", "class#4", "class#7"))
        | (col("i_category").isin("Men", "Jewelry", "Women")
           & col("i_class").isin("class#2", "class#5", "class#8")))
    monthly = (t["store_sales"]
               .join(it, on=col("ss_item_sk") == col("i_item_sk"))
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(t["store"],
                     on=col("ss_store_sk") == col("s_store_sk"))
               .group_by(col("i_category"), col("i_class"),
                         col("i_brand"), col("s_store_name"),
                         col("s_company_name"), col("d_moy"))
               .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_category"), col("i_brand"),
                            col("s_store_name"), col("s_company_name"))
    return (monthly
            .with_column("avg_monthly_sales",
                         F.avg(col("sum_sales")).over(w))
            .filter(F.when(col("avg_monthly_sales") != 0.0,
                           F.abs(col("sum_sales")
                                 - col("avg_monthly_sales"))
                           / col("avg_monthly_sales")).otherwise(0.0)
                    > 0.1)
            .order_by((col("sum_sales") - col("avg_monthly_sales")),
                      col("s_store_name"), col("i_category"),
                      col("i_class"), col("i_brand"), col("d_moy"))
            .limit(100))


def q98(t):
    """Store revenue ratio by item within class (q12's store twin)."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["store_sales"]
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "ss_ext_sales_price")




def q1(t):
    """Customers returning more than 1.2x their store's average return
    (CTE + per-store average join + customer join)."""
    ctr = (t["store_returns"]
           .join(t["date_dim"].filter(col("d_year") == 2000),
                 on=col("sr_returned_date_sk") == col("d_date_sk"))
           .group_by(col("sr_customer_sk"), col("sr_store_sk"))
           .agg(F.sum(col("sr_return_amt")).alias("ctr_total_return")))
    avg_ctr = (ctr.group_by(col("sr_store_sk"))
               .agg((F.avg(col("ctr_total_return")) * 1.2)
                    .alias("avg_return"))
               .select(col("sr_store_sk").alias("avg_store"),
                       col("avg_return")))
    st = t["store"].filter(col("s_state") == "TN")
    return (ctr
            .join(avg_ctr, on=col("sr_store_sk") == col("avg_store"))
            .filter(col("ctr_total_return") > col("avg_return"))
            .join(st, on=col("sr_store_sk") == col("s_store_sk"))
            .join(t["customer"],
                  on=col("sr_customer_sk") == col("c_customer_sk"))
            .select(col("c_customer_id"))
            .order_by(col("c_customer_id"))
            .limit(100))


def _channel_customers(t, sales_key, date_key, prefix):
    """Distinct (customer, d_date) pairs of one channel in the window —
    the building block of the q38/q87 set operations."""
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35)) \
        .select(col("d_date_sk").alias(f"{prefix}_dsk"), col("d_date")
                .alias(f"{prefix}_date"))
    return (t[sales_key[0]]
            .join(dd, on=col(date_key) == col(f"{prefix}_dsk"))
            .join(t["customer"],
                  on=col(sales_key[1]) == col("c_customer_sk"))
            .select(col("c_last_name").alias(f"{prefix}_ln"),
                    col("c_first_name").alias(f"{prefix}_fn"),
                    col(f"{prefix}_date"))
            .distinct())


def _channel_customer_sets(t):
    """(store, catalog, web) distinct (customer, date) sets — the shared
    operands of the q38 INTERSECT and q87 EXCEPT chains."""
    ss = _channel_customers(t, ("store_sales", "ss_customer_sk"),
                            "ss_sold_date_sk", "s")
    cs = _channel_customers(t, ("catalog_sales", "cs_bill_customer_sk"),
                            "cs_sold_date_sk", "c")
    ws = _channel_customers(t, ("web_sales", "ws_bill_customer_sk"),
                            "ws_sold_date_sk", "w")
    return ss, cs, ws


def q38(t):
    """INTERSECT of the three channels' (customer, date) sets, counted —
    expressed as the semi-join chain Spark plans for INTERSECT."""
    ss, cs, ws = _channel_customer_sets(t)
    both = (ss.join(cs, on=(col("s_ln") == col("c_ln"))
                    & (col("s_fn") == col("c_fn"))
                    & (col("s_date") == col("c_date")), how="left_semi")
            .join(ws, on=(col("s_ln") == col("w_ln"))
                  & (col("s_fn") == col("w_fn"))
                  & (col("s_date") == col("w_date")), how="left_semi"))
    return both.agg(F.count(lit(1)).alias("cnt"))


def q87(t):
    """EXCEPT version of q38: store customers with NO matching catalog or
    web activity (anti-join chain)."""
    ss, cs, ws = _channel_customer_sets(t)
    only = (ss.join(cs, on=(col("s_ln") == col("c_ln"))
                    & (col("s_fn") == col("c_fn"))
                    & (col("s_date") == col("c_date")), how="left_anti")
            .join(ws, on=(col("s_ln") == col("w_ln"))
                  & (col("s_fn") == col("w_fn"))
                  & (col("s_date") == col("w_date")), how="left_anti"))
    return only.agg(F.count(lit(1)).alias("cnt"))


def _weekly_pivot(t, years, prefix):
    dd = t["date_dim"].filter(col("d_year").isin(*years))
    sums = [F.sum(F.when(col("d_day_name") == day, col("ss_sales_price"))
                  .otherwise(0.0)).alias(f"{prefix}_{day[:3].lower()}")
            for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                        "Thursday", "Friday", "Saturday"]]
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .group_by(col("ss_store_sk"), col("d_moy"))
            .agg(*sums)
            .select(col("ss_store_sk").alias(f"{prefix}_store"),
                    col("d_moy").alias(f"{prefix}_moy"),
                    *[col(f"{prefix}_{d}") for d in
                      ("sun", "mon", "tue", "wed", "thu", "fri", "sat")]))


def q59(t):
    """Year-over-year weekly sales ratios per store (self-joined
    day-of-week pivots; monthly granularity stands in for week_seq,
    which the tiny-sf date_dim does not carry)."""
    y1 = _weekly_pivot(t, (1999,), "a")
    y2 = _weekly_pivot(t, (2000,), "b")
    joined = (y1.join(y2, on=(col("a_store") == col("b_store"))
                      & (col("a_moy") == col("b_moy")))
              .join(t["store"],
                    on=col("a_store") == col("s_store_sk")))
    out = [col("s_store_name"), col("a_moy")]
    for d in ("sun", "mon", "tue", "wed", "thu", "fri", "sat"):
        out.append((col(f"b_{d}") / col(f"a_{d}")).alias(f"r_{d}"))
    return (joined.select(*out)
            .order_by(col("s_store_name"), col("a_moy"))
            .limit(100))


def q88(t):
    """Store-traffic counts in eight half-hour buckets (the reference
    cross-joins eight count subqueries; scalar composition happens
    driver-side here, like the TPC-H scalar-subquery queries).  Spec
    deviations for the tiny-sf generator: the dep/vehicle predicate is
    broadened (dep<=5 or vehicles<=3 vs the spec's exact triples) and
    the window is 8:00-12:00 on the hour rather than 8:30-12:30."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") <= 5) | (col("hd_vehicle_count") <= 3))
    st = t["store"].filter(col("s_store_name") == "ese")
    base = (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["time_dim"],
                  on=col("ss_sold_time_sk") == col("t_time_sk")))
    data = {}
    for i, (h, half) in enumerate((h, m) for h in range(8, 12)
                                  for m in (0, 30)):
        c = (base.filter((col("t_hour") == h)
                         & (col("t_minute") >= half)
                         & (col("t_minute") < half + 30))
             .agg(F.count(lit(1)).alias("c")).collect()[0][0])
        data[f"b{i}"] = [int(c or 0)]
    # the eight scalars compose into the single output row driver-side,
    # like the TPC-H scalar-subquery queries (tpch q11/q15/q22)
    return base.session.from_pydict(data)


def q31(t):
    """County-level store-vs-web sales growth across consecutive quarters
    (two per-channel aggregates self-joined twice)."""
    def per_channel(sales, date_key, addr_key, prefix, qoy):
        dd = t["date_dim"].filter((col("d_year") == 2000)
                                  & (col("d_qoy") == qoy))
        return (t[sales]
                .join(dd, on=col(date_key) == col("d_date_sk"))
                .join(t["customer_address"],
                      on=col(addr_key) == col("ca_address_sk"))
                .group_by(col("ca_county"))
                .agg(F.sum(col(f"{prefix}_ext_sales_price"))
                     .alias(f"{prefix}{qoy}_sales"))
                .select(col("ca_county").alias(f"{prefix}{qoy}_county"),
                        col(f"{prefix}{qoy}_sales")))
    ss1 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 1)
    ss2 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 2)
    ss3 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 3)
    ws1 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 1)
    ws2 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 2)
    ws3 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 3)
    return (ss1.join(ss2, on=col("ss1_county") == col("ss2_county"))
            .join(ss3, on=col("ss1_county") == col("ss3_county"))
            .join(ws1, on=col("ss1_county") == col("ws1_county"))
            .join(ws2, on=col("ss1_county") == col("ws2_county"))
            .join(ws3, on=col("ss1_county") == col("ws3_county"))
            .filter((col("ss1_sales") > 0) & (col("ss2_sales") > 0)
                    & (col("ws1_sales") > 0) & (col("ws2_sales") > 0))
            # the query's point: counties where the WEB channel grew
            # faster than the STORE channel in both quarter steps
            .filter((col("ws2_sales") / col("ws1_sales")
                     > col("ss2_sales") / col("ss1_sales"))
                    & (col("ws3_sales") / col("ws2_sales")
                       > col("ss3_sales") / col("ss2_sales")))
            .select(col("ss1_county").alias("county"),
                    (col("ws2_sales") / col("ws1_sales"))
                    .alias("web_growth"),
                    (col("ss2_sales") / col("ss1_sales"))
                    .alias("store_growth"))
            .order_by(col("county"))
            .limit(100))


def _three_channel_by_item(t, item_filter):
    """q33/q56/q60 skeleton: per-manufacturer/item sums across the three
    channels in one month for out-of-timezone customers, unioned."""
    dd = t["date_dim"].filter((col("d_year") == 2000)
                              & (col("d_moy") == 1))
    it = t["item"].join(item_filter, on="i_item_sk", how="left_semi")

    def chan(sales, date_key, addr_key, price, item_key):
        return (t[sales]
                .join(dd, on=col(date_key) == col("d_date_sk"))
                .join(t["customer_address"].filter(
                    col("ca_gmt_offset") == -5.0),
                    on=col(addr_key) == col("ca_address_sk"))
                .join(it, on=col(item_key) == col("i_item_sk"))
                .group_by(col("i_manufact_id"))
                .agg(F.sum(col(price)).alias("chan_sales")))
    a = chan("store_sales", "ss_sold_date_sk", "ss_addr_sk",
             "ss_ext_sales_price", "ss_item_sk")
    b = chan("catalog_sales", "cs_sold_date_sk", "cs_bill_addr_sk",
             "cs_ext_sales_price", "cs_item_sk")
    c = chan("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
             "ws_ext_sales_price", "ws_item_sk")
    return (a.union(b).union(c)
            .group_by(col("i_manufact_id"))
            .agg(F.sum(col("chan_sales")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("i_manufact_id"))
            .limit(100))


def q33(t):
    """Manufacturer revenue across all three channels for one category's
    items (3-way union of channel aggregates)."""
    cat_items = (t["item"].filter(col("i_category") == "Books")
                 .select(col("i_item_sk")))
    return _three_channel_by_item(t, cat_items)


def q56(t):
    """q33's shape keyed by item COLOR set membership."""
    color_items = (t["item"]
                   .filter(col("i_color").isin("red", "blue", "green"))
                   .select(col("i_item_sk")))
    return _three_channel_by_item(t, color_items)


def q46(t):
    """Ticket-grouped sales where the purchase city differs from the
    customer's city, for dep/vehicle households on weekend days."""
    dd = t["date_dim"].filter(col("d_day_name").isin("Saturday",
                                                     "Sunday"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3))
    st = t["store"].filter(col("s_city").isin("Midway", "Fairview"))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(t["customer_address"],
                     on=col("ss_addr_sk") == col("ca_address_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("ca_city"))
               .agg(F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit"))
               .select(col("ss_ticket_number"), col("ss_customer_sk"),
                       col("ca_city").alias("bought_city"), col("amt"),
                       col("profit")))
    cur = t["customer_address"].select(
        col("ca_address_sk").alias("cur_sk"),
        col("ca_city").alias("cur_city"))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .join(cur, on=col("c_current_addr_sk") == col("cur_sk"))
            .filter(col("cur_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("cur_city"), col("bought_city"),
                    col("ss_ticket_number"), col("amt"), col("profit"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("ss_ticket_number"))
            .limit(100))


def q60(t):
    """q33's shape keyed by category (the spec's third variant)."""
    cat_items = (t["item"].filter(col("i_category") == "Music")
                 .select(col("i_item_sk")))
    return _three_channel_by_item(t, cat_items)


def q69(t):
    """Demographics of in-state customers with a store purchase but NO
    web or catalog activity in the window (semi + anti chain)."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    ca = t["customer_address"].filter(col("ca_state").isin("TN", "GA",
                                                           "TX"))
    return (t["customer"]
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left_anti")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left_anti")
            .group_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.avg(col("cd_dep_count")).alias("avg_dep"))
            .order_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .limit(100))


def q79(t):
    """Per-ticket profit for big-store weekday shopping by dep/vehicle
    households, joined back to the customer."""
    dd = t["date_dim"].filter(col("d_day_name") == "Monday")
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2))
    st = t["store"].filter(col("s_number_employees").between(200, 295))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("s_city"))
               .agg(F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit")))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("s_city"), col("profit"),
                    col("ss_ticket_number"), col("amt"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("s_city"), col("profit").desc(),
                      col("ss_ticket_number"))
            .limit(100))


def q92(t):
    """Web sales with an ext discount above 1.3x the item's average in
    the window (per-item scalar-subquery join).  Window widened to a full
    year and the manufacturer filter dropped (spec: 90 days, one
    manufacturer) — at tiny scale factors an item has ~1 row in 90 days
    and can never exceed 1.3x its own average."""
    dd = (t["date_dim"]
          .filter(col("d_date").between("2000-01-01", "2000-12-31"))
          .select(col("d_date_sk").alias("w_dsk")))
    windowed = (t["web_sales"]
                .join(dd, on=col("ws_sold_date_sk") == col("w_dsk")))
    item_avg = (windowed.group_by(col("ws_item_sk"))
                .agg((F.avg(col("ws_ext_discount_amt")) * 1.3)
                     .alias("bar"))
                .select(col("ws_item_sk").alias("avg_item"), col("bar")))
    return (windowed
            .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
            .join(item_avg, on=col("ws_item_sk") == col("avg_item"))
            .filter(col("ws_ext_discount_amt") > col("bar"))
            .agg(F.sum(col("ws_ext_discount_amt"))
                 .alias("excess_discount")))


def q8(t):
    """Store net profit for stores whose zip prefix matches a
    preferred-customer-heavy zip (zip-prefix semi-join; spec's literal
    400-zip IN list replaced by the generator's populated prefixes)."""
    dd = t["date_dim"].filter((col("d_year") == 2000)
                              & (col("d_qoy") == 2))
    pref = (t["customer"].filter(col("c_preferred_cust_flag") == "Y")
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by(F.substring(col("ca_zip"), 1, 2).alias("zip2"))
            .agg(F.count(lit(1)).alias("cnt"))
            .filter(col("cnt") >= 2)
            .select(col("zip2")))
    st = (t["store"]
          .with_column("s_zip2", F.substring(col("s_zip"), 1, 2))
          .join(pref, on=col("s_zip2") == col("zip2"), how="left_semi"))
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .group_by(col("s_store_name"))
            .agg(F.sum(col("ss_net_profit")).alias("net_profit"))
            .order_by(col("s_store_name"))
            .limit(100))


def q54(t):
    """Customers who bought a target category from catalog/web in one
    month, bucketed by their store revenue in the following quarter
    (cross-channel cohort -> store revenue histogram)."""
    it = t["item"].filter((col("i_category") == "Women"))
    dd1 = t["date_dim"].filter((col("d_year") == 2000)
                               & (col("d_moy") == 3))
    cs = (t["catalog_sales"]
          .select(col("cs_sold_date_sk").alias("sold_date"),
                  col("cs_item_sk").alias("sold_item"),
                  col("cs_bill_customer_sk").alias("cust")))
    ws = (t["web_sales"]
          .select(col("ws_sold_date_sk").alias("sold_date"),
                  col("ws_item_sk").alias("sold_item"),
                  col("ws_bill_customer_sk").alias("cust")))
    cohort = (cs.union(ws)
              .join(dd1, on=col("sold_date") == col("d_date_sk"))
              .join(it, on=col("sold_item") == col("i_item_sk"))
              .group_by(col("cust"))
              .agg(F.count(lit(1)).alias("_n"))
              .select(col("cust")))
    dd2 = t["date_dim"].filter((col("d_year") == 2000)
                               & col("d_moy").between(4, 6))
    revenue = (t["store_sales"]
               .join(cohort, on=col("ss_customer_sk") == col("cust"),
                     how="left_semi")
               .join(dd2, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .group_by(col("ss_customer_sk"))
               .agg(F.sum(col("ss_ext_sales_price")).alias("revenue")))
    return (revenue
            .with_column("segment",
                         F.floor(col("revenue") / 50.0))
            .group_by(col("segment"))
            .agg(F.count(lit(1)).alias("num_customers"))
            .order_by(col("segment"))
            .limit(100))


def q58(t):
    """Items whose revenue is comparable across ALL THREE channels
    (per-channel item aggregates joined with ratio bands).  Scaled for
    the generator: the window is the full year and the band is
    [0.5x, 1.75x] of the three-way average (spec: one week, +/-10%) —
    the tiny-sf channels have structurally different volumes
    (ss:cs:ws row counts ~4:2:1), so the spec band selects nothing
    while this one keeps a discriminating ~10% of common items."""
    dd = (t["date_dim"].filter(col("d_year") == 2000)
          .select(col("d_date_sk").alias("day_sk")))

    def chan(sales, date_key, item_key, price, prefix):
        return (t[sales]
                .join(dd, on=col(date_key) == col("day_sk"))
                .join(t["item"], on=col(item_key) == col("i_item_sk"))
                .group_by(col("i_item_id"))
                .agg(F.sum(col(price)).alias(f"{prefix}_rev"))
                .select(col("i_item_id").alias(f"{prefix}_id"),
                        col(f"{prefix}_rev")))
    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", "ss")
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_ext_sales_price", "cs")
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_ext_sales_price", "ws")
    avg3 = (col("ss_rev") + col("cs_rev") + col("ws_rev")) / 3.0
    joined = (ss.join(cs, on=col("ss_id") == col("cs_id"))
              .join(ws, on=col("ss_id") == col("ws_id"))
              .with_column("average", avg3))
    band = lambda c: (c >= 0.5 * col("average")) \
        & (c <= 1.75 * col("average"))  # noqa: E731
    return (joined
            .filter(band(col("ss_rev")) & band(col("cs_rev"))
                    & band(col("ws_rev")))
            .select(col("ss_id"), col("ss_rev"), col("cs_rev"),
                    col("ws_rev"), col("average"))
            .order_by(col("ss_id"))
            .limit(100))


def _inventory_price_band(t, fact, date_key, item_key):
    """q37/q82 skeleton: items in a price band with inventory on hand in
    a window, that also sold through the channel."""
    it = t["item"].filter(col("i_current_price").between(20.0, 50.0))
    dd = (t["date_dim"].filter(col("d_year") == 2000)
          .select(col("d_date_sk").alias("inv_dsk")))
    stocked = (t["inventory"]
               .filter(col("inv_quantity_on_hand").between(100, 500))
               .join(dd, on=col("inv_date_sk") == col("inv_dsk"))
               .select(col("inv_item_sk")).distinct())
    sold = (t[fact]
            .join(t["date_dim"].filter(col("d_year") == 2000)
                  .select(col("d_date_sk").alias("sold_dsk")),
                  on=col(date_key) == col("sold_dsk"))
            .select(col(item_key).alias("sold_item")).distinct())
    return (it
            .join(stocked, on=col("i_item_sk") == col("inv_item_sk"),
                  how="left_semi")
            .join(sold, on=col("i_item_sk") == col("sold_item"),
                  how="left_semi")
            .select(col("i_item_id"), col("i_item_desc"),
                    col("i_current_price"))
            .order_by(col("i_item_id"))
            .limit(100))


def q37(t):
    """Catalog items in a price band with inventory on hand (inventory
    semi-join; spec window widened to the year for tiny-sf population)."""
    return _inventory_price_band(t, "catalog_sales", "cs_sold_date_sk",
                                 "cs_item_sk")


def q82(t):
    """q37's store twin."""
    return _inventory_price_band(t, "store_sales", "ss_sold_date_sk",
                                 "ss_item_sk")


def q93(t):
    """Per-customer effective sales after backing out returns for one
    return reason (sale left-joined to its returns on ticket+item)."""
    sr = (t["store_returns"]
          .join(t["reason"].filter(col("r_reason_desc") == "reason 3"),
                on=col("sr_reason_sk") == col("r_reason_sk"))
          .select(col("sr_ticket_number").alias("rt"),
                  col("sr_item_sk").alias("ri"),
                  col("sr_return_quantity")))
    act = (t["store_sales"]
           .join(sr, on=(col("ss_ticket_number") == col("rt"))
                 & (col("ss_item_sk") == col("ri")), how="left")
           .with_column(
               "act_sales",
               F.when(~col("sr_return_quantity").is_null(),
                      (col("ss_quantity") - col("sr_return_quantity"))
                      * col("ss_sales_price"))
               .otherwise(col("ss_quantity") * col("ss_sales_price"))))
    return (act.group_by(col("ss_customer_sk"))
            .agg(F.sum(col("act_sales")).alias("sumsales"))
            .order_by(col("sumsales").desc(), col("ss_customer_sk"))
            .limit(100))


def q21(t):
    """Warehouse inventory balance around a pivot date: on-hand before vs
    after, kept when the ratio stays within [2/3, 3/2]."""
    dd = t["date_dim"].filter(col("d_date").between("2000-02-10",
                                                    "2000-04-10"))
    it = t["item"].filter(col("i_current_price").between(0.99, 60.0))
    return (t["inventory"]
            .join(dd, on=col("inv_date_sk") == col("d_date_sk"))
            .join(it, on=col("inv_item_sk") == col("i_item_sk"))
            .join(t["warehouse"],
                  on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
            .group_by(col("w_warehouse_name"), col("i_item_id"))
            .agg(F.sum(F.when(col("d_date") < "2000-03-11",
                              col("inv_quantity_on_hand"))
                       .otherwise(0)).alias("inv_before"),
                 F.sum(F.when(col("d_date") >= "2000-03-11",
                              col("inv_quantity_on_hand"))
                       .otherwise(0)).alias("inv_after"))
            .filter(F.when(col("inv_before") > 0,
                           col("inv_after") / col("inv_before"))
                    .otherwise(0.0).between(2.0 / 3.0, 3.0 / 2.0))
            .order_by(col("w_warehouse_name"), col("i_item_id"))
            .limit(100))


def q22(t):
    """Average inventory on hand over a year, ROLLUP'd down the product
    hierarchy (category/brand/class/item; i_item_desc stands in for the
    spec's i_product_name, which the tiny-sf item table does not carry)."""
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))
    return (t["inventory"]
            .join(dd, on=col("inv_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("inv_item_sk") == col("i_item_sk"))
            .rollup(col("i_category"), col("i_brand"), col("i_class"),
                    col("i_item_desc"))
            .agg(F.avg(col("inv_quantity_on_hand")).alias("qoh"))
            .order_by(col("qoh"), col("i_category"), col("i_brand"),
                      col("i_class"), col("i_item_desc"))
            .limit(100))


def q41(t):
    """Manufacturers with at least one item in the queried color set —
    the spec's correlated count(*)>0 subquery as a distinct semi-join
    (i_item_desc stands in for i_product_name)."""
    inner = (t["item"]
             .filter(col("i_color").isin("red", "navy", "slate"))
             .select(col("i_manufact").alias("m_manufact"))
             .distinct())
    return (t["item"]
            .filter(col("i_manufact_id").between(5, 15))
            .join(inner, on=col("i_manufact") == col("m_manufact"),
                  how="left_semi")
            .select(col("i_item_desc")).distinct()
            .order_by(col("i_item_desc"))
            .limit(100))


def q44(t):
    """Best and worst ten items by average store net profit, paired rank
    by rank (two opposite-order rank windows joined on position)."""
    from spark_rapids_tpu.plan.logical import Window
    perf = (t["store_sales"]
            .filter(col("ss_store_sk") == 4)
            .group_by(col("ss_item_sk"))
            .agg(F.avg(col("ss_net_profit")).alias("rank_col")))
    asc = (perf.with_column(
        "rnk", F.rank().over(Window.order_by(col("rank_col").asc())))
        .filter(col("rnk") < 11)
        .select(col("ss_item_sk").alias("worst_sk"), col("rnk")))
    desc = (perf.with_column(
        "rnk2", F.rank().over(Window.order_by(col("rank_col").desc())))
        .filter(col("rnk2") < 11)
        .select(col("ss_item_sk").alias("best_sk"), col("rnk2")))
    i1 = t["item"].select(col("i_item_sk").alias("i1_sk"),
                          col("i_item_desc").alias("best_performing"))
    i2 = t["item"].select(col("i_item_sk").alias("i2_sk"),
                          col("i_item_desc").alias("worst_performing"))
    return (asc.join(desc, on=col("rnk") == col("rnk2"))
            .join(i1, on=col("best_sk") == col("i1_sk"))
            .join(i2, on=col("worst_sk") == col("i2_sk"))
            .select(col("rnk"), col("best_performing"),
                    col("worst_performing"))
            .order_by(col("rnk"))
            .limit(100))


def _quarterly_deviation(t, attr_col, period_col):
    """Shared q53/q63 shape: per-{manufacturer,manager} period sales vs
    the attribute's average over all periods (window over agg), keeping
    periods deviating by more than 10%."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_month_seq").between(12, 23))
    it = t["item"].filter(
        (col("i_category").isin("Books", "Children", "Electronics")
         & col("i_class").isin("class#1", "class#3", "class#5"))
        | (col("i_category").isin("Women", "Music", "Men")
           & col("i_class").isin("class#2", "class#4", "class#6")))
    sums = (t["store_sales"]
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .group_by(col(attr_col), col(period_col))
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col(attr_col))
    return (sums
            .with_column("avg_quarterly_sales",
                         F.avg(col("sum_sales")).over(w))
            .filter(F.when(col("avg_quarterly_sales") > 0.0,
                           F.abs(col("sum_sales")
                                 - col("avg_quarterly_sales"))
                           / col("avg_quarterly_sales")).otherwise(0.0)
                    > 0.1)
            .order_by(col("avg_quarterly_sales"), col("sum_sales"),
                      col(attr_col))
            .limit(100))


def q53(t):
    """Manufacturer quarterly sales deviating from their yearly average."""
    return _quarterly_deviation(t, "i_manufact_id", "d_qoy")


def q63(t):
    """q53's manager/monthly twin."""
    return _quarterly_deviation(t, "i_manager_id", "d_moy")


def q67(t):
    """Store/item sales ROLLUP down the full product-time hierarchy with
    a top-100-per-category rank (i_item_id and s_store_name stand in for
    the spec's i_product_name and s_store_id)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))
    rolled = (t["store_sales"]
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"],
                    on=col("ss_store_sk") == col("s_store_sk"))
              .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
              .rollup(col("i_category"), col("i_class"), col("i_brand"),
                      col("i_item_id"), col("d_year"), col("d_qoy"),
                      col("d_moy"), col("s_store_name"))
              .agg(F.sum(col("ss_sales_price") * col("ss_quantity"))
                   .alias("sumsales")))
    w = Window.partition_by(col("i_category")) \
        .order_by(col("sumsales").desc())
    return (rolled.with_column("rk", F.rank().over(w))
            .filter(col("rk") <= 100)
            .order_by(col("i_category"), col("i_class"), col("i_brand"),
                      col("i_item_id"), col("d_year"), col("d_qoy"),
                      col("d_moy"), col("s_store_name"), col("sumsales"),
                      col("rk"))
            .limit(100))


def q70(t):
    """Profit ROLLUP by state/county, restricted to the five most
    profitable states (rank window over an aggregate, semi-joined back
    into the store dimension)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))
    state_rank = (t["store_sales"]
                  .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
                  .join(t["store"],
                        on=col("ss_store_sk") == col("s_store_sk"))
                  .group_by(col("s_state"))
                  .agg(F.sum(col("ss_net_profit")).alias("sp"))
                  .with_column("r", F.rank().over(
                      Window.order_by(col("sp").desc())))
                  .filter(col("r") <= 5)
                  .select(col("s_state").alias("top_state")))
    st = t["store"].join(state_rank,
                         on=col("s_state") == col("top_state"),
                         how="left_semi")
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .rollup(col("s_state"), col("s_county"))
            .agg(F.sum(col("ss_net_profit")).alias("total_sum"))
            .order_by(col("total_sum").desc(), col("s_state"),
                      col("s_county"))
            .limit(100))


def q86(t):
    """q36's web twin: net-paid ROLLUP by category/class with an
    in-category rank (ws_ext_sales_price stands in for ws_net_paid)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))
    rolled = (t["web_sales"]
              .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"))
              .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
              .rollup(col("i_category"), col("i_class"))
              .agg(F.sum(col("ws_ext_sales_price"))
                   .alias("total_sum")))
    w = Window.partition_by(col("i_category")) \
        .order_by(col("total_sum").desc())
    return (rolled
            .with_column("rank_within_parent", F.rank().over(w))
            .order_by(col("i_category"), col("rank_within_parent"))
            .limit(100))


def q97(t):
    """Channel overlap of (customer, item) purchase pairs: store vs
    catalog FULL OUTER join, counted into store-only / catalog-only /
    both buckets."""
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))
    ssci = (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .select(col("ss_customer_sk").alias("sc"),
                    col("ss_item_sk").alias("si"))
            .distinct())
    csci = (t["catalog_sales"]
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .select(col("cs_bill_customer_sk").alias("cc"),
                    col("cs_item_sk").alias("ci"))
            .distinct())
    return (ssci.join(csci, on=(col("sc") == col("cc"))
                      & (col("si") == col("ci")), how="full")
            .agg(F.sum(F.when(col("sc").is_not_null()
                              & col("cc").is_null(), 1).otherwise(0))
                 .alias("store_only"),
                 F.sum(F.when(col("sc").is_null()
                              & col("cc").is_not_null(), 1).otherwise(0))
                 .alias("catalog_only"),
                 F.sum(F.when(col("sc").is_not_null()
                              & col("cc").is_not_null(), 1).otherwise(0))
                 .alias("store_and_catalog")))


def q2(t):
    """Year-over-year web+catalog day-of-week ratios (q59's two-channel
    twin: the channels union BEFORE the pivot; monthly granularity stands
    in for week_seq as in q59)."""
    wscs = (t["web_sales"]
            .select(col("ws_sold_date_sk").alias("sold_date_sk"),
                    col("ws_ext_sales_price").alias("sales_price"))
            .union(t["catalog_sales"]
                   .select(col("cs_sold_date_sk").alias("sold_date_sk"),
                           col("cs_ext_sales_price")
                           .alias("sales_price"))))

    def pivot(year, prefix):
        dd = t["date_dim"].filter(col("d_year") == year)
        sums = [F.sum(F.when(col("d_day_name") == day,
                             col("sales_price")).otherwise(0.0))
                .alias(f"{prefix}_{day[:3].lower()}")
                for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                            "Thursday", "Friday", "Saturday"]]
        return (wscs.join(dd,
                          on=col("sold_date_sk") == col("d_date_sk"))
                .group_by(col("d_moy"))
                .agg(*sums)
                .select(col("d_moy").alias(f"{prefix}_moy"),
                        *[col(f"{prefix}_{d}") for d in
                          ("sun", "mon", "tue", "wed", "thu", "fri",
                           "sat")]))

    y1, y2 = pivot(2001, "a"), pivot(2002, "b")
    out = [col("a_moy")]
    for d in ("sun", "mon", "tue", "wed", "thu", "fri", "sat"):
        out.append(F.round(col("b_{0}".format(d))
                           / col("a_{0}".format(d)), 2)
                   .alias(f"r_{d}"))
    return (y1.join(y2, on=col("a_moy") == col("b_moy"))
            .select(*out)
            .order_by(col("a_moy"))
            .limit(100))


def q9(t):
    """Five quantity-band CASE picks (bucket count decides whether the
    discount or the profit average is reported), composed driver-side
    from per-band aggregates like the other scalar-subquery queries
    (q88/tpch q11)."""
    bands = [(1, 20, 74129), (21, 40, 122840), (41, 60, 56580),
             (61, 80, 10097), (81, 100, 165306)]
    data = {}
    for i, (lo, hi, thresh) in enumerate(bands, start=1):
        row = (t["store_sales"]
               .filter(col("ss_quantity").between(lo, hi))
               .agg(F.count(lit(1)).alias("cnt"),
                    F.avg(col("ss_ext_discount_amt")).alias("disc"),
                    F.avg(col("ss_net_profit")).alias("prof"))
               .collect()[0])
        cnt, disc, prof = row
        # the spec's threshold count scaled to the tiny-sf row budget
        data[f"bucket{i}"] = [float(disc if (cnt or 0) > thresh * 1e-4
                                    else prof)]
    return t["store_sales"].session.from_pydict(data)


def q17(t):
    """Quantity statistics (mean + stdev + coefficient of variation) over
    the sale->return->catalog-repurchase chain, by item and store state.
    stdev_samp is composed from sum/sum-of-squares/count, the same
    decomposition the engine's two-pass variance would use."""
    joined = _sale_return_catalog(
        t, col("d_qoy") == 1, col("d_qoy").isin(1, 2, 3),
        col("d_qoy").isin(1, 2, 3))

    def stats(q, name):
        n = F.count(lit(1))
        s = F.sum(q)
        s2 = F.sum(q * q)
        return [n.alias(f"{name}_count"), s.alias(f"{name}_sum"),
                s2.alias(f"{name}_sumsq")]

    aggd = (joined
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_state"))
            .agg(*(stats(col("ss_quantity").cast("double"), "ss")
                   + stats(col("sr_return_quantity").cast("double"), "sr")
                   + stats(col("cs_quantity").cast("double"), "cs"))))
    out = [col("i_item_id"), col("i_item_desc"), col("s_state")]
    for name in ("ss", "sr", "cs"):
        n, s, s2 = (col(f"{name}_count"), col(f"{name}_sum"),
                    col(f"{name}_sumsq"))
        mean = s / n
        var = F.when(n > 1, (s2 - s * s / n) / (n - 1)).otherwise(0.0)
        out += [n.alias(f"{name}_qty_count"),
                mean.alias(f"{name}_qty_av"),
                F.sqrt(var).alias(f"{name}_qty_stdev"),
                (F.sqrt(var) / mean).alias(f"{name}_qty_cov")]
    return (aggd.select(*out)
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_state"))
            .limit(100))


def q18(t):
    """Catalog purchase averages for a demographic slice, ROLLUP'd down
    the customer geography (the spec's c_birth_year output is omitted:
    the tiny-sf customer table carries birth month only)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "F")
        & (col("cd_education_status") == "Unknown"))
    cust = t["customer"].filter(col("c_birth_month").isin(1, 6, 8, 9,
                                                          12, 2))
    dd = t["date_dim"].filter(col("d_year") == 1998)
    return (t["catalog_sales"]
            .join(cd, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
            .join(cust,
                  on=col("cs_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
            .rollup(col("ca_country"), col("ca_state"), col("ca_county"),
                    col("i_item_id"))
            .agg(F.avg(col("cs_quantity").cast("double")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_sales_price")).alias("agg4"))
            .order_by(col("ca_country"), col("ca_state"),
                      col("ca_county"), col("i_item_id"))
            .limit(100))


def q28(t):
    """Six list-price band statistics (avg + count + distinct count per
    band), composed driver-side like q88/q9."""
    bands = [(0, 5, 11, 40, 14), (6, 10, 91, 200, 108),
             (11, 15, 66, 350, 123), (16, 20, 142, 500, 272),
             (21, 25, 135, 650, 146), (26, 30, 28, 800, 123)]
    data = {}
    for i, (qlo, qhi, plo, wlo, clo) in enumerate(bands, 1):
        row = (t["store_sales"]
               .filter(col("ss_quantity").between(qlo, qhi)
                       & (col("ss_list_price").between(plo, plo + 10)
                          | col("ss_coupon_amt").between(clo, clo + 1000)
                          | col("ss_ext_wholesale_cost")
                          .between(wlo, wlo + 100)))
               .agg(F.avg(col("ss_list_price")).alias("a"),
                    F.count(col("ss_list_price")).alias("c"),
                    F.count_distinct(col("ss_list_price")).alias("d"))
               .collect()[0])
        data[f"b{i}_avg"] = [float(row[0] or 0.0)]
        data[f"b{i}_count"] = [int(row[1] or 0)]
        data[f"b{i}_distinct"] = [int(row[2] or 0)]
    return t["store_sales"].session.from_pydict(data)


def q39(t):
    """Inventory demand variability: per (item, warehouse, month) mean
    and stdev of on-hand quantity, consecutive months self-joined where
    both months' coefficient of variation exceeds 0.3 (the spec's 1.0
    threshold, scaled to the generator's uniform quantities whose cov
    tops out near 0.6; stdev composed from sum/sumsq/count as in q17)."""
    dd = t["date_dim"].filter(col("d_year") == 2001)
    base = (t["inventory"]
            .join(dd, on=col("inv_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("inv_item_sk") == col("i_item_sk"))
            .join(t["warehouse"],
                  on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
            .group_by(col("w_warehouse_sk"), col("i_item_sk"),
                      col("d_moy"))
            .agg(F.count(lit(1)).alias("n"),
                 F.sum(col("inv_quantity_on_hand").cast("double"))
                 .alias("s"),
                 F.sum(col("inv_quantity_on_hand").cast("double")
                       * col("inv_quantity_on_hand").cast("double"))
                 .alias("s2")))
    mean = col("s") / col("n")
    var = F.when(col("n") > 1,
                 (col("s2") - col("s") * col("s") / col("n"))
                 / (col("n") - 1)).otherwise(0.0)
    cov = (base
           .with_column("mean", mean)
           .with_column("cov", F.when(col("mean") == 0.0, 0.0)
                        .otherwise(F.sqrt(var) / col("mean")))
           .filter(col("cov") > 0.3))
    m1 = cov.select(col("w_warehouse_sk").alias("w1"),
                    col("i_item_sk").alias("i1"),
                    col("d_moy").alias("moy1"),
                    col("mean").alias("mean1"), col("cov").alias("cov1")) \
        .filter(col("moy1") == 3)
    m2 = cov.select(col("w_warehouse_sk").alias("w2"),
                    col("i_item_sk").alias("i2"),
                    col("d_moy").alias("moy2"),
                    col("mean").alias("mean2"), col("cov").alias("cov2")) \
        .filter(col("moy2") == 4)
    return (m1.join(m2, on=(col("w1") == col("w2"))
                    & (col("i1") == col("i2")))
            .select(col("w1"), col("i1"), col("mean1"), col("cov1"),
                    col("mean2"), col("cov2"))
            .order_by(col("w1"), col("i1"), col("mean1"), col("cov1"),
                      col("mean2"), col("cov2"))
            .limit(100))


def q50(t):
    """Return-latency buckets per store: days between sale and return,
    counted into <=30/31-60/61-90/91-120/>120 bands (date_dim joined
    twice, once per side of the sale->return pair)."""
    d1 = t["date_dim"].select(col("d_date_sk").alias("sold_dsk"),
                              col("d_date").alias("sold_date"))
    d2 = (t["date_dim"].filter((col("d_year") == 2001)
                               & (col("d_moy") == 8))
          .select(col("d_date_sk").alias("ret_dsk"),
                  col("d_date").alias("ret_date")))
    joined = (t["store_sales"]
              .join(t["store_returns"],
                    on=(col("ss_ticket_number") == col("sr_ticket_number"))
                    & (col("ss_item_sk") == col("sr_item_sk"))
                    & (col("ss_customer_sk") == col("sr_customer_sk")))
              .join(d1, on=col("ss_sold_date_sk") == col("sold_dsk"))
              .join(d2, on=col("sr_returned_date_sk") == col("ret_dsk"))
              .join(t["store"],
                    on=col("ss_store_sk") == col("s_store_sk"))
              .with_column("lag_days", F.datediff(col("ret_date"),
                                                  col("sold_date"))))
    buckets = [
        F.sum(F.when(col("lag_days") <= 30, 1).otherwise(0))
        .alias("d30"),
        F.sum(F.when((col("lag_days") > 30) & (col("lag_days") <= 60), 1)
              .otherwise(0)).alias("d31_60"),
        F.sum(F.when((col("lag_days") > 60) & (col("lag_days") <= 90), 1)
              .otherwise(0)).alias("d61_90"),
        F.sum(F.when((col("lag_days") > 90) & (col("lag_days") <= 120), 1)
              .otherwise(0)).alias("d91_120"),
        F.sum(F.when(col("lag_days") > 120, 1).otherwise(0))
        .alias("d120plus")]
    return (joined
            .group_by(col("s_store_name"), col("s_company_name"),
                      col("s_county"), col("s_city"), col("s_state"),
                      col("s_zip"))
            .agg(*buckets)
            .order_by(col("s_store_name"), col("s_company_name"),
                      col("s_county"), col("s_city"), col("s_state"),
                      col("s_zip"))
            .limit(100))


def q51(t):
    """Cumulative web vs store revenue per item over time: running sums
    windowed per item, FULL OUTER joined on (item, period), kept while
    the web cumulative exceeds the store cumulative (monthly periods
    stand in for the spec's daily ones at tiny scale factors, the q59/q2
    convention)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35))

    def cumulative(sales, item_c, date_c, price_c, prefix):
        daily = (sales.join(dd, on=col(date_c) == col("d_date_sk"))
                 .group_by(col(item_c), col("d_month_seq"))
                 .agg(F.sum(col(price_c)).alias("daily")))
        w = (Window.partition_by(col(item_c))
             .order_by(col("d_month_seq"))
             .rows_between(-(1 << 62), 0))
        return (daily
                .with_column("cume", F.sum(col("daily")).over(w))
                .select(col(item_c).alias(f"{prefix}_item_sk"),
                        col("d_month_seq").alias(f"{prefix}_date"),
                        col("cume").alias(f"{prefix}_cume")))

    web = cumulative(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
                     "ws_ext_sales_price", "web")
    store = cumulative(t["store_sales"], "ss_item_sk",
                       "ss_sold_date_sk", "ss_ext_sales_price", "store")
    return (web.join(store,
                     on=(col("web_item_sk") == col("store_item_sk"))
                     & (col("web_date") == col("store_date")),
                     how="full")
            .filter(col("web_cume") > col("store_cume"))
            .select(F.coalesce(col("web_item_sk"), col("store_item_sk"))
                    .alias("item_sk"),
                    F.coalesce(col("web_date"), col("store_date"))
                    .alias("d_date"),
                    col("web_cume"), col("store_cume"))
            .order_by(col("item_sk"), col("d_date"))
            .limit(100))


def q61(t):
    """Promotional share of store revenue for one category and month:
    promotional sales (email/event promos) over all sales, the two
    single-row aggregates composed driver-side (q88's pattern)."""
    dd = t["date_dim"].filter((col("d_year") == 1998)
                              & (col("d_moy") == 11))
    it = t["item"].filter(col("i_category") == "Jewelry")
    st = t["store"].filter(col("s_gmt_offset") == -5.0)
    base = (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .filter(col("ca_gmt_offset") == -5.0))
    promo = (base.join(t["promotion"],
                       on=col("ss_promo_sk") == col("p_promo_sk"))
             .filter((col("p_channel_email") == "Y")
                     | (col("p_channel_event") == "Y"))
             .agg(F.sum(col("ss_ext_sales_price")).alias("promotions"))
             .collect()[0][0])
    total = (base.agg(F.sum(col("ss_ext_sales_price")).alias("total"))
             .collect()[0][0])
    promo = float(promo or 0.0)
    total = float(total or 0.0)
    ratio = promo / total * 100.0 if total else 0.0
    return t["store_sales"].session.from_pydict(
        {"promotions": [promo], "total": [total], "ratio": [ratio]})


def _year_total(t, sales_key, cust_key, date_key, price_col, year,
                prefix):
    """Per-customer yearly revenue for one channel — the q4/q11/q74
    building block (ext_sales_price stands in for the spec's list-price
    minus discount arithmetic, which the tiny-sf fact tables fold into
    one column)."""
    dd = t["date_dim"].filter(col("d_year") == year)
    return (t[sales_key]
            .join(dd, on=col(date_key) == col("d_date_sk"))
            .join(t["customer"],
                  on=col(cust_key) == col("c_customer_sk"))
            .group_by(col("c_customer_sk"))
            .agg(F.sum(col(price_col)).alias(f"{prefix}_total"))
            .select(col("c_customer_sk").alias(f"{prefix}_cust"),
                    col(f"{prefix}_total")))


def q11(t):
    """Customers whose web growth outpaced their store growth between two
    years (four per-channel year totals self-joined per customer)."""
    s1 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 2001, "s1")
    s2 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 2002, "s2")
    w1 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 2001, "w1")
    w2 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 2002, "w2")
    return (s1.join(s2, on=col("s1_cust") == col("s2_cust"))
            .join(w1, on=col("s1_cust") == col("w1_cust"))
            .join(w2, on=col("s1_cust") == col("w2_cust"))
            .filter((col("s1_total") > 0) & (col("w1_total") > 0)
                    & (col("w2_total") / col("w1_total")
                       > col("s2_total") / col("s1_total")))
            .join(t["customer"],
                  on=col("s1_cust") == col("c_customer_sk"))
            .select(col("c_customer_id"), col("c_first_name"),
                    col("c_last_name"), col("c_preferred_cust_flag"))
            .order_by(col("c_customer_id"))
            .limit(100))


def q4(t):
    """q11 plus the catalog channel: customers whose catalog growth beats
    store growth AND web growth beats store growth (six year totals)."""
    s1 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 2001, "s1")
    s2 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 2002, "s2")
    c1 = _year_total(t, "catalog_sales", "cs_bill_customer_sk",
                     "cs_sold_date_sk", "cs_ext_sales_price", 2001, "c1")
    c2 = _year_total(t, "catalog_sales", "cs_bill_customer_sk",
                     "cs_sold_date_sk", "cs_ext_sales_price", 2002, "c2")
    w1 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 2001, "w1")
    w2 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 2002, "w2")
    return (s1.join(s2, on=col("s1_cust") == col("s2_cust"))
            .join(c1, on=col("s1_cust") == col("c1_cust"))
            .join(c2, on=col("s1_cust") == col("c2_cust"))
            .join(w1, on=col("s1_cust") == col("w1_cust"))
            .join(w2, on=col("s1_cust") == col("w2_cust"))
            .filter((col("s1_total") > 0) & (col("c1_total") > 0)
                    & (col("w1_total") > 0)
                    & (col("c2_total") / col("c1_total")
                       > col("s2_total") / col("s1_total"))
                    & (col("w2_total") / col("w1_total")
                       > col("s2_total") / col("s1_total")))
            .join(t["customer"],
                  on=col("s1_cust") == col("c_customer_sk"))
            .select(col("c_customer_id"), col("c_first_name"),
                    col("c_last_name"), col("c_preferred_cust_flag"))
            .order_by(col("c_customer_id"))
            .limit(100))


def q74(t):
    """q11's earlier-year twin (1999 vs 2000), kept as its own entry
    because the spec's parameter bindings differ."""
    s1 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 1999, "s1")
    s2 = _year_total(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price", 2000, "s2")
    w1 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 1999, "w1")
    w2 = _year_total(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price", 2000, "w2")
    return (s1.join(s2, on=col("s1_cust") == col("s2_cust"))
            .join(w1, on=col("s1_cust") == col("w1_cust"))
            .join(w2, on=col("s1_cust") == col("w2_cust"))
            .filter((col("s1_total") > 0) & (col("w1_total") > 0)
                    & (col("w2_total") / col("w1_total")
                       > col("s2_total") / col("s1_total")))
            .join(t["customer"],
                  on=col("s1_cust") == col("c_customer_sk"))
            .select(col("c_customer_id"), col("c_first_name"),
                    col("c_last_name"))
            .order_by(col("c_customer_id"))
            .limit(100))


def q14(t):
    """Cross-channel items (brand/class/category sold through ALL THREE
    channels — the spec's INTERSECT, expressed as semi-join chains like
    q38) whose channel sales beat the all-channel average (driver-side
    scalar), ROLLUP'd by channel and hierarchy."""
    dd = t["date_dim"].filter(col("d_year").isin(1999, 2000, 2001))

    def channel_keys(sales, item_c, date_c, p):
        return (t[sales]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .join(t["item"], on=col(item_c) == col("i_item_sk"))
                .select(col("i_brand_id").alias(f"{p}_brand"),
                        col("i_class_id").alias(f"{p}_class"),
                        col("i_category_id").alias(f"{p}_cat"))
                .distinct())

    ss_k = channel_keys("store_sales", "ss_item_sk", "ss_sold_date_sk",
                        "s")
    cs_k = channel_keys("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                        "c")
    ws_k = channel_keys("web_sales", "ws_item_sk", "ws_sold_date_sk",
                        "w")
    cross = (ss_k
             .join(cs_k, on=(col("s_brand") == col("c_brand"))
                   & (col("s_class") == col("c_class"))
                   & (col("s_cat") == col("c_cat")), how="left_semi")
             .join(ws_k, on=(col("s_brand") == col("w_brand"))
                   & (col("s_class") == col("w_class"))
                   & (col("s_cat") == col("w_cat")), how="left_semi"))
    cross_items = (t["item"]
                   .join(cross,
                         on=(col("i_brand_id") == col("s_brand"))
                         & (col("i_class_id") == col("s_class"))
                         & (col("i_category_id") == col("s_cat")),
                         how="left_semi")
                   .select(col("i_item_sk").alias("ci_sk")))

    # average per-channel (quantity x list_price) — the spec's scalar CTE
    avg_rows = []
    for sales, qty_c, price_c, date_c in (
            ("store_sales", "ss_quantity", "ss_list_price",
             "ss_sold_date_sk"),
            ("catalog_sales", "cs_quantity", "cs_list_price",
             "cs_sold_date_sk"),
            ("web_sales", "ws_quantity", "ws_list_price",
             "ws_sold_date_sk")):
        v = (t[sales].join(dd, on=col(date_c) == col("d_date_sk"))
             .agg(F.avg(col(qty_c).cast("double") * col(price_c))
                  .alias("a")).collect()[0][0])
        avg_rows.append(float(v or 0.0))
    avg_sales = sum(avg_rows) / len(avg_rows)

    dd2 = t["date_dim"].filter((col("d_year") == 2001)
                               & (col("d_moy") == 11))

    def channel_sales(sales, item_c, date_c, qty_c, price_c, label):
        return (t[sales]
                .join(dd2, on=col(date_c) == col("d_date_sk"))
                .join(cross_items, on=col(item_c) == col("ci_sk"),
                      how="left_semi")
                .join(t["item"], on=col(item_c) == col("i_item_sk"))
                .group_by(col("i_brand_id"), col("i_class_id"),
                          col("i_category_id"))
                .agg(F.sum(col(qty_c).cast("double") * col(price_c))
                     .alias("sales"),
                     F.count(lit(1)).alias("number_sales"))
                .filter(col("sales") > avg_sales)
                .select(lit(label).alias("channel"), col("i_brand_id"),
                        col("i_class_id"), col("i_category_id"),
                        col("sales"), col("number_sales")))

    unioned = (channel_sales("store_sales", "ss_item_sk",
                             "ss_sold_date_sk", "ss_quantity",
                             "ss_list_price", "store")
               .union(channel_sales("catalog_sales", "cs_item_sk",
                                    "cs_sold_date_sk", "cs_quantity",
                                    "cs_list_price", "catalog"))
               .union(channel_sales("web_sales", "ws_item_sk",
                                    "ws_sold_date_sk", "ws_quantity",
                                    "ws_list_price", "web")))
    return (unioned
            .rollup(col("channel"), col("i_brand_id"), col("i_class_id"),
                    col("i_category_id"))
            .agg(F.sum(col("sales")).alias("sum_sales"),
                 F.sum(col("number_sales")).alias("sum_number_sales"))
            .order_by(col("channel"), col("i_brand_id"),
                      col("i_class_id"), col("i_category_id"))
            .limit(100))


def q23(t):
    """Catalog+web revenue in one month from the best store customers
    buying frequently-bought-in-store items (two scalar CTEs: the
    frequent-item set as a semi-join, the best-customer cut against a
    driver-side max)."""
    dd4 = t["date_dim"].filter(col("d_year").isin(2000, 2001, 2002,
                                                  2003))
    # items sold on >4 distinct days in the window (spec: count(*) > 4
    # per (item, date) key folded to a per-item frequency)
    frequent = (t["store_sales"]
                .join(dd4, on=col("ss_sold_date_sk") == col("d_date_sk"))
                .group_by(col("ss_item_sk"))
                .agg(F.count_distinct(col("ss_sold_date_sk"))
                     .alias("days"))
                .filter(col("days") > 4)
                .select(col("ss_item_sk").alias("freq_sk")))
    # customer store totals and the max of them
    totals = (t["store_sales"]
              .group_by(col("ss_customer_sk"))
              .agg(F.sum(col("ss_quantity").cast("double")
                         * col("ss_sales_price")).alias("csales")))
    tpcds_cmax = float(totals.agg(F.max(col("csales")).alias("m"))
                       .collect()[0][0] or 0.0)
    best = (totals.filter(col("csales") > 0.5 * tpcds_cmax)
            .select(col("ss_customer_sk").alias("best_cust")))
    dd1 = t["date_dim"].filter((col("d_year") == 2000)
                               & (col("d_moy") == 2))
    cs_part = (t["catalog_sales"]
               .join(dd1, on=col("cs_sold_date_sk") == col("d_date_sk"))
               .join(frequent, on=col("cs_item_sk") == col("freq_sk"),
                     how="left_semi")
               .join(best,
                     on=col("cs_bill_customer_sk") == col("best_cust"),
                     how="left_semi")
               .select((col("cs_quantity").cast("double")
                        * col("cs_list_price")).alias("sales")))
    ws_part = (t["web_sales"]
               .join(dd1, on=col("ws_sold_date_sk") == col("d_date_sk"))
               .join(frequent, on=col("ws_item_sk") == col("freq_sk"),
                     how="left_semi")
               .join(best,
                     on=col("ws_bill_customer_sk") == col("best_cust"),
                     how="left_semi")
               .select((col("ws_quantity").cast("double")
                        * col("ws_list_price")).alias("sales")))
    return (cs_part.union(ws_part)
            .agg(F.sum(col("sales")).alias("total_sales")))


def q16(t):
    """Catalog orders in a 60-day window shipped from a state, fulfilled
    from MORE than one warehouse (EXISTS with an inequality -> semi join
    on order with warehouse mismatch) and never returned (NOT EXISTS ->
    anti join).  cs_ext_sales_price stands in for the spec's
    cs_ext_ship_cost; the call-center county filter is folded into the
    join (the tiny-sf call_center table carries no county)."""
    dd = t["date_dim"].filter(col("d_date").between("2002-02-01",
                                                    "2002-04-02"))
    ca = t["customer_address"].filter(col("ca_state") == "GA")
    other_wh = t["catalog_sales"].select(
        col("cs_order_number").alias("o2"),
        col("cs_warehouse_sk").alias("w2"))
    returned = t["catalog_returns"].select(
        col("cr_order_number").alias("ro"))
    base = (t["catalog_sales"]
            .join(dd, on=col("cs_ship_date_sk") == col("d_date_sk"))
            .join(ca, on=col("cs_ship_addr_sk") == col("ca_address_sk"))
            .join(t["call_center"],
                  on=col("cs_call_center_sk") == col("cc_call_center_sk"))
            .join(other_wh, on=(col("cs_order_number") == col("o2"))
                  & (col("cs_warehouse_sk") != col("w2")),
                  how="left_semi")
            .join(returned, on=col("cs_order_number") == col("ro"),
                  how="left_anti"))
    return (base.agg(F.count_distinct(col("cs_order_number"))
                     .alias("order_count"),
                     F.sum(col("cs_ext_sales_price"))
                     .alias("total_shipping_cost"),
                     F.sum(col("cs_net_profit")).alias("total_net_profit")))


def q94(t):
    """q16's web twin: web orders shipped from more than one warehouse
    with no returns (ws_ext_sales_price stands in for ws_ext_ship_cost;
    the 60-day window widened to four months for the tiny-sf row
    budget)."""
    dd = t["date_dim"].filter(col("d_date").between("1999-02-01",
                                                    "1999-06-02"))
    ca = t["customer_address"].filter(col("ca_state") == "TX")
    other_wh = t["web_sales"].select(
        col("ws_order_number").alias("o2"),
        col("ws_warehouse_sk").alias("w2"))
    returned = t["web_returns"].select(
        col("wr_order_number").alias("ro"))
    base = (t["web_sales"]
            .join(dd, on=col("ws_ship_date_sk") == col("d_date_sk"))
            .join(ca, on=col("ws_ship_addr_sk") == col("ca_address_sk"))
            .join(t["web_site"],
                  on=col("ws_web_site_sk") == col("web_site_sk"))
            .join(other_wh, on=(col("ws_order_number") == col("o2"))
                  & (col("ws_warehouse_sk") != col("w2")),
                  how="left_semi")
            .join(returned, on=col("ws_order_number") == col("ro"),
                  how="left_anti"))
    return (base.agg(F.count_distinct(col("ws_order_number"))
                     .alias("order_count"),
                     F.sum(col("ws_ext_sales_price"))
                     .alias("total_shipping_cost"),
                     F.sum(col("ws_net_profit")).alias("total_net_profit")))


def q95(t):
    """Web orders from multi-warehouse fulfilment where the order WAS
    returned (q94's returned complement: both the order and its return
    must sit in the two-warehouse order set; q94's widened four-month
    window, which the added was-returned cut needs even more)."""
    dd = t["date_dim"].filter(col("d_date").between("1999-02-01",
                                                    "1999-06-02"))
    ca = t["customer_address"].filter(col("ca_state") == "TX")
    ws1 = t["web_sales"].select(col("ws_order_number").alias("p1"),
                                col("ws_warehouse_sk").alias("pw1"))
    ws2 = t["web_sales"].select(col("ws_order_number").alias("p2"),
                                col("ws_warehouse_sk").alias("pw2"))
    ws_wh = (ws1.join(ws2, on=(col("p1") == col("p2"))
                      & (col("pw1") != col("pw2")))
             .select(col("p1").alias("wh_order")).distinct())
    returned = (t["web_returns"]
                .join(ws_wh, on=col("wr_order_number") == col("wh_order"),
                      how="left_semi")
                .select(col("wr_order_number").alias("ro")).distinct())
    base = (t["web_sales"]
            .join(dd, on=col("ws_ship_date_sk") == col("d_date_sk"))
            .join(ca, on=col("ws_ship_addr_sk") == col("ca_address_sk"))
            .join(t["web_site"],
                  on=col("ws_web_site_sk") == col("web_site_sk"))
            .join(ws_wh, on=col("ws_order_number") == col("wh_order"),
                  how="left_semi")
            .join(returned, on=col("ws_order_number") == col("ro"),
                  how="left_semi"))
    return (base.agg(F.count_distinct(col("ws_order_number"))
                     .alias("order_count"),
                     F.sum(col("ws_ext_sales_price"))
                     .alias("total_shipping_cost"),
                     F.sum(col("ws_net_profit")).alias("total_net_profit")))


def _ship_latency_buckets(t, sales_key, sold_c, ship_c, wh_c, mode_c,
                          group_dim, group_key, group_out):
    """q62/q99 core: days between order and ship, bucketed per
    (warehouse, ship mode, {web site | call center})."""
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35)) \
        .select(col("d_date_sk").alias("ship_dsk"))
    lag = col(ship_c) - col(sold_c)  # consecutive date_sks: sk diff IS days
    buckets = [
        F.sum(F.when(lag <= 30, 1).otherwise(0)).alias("d30"),
        F.sum(F.when((lag > 30) & (lag <= 60), 1).otherwise(0))
        .alias("d31_60"),
        F.sum(F.when((lag > 60) & (lag <= 90), 1).otherwise(0))
        .alias("d61_90"),
        F.sum(F.when((lag > 90) & (lag <= 120), 1).otherwise(0))
        .alias("d91_120"),
        F.sum(F.when(lag > 120, 1).otherwise(0)).alias("d120plus")]
    return (t[sales_key]
            .join(dd, on=col(ship_c) == col("ship_dsk"))
            .join(t["warehouse"], on=col(wh_c) == col("w_warehouse_sk"))
            .join(t["ship_mode"],
                  on=col(mode_c) == col("sm_ship_mode_sk"))
            .join(t[group_dim], on=group_key)
            .group_by(col("w_warehouse_name"), col("sm_type"),
                      col(group_out))
            .agg(*buckets)
            .order_by(col("w_warehouse_name"), col("sm_type"),
                      col(group_out))
            .limit(100))


def q62(t):
    """Web ship-latency buckets per warehouse x ship mode x site."""
    return _ship_latency_buckets(
        t, "web_sales", "ws_sold_date_sk", "ws_ship_date_sk",
        "ws_warehouse_sk", "ws_ship_mode_sk", "web_site",
        col("ws_web_site_sk") == col("web_site_sk"), "web_site_id")


def q99(t):
    """q62's catalog twin (call center instead of web site)."""
    return _ship_latency_buckets(
        t, "catalog_sales", "cs_sold_date_sk", "cs_ship_date_sk",
        "cs_warehouse_sk", "cs_ship_mode_sk", "call_center",
        col("cs_call_center_sk") == col("cc_call_center_sk"), "cc_name")


def q66(t):
    """Warehouse shipping volume pivoted into monthly columns (web +
    catalog union, carrier-filtered, time-of-day window; w_warehouse_name
    is the only warehouse attribute the tiny-sf table carries)."""
    dd = t["date_dim"].filter(col("d_year") == 2001)
    td = t["time_dim"].filter(col("t_hour").between(8, 16))
    sm = t["ship_mode"].filter(col("sm_carrier").isin("UPS", "FEDEX"))

    def channel(sales, date_c, time_c, wh_c, mode_c, qty_c, price_c):
        monthly = [F.sum(F.when(col("d_moy") == m,
                                col(qty_c).cast("double") * col(price_c))
                         .otherwise(0.0)).alias(f"m{m}_sales")
                   for m in range(1, 13)]
        return (t[sales]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .join(td, on=col(time_c) == col("t_time_sk"))
                .join(sm, on=col(mode_c) == col("sm_ship_mode_sk"))
                .join(t["warehouse"],
                      on=col(wh_c) == col("w_warehouse_sk"))
                .group_by(col("w_warehouse_name"), col("d_year"))
                .agg(*monthly))

    web = channel("web_sales", "ws_sold_date_sk", "ws_sold_time_sk",
                  "ws_warehouse_sk", "ws_ship_mode_sk", "ws_quantity",
                  "ws_list_price")
    cat = channel("catalog_sales", "cs_sold_date_sk", "cs_sold_time_sk",
                  "cs_warehouse_sk", "cs_ship_mode_sk", "cs_quantity",
                  "cs_list_price")
    return (web.union(cat)
            .group_by(col("w_warehouse_name"), col("d_year"))
            .agg(*[F.sum(col(f"m{m}_sales")).alias(f"jan_dec_{m}")
                   for m in range(1, 13)])
            .order_by(col("w_warehouse_name"))
            .limit(100))


def q71(t):
    """Brand revenue by hour across all three channels for one month,
    restricted to breakfast/dinner hours (union BEFORE the time join)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1999))
    # a band of managers instead of the spec's single one: at tiny sf a
    # 1-in-40 manager cut of one month's meal-hour rows selects nothing
    it = t["item"].filter(col("i_manager_id").between(1, 8))
    td = t["time_dim"].filter(col("t_hour").isin(7, 8, 18, 19))
    parts = [
        ("web_sales", "ws_ext_sales_price", "ws_item_sk",
         "ws_sold_date_sk", "ws_sold_time_sk"),
        ("catalog_sales", "cs_ext_sales_price", "cs_item_sk",
         "cs_sold_date_sk", "cs_sold_time_sk"),
        ("store_sales", "ss_ext_sales_price", "ss_item_sk",
         "ss_sold_date_sk", "ss_sold_time_sk")]
    unioned = None
    for sales, price_c, item_c, date_c, time_c in parts:
        part = (t[sales]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .select(col(price_c).alias("ext_price"),
                        col(item_c).alias("sold_item_sk"),
                        col(time_c).alias("time_sk")))
        unioned = part if unioned is None else unioned.union(part)
    return (unioned
            .join(it, on=col("sold_item_sk") == col("i_item_sk"))
            .join(td, on=col("time_sk") == col("t_time_sk"))
            .group_by(col("i_brand_id"), col("i_brand"), col("t_hour"),
                      col("t_minute"))
            .agg(F.sum(col("ext_price")).alias("ext_price_sum"))
            .order_by(col("ext_price_sum").desc(), col("i_brand_id"),
                      col("t_hour"), col("t_minute"))
            .limit(100))


def q72(t):
    """Catalog lines whose inventory at a warehouse ran below the ordered
    quantity in the sale month, by demographic slice, with promo and
    return left joins counted (monthly inventory stands in for the
    spec's week_seq alignment; ship >5 days after sale kept)."""
    dd1 = (t["date_dim"].filter(col("d_year") == 2000)
           .select(col("d_date_sk").alias("sold_dsk"),
                   col("d_moy").alias("sold_moy"),
                   col("d_date").alias("sold_date")))
    dd2 = t["date_dim"].select(col("d_date_sk").alias("inv_dsk"),
                               col("d_moy").alias("inv_moy"),
                               col("d_year").alias("inv_year"))
    cd = t["customer_demographics"].filter(
        col("cd_marital_status") == "M")
    hd = t["household_demographics"].filter(
        col("hd_buy_potential") == ">10000")
    joined = (t["catalog_sales"]
              .join(dd1, on=col("cs_sold_date_sk") == col("sold_dsk"))
              .join(t["inventory"],
                    on=col("cs_item_sk") == col("inv_item_sk"))
              .join(dd2, on=col("inv_date_sk") == col("inv_dsk"))
              .filter((col("inv_year") == 2000)
                      & (col("inv_moy") == col("sold_moy"))
                      & (col("inv_quantity_on_hand") < col("cs_quantity"))
                      & (col("cs_ship_date_sk") - col("cs_sold_date_sk")
                         > 5))
              .join(t["warehouse"],
                    on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
              .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
              .join(cd, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
              .join(hd, on=col("cs_ship_hdemo_sk") == col("hd_demo_sk"))
              .join(t["promotion"],
                    on=col("cs_promo_sk") == col("p_promo_sk"),
                    how="left")
              .join(t["catalog_returns"]
                    .select(col("cr_item_sk").alias("cri"),
                            col("cr_order_number").alias("cro")),
                    on=(col("cs_item_sk") == col("cri"))
                    & (col("cs_order_number") == col("cro")),
                    how="left"))
    return (joined
            .group_by(col("i_item_desc"), col("w_warehouse_name"),
                      col("sold_moy"))
            .agg(F.sum(F.when(col("p_promo_sk").is_null(), 1)
                       .otherwise(0)).alias("no_promo"),
                 F.sum(F.when(col("p_promo_sk").is_not_null(), 1)
                       .otherwise(0)).alias("promo"),
                 F.count(lit(1)).alias("total_cnt"))
            .order_by(col("total_cnt").desc(), col("i_item_desc"),
                      col("w_warehouse_name"), col("sold_moy"))
            .limit(100))


def q76(t):
    """Sales rows whose channel foreign key is NULL (dsdgen leaves a
    fraction of fks null), unioned across channels and counted per
    year/quarter/category."""
    parts = []
    for sales, null_c, price_c, item_c, date_c, channel, col_name in (
            ("store_sales", "ss_store_sk", "ss_ext_sales_price",
             "ss_item_sk", "ss_sold_date_sk", "store", "ss_store_sk"),
            ("web_sales", "ws_ship_customer_sk", "ws_ext_sales_price",
             "ws_item_sk", "ws_sold_date_sk", "web",
             "ws_ship_customer_sk"),
            ("catalog_sales", "cs_ship_addr_sk", "cs_ext_sales_price",
             "cs_item_sk", "cs_sold_date_sk", "catalog",
             "cs_ship_addr_sk")):
        parts.append(
            t[sales].filter(col(null_c).is_null())
            .join(t["item"], on=col(item_c) == col("i_item_sk"))
            .join(t["date_dim"],
                  on=col(date_c) == col("d_date_sk"))
            .select(lit(channel).alias("channel"),
                    lit(col_name).alias("col_name"), col("d_year"),
                    col("d_qoy"), col("i_category"),
                    col(price_c).alias("ext_sales_price")))
    unioned = parts[0].union(parts[1]).union(parts[2])
    return (unioned
            .group_by(col("channel"), col("col_name"), col("d_year"),
                      col("d_qoy"), col("i_category"))
            .agg(F.count(lit(1)).alias("sales_cnt"),
                 F.sum(col("ext_sales_price")).alias("sales_amt"))
            .order_by(col("channel"), col("col_name"), col("d_year"),
                      col("d_qoy"), col("i_category"))
            .limit(100))


def _returns_above_state_avg(t, returns_key, cust_c, date_c, amt_c,
                             year, out_state):
    """q30/q81 core: customers returning more than 1.2x their state's
    average (q1's channel twins; the returning customer's CURRENT address
    state stands in for the spec's return-address state, which the
    tiny-sf returns tables do not carry)."""
    dd = t["date_dim"].filter(col("d_year") == year)
    ctr = (t[returns_key]
           .join(dd, on=col(date_c) == col("d_date_sk"))
           .join(t["customer"],
                 on=col(cust_c) == col("c_customer_sk"))
           .join(t["customer_address"],
                 on=col("c_current_addr_sk") == col("ca_address_sk"))
           .group_by(col(cust_c), col("ca_state"))
           .agg(F.sum(col(amt_c)).alias("ctr_total_return")))
    avg_ctr = (ctr.group_by(col("ca_state"))
               .agg((F.avg(col("ctr_total_return")) * 1.2)
                    .alias("avg_return"))
               .select(col("ca_state").alias("avg_state"),
                       col("avg_return")))
    return (ctr
            .join(avg_ctr, on=col("ca_state") == col("avg_state"))
            .filter(col("ctr_total_return") > col("avg_return"))
            .filter(col("ca_state") == out_state)
            .join(t["customer"],
                  on=col(cust_c) == col("c_customer_sk"))
            .select(col("c_customer_id"), col("c_salutation"),
                    col("c_first_name"), col("c_last_name"),
                    col("ctr_total_return"))
            .order_by(col("c_customer_id"), col("ctr_total_return"))
            .limit(100))


def q30(t):
    """Web customers returning more than 1.2x their state's average."""
    return _returns_above_state_avg(
        t, "web_returns", "wr_returning_customer_sk",
        "wr_returned_date_sk", "wr_return_amt", 2002, "TN")


def q81(t):
    """q30's catalog twin."""
    return _returns_above_state_avg(
        t, "catalog_returns", "cr_returning_customer_sk",
        "cr_returned_date_sk", "cr_return_amount", 2000, "GA")


def q32(t):
    """Catalog discounts exceeding 1.3x the item's average discount over
    a 90-day window (q92's catalog twin)."""
    dd = t["date_dim"].filter(col("d_date").between("2000-01-27",
                                                    "2000-04-26"))
    it = t["item"].filter(col("i_manufact_id") == 7)
    windowed = (t["catalog_sales"]
                .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk")))
    item_avg = (windowed
                .group_by(col("cs_item_sk"))
                .agg((F.avg(col("cs_ext_discount_amt")) * 1.3)
                     .alias("disc_bar"))
                .select(col("cs_item_sk").alias("bar_sk"),
                        col("disc_bar")))
    return (windowed
            .join(it, on=col("cs_item_sk") == col("i_item_sk"))
            .join(item_avg, on=col("cs_item_sk") == col("bar_sk"))
            .filter(col("cs_ext_discount_amt") > col("disc_bar"))
            .agg(F.sum(col("cs_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q40(t):
    """Catalog net value per warehouse/item/state around a pivot date,
    returns backed out via the sale's left-joined return row
    (cr_return_amount stands in for the spec's cr_refunded_cash)."""
    dd = t["date_dim"].filter(col("d_date").between("2000-02-10",
                                                    "2000-04-10"))
    it = t["item"].filter(col("i_current_price").between(0.99, 60.0))
    cr = t["catalog_returns"].select(
        col("cr_item_sk").alias("cri"),
        col("cr_order_number").alias("cro"),
        col("cr_return_amount"))
    joined = (t["catalog_sales"]
              .join(cr, on=(col("cs_item_sk") == col("cri"))
                    & (col("cs_order_number") == col("cro")), how="left")
              .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
              .join(it, on=col("cs_item_sk") == col("i_item_sk"))
              .join(t["warehouse"],
                    on=col("cs_warehouse_sk") == col("w_warehouse_sk"))
              .with_column("net", col("cs_sales_price")
                           - F.coalesce(col("cr_return_amount"),
                                        lit(0.0))))
    return (joined
            .group_by(col("w_warehouse_name"), col("i_item_id"))
            .agg(F.sum(F.when(col("d_date") < "2000-03-11", col("net"))
                       .otherwise(0.0)).alias("sales_before"),
                 F.sum(F.when(col("d_date") >= "2000-03-11", col("net"))
                       .otherwise(0.0)).alias("sales_after"))
            .order_by(col("w_warehouse_name"), col("i_item_id"))
            .limit(100))


def q49(t):
    """Worst return ratios per channel: currency and quantity return
    rates ranked per channel, the top tier unioned (net_paid stood in by
    ext_sales_price; returns tied to their sale by order/ticket+item)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter((col("d_year") == 2000)
                              & (col("d_moy") == 12))

    def channel(sales, ret, s_item, s_ord, s_qty, s_price, r_item,
                r_ord, r_qty, r_amt, date_c, label):
        rets = t[ret].select(col(r_item).alias("ri"),
                             col(r_ord).alias("ro"),
                             col(r_qty).alias("rq"),
                             col(r_amt).alias("ra"))
        base = (t[sales]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .filter(col(s_qty) > 0)
                .join(rets, on=(col(s_item) == col("ri"))
                      & (col(s_ord) == col("ro")), how="left")
                .group_by(col(s_item))
                .agg(F.sum(F.coalesce(col("rq"), lit(0)).cast("double"))
                     .alias("return_qty"),
                     F.sum(col(s_qty).cast("double")).alias("sold_qty"),
                     F.sum(F.coalesce(col("ra"), lit(0.0)))
                     .alias("return_amt"),
                     F.sum(col(s_price)).alias("sold_amt"))
                .with_column("return_ratio",
                             col("return_qty") / col("sold_qty"))
                .with_column("currency_ratio",
                             col("return_amt") / col("sold_amt")))
        ranked = (base
                  .with_column("return_rank", F.rank().over(
                      Window.order_by(col("return_ratio"))))
                  .with_column("currency_rank", F.rank().over(
                      Window.order_by(col("currency_ratio")))))
        return (ranked
                .filter((col("return_rank") <= 10)
                        | (col("currency_rank") <= 10))
                .select(lit(label).alias("channel"),
                        col(s_item).alias("item"), col("return_ratio"),
                        col("return_rank"), col("currency_rank")))

    web = channel("web_sales", "web_returns", "ws_item_sk",
                  "ws_order_number", "ws_quantity", "ws_ext_sales_price",
                  "wr_item_sk", "wr_order_number", "wr_return_quantity",
                  "wr_return_amt", "ws_sold_date_sk", "web")
    cat = channel("catalog_sales", "catalog_returns", "cs_item_sk",
                  "cs_order_number", "cs_quantity", "cs_ext_sales_price",
                  "cr_item_sk", "cr_order_number", "cr_return_quantity",
                  "cr_return_amount", "cs_sold_date_sk", "catalog")
    st = channel("store_sales", "store_returns", "ss_item_sk",
                 "ss_ticket_number", "ss_quantity", "ss_ext_sales_price",
                 "sr_item_sk", "sr_ticket_number", "sr_return_quantity",
                 "sr_return_amt", "ss_sold_date_sk", "store")
    return (web.union(cat).union(st)
            .distinct()
            .order_by(col("channel"), col("return_rank"),
                      col("currency_rank"), col("item"))
            .limit(100))


def q83(t):
    """Items returned through all three channels in one year, joined
    pairwise on item with per-channel return shares (the year stands in
    for the spec's three week_seq windows: three independently-drawn
    return streams share no item in any narrower window at tiny sf)."""
    dd = t["date_dim"].filter(col("d_year") == 2000)

    def channel_returns(ret, item_c, date_c, qty_c, prefix):
        return (t[ret]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .join(t["item"], on=col(item_c) == col("i_item_sk"))
                .group_by(col("i_item_id"))
                .agg(F.sum(col(qty_c).cast("double"))
                     .alias(f"{prefix}_qty"))
                .select(col("i_item_id").alias(f"{prefix}_item"),
                        col(f"{prefix}_qty")))

    sr = channel_returns("store_returns", "sr_item_sk",
                         "sr_returned_date_sk", "sr_return_quantity",
                         "sr")
    cr = channel_returns("catalog_returns", "cr_item_sk",
                         "cr_returned_date_sk", "cr_return_quantity",
                         "cr")
    wr = channel_returns("web_returns", "wr_item_sk",
                         "wr_returned_date_sk", "wr_return_quantity",
                         "wr")
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty")) / 3.0
    return (sr.join(cr, on=col("sr_item") == col("cr_item"))
            .join(wr, on=col("sr_item") == col("wr_item"))
            .select(col("sr_item").alias("item_id"), col("sr_qty"),
                    (col("sr_qty") / total / 3.0 * 100.0)
                    .alias("sr_dev"),
                    col("cr_qty"),
                    (col("cr_qty") / total / 3.0 * 100.0)
                    .alias("cr_dev"),
                    col("wr_qty"),
                    (col("wr_qty") / total / 3.0 * 100.0)
                    .alias("wr_dev"),
                    total.alias("average"))
            .order_by(col("item_id"), col("sr_qty"))
            .limit(100))


def q84(t):
    """Customers in one city within an income band, surfaced through
    their store returns (income band resolved customer -> household
    demographics -> income_band; cd tied to the return's demographic)."""
    ca = t["customer_address"].filter(col("ca_city") == "Midway")
    ib = t["income_band"].filter((col("ib_lower_bound") >= 20_000)
                                 & (col("ib_upper_bound") <= 70_000))
    return (t["customer"]
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["household_demographics"],
                  on=col("c_current_hdemo_sk") == col("hd_demo_sk"))
            .join(ib, on=col("hd_income_band_sk")
                  == col("ib_income_band_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(t["store_returns"],
                  on=col("sr_cdemo_sk") == col("cd_demo_sk"))
            .select(col("c_customer_id").alias("customer_id"),
                    F.concat(col("c_last_name"), lit(", "),
                             col("c_first_name")).alias("customername"))
            .order_by(col("customer_id"))
            .limit(100))


def q90(t):
    """AM/PM ratio of web order counts for one page-size class and
    household size (two scalar window counts composed driver-side like
    q88/q61)."""
    hd = t["household_demographics"].filter(col("hd_dep_count") == 6)
    wp = t["web_page"].filter(col("wp_char_count").between(5000, 5200))

    def count_window(h_lo, h_hi):
        td = t["time_dim"].filter(col("t_hour").between(h_lo, h_hi))
        v = (t["web_sales"]
             .join(td, on=col("ws_sold_time_sk") == col("t_time_sk"))
             .join(hd, on=col("ws_ship_hdemo_sk") == col("hd_demo_sk"))
             .join(wp, on=col("ws_web_page_sk") == col("wp_web_page_sk"))
             .agg(F.count(lit(1)).alias("c")).collect()[0][0])
        return int(v or 0)

    amc, pmc = count_window(8, 9), count_window(19, 20)
    ratio = (amc / pmc) if pmc else 0.0
    return t["web_sales"].session.from_pydict(
        {"am_count": [amc], "pm_count": [pmc], "am_pm_ratio": [ratio]})


def q91(t):
    """Call-center losses from returns by educated/affluent customers in
    one month (cc_name stands in for the spec's manager rollup columns)."""
    # predicates broadened from the spec's single-month/single-tuple
    # bindings (q88's convention): a 1/35 demographic tuple of one
    # month's catalog returns selects nothing at tiny sf
    dd = t["date_dim"].filter(col("d_year") == 1998)
    cd = t["customer_demographics"].filter(
        col("cd_education_status").isin("Unknown", "Advanced Degree"))
    hd = t["household_demographics"].filter(
        col("hd_buy_potential").isin(">10000", "1001-5000"))
    ca = t["customer_address"]
    return (t["catalog_returns"]
            .join(dd, on=col("cr_returned_date_sk") == col("d_date_sk"))
            .join(t["call_center"],
                  on=col("cr_call_center_sk") == col("cc_call_center_sk"))
            .join(t["customer"], on=col("cr_returning_customer_sk")
                  == col("c_customer_sk"))
            .join(cd, on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(hd, on=col("c_current_hdemo_sk") == col("hd_demo_sk"))
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by(col("cc_name"), col("cd_marital_status"),
                      col("cd_education_status"))
            .agg(F.sum(col("cr_net_loss")).alias("returns_loss"))
            .order_by(col("returns_loss").desc(), col("cc_name"))
            .limit(100))


def q24(t):
    """Store-channel net paid per customer and item color where the
    customer's birth country differs from their address country and the
    store shares the customer's zip; customers spending above 5% of the
    average (driver-side scalar threshold; ss_sales_price stands in for
    ss_net_paid)."""
    # the spec's single-market cut is omitted: the zip+birth-country
    # funnel already leaves ~a dozen rows at tiny sf, and a handful of
    # stores cannot cover every market id
    st = t["store"]
    netpaid = (t["store_sales"]
               .join(t["store_returns"],
                     on=(col("ss_ticket_number") == col("sr_ticket_number"))
                     & (col("ss_item_sk") == col("sr_item_sk")))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
               .join(t["customer"],
                     on=col("ss_customer_sk") == col("c_customer_sk"))
               .join(t["customer_address"],
                     on=col("c_current_addr_sk") == col("ca_address_sk"))
               .filter((F.upper(col("c_birth_country"))
                        != F.upper(col("ca_country")))
                       & (col("s_zip") == col("ca_zip")))
               .group_by(col("c_last_name"), col("c_first_name"),
                         col("s_store_name"), col("ca_state"),
                         col("s_state"), col("i_color"),
                         col("i_current_price"), col("i_manager_id"))
               .agg(F.sum(col("ss_sales_price")).alias("netpaid")))
    thr = (netpaid.agg(F.avg(col("netpaid")).alias("a"))
           .collect()[0][0])
    thr = 0.05 * float(thr or 0.0)
    return (netpaid
            .filter(col("i_color") == "red")
            .group_by(col("c_last_name"), col("c_first_name"),
                      col("s_store_name"))
            .agg(F.sum(col("netpaid")).alias("paid"))
            .filter(col("paid") > thr)
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("s_store_name"))
            .limit(100))


def q64(t):
    """Cross-channel item economics two years running: store sales with
    a return and a healthy catalog channel (items whose catalog revenue
    dwarfs their catalog refunds), dimensioned through customer
    demographics, income bands, and geography; the per-year rollups are
    self-joined to compare consecutive years (the spec's widest
    snowflake, trimmed to the columns the tiny-sf tables carry)."""
    # cs_ui: items whose catalog revenue > 2x their refunds
    cr_agg = (t["catalog_returns"]
              .group_by(col("cr_item_sk"))
              .agg(F.sum(col("cr_return_amount")).alias("refund"))
              .select(col("cr_item_sk").alias("cri"), col("refund")))
    cs_ui = (t["catalog_sales"]
             .group_by(col("cs_item_sk"))
             .agg(F.sum(col("cs_ext_sales_price")).alias("cs_rev"))
             .join(cr_agg, on=col("cs_item_sk") == col("cri"),
                   how="left")
             .filter(col("cs_rev")
                     > 2.0 * F.coalesce(col("refund"), lit(0.0)))
             .select(col("cs_item_sk").alias("ui_sk")))
    it = t["item"].filter(col("i_color").isin("amber", "navy")
                          & col("i_current_price").between(10.0, 80.0))

    def cross_sales(year, prefix):
        dd = t["date_dim"].filter(col("d_year") == year)
        base = (t["store_sales"]
                .join(t["store_returns"],
                      on=(col("ss_ticket_number")
                          == col("sr_ticket_number"))
                      & (col("ss_item_sk") == col("sr_item_sk")))
                .join(cs_ui, on=col("ss_item_sk") == col("ui_sk"),
                      how="left_semi")
                .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
                .join(it, on=col("ss_item_sk") == col("i_item_sk"))
                .join(t["store"],
                      on=col("ss_store_sk") == col("s_store_sk"))
                .join(t["customer"],
                      on=col("ss_customer_sk") == col("c_customer_sk"))
                .join(t["customer_demographics"],
                      on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
                .join(t["household_demographics"],
                      on=col("c_current_hdemo_sk") == col("hd_demo_sk"))
                .join(t["income_band"], on=col("hd_income_band_sk")
                      == col("ib_income_band_sk"))
                .join(t["customer_address"],
                      on=col("c_current_addr_sk") == col("ca_address_sk")))
        return (base
                .group_by(col("i_item_desc"), col("s_store_name"),
                          col("s_zip"))
                .agg(F.count(lit(1)).alias(f"{prefix}_cnt"),
                     F.sum(col("ss_ext_sales_price"))
                     .alias(f"{prefix}_sales"),
                     F.sum(col("ss_ext_wholesale_cost"))
                     .alias(f"{prefix}_cost"))
                .select(col("i_item_desc").alias(f"{prefix}_item"),
                        col("s_store_name").alias(f"{prefix}_store"),
                        col("s_zip").alias(f"{prefix}_zip"),
                        col(f"{prefix}_cnt"), col(f"{prefix}_sales"),
                        col(f"{prefix}_cost")))

    y1 = cross_sales(2000, "y1")
    y2 = cross_sales(2001, "y2")
    return (y1.join(y2, on=(col("y1_item") == col("y2_item"))
                    & (col("y1_store") == col("y2_store"))
                    & (col("y1_zip") == col("y2_zip")))
            .filter(col("y2_cnt") <= col("y1_cnt"))
            .select(col("y1_item"), col("y1_store"), col("y1_zip"),
                    col("y1_cnt"), col("y1_sales"), col("y1_cost"),
                    col("y2_cnt"), col("y2_sales"), col("y2_cost"))
            .order_by(col("y1_item"), col("y1_store"), col("y1_zip"))
            .limit(100))


def q75(t):
    """Yearly item-family volumes net of returns across all channels,
    consecutive years joined where current volume dropped below 90% of
    the prior year's."""
    def channel(sales, ret, s_item, s_ord, s_qty, s_price, r_item,
                r_ord, r_qty, r_amt, date_c):
        rets = t[ret].select(col(r_item).alias("ri"),
                             col(r_ord).alias("ro"),
                             col(r_qty).alias("rq"),
                             col(r_amt).alias("ra"))
        return (t[sales]
                .join(t["date_dim"],
                      on=col(date_c) == col("d_date_sk"))
                .join(t["item"], on=col(s_item) == col("i_item_sk"))
                .join(rets, on=(col(s_item) == col("ri"))
                      & (col(s_ord) == col("ro")), how="left")
                .select(col("d_year"), col("i_brand_id"),
                        col("i_class_id"), col("i_category_id"),
                        col("i_manufact_id"),
                        (col(s_qty) - F.coalesce(col("rq"), lit(0)))
                        .cast("double").alias("sales_cnt"),
                        (col(s_price) - F.coalesce(col("ra"), lit(0.0)))
                        .alias("sales_amt")))

    all_sales = (channel("store_sales", "store_returns", "ss_item_sk",
                         "ss_ticket_number", "ss_quantity",
                         "ss_ext_sales_price", "sr_item_sk",
                         "sr_ticket_number", "sr_return_quantity",
                         "sr_return_amt", "ss_sold_date_sk")
                 .union(channel("catalog_sales", "catalog_returns",
                                "cs_item_sk", "cs_order_number",
                                "cs_quantity", "cs_ext_sales_price",
                                "cr_item_sk", "cr_order_number",
                                "cr_return_quantity", "cr_return_amount",
                                "cs_sold_date_sk"))
                 .union(channel("web_sales", "web_returns", "ws_item_sk",
                                "ws_order_number", "ws_quantity",
                                "ws_ext_sales_price", "wr_item_sk",
                                "wr_order_number", "wr_return_quantity",
                                "wr_return_amt", "ws_sold_date_sk"))
                 .group_by(col("d_year"), col("i_brand_id"),
                           col("i_class_id"), col("i_category_id"),
                           col("i_manufact_id"))
                 .agg(F.sum(col("sales_cnt")).alias("sales_cnt"),
                      F.sum(col("sales_amt")).alias("sales_amt")))
    prev = all_sales.filter(col("d_year") == 2001).select(
        col("i_brand_id").alias("pb"), col("i_class_id").alias("pc"),
        col("i_category_id").alias("pg"),
        col("i_manufact_id").alias("pm"),
        col("sales_cnt").alias("prev_cnt"),
        col("sales_amt").alias("prev_amt"))
    curr = all_sales.filter(col("d_year") == 2002)
    return (curr.join(prev, on=(col("i_brand_id") == col("pb"))
                      & (col("i_class_id") == col("pc"))
                      & (col("i_category_id") == col("pg"))
                      & (col("i_manufact_id") == col("pm")))
            .filter((col("prev_cnt") > 0)
                    & (col("sales_cnt") / col("prev_cnt") < 0.9))
            .select(col("i_brand_id"), col("i_class_id"),
                    col("i_category_id"), col("i_manufact_id"),
                    col("prev_cnt"), col("sales_cnt"),
                    (col("sales_cnt") - col("prev_cnt"))
                    .alias("sales_cnt_diff"),
                    (col("sales_amt") - col("prev_amt"))
                    .alias("sales_amt_diff"))
            .order_by(col("sales_cnt_diff"), col("i_brand_id"),
                      col("i_class_id"), col("i_category_id"),
                      col("i_manufact_id"))
            .limit(100))


def q77(t):
    """Per-channel sales and returns over a 30-day window, FULL OUTER
    joined per channel entity (store / call center / web page) and
    ROLLUP'd across channels (q5's profit-focused sibling)."""
    dd = t["date_dim"].filter((col("d_date") >= "2000-08-23")
                              & (col("d_date") <= "2000-09-22"))

    def side(tbl, date_c, key_c, amt_c, profit_c, prefix):
        aggs = [F.sum(col(amt_c)).alias(f"{prefix}_amt"),
                F.sum(col(profit_c)).alias(f"{prefix}_profit")]
        return (t[tbl].join(dd, on=col(date_c) == col("d_date_sk"))
                .group_by(col(key_c))
                .agg(*aggs)
                .select(col(key_c).alias(f"{prefix}_key"),
                        col(f"{prefix}_amt"), col(f"{prefix}_profit")))

    def channel(label, sales, returns):
        return (sales.join(returns, on=col("s_key") == col("r_key"),
                           how="full")
                .select(lit(label).alias("channel"),
                        F.coalesce(col("s_key"), col("r_key"))
                        .alias("id"),
                        F.coalesce(col("s_amt"), lit(0.0))
                        .alias("sales"),
                        F.coalesce(col("r_amt"), lit(0.0))
                        .alias("returns"),
                        (F.coalesce(col("s_profit"), lit(0.0))
                         - F.coalesce(col("r_profit"), lit(0.0)))
                        .alias("profit")))

    ss = side("store_sales", "ss_sold_date_sk", "ss_store_sk",
              "ss_ext_sales_price", "ss_net_profit", "s")
    sr = side("store_returns", "sr_returned_date_sk", "sr_store_sk",
              "sr_return_amt", "sr_net_loss", "r")
    cs = side("catalog_sales", "cs_sold_date_sk", "cs_call_center_sk",
              "cs_ext_sales_price", "cs_net_profit", "s")
    cr = side("catalog_returns", "cr_returned_date_sk",
              "cr_call_center_sk", "cr_return_amount", "cr_net_loss",
              "r")
    ws = side("web_sales", "ws_sold_date_sk", "ws_web_page_sk",
              "ws_ext_sales_price", "ws_net_profit", "s")
    wr = side("web_returns", "wr_returned_date_sk", "wr_web_page_sk",
              "wr_return_amt", "wr_net_loss", "r")
    unioned = (channel("store channel", ss, sr)
               .union(channel("catalog channel", cs, cr))
               .union(channel("web channel", ws, wr)))
    return (unioned
            .rollup(col("channel"), col("id"))
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns")).alias("returns"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel"), col("id"))
            .limit(100))


def q78(t):
    """Yearly (customer, item) volumes per channel EXCLUDING returned
    sales (left-join-null return filters), store joined against web and
    catalog activity of the same customer/item/year."""
    def channel(sales, ret, s_item, s_ord_or_tick, s_cust, s_qty,
                s_price, r_item, r_ord, date_c, prefix):
        rets = t[ret].select(col(r_item).alias(f"{prefix}ri"),
                             col(r_ord).alias(f"{prefix}ro"))
        base = (t[sales]
                .join(rets,
                      on=(col(s_item) == col(f"{prefix}ri"))
                      & (col(s_ord_or_tick) == col(f"{prefix}ro")),
                      how="left")
                .filter(col(f"{prefix}ro").is_null())
                .join(t["date_dim"],
                      on=col(date_c) == col("d_date_sk")))
        return (base
                .group_by(col("d_year"), col(s_item), col(s_cust))
                .agg(F.sum(col(s_qty).cast("double"))
                     .alias(f"{prefix}_qty"),
                     F.sum(col(s_price)).alias(f"{prefix}_amt"))
                .select(col("d_year").alias(f"{prefix}_year"),
                        col(s_item).alias(f"{prefix}_item"),
                        col(s_cust).alias(f"{prefix}_cust"),
                        col(f"{prefix}_qty"), col(f"{prefix}_amt")))

    ss = channel("store_sales", "store_returns", "ss_item_sk",
                 "ss_ticket_number", "ss_customer_sk", "ss_quantity",
                 "ss_ext_sales_price", "sr_item_sk", "sr_ticket_number",
                 "ss_sold_date_sk", "ss")
    ws = channel("web_sales", "web_returns", "ws_item_sk",
                 "ws_order_number", "ws_bill_customer_sk", "ws_quantity",
                 "ws_ext_sales_price", "wr_item_sk", "wr_order_number",
                 "ws_sold_date_sk", "ws")
    cs = channel("catalog_sales", "catalog_returns", "cs_item_sk",
                 "cs_order_number", "cs_bill_customer_sk", "cs_quantity",
                 "cs_ext_sales_price", "cr_item_sk", "cr_order_number",
                 "cs_sold_date_sk", "cs")
    return (ss.filter(col("ss_year") == 2000)
            .join(ws, on=(col("ws_year") == col("ss_year"))
                  & (col("ws_item") == col("ss_item"))
                  & (col("ws_cust") == col("ss_cust")), how="left")
            .join(cs, on=(col("cs_year") == col("ss_year"))
                  & (col("cs_item") == col("ss_item"))
                  & (col("cs_cust") == col("ss_cust")), how="left")
            .filter((F.coalesce(col("ws_qty"), lit(0.0)) > 0)
                    | (F.coalesce(col("cs_qty"), lit(0.0)) > 0))
            .select(col("ss_item"), col("ss_cust"), col("ss_qty"),
                    col("ss_amt"),
                    (col("ss_qty")
                     / (F.coalesce(col("ws_qty"), lit(0.0))
                        + F.coalesce(col("cs_qty"), lit(0.0))))
                    .alias("ratio"))
            .order_by(col("ratio").desc(), col("ss_qty").desc(),
                      col("ss_item"), col("ss_cust"))
            .limit(100))


def q80(t):
    """30-day sales/returns/profit per item across channels with a
    non-event promotion filter, returns tied to their sale, ROLLUP'd by
    channel and item (q5 by item instead of by outlet; p_channel_event
    stands in for the spec's p_channel_tv)."""
    dd = t["date_dim"].filter((col("d_date") >= "2000-08-23")
                              & (col("d_date") <= "2000-09-22"))
    it = t["item"].filter(col("i_current_price") > 50.0)
    pr = t["promotion"].filter(col("p_channel_event") == "N")

    def channel(sales, ret, s_item, s_ord, s_promo, s_price, s_profit,
                r_item, r_ord, r_amt, r_loss, date_c, ent, label):
        rets = t[ret].select(col(r_item).alias("ri"),
                             col(r_ord).alias("ro"),
                             col(r_amt).alias("ramt"),
                             col(r_loss).alias("rloss"))
        return (t[sales]
                .join(dd, on=col(date_c) == col("d_date_sk"))
                .join(it, on=col(s_item) == col("i_item_sk"))
                .join(pr, on=col(s_promo) == col("p_promo_sk"))
                .join(rets, on=(col(s_item) == col("ri"))
                      & (col(s_ord) == col("ro")), how="left")
                .group_by(col(ent))
                .agg(F.sum(col(s_price)).alias("sales"),
                     F.sum(F.coalesce(col("ramt"), lit(0.0)))
                     .alias("returns"),
                     F.sum(col(s_profit)
                           - F.coalesce(col("rloss"), lit(0.0)))
                     .alias("profit"))
                .select(lit(label).alias("channel"),
                        col(ent).alias("id"), col("sales"),
                        col("returns"), col("profit")))

    ssr = channel("store_sales", "store_returns", "ss_item_sk",
                  "ss_ticket_number", "ss_promo_sk",
                  "ss_ext_sales_price", "ss_net_profit", "sr_item_sk",
                  "sr_ticket_number", "sr_return_amt", "sr_net_loss",
                  "ss_sold_date_sk", "ss_store_sk", "store channel")
    csr = channel("catalog_sales", "catalog_returns", "cs_item_sk",
                  "cs_order_number", "cs_promo_sk",
                  "cs_ext_sales_price", "cs_net_profit", "cr_item_sk",
                  "cr_order_number", "cr_return_amount", "cr_net_loss",
                  "cs_sold_date_sk", "cs_catalog_page_sk",
                  "catalog channel")
    wsr = channel("web_sales", "web_returns", "ws_item_sk",
                  "ws_order_number", "ws_promo_sk",
                  "ws_ext_sales_price", "ws_net_profit", "wr_item_sk",
                  "wr_order_number", "wr_return_amt", "wr_net_loss",
                  "ws_sold_date_sk", "ws_web_site_sk", "web channel")
    return (ssr.union(csr).union(wsr)
            .rollup(col("channel"), col("id"))
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns")).alias("returns"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel"), col("id"))
            .limit(100))


def q85(t):
    """Web return reasons with quantity/refund/fee averages for coupled
    demographic-and-price or geography-and-profit slices (the spec's
    triple-OR join conditions kept as post-join filters; wr_net_loss
    stands in for wr_fee, wr_return_amt for wr_refunded_cash)."""
    cd1 = t["customer_demographics"].select(
        col("cd_demo_sk").alias("cd1_sk"),
        col("cd_marital_status").alias("ms1"),
        col("cd_education_status").alias("es1"))
    cd2 = t["customer_demographics"].select(
        col("cd_demo_sk").alias("cd2_sk"),
        col("cd_marital_status").alias("ms2"),
        col("cd_education_status").alias("es2"))
    # education-only tuples with widened price bands (the spec's exact
    # (marital, education) pairs select ~1/35 of demographics — nothing
    # at tiny sf; the coupled-OR SHAPE is what the query exercises)
    demo_price = (
        ((col("es1") == "4 yr Degree")
         & col("ws_sales_price").between(0.0, 180.0))
        | ((col("es1") == "College")
           & col("ws_sales_price").between(0.0, 120.0))
        | ((col("es1") == "Secondary")
           & col("ws_sales_price").between(50.0, 180.0)))
    geo_profit = (
        (col("ca_state").isin("TN", "SD", "AL")
         & col("ws_net_profit").between(0, 200))
        | (col("ca_state").isin("GA", "MI", "OH")
           & col("ws_net_profit").between(50, 300))
        | (col("ca_state").isin("TX", "CA")
           & col("ws_net_profit").between(-100, 250)))
    return (t["web_sales"]
            .join(t["web_returns"],
                  on=(col("ws_item_sk") == col("wr_item_sk"))
                  & (col("ws_order_number") == col("wr_order_number")))
            .join(t["web_page"],
                  on=col("ws_web_page_sk") == col("wp_web_page_sk"))
            .join(cd1, on=col("wr_refunded_cdemo_sk") == col("cd1_sk"))
            .join(cd2, on=col("wr_returning_cdemo_sk") == col("cd2_sk"))
            .join(t["customer_address"],
                  on=col("wr_refunded_addr_sk") == col("ca_address_sk"))
            .join(t["reason"],
                  on=col("wr_reason_sk") == col("r_reason_sk"))
            .filter((col("ms1") == col("ms2")) & (col("es1") == col("es2"))
                    & demo_price & geo_profit)
            .group_by(col("r_reason_desc"))
            .agg(F.avg(col("ws_quantity").cast("double")).alias("q_avg"),
                 F.avg(col("wr_return_amt")).alias("refund_avg"),
                 F.avg(col("wr_net_loss")).alias("fee_avg"))
            .order_by(col("r_reason_desc"), col("q_avg"),
                      col("refund_avg"), col("fee_avg"))
            .limit(100))


QUERIES = {n: globals()[f"q{n}"] for n in range(1, 100)}

