"""TPC-DS star-join queries in the DataFrame API (public TPC-DS spec
templates, expressed in this repo's own DSL — BASELINE.md staged config 3).

Each `qN(t)` takes {table_name: DataFrame} and returns a DataFrame.  The
shapes exercised: dimension broadcast joins into the store_sales fact,
multi-dimension chains, string-prefix anti-conditions (q19), and the
pure-count multi-way join (q96)."""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit


def q3(t):
    """Brand revenue by year for one manufacturer in November."""
    dd = t["date_dim"].filter(col("d_moy") == 11)
    it = t["item"].filter(col("i_manufact_id") == 12)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_discount_amt")).alias("sum_agg"))
            .order_by(col("d_year"), col("sum_agg").desc(),
                      col("i_brand_id"))
            .limit(100))


def q5(t):
    """Sales/returns/profit per channel over a 14-day window, rolled up by
    (channel, id) — the reference's headline TPCxBB-era shape: three
    union'd sales+returns channels, a dimension join each, and a ROLLUP
    aggregate (BASELINE staged config 3)."""
    dd = t["date_dim"].filter((col("d_date") >= "2000-08-23")
                              & (col("d_date") <= "2000-09-06"))

    def channel(sales, returns, sales_cols, ret_cols, dim, dim_key,
                dim_id, label):
        """One channel: union sales rows (returns zeroed) with return rows
        (sales zeroed), join the date window and the channel dimension,
        aggregate per dimension id."""
        s_key, s_date, s_price, s_profit = sales_cols
        r_key, r_date, r_amt, r_loss = ret_cols
        s_part = sales.select(
            col(s_key).alias("page_sk"), col(s_date).alias("date_sk"),
            col(s_price).alias("sales_price"),
            col(s_profit).alias("profit"),
            (col(s_price) * 0.0).alias("return_amt"),
            (col(s_price) * 0.0).alias("net_loss"))
        r_part = returns.select(
            col(r_key).alias("page_sk"), col(r_date).alias("date_sk"),
            (col(r_amt) * 0.0).alias("sales_price"),
            (col(r_amt) * 0.0).alias("profit"),
            col(r_amt).alias("return_amt"), col(r_loss).alias("net_loss"))
        return (s_part.union(r_part)
                .join(dd, on=col("date_sk") == col("d_date_sk"))
                .join(dim, on=col("page_sk") == col(dim_key))
                .group_by(col(dim_id))
                .agg(F.sum(col("sales_price")).alias("sales"),
                     F.sum(col("return_amt")).alias("returns"),
                     F.sum(col("profit") - col("net_loss")).alias("profit"))
                .select(lit(label).alias("channel"),
                        col(dim_id).alias("id"), col("sales"),
                        col("returns"), col("profit")))

    ssr = channel(
        t["store_sales"], t["store_returns"],
        ("ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
         "ss_net_profit"),
        ("sr_store_sk", "sr_returned_date_sk", "sr_return_amt",
         "sr_net_loss"),
        t["store"], "s_store_sk", "s_store_name", "store channel")
    csr = channel(
        t["catalog_sales"], t["catalog_returns"],
        ("cs_catalog_page_sk", "cs_sold_date_sk", "cs_ext_sales_price",
         "cs_net_profit"),
        ("cr_catalog_page_sk", "cr_returned_date_sk", "cr_return_amount",
         "cr_net_loss"),
        t["catalog_page"], "cp_catalog_page_sk", "cp_catalog_page_id",
        "catalog channel")
    # web returns resolve their site through the originating sale
    # (left outer on item+order, the spec's join)
    wr = (t["web_returns"]
          .join(t["web_sales"]
                .select(col("ws_item_sk").alias("wsi"),
                        col("ws_order_number").alias("wso"),
                        col("ws_web_site_sk").alias("site_sk")),
                on=(col("wr_item_sk") == col("wsi"))
                & (col("wr_order_number") == col("wso")), how="left")
          .select(col("site_sk").alias("wr_site_sk"),
                  col("wr_returned_date_sk"), col("wr_return_amt"),
                  col("wr_net_loss")))
    wsr = channel(
        t["web_sales"], wr,
        ("ws_web_site_sk", "ws_sold_date_sk", "ws_ext_sales_price",
         "ws_net_profit"),
        ("wr_site_sk", "wr_returned_date_sk", "wr_return_amt",
         "wr_net_loss"),
        t["web_site"], "web_site_sk", "web_site_id", "web channel")

    return (ssr.union(csr).union(wsr)
            .rollup(col("channel"), col("id"))
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns")).alias("returns"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel"), col("id"))
            .limit(100))


def q7(t):
    """Average sales metrics per item for one demographics tuple with a
    non-event/non-email promotion."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(pr, on=col("ss_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q19(t):
    """Brand revenue where the customer's zip prefix differs from the
    store's (out-of-neighborhood purchases)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1998))
    it = t["item"].filter(col("i_manager_id") == 8)
    joined = (dd.join(t["store_sales"],
                      on=col("d_date_sk") == col("ss_sold_date_sk"))
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(t["customer"],
                    on=col("ss_customer_sk") == col("c_customer_sk"))
              .join(t["customer_address"],
                    on=col("c_current_addr_sk") == col("ca_address_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .filter(F.substring(col("ca_zip"), 1, 5)
                      != F.substring(col("s_zip"), 1, 5)))
    return (joined
            .group_by(col("i_brand_id"), col("i_brand"),
                      col("i_manufact_id"), col("i_manufact"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand"),
                      col("i_brand_id"), col("i_manufact_id"),
                      col("i_manufact"))
            .limit(100))


def q42(t):
    """Category revenue for one manager's items in November."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_category_id"),
                      col("i_category"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("d_year"),
                      col("i_category_id"), col("i_category"))
            .limit(100))


def q52(t):
    """Brand revenue for one manager's items in November (brand cut of
    q42)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand"), col("i_brand_id"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year"), col("ext_price").desc(),
                      col("i_brand_id"))
            .limit(100))


def q55(t):
    """Brand revenue for one manager in one month."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1999))
    it = t["item"].filter(col("i_manager_id") == 28)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id"))
            .limit(100))


def q96(t):
    """Count of evening purchases by high-dependent-count households at
    one store."""
    td = t["time_dim"].filter((col("t_hour") == 20)
                              & (col("t_minute") >= 30))
    hd = t["household_demographics"].filter(col("hd_dep_count") == 7)
    st = t["store"].filter(col("s_store_name") == "ese")
    return (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count(lit(1)).alias("cnt")))


# --------------------------------------------------------------------------
# round-4 breadth tier: the operator shapes the first 8 queries miss —
# EXISTS/IN rewrites (q10/q35), windows over joins (q47/q57/q89), multi-
# fact chains (q25/q29), scalar subqueries (q6/q65), ticket-grouped counts
# (q34/q73/q68), day-of-week pivots (q43), OR-branch demographic filters
# (q13/q48).  Public TPC-DS spec templates in this repo's DSL; parameter
# windows widened where the tiny-sf generator would otherwise select empty
# sets (each docstring notes it).  Reference breadth model:
# integration_tests/.../tpcxbb/TpcxbbLikeSpark.scala.
# --------------------------------------------------------------------------


def q6(t):
    """States whose customers bought items priced >= 1.2x their category
    average in one month (scalar subquery for the month_seq + per-category
    average join)."""
    month_seq = t["date_dim"].filter((col("d_year") == 2001)
                                     & (col("d_moy") == 1)) \
        .agg(F.min(col("d_month_seq")).alias("m")).collect()[0][0]
    dd = t["date_dim"].filter(col("d_month_seq") == month_seq)
    cat_avg = (t["item"].group_by(col("i_category"))
               .agg(F.avg(col("i_current_price")).alias("cat_price"))
               .select(col("i_category").alias("avg_cat"),
                       col("cat_price")))
    it = (t["item"].join(cat_avg, on=col("i_category") == col("avg_cat"))
          .filter(col("i_current_price") > 1.2 * col("cat_price")))
    return (t["customer_address"]
            .join(t["customer"],
                  on=col("ca_address_sk") == col("c_current_addr_sk"))
            .join(t["store_sales"],
                  on=col("c_customer_sk") == col("ss_customer_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("ca_state"))
            .agg(F.count(lit(1)).alias("cnt"))
            .filter(col("cnt") >= 1)  # spec: >= 10 (SF1000 scale)
            .order_by(col("cnt"), col("ca_state"))
            .limit(100))


_DATE_KEY = {"ss_cust": "ss_sold_date_sk", "ws_cust": "ws_sold_date_sk",
             "cs_cust": "cs_sold_date_sk"}


def _active_customers(t, sales, cust_key, alias):
    """Distinct customers with activity in 2000 (the EXISTS rewrite:
    aggregate-then-join, how Spark plans the subquery)."""
    dd = t["date_dim"].filter(col("d_year") == 2000)
    return (sales.join(dd, on=col(_DATE_KEY[alias]) == col("d_date_sk"))
            .group_by(col(cust_key))
            .agg(F.count(lit(1)).alias("_c"))
            .select(col(cust_key).alias(alias)))


def _channel_activity(t):
    """Distinct active-customer sets per channel in the year-2000 window
    (shared by the q10/q35/q69 EXISTS rewrites)."""
    return (_active_customers(t, t["store_sales"], "ss_customer_sk",
                              "ss_cust"),
            _active_customers(t, t["web_sales"], "ws_bill_customer_sk",
                              "ws_cust"),
            _active_customers(t, t["catalog_sales"],
                              "cs_ship_customer_sk", "cs_cust"))


def q10(t):
    """Demographics counts for customers in selected counties with a store
    purchase AND (a web OR a catalog purchase) in the year — the
    EXISTS/left-semi + existence-flag rewrite."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    ca = t["customer_address"].filter(col("ca_county").isin(
        "Williamson County", "Walker County", "Ziebach County"))
    return (t["customer"]
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left")
            .filter(~(col("ws_cust").is_null()
                      & col("cs_cust").is_null()))
            .group_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.min(col("cd_dep_count")).alias("min_dep"),
                 F.max(col("cd_dep_count")).alias("max_dep"),
                 F.avg(col("cd_dep_count")).alias("avg_dep"))
            .order_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .limit(100))


def _revenue_ratio(sales_joined, revenue_col):
    """Shared q12/q20/q98 tail: per-item revenue + class-partitioned
    revenue ratio window."""
    from spark_rapids_tpu.plan.logical import Window
    grouped = (sales_joined
               .group_by(col("i_item_id"), col("i_item_desc"),
                         col("i_category"), col("i_class"),
                         col("i_current_price"))
               .agg(F.sum(col(revenue_col)).alias("itemrevenue")))
    w = Window.partition_by(col("i_class"))
    return (grouped
            .with_column("revenueratio",
                         col("itemrevenue") * lit(100.0)
                         / F.sum(col("itemrevenue")).over(w))
            .order_by(col("i_category"), col("i_class"), col("i_item_id"),
                      col("i_item_desc"), col("revenueratio"))
            .limit(100))


def q12(t):
    """Web revenue ratio by item within class (window over join).  Date
    window widened to the year (spec: 30 days) for tiny-sf population."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["web_sales"]
              .join(it, on=col("ws_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "ws_ext_sales_price")


def q13(t):
    """Averages under OR'd demographic x household x address branches."""
    cd, hd, ca = (t["customer_demographics"], t["household_demographics"],
                  t["customer_address"])
    dd = t["date_dim"].filter(col("d_year") == 2001)
    demo_ok = (
        ((col("cd_marital_status") == "M")
         & (col("cd_education_status") == "Advanced Degree")
         & col("ss_sales_price").between(100.0, 150.0)
         & (col("hd_dep_count") == 3))
        | ((col("cd_marital_status") == "S")
           & (col("cd_education_status") == "College")
           & col("ss_sales_price").between(50.0, 100.0)
           & (col("hd_dep_count") == 1))
        | ((col("cd_marital_status") == "W")
           & (col("cd_education_status") == "2 yr Degree")
           & col("ss_sales_price").between(150.0, 200.0)
           & (col("hd_dep_count") == 1)))
    addr_ok = (
        (col("ca_state").isin("TX", "OH", "TN")
         & col("ss_net_profit").between(100.0, 200.0))
        | (col("ca_state").isin("OR", "NM", "KY")
           & col("ss_net_profit").between(150.0, 300.0))
        | (col("ca_state").isin("VA", "TX", "MS")
           & col("ss_net_profit").between(50.0, 250.0)))
    return (t["store_sales"]
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(ca, on=col("ss_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(demo_ok & addr_ok
                    & (col("ca_country") == "United States"))
            .agg(F.avg(col("ss_quantity")).alias("avg_qty"),
                 F.avg(col("ss_ext_sales_price")).alias("avg_price"),
                 F.avg(col("ss_ext_wholesale_cost")).alias("avg_cost"),
                 F.sum(col("ss_ext_wholesale_cost")).alias("sum_cost")))


def q15(t):
    """Catalog revenue per customer zip for select zips/states or big
    tickets."""
    dd = t["date_dim"].filter((col("d_qoy") == 2)
                              & (col("d_year") == 2001))
    return (t["catalog_sales"]
            .join(t["customer"],
                  on=col("cs_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5).isin(
                "85669", "86197", "88274", "83405", "86475")
                | col("ca_state").isin("CA", "GA", "TX")
                | (col("cs_sales_price") > 500.0))
            .group_by(col("ca_zip"))
            .agg(F.sum(col("cs_sales_price")).alias("total"))
            .order_by(col("ca_zip"))
            .limit(100))


def q20(t):
    """Catalog revenue ratio by item within class (q12's catalog twin)."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["catalog_sales"]
              .join(it, on=col("cs_item_sk") == col("i_item_sk"))
              .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "cs_ext_sales_price")


def _sale_return_catalog(t, d1_filter, d2_filter, d3_filter):
    """q25/q29 chain: store sale -> its return -> catalog re-purchase by
    the same customer of the same item, each leg date-filtered."""
    d1 = t["date_dim"].filter(d1_filter).select(col("d_date_sk")
                                                .alias("d1_sk"))
    d2 = t["date_dim"].filter(d2_filter).select(col("d_date_sk")
                                                .alias("d2_sk"))
    d3 = t["date_dim"].filter(d3_filter).select(col("d_date_sk")
                                                .alias("d3_sk"))
    return (t["store_sales"]
            .join(t["store_returns"],
                  on=(col("ss_customer_sk") == col("sr_customer_sk"))
                  & (col("ss_item_sk") == col("sr_item_sk"))
                  & (col("ss_ticket_number") == col("sr_ticket_number")))
            .join(t["catalog_sales"],
                  on=(col("sr_customer_sk") == col("cs_bill_customer_sk"))
                  & (col("sr_item_sk") == col("cs_item_sk")))
            .join(d1, on=col("ss_sold_date_sk") == col("d1_sk"))
            .join(d2, on=col("sr_returned_date_sk") == col("d2_sk"))
            .join(d3, on=col("cs_sold_date_sk") == col("d3_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk")))


def q25(t):
    """Profit across the sale->return->catalog chain per item x store.
    Date legs widened to the full year (spec: month windows) so the tiny-sf
    chain stays populated."""
    joined = _sale_return_catalog(
        t, col("d_year") == 2000, col("d_year") == 2000,
        col("d_year") == 2000)
    return (joined
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .agg(F.sum(col("ss_net_profit")).alias("store_sales_profit"),
                 F.sum(col("sr_net_loss")).alias("store_returns_loss"),
                 F.sum(col("cs_net_profit")).alias("catalog_sales_profit"))
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .limit(100))


def q26(t):
    """Catalog averages per item for one demographics tuple (q7's catalog
    twin)."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["catalog_sales"]
            .join(cd, on=col("cs_bill_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
            .join(pr, on=col("cs_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q27(t):
    """ROLLUP(item, state) averages for one demographics tuple."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "F") & (col("cd_marital_status") == "D")
        & (col("cd_education_status") == "Primary"))
    dd = t["date_dim"].filter(col("d_year") == 1999)
    st = t["store"].filter(col("s_state").isin("TN", "SD", "AL", "GA"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .rollup(col("i_item_id"), col("s_state"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"), col("s_state"))
            .limit(100))


def q29(t):
    """Quantities across the sale->return->catalog chain (q25's quantity
    cut)."""
    joined = _sale_return_catalog(
        t, col("d_year") == 2000, col("d_year") == 2000,
        col("d_year").isin(2000, 2001, 2002))
    return (joined
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .agg(F.sum(col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(col("sr_return_quantity"))
                 .alias("store_returns_quantity"),
                 F.sum(col("cs_quantity")).alias("catalog_sales_quantity"))
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_sk"), col("s_store_name"))
            .limit(100))


def _ticket_counts(t, date_filter, hd_filter, county_filter, lo, hi):
    """q34/q73 core: per-ticket line counts within bounds, joined back to
    the customer."""
    dd = t["date_dim"].filter(date_filter)
    hd = t["household_demographics"].filter(hd_filter)
    st = t["store"].filter(county_filter)
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"))
               .agg(F.count(lit(1)).alias("cnt"))
               .filter(col("cnt").between(lo, hi)))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("c_salutation"), col("c_preferred_cust_flag"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("c_salutation"), col("c_preferred_cust_flag")
                      .desc(), col("ss_ticket_number"))
            .limit(1000))


def q34(t):
    """Big-basket customers (count bounds scaled to the ~4-line tickets
    the tiny-sf generator produces; spec: 15..20)."""
    return _ticket_counts(
        t,
        (col("d_dom").between(1, 3) | col("d_dom").between(25, 28))
        & col("d_year").isin(1999, 2000, 2001),
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)
        & (col("hd_dep_count") > 0.2 * col("hd_vehicle_count")),
        col("s_county").isin("Williamson County", "Ziebach County",
                             "Walker County", "Daviess County"),
        2, 4)


def q35(t):
    """Demographics x state stats for customers with a store purchase AND
    (web OR catalog) activity (q10 with address grouping)."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    return (t["customer"]
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left")
            .filter(~(col("ws_cust").is_null()
                      & col("cs_cust").is_null()))
            .group_by(col("ca_state"), col("cd_gender"),
                      col("cd_marital_status"), col("cd_dep_count"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.min(col("cd_dep_employed_count")).alias("min_emp"),
                 F.max(col("cd_dep_employed_count")).alias("max_emp"),
                 F.avg(col("cd_dep_college_count")).alias("avg_col"))
            .order_by(col("ca_state"), col("cd_gender"),
                      col("cd_marital_status"), col("cd_dep_count"))
            .limit(100))


def q36(t):
    """Gross-margin ROLLUP by category/class with an in-category margin
    rank (window over a rollup)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_year") == 2001)
    st = t["store"].filter(col("s_state").isin("TN", "SD", "AL", "GA",
                                               "MI", "OH", "TX", "CA"))
    rolled = (t["store_sales"]
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
              .join(st, on=col("ss_store_sk") == col("s_store_sk"))
              .rollup(col("i_category"), col("i_class"))
              .agg(F.sum(col("ss_net_profit")).alias("profit"),
                   F.sum(col("ss_ext_sales_price")).alias("sales"))
              .with_column("gross_margin",
                           col("profit") / col("sales")))
    w = Window.partition_by(col("i_category")) \
        .order_by(col("gross_margin"))
    return (rolled
            .with_column("rank_within_parent", F.rank().over(w))
            .order_by(col("i_category"), col("rank_within_parent"))
            .limit(100))


def q43(t):
    """Per-store day-of-week sales pivot (conditional-sum pivot)."""
    dd = t["date_dim"].filter(col("d_year") == 2000)
    st = t["store"].filter(col("s_gmt_offset") == -5.0)
    day_sum = [
        F.sum(F.when(col("d_day_name") == day, col("ss_sales_price"))
              .otherwise(0.0)).alias(f"{day[:3].lower()}_sales")
        for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                    "Thursday", "Friday", "Saturday"]]
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .group_by(col("s_store_name"), col("s_store_sk"))
            .agg(*day_sum)
            .order_by(col("s_store_name"), col("s_store_sk"))
            .limit(100))


def q45(t):
    """Web revenue by customer zip/city for select zips or select items."""
    dd = t["date_dim"].filter((col("d_qoy") == 2)
                              & (col("d_year") == 2001))
    return (t["web_sales"]
            .join(t["customer"],
                  on=col("ws_bill_customer_sk") == col("c_customer_sk"))
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
            .filter(F.substring(col("ca_zip"), 1, 5).isin(
                "85669", "86197", "88274", "83405", "86475")
                | col("i_item_sk").isin(2, 3, 5, 7, 11, 13, 17, 19, 23,
                                        29))
            .group_by(col("ca_zip"), col("ca_city"))
            .agg(F.sum(col("ws_ext_sales_price")).alias("total"))
            .order_by(col("ca_zip"), col("ca_city"))
            .limit(100))


def _monthly_deviation(joined, group_cols, order_cols):
    """q47/q57 core: monthly sums, year-partition average, lag/lead
    neighbors, >10% deviation filter."""
    from spark_rapids_tpu.plan.logical import Window
    monthly = (joined
               .group_by(*[col(c) for c in group_cols + ["d_year",
                                                         "d_moy"]])
               .agg(F.sum(col("sales_col")).alias("sum_sales")))
    w_avg = Window.partition_by(*[col(c) for c in group_cols + ["d_year"]])
    w_seq = Window.partition_by(*[col(c) for c in group_cols]) \
        .order_by(col("d_year"), col("d_moy"))
    flagged = (monthly
               .with_column("avg_monthly_sales",
                            F.avg(col("sum_sales")).over(w_avg))
               .with_column("psum", F.lag(col("sum_sales"), 1).over(w_seq))
               .with_column("nsum", F.lead(col("sum_sales"), 1)
                            .over(w_seq))
               .filter((col("avg_monthly_sales") > 0)
                       & (F.abs(col("sum_sales")
                                - col("avg_monthly_sales"))
                          / col("avg_monthly_sales") > 0.1)
                       & (col("d_year") == 1999)))
    return (flagged
            .order_by(*([col("avg_monthly_sales").desc(),
                         col("sum_sales")]
                        + [col(c) for c in order_cols]))
            .limit(100))


def q47(t):
    """Store monthly sales deviating >10% from the yearly average, with
    neighboring months (windows over a 3-way join)."""
    dd = t["date_dim"].filter(col("d_year").isin(1998, 1999, 2000))
    joined = (t["store_sales"]
              .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .with_column("sales_col", col("ss_sales_price")))
    return _monthly_deviation(
        joined, ["i_category", "i_brand", "s_store_name",
                 "s_company_name"],
        ["i_category", "i_brand", "s_store_name", "s_company_name",
         "d_year", "d_moy"])


def q48(t):
    """Store quantity sum under OR'd demographic/address branches (q13's
    quantity cut)."""
    dd = t["date_dim"].filter(col("d_year") == 2001)
    demo_ok = (
        ((col("cd_marital_status") == "M")
         & (col("cd_education_status") == "4 yr Degree")
         & col("ss_sales_price").between(100.0, 150.0))
        | ((col("cd_marital_status") == "D")
           & (col("cd_education_status") == "2 yr Degree")
           & col("ss_sales_price").between(50.0, 100.0))
        | ((col("cd_marital_status") == "S")
           & (col("cd_education_status") == "College")
           & col("ss_sales_price").between(150.0, 200.0)))
    addr_ok = (
        (col("ca_state").isin("CO", "OH", "TX")
         & col("ss_net_profit").between(0.0, 2000.0))
        | (col("ca_state").isin("OR", "MN", "KY")
           & col("ss_net_profit").between(150.0, 3000.0))
        | (col("ca_state").isin("VA", "CA", "MS")
           & col("ss_net_profit").between(50.0, 25000.0)))
    return (t["store_sales"]
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["customer_demographics"],
                  on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(t["customer_address"],
                  on=col("ss_addr_sk") == col("ca_address_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .filter(demo_ok & addr_ok
                    & (col("ca_country") == "United States"))
            .agg(F.sum(col("ss_quantity")).alias("total_quantity")))


def q57(t):
    """Catalog monthly sales deviation by call center (q47's catalog
    twin)."""
    dd = t["date_dim"].filter(col("d_year").isin(1998, 1999, 2000))
    joined = (t["catalog_sales"]
              .join(t["item"], on=col("cs_item_sk") == col("i_item_sk"))
              .join(dd, on=col("cs_sold_date_sk") == col("d_date_sk"))
              .join(t["call_center"],
                    on=col("cs_call_center_sk") == col("cc_call_center_sk"))
              .with_column("sales_col", col("cs_sales_price")))
    return _monthly_deviation(
        joined, ["i_category", "i_brand", "cc_name"],
        ["i_category", "i_brand", "cc_name", "d_year", "d_moy"])


def q65(t):
    """Store/item pairs whose revenue is below the store's average
    (aggregate-of-aggregate self join; spec threshold 0.1x scaled to 1.0x
    for tiny-sf row counts)."""
    month_lo = t["date_dim"].filter((col("d_year") == 2000)
                                    & (col("d_moy") == 1)) \
        .agg(F.min(col("d_month_seq")).alias("m")).collect()[0][0]
    dd = t["date_dim"].filter(col("d_month_seq").between(
        month_lo, month_lo + 11))
    revenue = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .group_by(col("ss_store_sk"), col("ss_item_sk"))
               .agg(F.sum(col("ss_sales_price")).alias("revenue")))
    store_avg = (revenue.group_by(col("ss_store_sk"))
                 .agg(F.avg(col("revenue")).alias("ave"))
                 .select(col("ss_store_sk").alias("avg_store"),
                         col("ave")))
    return (revenue
            .join(store_avg, on=col("ss_store_sk") == col("avg_store"))
            .filter(col("revenue") <= col("ave"))
            .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .select(col("s_store_name"), col("i_item_desc"),
                    col("revenue"), col("i_current_price"))
            .order_by(col("s_store_name"), col("i_item_desc"),
                      col("revenue"))
            .limit(100))


def q68(t):
    """Ticket-grouped city sums where the purchase city differs from the
    customer's current city."""
    dd = t["date_dim"].filter(col("d_dom").between(1, 2)
                              & col("d_year").isin(1998, 1999, 2000))
    st = t["store"].filter(col("s_city").isin("Midway", "Fairview"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(t["customer_address"],
                     on=col("ss_addr_sk") == col("ca_address_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("ca_city"))
               .agg(F.sum(col("ss_ext_sales_price")).alias("extended_price"),
                    F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit"))
               .select(col("ss_ticket_number"), col("ss_customer_sk"),
                       col("ca_city").alias("bought_city"),
                       col("extended_price"), col("amt"), col("profit")))
    cur = t["customer_address"].select(col("ca_address_sk").alias("cur_sk"),
                                       col("ca_city").alias("cur_city"))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .join(cur, on=col("c_current_addr_sk") == col("cur_sk"))
            .filter(col("cur_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("cur_city"), col("bought_city"),
                    col("ss_ticket_number"), col("extended_price"),
                    col("amt"), col("profit"))
            .order_by(col("c_last_name"), col("ss_ticket_number"))
            .limit(100))


def q73(t):
    """Frequent-shopper baskets (q34's narrow cut; count bounds scaled to
    the ~4-line tickets; spec: 1..5)."""
    return _ticket_counts(
        t,
        col("d_dom").between(1, 2) & col("d_year").isin(1999, 2000, 2001),
        col("hd_buy_potential").isin(">10000", "Unknown")
        & (col("hd_vehicle_count") > 0)
        & (col("hd_dep_count") > 0.5 * col("hd_vehicle_count")),
        col("s_county").isin("Williamson County", "Ziebach County",
                             "Walker County", "Daviess County"),
        1, 5)


def q89(t):
    """Monthly class/brand/store sales deviating from the yearly average
    (window over join, no lag/lead)."""
    from spark_rapids_tpu.plan.logical import Window
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(
        (col("i_category").isin("Books", "Electronics", "Sports")
         & col("i_class").isin("class#1", "class#4", "class#7"))
        | (col("i_category").isin("Men", "Jewelry", "Women")
           & col("i_class").isin("class#2", "class#5", "class#8")))
    monthly = (t["store_sales"]
               .join(it, on=col("ss_item_sk") == col("i_item_sk"))
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(t["store"],
                     on=col("ss_store_sk") == col("s_store_sk"))
               .group_by(col("i_category"), col("i_class"),
                         col("i_brand"), col("s_store_name"),
                         col("s_company_name"), col("d_moy"))
               .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_category"), col("i_brand"),
                            col("s_store_name"), col("s_company_name"))
    return (monthly
            .with_column("avg_monthly_sales",
                         F.avg(col("sum_sales")).over(w))
            .filter(F.when(col("avg_monthly_sales") != 0.0,
                           F.abs(col("sum_sales")
                                 - col("avg_monthly_sales"))
                           / col("avg_monthly_sales")).otherwise(0.0)
                    > 0.1)
            .order_by((col("sum_sales") - col("avg_monthly_sales")),
                      col("s_store_name"), col("i_category"),
                      col("i_class"), col("i_brand"), col("d_moy"))
            .limit(100))


def q98(t):
    """Store revenue ratio by item within class (q12's store twin)."""
    dd = t["date_dim"].filter(col("d_year") == 1999)
    it = t["item"].filter(col("i_category").isin("Sports", "Books",
                                                 "Home"))
    joined = (t["store_sales"]
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk")))
    return _revenue_ratio(joined, "ss_ext_sales_price")




def q1(t):
    """Customers returning more than 1.2x their store's average return
    (CTE + per-store average join + customer join)."""
    ctr = (t["store_returns"]
           .join(t["date_dim"].filter(col("d_year") == 2000),
                 on=col("sr_returned_date_sk") == col("d_date_sk"))
           .group_by(col("sr_customer_sk"), col("sr_store_sk"))
           .agg(F.sum(col("sr_return_amt")).alias("ctr_total_return")))
    avg_ctr = (ctr.group_by(col("sr_store_sk"))
               .agg((F.avg(col("ctr_total_return")) * 1.2)
                    .alias("avg_return"))
               .select(col("sr_store_sk").alias("avg_store"),
                       col("avg_return")))
    st = t["store"].filter(col("s_state") == "TN")
    return (ctr
            .join(avg_ctr, on=col("sr_store_sk") == col("avg_store"))
            .filter(col("ctr_total_return") > col("avg_return"))
            .join(st, on=col("sr_store_sk") == col("s_store_sk"))
            .join(t["customer"],
                  on=col("sr_customer_sk") == col("c_customer_sk"))
            .select(col("c_customer_id"))
            .order_by(col("c_customer_id"))
            .limit(100))


def _channel_customers(t, sales_key, date_key, prefix):
    """Distinct (customer, d_date) pairs of one channel in the window —
    the building block of the q38/q87 set operations."""
    dd = t["date_dim"].filter(col("d_month_seq").between(24, 35)) \
        .select(col("d_date_sk").alias(f"{prefix}_dsk"), col("d_date")
                .alias(f"{prefix}_date"))
    return (t[sales_key[0]]
            .join(dd, on=col(date_key) == col(f"{prefix}_dsk"))
            .join(t["customer"],
                  on=col(sales_key[1]) == col("c_customer_sk"))
            .select(col("c_last_name").alias(f"{prefix}_ln"),
                    col("c_first_name").alias(f"{prefix}_fn"),
                    col(f"{prefix}_date"))
            .distinct())


def _channel_customer_sets(t):
    """(store, catalog, web) distinct (customer, date) sets — the shared
    operands of the q38 INTERSECT and q87 EXCEPT chains."""
    ss = _channel_customers(t, ("store_sales", "ss_customer_sk"),
                            "ss_sold_date_sk", "s")
    cs = _channel_customers(t, ("catalog_sales", "cs_bill_customer_sk"),
                            "cs_sold_date_sk", "c")
    ws = _channel_customers(t, ("web_sales", "ws_bill_customer_sk"),
                            "ws_sold_date_sk", "w")
    return ss, cs, ws


def q38(t):
    """INTERSECT of the three channels' (customer, date) sets, counted —
    expressed as the semi-join chain Spark plans for INTERSECT."""
    ss, cs, ws = _channel_customer_sets(t)
    both = (ss.join(cs, on=(col("s_ln") == col("c_ln"))
                    & (col("s_fn") == col("c_fn"))
                    & (col("s_date") == col("c_date")), how="left_semi")
            .join(ws, on=(col("s_ln") == col("w_ln"))
                  & (col("s_fn") == col("w_fn"))
                  & (col("s_date") == col("w_date")), how="left_semi"))
    return both.agg(F.count(lit(1)).alias("cnt"))


def q87(t):
    """EXCEPT version of q38: store customers with NO matching catalog or
    web activity (anti-join chain)."""
    ss, cs, ws = _channel_customer_sets(t)
    only = (ss.join(cs, on=(col("s_ln") == col("c_ln"))
                    & (col("s_fn") == col("c_fn"))
                    & (col("s_date") == col("c_date")), how="left_anti")
            .join(ws, on=(col("s_ln") == col("w_ln"))
                  & (col("s_fn") == col("w_fn"))
                  & (col("s_date") == col("w_date")), how="left_anti"))
    return only.agg(F.count(lit(1)).alias("cnt"))


def _weekly_pivot(t, years, prefix):
    dd = t["date_dim"].filter(col("d_year").isin(*years))
    sums = [F.sum(F.when(col("d_day_name") == day, col("ss_sales_price"))
                  .otherwise(0.0)).alias(f"{prefix}_{day[:3].lower()}")
            for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                        "Thursday", "Friday", "Saturday"]]
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .group_by(col("ss_store_sk"), col("d_moy"))
            .agg(*sums)
            .select(col("ss_store_sk").alias(f"{prefix}_store"),
                    col("d_moy").alias(f"{prefix}_moy"),
                    *[col(f"{prefix}_{d}") for d in
                      ("sun", "mon", "tue", "wed", "thu", "fri", "sat")]))


def q59(t):
    """Year-over-year weekly sales ratios per store (self-joined
    day-of-week pivots; monthly granularity stands in for week_seq,
    which the tiny-sf date_dim does not carry)."""
    y1 = _weekly_pivot(t, (1999,), "a")
    y2 = _weekly_pivot(t, (2000,), "b")
    joined = (y1.join(y2, on=(col("a_store") == col("b_store"))
                      & (col("a_moy") == col("b_moy")))
              .join(t["store"],
                    on=col("a_store") == col("s_store_sk")))
    out = [col("s_store_name"), col("a_moy")]
    for d in ("sun", "mon", "tue", "wed", "thu", "fri", "sat"):
        out.append((col(f"b_{d}") / col(f"a_{d}")).alias(f"r_{d}"))
    return (joined.select(*out)
            .order_by(col("s_store_name"), col("a_moy"))
            .limit(100))


def q88(t):
    """Store-traffic counts in eight half-hour buckets (the reference
    cross-joins eight count subqueries; scalar composition happens
    driver-side here, like the TPC-H scalar-subquery queries).  Spec
    deviations for the tiny-sf generator: the dep/vehicle predicate is
    broadened (dep<=5 or vehicles<=3 vs the spec's exact triples) and
    the window is 8:00-12:00 on the hour rather than 8:30-12:30."""
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") <= 5) | (col("hd_vehicle_count") <= 3))
    st = t["store"].filter(col("s_store_name") == "ese")
    base = (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .join(t["time_dim"],
                  on=col("ss_sold_time_sk") == col("t_time_sk")))
    data = {}
    for i, (h, half) in enumerate((h, m) for h in range(8, 12)
                                  for m in (0, 30)):
        c = (base.filter((col("t_hour") == h)
                         & (col("t_minute") >= half)
                         & (col("t_minute") < half + 30))
             .agg(F.count(lit(1)).alias("c")).collect()[0][0])
        data[f"b{i}"] = [int(c or 0)]
    # the eight scalars compose into the single output row driver-side,
    # like the TPC-H scalar-subquery queries (tpch q11/q15/q22)
    return base.session.from_pydict(data)


def q31(t):
    """County-level store-vs-web sales growth across consecutive quarters
    (two per-channel aggregates self-joined twice)."""
    def per_channel(sales, date_key, addr_key, prefix, qoy):
        dd = t["date_dim"].filter((col("d_year") == 2000)
                                  & (col("d_qoy") == qoy))
        return (t[sales]
                .join(dd, on=col(date_key) == col("d_date_sk"))
                .join(t["customer_address"],
                      on=col(addr_key) == col("ca_address_sk"))
                .group_by(col("ca_county"))
                .agg(F.sum(col(f"{prefix}_ext_sales_price"))
                     .alias(f"{prefix}{qoy}_sales"))
                .select(col("ca_county").alias(f"{prefix}{qoy}_county"),
                        col(f"{prefix}{qoy}_sales")))
    ss1 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 1)
    ss2 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 2)
    ss3 = per_channel("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                      "ss", 3)
    ws1 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 1)
    ws2 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 2)
    ws3 = per_channel("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                      "ws", 3)
    return (ss1.join(ss2, on=col("ss1_county") == col("ss2_county"))
            .join(ss3, on=col("ss1_county") == col("ss3_county"))
            .join(ws1, on=col("ss1_county") == col("ws1_county"))
            .join(ws2, on=col("ss1_county") == col("ws2_county"))
            .join(ws3, on=col("ss1_county") == col("ws3_county"))
            .filter((col("ss1_sales") > 0) & (col("ss2_sales") > 0)
                    & (col("ws1_sales") > 0) & (col("ws2_sales") > 0))
            # the query's point: counties where the WEB channel grew
            # faster than the STORE channel in both quarter steps
            .filter((col("ws2_sales") / col("ws1_sales")
                     > col("ss2_sales") / col("ss1_sales"))
                    & (col("ws3_sales") / col("ws2_sales")
                       > col("ss3_sales") / col("ss2_sales")))
            .select(col("ss1_county").alias("county"),
                    (col("ws2_sales") / col("ws1_sales"))
                    .alias("web_growth"),
                    (col("ss2_sales") / col("ss1_sales"))
                    .alias("store_growth"))
            .order_by(col("county"))
            .limit(100))


def _three_channel_by_item(t, item_filter):
    """q33/q56/q60 skeleton: per-manufacturer/item sums across the three
    channels in one month for out-of-timezone customers, unioned."""
    dd = t["date_dim"].filter((col("d_year") == 2000)
                              & (col("d_moy") == 1))
    it = t["item"].join(item_filter, on="i_item_sk", how="left_semi")

    def chan(sales, date_key, addr_key, price, item_key):
        return (t[sales]
                .join(dd, on=col(date_key) == col("d_date_sk"))
                .join(t["customer_address"].filter(
                    col("ca_gmt_offset") == -5.0),
                    on=col(addr_key) == col("ca_address_sk"))
                .join(it, on=col(item_key) == col("i_item_sk"))
                .group_by(col("i_manufact_id"))
                .agg(F.sum(col(price)).alias("chan_sales")))
    a = chan("store_sales", "ss_sold_date_sk", "ss_addr_sk",
             "ss_ext_sales_price", "ss_item_sk")
    b = chan("catalog_sales", "cs_sold_date_sk", "cs_bill_addr_sk",
             "cs_ext_sales_price", "cs_item_sk")
    c = chan("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
             "ws_ext_sales_price", "ws_item_sk")
    return (a.union(b).union(c)
            .group_by(col("i_manufact_id"))
            .agg(F.sum(col("chan_sales")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("i_manufact_id"))
            .limit(100))


def q33(t):
    """Manufacturer revenue across all three channels for one category's
    items (3-way union of channel aggregates)."""
    cat_items = (t["item"].filter(col("i_category") == "Books")
                 .select(col("i_item_sk")))
    return _three_channel_by_item(t, cat_items)


def q56(t):
    """q33's shape keyed by item COLOR set membership."""
    color_items = (t["item"]
                   .filter(col("i_color").isin("red", "blue", "green"))
                   .select(col("i_item_sk")))
    return _three_channel_by_item(t, color_items)


def q46(t):
    """Ticket-grouped sales where the purchase city differs from the
    customer's city, for dep/vehicle households on weekend days."""
    dd = t["date_dim"].filter(col("d_day_name").isin("Saturday",
                                                     "Sunday"))
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 4) | (col("hd_vehicle_count") == 3))
    st = t["store"].filter(col("s_city").isin("Midway", "Fairview"))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .join(t["customer_address"],
                     on=col("ss_addr_sk") == col("ca_address_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("ca_city"))
               .agg(F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit"))
               .select(col("ss_ticket_number"), col("ss_customer_sk"),
                       col("ca_city").alias("bought_city"), col("amt"),
                       col("profit")))
    cur = t["customer_address"].select(
        col("ca_address_sk").alias("cur_sk"),
        col("ca_city").alias("cur_city"))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .join(cur, on=col("c_current_addr_sk") == col("cur_sk"))
            .filter(col("cur_city") != col("bought_city"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("cur_city"), col("bought_city"),
                    col("ss_ticket_number"), col("amt"), col("profit"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("ss_ticket_number"))
            .limit(100))


def q60(t):
    """q33's shape keyed by category (the spec's third variant)."""
    cat_items = (t["item"].filter(col("i_category") == "Music")
                 .select(col("i_item_sk")))
    return _three_channel_by_item(t, cat_items)


def q69(t):
    """Demographics of in-state customers with a store purchase but NO
    web or catalog activity in the window (semi + anti chain)."""
    ss_c, ws_c, cs_c = _channel_activity(t)
    ca = t["customer_address"].filter(col("ca_state").isin("TN", "GA",
                                                           "TX"))
    return (t["customer"]
            .join(ca, on=col("c_current_addr_sk") == col("ca_address_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .join(ss_c, on=col("c_customer_sk") == col("ss_cust"),
                  how="left_semi")
            .join(ws_c, on=col("c_customer_sk") == col("ws_cust"),
                  how="left_anti")
            .join(cs_c, on=col("c_customer_sk") == col("cs_cust"),
                  how="left_anti")
            .group_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .agg(F.count(lit(1)).alias("cnt"),
                 F.avg(col("cd_dep_count")).alias("avg_dep"))
            .order_by(col("cd_gender"), col("cd_marital_status"),
                      col("cd_education_status"))
            .limit(100))


def q79(t):
    """Per-ticket profit for big-store weekday shopping by dep/vehicle
    households, joined back to the customer."""
    dd = t["date_dim"].filter(col("d_day_name") == "Monday")
    hd = t["household_demographics"].filter(
        (col("hd_dep_count") == 6) | (col("hd_vehicle_count") > 2))
    st = t["store"].filter(col("s_number_employees").between(200, 295))
    grouped = (t["store_sales"]
               .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .join(st, on=col("ss_store_sk") == col("s_store_sk"))
               .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
               .group_by(col("ss_ticket_number"), col("ss_customer_sk"),
                         col("s_city"))
               .agg(F.sum(col("ss_coupon_amt")).alias("amt"),
                    F.sum(col("ss_net_profit")).alias("profit")))
    return (grouped
            .join(t["customer"],
                  on=col("ss_customer_sk") == col("c_customer_sk"))
            .select(col("c_last_name"), col("c_first_name"),
                    col("s_city"), col("profit"),
                    col("ss_ticket_number"), col("amt"))
            .order_by(col("c_last_name"), col("c_first_name"),
                      col("s_city"), col("profit").desc(),
                      col("ss_ticket_number"))
            .limit(100))


def q92(t):
    """Web sales with an ext discount above 1.3x the item's average in
    the window (per-item scalar-subquery join).  Window widened to a full
    year and the manufacturer filter dropped (spec: 90 days, one
    manufacturer) — at tiny scale factors an item has ~1 row in 90 days
    and can never exceed 1.3x its own average."""
    dd = (t["date_dim"]
          .filter(col("d_date").between("2000-01-01", "2000-12-31"))
          .select(col("d_date_sk").alias("w_dsk")))
    windowed = (t["web_sales"]
                .join(dd, on=col("ws_sold_date_sk") == col("w_dsk")))
    item_avg = (windowed.group_by(col("ws_item_sk"))
                .agg((F.avg(col("ws_ext_discount_amt")) * 1.3)
                     .alias("bar"))
                .select(col("ws_item_sk").alias("avg_item"), col("bar")))
    return (windowed
            .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
            .join(item_avg, on=col("ws_item_sk") == col("avg_item"))
            .filter(col("ws_ext_discount_amt") > col("bar"))
            .agg(F.sum(col("ws_ext_discount_amt"))
                 .alias("excess_discount")))


def q8(t):
    """Store net profit for stores whose zip prefix matches a
    preferred-customer-heavy zip (zip-prefix semi-join; spec's literal
    400-zip IN list replaced by the generator's populated prefixes)."""
    dd = t["date_dim"].filter((col("d_year") == 2000)
                              & (col("d_qoy") == 2))
    pref = (t["customer"].filter(col("c_preferred_cust_flag") == "Y")
            .join(t["customer_address"],
                  on=col("c_current_addr_sk") == col("ca_address_sk"))
            .group_by(F.substring(col("ca_zip"), 1, 2).alias("zip2"))
            .agg(F.count(lit(1)).alias("cnt"))
            .filter(col("cnt") >= 2)
            .select(col("zip2")))
    st = (t["store"]
          .with_column("s_zip2", F.substring(col("s_zip"), 1, 2))
          .join(pref, on=col("s_zip2") == col("zip2"), how="left_semi"))
    return (t["store_sales"]
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .group_by(col("s_store_name"))
            .agg(F.sum(col("ss_net_profit")).alias("net_profit"))
            .order_by(col("s_store_name"))
            .limit(100))


def q54(t):
    """Customers who bought a target category from catalog/web in one
    month, bucketed by their store revenue in the following quarter
    (cross-channel cohort -> store revenue histogram)."""
    it = t["item"].filter((col("i_category") == "Women"))
    dd1 = t["date_dim"].filter((col("d_year") == 2000)
                               & (col("d_moy") == 3))
    cs = (t["catalog_sales"]
          .select(col("cs_sold_date_sk").alias("sold_date"),
                  col("cs_item_sk").alias("sold_item"),
                  col("cs_bill_customer_sk").alias("cust")))
    ws = (t["web_sales"]
          .select(col("ws_sold_date_sk").alias("sold_date"),
                  col("ws_item_sk").alias("sold_item"),
                  col("ws_bill_customer_sk").alias("cust")))
    cohort = (cs.union(ws)
              .join(dd1, on=col("sold_date") == col("d_date_sk"))
              .join(it, on=col("sold_item") == col("i_item_sk"))
              .group_by(col("cust"))
              .agg(F.count(lit(1)).alias("_n"))
              .select(col("cust")))
    dd2 = t["date_dim"].filter((col("d_year") == 2000)
                               & col("d_moy").between(4, 6))
    revenue = (t["store_sales"]
               .join(cohort, on=col("ss_customer_sk") == col("cust"),
                     how="left_semi")
               .join(dd2, on=col("ss_sold_date_sk") == col("d_date_sk"))
               .group_by(col("ss_customer_sk"))
               .agg(F.sum(col("ss_ext_sales_price")).alias("revenue")))
    return (revenue
            .with_column("segment",
                         F.floor(col("revenue") / 50.0))
            .group_by(col("segment"))
            .agg(F.count(lit(1)).alias("num_customers"))
            .order_by(col("segment"))
            .limit(100))


def q58(t):
    """Items whose revenue is comparable across ALL THREE channels
    (per-channel item aggregates joined with ratio bands).  Scaled for
    the generator: the window is the full year and the band is
    [0.5x, 1.75x] of the three-way average (spec: one week, +/-10%) —
    the tiny-sf channels have structurally different volumes
    (ss:cs:ws row counts ~4:2:1), so the spec band selects nothing
    while this one keeps a discriminating ~10% of common items."""
    dd = (t["date_dim"].filter(col("d_year") == 2000)
          .select(col("d_date_sk").alias("day_sk")))

    def chan(sales, date_key, item_key, price, prefix):
        return (t[sales]
                .join(dd, on=col(date_key) == col("day_sk"))
                .join(t["item"], on=col(item_key) == col("i_item_sk"))
                .group_by(col("i_item_id"))
                .agg(F.sum(col(price)).alias(f"{prefix}_rev"))
                .select(col("i_item_id").alias(f"{prefix}_id"),
                        col(f"{prefix}_rev")))
    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", "ss")
    cs = chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_ext_sales_price", "cs")
    ws = chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_ext_sales_price", "ws")
    avg3 = (col("ss_rev") + col("cs_rev") + col("ws_rev")) / 3.0
    joined = (ss.join(cs, on=col("ss_id") == col("cs_id"))
              .join(ws, on=col("ss_id") == col("ws_id"))
              .with_column("average", avg3))
    band = lambda c: (c >= 0.5 * col("average")) \
        & (c <= 1.75 * col("average"))  # noqa: E731
    return (joined
            .filter(band(col("ss_rev")) & band(col("cs_rev"))
                    & band(col("ws_rev")))
            .select(col("ss_id"), col("ss_rev"), col("cs_rev"),
                    col("ws_rev"), col("average"))
            .order_by(col("ss_id"))
            .limit(100))


def _inventory_price_band(t, fact, date_key, item_key):
    """q37/q82 skeleton: items in a price band with inventory on hand in
    a window, that also sold through the channel."""
    it = t["item"].filter(col("i_current_price").between(20.0, 50.0))
    dd = (t["date_dim"].filter(col("d_year") == 2000)
          .select(col("d_date_sk").alias("inv_dsk")))
    stocked = (t["inventory"]
               .filter(col("inv_quantity_on_hand").between(100, 500))
               .join(dd, on=col("inv_date_sk") == col("inv_dsk"))
               .select(col("inv_item_sk")).distinct())
    sold = (t[fact]
            .join(t["date_dim"].filter(col("d_year") == 2000)
                  .select(col("d_date_sk").alias("sold_dsk")),
                  on=col(date_key) == col("sold_dsk"))
            .select(col(item_key).alias("sold_item")).distinct())
    return (it
            .join(stocked, on=col("i_item_sk") == col("inv_item_sk"),
                  how="left_semi")
            .join(sold, on=col("i_item_sk") == col("sold_item"),
                  how="left_semi")
            .select(col("i_item_id"), col("i_item_desc"),
                    col("i_current_price"))
            .order_by(col("i_item_id"))
            .limit(100))


def q37(t):
    """Catalog items in a price band with inventory on hand (inventory
    semi-join; spec window widened to the year for tiny-sf population)."""
    return _inventory_price_band(t, "catalog_sales", "cs_sold_date_sk",
                                 "cs_item_sk")


def q82(t):
    """q37's store twin."""
    return _inventory_price_band(t, "store_sales", "ss_sold_date_sk",
                                 "ss_item_sk")


def q93(t):
    """Per-customer effective sales after backing out returns for one
    return reason (sale left-joined to its returns on ticket+item)."""
    sr = (t["store_returns"]
          .join(t["reason"].filter(col("r_reason_desc") == "reason 3"),
                on=col("sr_reason_sk") == col("r_reason_sk"))
          .select(col("sr_ticket_number").alias("rt"),
                  col("sr_item_sk").alias("ri"),
                  col("sr_return_quantity")))
    act = (t["store_sales"]
           .join(sr, on=(col("ss_ticket_number") == col("rt"))
                 & (col("ss_item_sk") == col("ri")), how="left")
           .with_column(
               "act_sales",
               F.when(~col("sr_return_quantity").is_null(),
                      (col("ss_quantity") - col("sr_return_quantity"))
                      * col("ss_sales_price"))
               .otherwise(col("ss_quantity") * col("ss_sales_price"))))
    return (act.group_by(col("ss_customer_sk"))
            .agg(F.sum(col("act_sales")).alias("sumsales"))
            .order_by(col("sumsales").desc(), col("ss_customer_sk"))
            .limit(100))


QUERIES = {n: globals()[f"q{n}"] for n in
           (1, 3, 5, 6, 7, 8, 10, 12, 13, 15, 19, 20, 25, 26, 27, 29,
            31, 33, 34, 35, 36, 37, 38, 42, 43, 45, 46, 47, 48, 52, 54,
            55, 56, 57, 58, 59, 60, 65, 68, 69, 73, 79, 82, 87, 88, 89,
            92, 93, 96, 98)}

