"""TPC-DS star-join queries in the DataFrame API (public TPC-DS spec
templates, expressed in this repo's own DSL — BASELINE.md staged config 3).

Each `qN(t)` takes {table_name: DataFrame} and returns a DataFrame.  The
shapes exercised: dimension broadcast joins into the store_sales fact,
multi-dimension chains, string-prefix anti-conditions (q19), and the
pure-count multi-way join (q96)."""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit


def q3(t):
    """Brand revenue by year for one manufacturer in November."""
    dd = t["date_dim"].filter(col("d_moy") == 11)
    it = t["item"].filter(col("i_manufact_id") == 12)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_discount_amt")).alias("sum_agg"))
            .order_by(col("d_year"), col("sum_agg").desc(),
                      col("i_brand_id"))
            .limit(100))


def q7(t):
    """Average sales metrics per item for one demographics tuple with a
    non-event/non-email promotion."""
    cd = t["customer_demographics"].filter(
        (col("cd_gender") == "M") & (col("cd_marital_status") == "S")
        & (col("cd_education_status") == "College"))
    dd = t["date_dim"].filter(col("d_year") == 2000)
    pr = t["promotion"].filter((col("p_channel_email") == "N")
                               | (col("p_channel_event") == "N"))
    return (t["store_sales"]
            .join(cd, on=col("ss_cdemo_sk") == col("cd_demo_sk"))
            .join(dd, on=col("ss_sold_date_sk") == col("d_date_sk"))
            .join(t["item"], on=col("ss_item_sk") == col("i_item_sk"))
            .join(pr, on=col("ss_promo_sk") == col("p_promo_sk"))
            .group_by(col("i_item_id"))
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"))
            .order_by(col("i_item_id"))
            .limit(100))


def q19(t):
    """Brand revenue where the customer's zip prefix differs from the
    store's (out-of-neighborhood purchases)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1998))
    it = t["item"].filter(col("i_manager_id") == 8)
    joined = (dd.join(t["store_sales"],
                      on=col("d_date_sk") == col("ss_sold_date_sk"))
              .join(it, on=col("ss_item_sk") == col("i_item_sk"))
              .join(t["customer"],
                    on=col("ss_customer_sk") == col("c_customer_sk"))
              .join(t["customer_address"],
                    on=col("c_current_addr_sk") == col("ca_address_sk"))
              .join(t["store"], on=col("ss_store_sk") == col("s_store_sk"))
              .filter(F.substring(col("ca_zip"), 1, 5)
                      != F.substring(col("s_zip"), 1, 5)))
    return (joined
            .group_by(col("i_brand_id"), col("i_brand"),
                      col("i_manufact_id"), col("i_manufact"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand"),
                      col("i_brand_id"), col("i_manufact_id"),
                      col("i_manufact"))
            .limit(100))


def q42(t):
    """Category revenue for one manager's items in November."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_category_id"),
                      col("i_category"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("total_sales"))
            .order_by(col("total_sales").desc(), col("d_year"),
                      col("i_category_id"), col("i_category"))
            .limit(100))


def q52(t):
    """Brand revenue for one manager's items in November (brand cut of
    q42)."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 2000))
    it = t["item"].filter(col("i_manager_id") == 1)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("d_year"), col("i_brand"), col("i_brand_id"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year"), col("ext_price").desc(),
                      col("i_brand_id"))
            .limit(100))


def q55(t):
    """Brand revenue for one manager in one month."""
    dd = t["date_dim"].filter((col("d_moy") == 11)
                              & (col("d_year") == 1999))
    it = t["item"].filter(col("i_manager_id") == 28)
    return (dd.join(t["store_sales"],
                    on=col("d_date_sk") == col("ss_sold_date_sk"))
            .join(it, on=col("ss_item_sk") == col("i_item_sk"))
            .group_by(col("i_brand_id"), col("i_brand"))
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id"))
            .limit(100))


def q96(t):
    """Count of evening purchases by high-dependent-count households at
    one store."""
    td = t["time_dim"].filter((col("t_hour") == 20)
                              & (col("t_minute") >= 30))
    hd = t["household_demographics"].filter(col("hd_dep_count") == 7)
    st = t["store"].filter(col("s_store_name") == "ese")
    return (t["store_sales"]
            .join(hd, on=col("ss_hdemo_sk") == col("hd_demo_sk"))
            .join(td, on=col("ss_sold_time_sk") == col("t_time_sk"))
            .join(st, on=col("ss_store_sk") == col("s_store_sk"))
            .agg(F.count(lit(1)).alias("cnt")))


QUERIES = {3: q3, 7: q7, 19: q19, 42: q42, 52: q52, 55: q55, 96: q96}
