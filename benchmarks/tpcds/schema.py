"""TPC-DS table schemas (all 24 tables the 99-query
tier; columns trimmed to those the queries touch plus keys).
Reference counterpart: the TPC-DS benchmark drivers the reference ships
under integration_tests (BASELINE.md staged config 3: TPC-DS q3/q5
broadcast + shuffled hash joins)."""
from spark_rapids_tpu.types import (DateType, DoubleType, LongType, Schema,
                                    StringType, StructField as F)

DATE_DIM = Schema([
    F("d_date_sk", LongType), F("d_date", DateType),
    F("d_year", LongType), F("d_moy", LongType), F("d_dom", LongType),
    F("d_qoy", LongType), F("d_day_name", StringType),
    F("d_month_seq", LongType)])

ITEM = Schema([
    F("i_item_sk", LongType), F("i_item_id", StringType),
    F("i_brand_id", LongType), F("i_brand", StringType),
    F("i_category_id", LongType), F("i_category", StringType),
    F("i_manufact_id", LongType), F("i_manufact", StringType),
    F("i_manager_id", LongType), F("i_current_price", DoubleType),
    F("i_class_id", LongType), F("i_class", StringType),
    F("i_item_desc", StringType), F("i_color", StringType)])

STORE_SALES = Schema([
    F("ss_sold_date_sk", LongType), F("ss_sold_time_sk", LongType),
    F("ss_item_sk", LongType), F("ss_customer_sk", LongType),
    F("ss_cdemo_sk", LongType), F("ss_hdemo_sk", LongType),
    F("ss_addr_sk", LongType), F("ss_store_sk", LongType),
    F("ss_promo_sk", LongType), F("ss_ticket_number", LongType),
    F("ss_quantity", LongType), F("ss_list_price", DoubleType),
    F("ss_sales_price", DoubleType), F("ss_ext_discount_amt", DoubleType),
    F("ss_ext_sales_price", DoubleType),
    F("ss_ext_wholesale_cost", DoubleType), F("ss_coupon_amt", DoubleType),
    F("ss_net_profit", DoubleType)])

CUSTOMER_DEMOGRAPHICS = Schema([
    F("cd_demo_sk", LongType), F("cd_gender", StringType),
    F("cd_marital_status", StringType),
    F("cd_education_status", StringType), F("cd_dep_count", LongType),
    F("cd_dep_employed_count", LongType),
    F("cd_dep_college_count", LongType)])

PROMOTION = Schema([
    F("p_promo_sk", LongType), F("p_channel_email", StringType),
    F("p_channel_event", StringType)])

CUSTOMER = Schema([
    F("c_customer_sk", LongType), F("c_customer_id", StringType),
    F("c_current_addr_sk", LongType), F("c_birth_month", LongType),
    F("c_current_cdemo_sk", LongType), F("c_current_hdemo_sk", LongType),
    F("c_first_name", StringType), F("c_last_name", StringType),
    F("c_salutation", StringType), F("c_preferred_cust_flag", StringType),
    F("c_birth_country", StringType)])

CUSTOMER_ADDRESS = Schema([
    F("ca_address_sk", LongType), F("ca_zip", StringType),
    F("ca_gmt_offset", DoubleType), F("ca_state", StringType),
    F("ca_county", StringType), F("ca_city", StringType),
    F("ca_country", StringType)])

STORE = Schema([
    F("s_store_sk", LongType), F("s_store_name", StringType),
    F("s_zip", StringType), F("s_number_employees", LongType),
    F("s_company_name", StringType), F("s_state", StringType),
    F("s_county", StringType), F("s_city", StringType),
    F("s_gmt_offset", DoubleType), F("s_market_id", LongType)])

HOUSEHOLD_DEMOGRAPHICS = Schema([
    F("hd_demo_sk", LongType), F("hd_dep_count", LongType),
    F("hd_vehicle_count", LongType), F("hd_buy_potential", StringType),
    F("hd_income_band_sk", LongType)])

TIME_DIM = Schema([
    F("t_time_sk", LongType), F("t_hour", LongType),
    F("t_minute", LongType)])

STORE_RETURNS = Schema([
    F("sr_returned_date_sk", LongType), F("sr_store_sk", LongType),
    F("sr_return_amt", DoubleType), F("sr_net_loss", DoubleType),
    F("sr_item_sk", LongType), F("sr_customer_sk", LongType),
    F("sr_ticket_number", LongType), F("sr_return_quantity", LongType),
    F("sr_reason_sk", LongType), F("sr_cdemo_sk", LongType)])

WAREHOUSE = Schema([
    F("w_warehouse_sk", LongType), F("w_warehouse_name", StringType)])

INVENTORY = Schema([
    F("inv_date_sk", LongType), F("inv_item_sk", LongType),
    F("inv_warehouse_sk", LongType),
    F("inv_quantity_on_hand", LongType)])

REASON = Schema([
    F("r_reason_sk", LongType), F("r_reason_desc", StringType)])

CATALOG_SALES = Schema([
    F("cs_sold_date_sk", LongType), F("cs_catalog_page_sk", LongType),
    F("cs_item_sk", LongType), F("cs_order_number", LongType),
    F("cs_ext_sales_price", DoubleType), F("cs_net_profit", DoubleType),
    F("cs_bill_customer_sk", LongType), F("cs_ship_customer_sk", LongType),
    F("cs_bill_cdemo_sk", LongType), F("cs_call_center_sk", LongType),
    F("cs_promo_sk", LongType), F("cs_quantity", LongType),
    F("cs_list_price", DoubleType), F("cs_sales_price", DoubleType),
    F("cs_coupon_amt", DoubleType), F("cs_bill_addr_sk", LongType),
    F("cs_ship_date_sk", LongType), F("cs_ship_mode_sk", LongType),
    F("cs_warehouse_sk", LongType), F("cs_ship_addr_sk", LongType),
    F("cs_ext_discount_amt", DoubleType), F("cs_sold_time_sk", LongType),
    F("cs_ship_hdemo_sk", LongType)])

CATALOG_RETURNS = Schema([
    F("cr_returned_date_sk", LongType), F("cr_catalog_page_sk", LongType),
    F("cr_return_amount", DoubleType), F("cr_net_loss", DoubleType),
    F("cr_item_sk", LongType), F("cr_order_number", LongType),
    F("cr_call_center_sk", LongType),
    F("cr_returning_customer_sk", LongType),
    F("cr_return_quantity", LongType)])

WEB_SALES = Schema([
    F("ws_sold_date_sk", LongType), F("ws_web_site_sk", LongType),
    F("ws_item_sk", LongType), F("ws_order_number", LongType),
    F("ws_ext_sales_price", DoubleType), F("ws_net_profit", DoubleType),
    F("ws_bill_customer_sk", LongType), F("ws_bill_addr_sk", LongType),
    F("ws_ext_discount_amt", DoubleType),
    F("ws_quantity", LongType), F("ws_list_price", DoubleType),
    F("ws_sales_price", DoubleType), F("ws_ship_date_sk", LongType),
    F("ws_warehouse_sk", LongType), F("ws_ship_mode_sk", LongType),
    F("ws_promo_sk", LongType), F("ws_sold_time_sk", LongType),
    F("ws_web_page_sk", LongType), F("ws_ship_customer_sk", LongType),
    F("ws_ship_addr_sk", LongType), F("ws_ship_hdemo_sk", LongType)])

WEB_RETURNS = Schema([
    F("wr_returned_date_sk", LongType), F("wr_item_sk", LongType),
    F("wr_order_number", LongType), F("wr_return_amt", DoubleType),
    F("wr_net_loss", DoubleType),
    F("wr_returning_customer_sk", LongType), F("wr_reason_sk", LongType),
    F("wr_return_quantity", LongType),
    F("wr_refunded_cdemo_sk", LongType),
    F("wr_returning_cdemo_sk", LongType),
    F("wr_refunded_addr_sk", LongType), F("wr_web_page_sk", LongType)])

SHIP_MODE = Schema([
    F("sm_ship_mode_sk", LongType), F("sm_type", StringType),
    F("sm_carrier", StringType)])

WEB_PAGE = Schema([
    F("wp_web_page_sk", LongType), F("wp_char_count", LongType)])

INCOME_BAND = Schema([
    F("ib_income_band_sk", LongType), F("ib_lower_bound", LongType),
    F("ib_upper_bound", LongType)])

CATALOG_PAGE = Schema([
    F("cp_catalog_page_sk", LongType), F("cp_catalog_page_id", StringType)])

WEB_SITE = Schema([
    F("web_site_sk", LongType), F("web_site_id", StringType)])

CALL_CENTER = Schema([
    F("cc_call_center_sk", LongType), F("cc_name", StringType)])

SCHEMAS = {
    "date_dim": DATE_DIM, "item": ITEM, "store_sales": STORE_SALES,
    "customer_demographics": CUSTOMER_DEMOGRAPHICS, "promotion": PROMOTION,
    "customer": CUSTOMER, "customer_address": CUSTOMER_ADDRESS,
    "store": STORE, "household_demographics": HOUSEHOLD_DEMOGRAPHICS,
    "time_dim": TIME_DIM, "store_returns": STORE_RETURNS,
    "catalog_sales": CATALOG_SALES, "catalog_returns": CATALOG_RETURNS,
    "web_sales": WEB_SALES, "web_returns": WEB_RETURNS,
    "catalog_page": CATALOG_PAGE, "web_site": WEB_SITE,
    "call_center": CALL_CENTER, "warehouse": WAREHOUSE,
    "inventory": INVENTORY, "reason": REASON, "ship_mode": SHIP_MODE,
    "web_page": WEB_PAGE, "income_band": INCOME_BAND,
}
