"""TPC-H-like benchmark suite (reference: integration_tests/.../tpch/)."""
from .datagen import days, generate, load_tables
from .queries import QUERIES
from .schema import SCHEMAS

__all__ = ["days", "generate", "load_tables", "QUERIES", "SCHEMAS"]
