"""TPC-H-like data generator (structure-faithful, not dbgen-exact).

Row counts scale with `sf` like the spec (lineitem ~ 6M * sf); key
relationships (orders->customer, lineitem->orders/part/supplier,
partsupp->part/supplier, nested region/nation) and the value domains the
22 queries filter on (segments, brands, types like "%BRASS", date ranges,
priorities, ship modes, phone country codes) are all generated so every
query selects a meaningful subset.  Reference counterpart: the .tbl
fixtures + converters in integration_tests (TpchLikeSpark.scala:49-290).
"""
from __future__ import annotations

import datetime

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)


def days(s: str) -> int:
    """'1994-01-01' -> days since epoch (our DateType representation)."""
    y, m, d = map(int, s.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


START = days("1992-01-01")
END = days("1998-08-02")

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
           "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
           "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
           "UNITED KINGDOM", "UNITED STATES"]
NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1, 2, 3,
                 4, 2, 3, 3, 1]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPES_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPES_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPES_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINERS_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINERS_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
          "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
          "floral", "forest", "frosted", "gainsboro", "ghost", "gold",
          "goldenrod", "green", "grey", "honeydew", "hot", "indian", "ivory",
          "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime",
          "linen", "magenta", "maroon", "medium", "metallic", "midnight",
          "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange",
          "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
          "powder", "puff", "purple", "red", "rose", "rosy", "royal",
          "saddle", "salmon", "sandy", "seashell", "sienna", "sky", "slate",
          "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato",
          "turquoise", "violet", "wheat", "white", "yellow"]
WORDS = ["express", "special", "pending", "deposits", "packages", "regular",
         "requests", "accounts", "ironic", "final", "unusual", "Customer",
         "Complaints", "carefully", "quickly", "furiously", "slyly"]


def _comment(rng, n):
    k = rng.randint(2, 6, n)
    w = np.array(WORDS)
    return [" ".join(w[rng.randint(0, len(w), kk)]) for kk in k]


def generate(sf: float = 0.001, seed: int = 42):
    """Returns {table_name: dict of column -> python list}."""
    rng = np.random.RandomState(seed)
    out = {}

    out["region"] = {
        "r_regionkey": list(range(5)),
        "r_name": REGIONS,
        "r_comment": _comment(rng, 5),
    }
    nn = len(NATIONS)
    out["nation"] = {
        "n_nationkey": list(range(nn)),
        "n_name": NATIONS,
        "n_regionkey": NATION_REGION,
        "n_comment": _comment(rng, nn),
    }

    n_supp = max(10, int(10_000 * sf))
    supp_nation = rng.randint(0, nn, n_supp)
    out["supplier"] = {
        "s_suppkey": list(range(1, n_supp + 1)),
        "s_name": [f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
        "s_address": [f"addr {i}" for i in range(n_supp)],
        "s_nationkey": supp_nation.tolist(),
        "s_phone": [f"{nk + 10}-{rng.randint(100, 999)}-"
                    f"{rng.randint(100, 999)}-{rng.randint(1000, 9999)}"
                    for nk in supp_nation],
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp),
                              2).tolist(),
        "s_comment": _comment(rng, n_supp),
    }

    n_cust = max(30, int(150_000 * sf))
    cust_nation = rng.randint(0, nn, n_cust)
    out["customer"] = {
        "c_custkey": list(range(1, n_cust + 1)),
        "c_name": [f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
        "c_address": [f"caddr {i}" for i in range(n_cust)],
        "c_nationkey": cust_nation.tolist(),
        "c_phone": [f"{nk + 10}-{rng.randint(100, 999)}-"
                    f"{rng.randint(100, 999)}-{rng.randint(1000, 9999)}"
                    for nk in cust_nation],
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust),
                              2).tolist(),
        "c_mktsegment": [SEGMENTS[i] for i in rng.randint(0, 5, n_cust)],
        "c_comment": _comment(rng, n_cust),
    }

    n_part = max(20, int(200_000 * sf))
    out["part"] = {
        "p_partkey": list(range(1, n_part + 1)),
        "p_name": [" ".join(np.array(COLORS)[rng.choice(len(COLORS), 5,
                                                        replace=False)])
                   for _ in range(n_part)],
        "p_mfgr": [f"Manufacturer#{rng.randint(1, 6)}"
                   for _ in range(n_part)],
        "p_brand": [f"Brand#{rng.randint(1, 6)}{rng.randint(1, 6)}"
                    for _ in range(n_part)],
        "p_type": [f"{TYPES_1[rng.randint(0, 6)]} "
                   f"{TYPES_2[rng.randint(0, 5)]} "
                   f"{TYPES_3[rng.randint(0, 5)]}" for _ in range(n_part)],
        "p_size": rng.randint(1, 51, n_part).tolist(),
        "p_container": [f"{CONTAINERS_1[rng.randint(0, 5)]} "
                        f"{CONTAINERS_2[rng.randint(0, 8)]}"
                        for _ in range(n_part)],
        "p_retailprice": np.round(900 + rng.uniform(0, 200, n_part),
                                  2).tolist(),
        "p_comment": _comment(rng, n_part),
    }

    n_ps = n_part * 4
    ps_part = np.repeat(np.arange(1, n_part + 1), 4)
    ps_supp = rng.randint(1, n_supp + 1, n_ps)
    out["partsupp"] = {
        "ps_partkey": ps_part.tolist(),
        "ps_suppkey": ps_supp.tolist(),
        "ps_availqty": rng.randint(1, 10_000, n_ps).tolist(),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_ps),
                                  2).tolist(),
        "ps_comment": _comment(rng, n_ps),
    }

    n_ord = max(100, int(1_500_000 * sf))
    o_date = rng.randint(START, END - 151, n_ord)
    out["orders"] = {
        "o_orderkey": list(range(1, n_ord + 1)),
        "o_custkey": rng.randint(1, n_cust + 1, n_ord).tolist(),
        "o_orderstatus": [["F", "O", "P"][i]
                          for i in rng.randint(0, 3, n_ord)],
        "o_totalprice": np.round(rng.uniform(900, 500_000, n_ord),
                                 2).tolist(),
        "o_orderdate": o_date.tolist(),
        "o_orderpriority": [PRIORITIES[i] for i in rng.randint(0, 5, n_ord)],
        "o_clerk": [f"Clerk#{rng.randint(1, 1000):09d}"
                    for _ in range(n_ord)],
        "o_shippriority": [0] * n_ord,
        "o_comment": _comment(rng, n_ord),
    }

    nl_per = rng.randint(1, 8, n_ord)
    l_ord = np.repeat(np.arange(1, n_ord + 1), nl_per)
    n_li = len(l_ord)
    l_odate = np.repeat(o_date, nl_per)
    ship = l_odate + rng.randint(1, 122, n_li)
    commit = l_odate + rng.randint(30, 91, n_li)
    receipt = ship + rng.randint(1, 31, n_li)
    qty = rng.randint(1, 51, n_li).astype(np.float64)
    price = np.round(qty * (900 + rng.uniform(0, 200, n_li)), 2)
    linenumber = np.concatenate([np.arange(1, k + 1) for k in nl_per])
    out["lineitem"] = {
        "l_orderkey": l_ord.tolist(),
        "l_partkey": rng.randint(1, n_part + 1, n_li).tolist(),
        "l_suppkey": rng.randint(1, n_supp + 1, n_li).tolist(),
        "l_linenumber": linenumber.tolist(),
        "l_quantity": qty.tolist(),
        "l_extendedprice": price.tolist(),
        "l_discount": np.round(rng.uniform(0.0, 0.10, n_li), 2).tolist(),
        "l_tax": np.round(rng.uniform(0.0, 0.08, n_li), 2).tolist(),
        "l_returnflag": [["A", "N", "R"][i] for i in
                         rng.randint(0, 3, n_li)],
        "l_linestatus": [["F", "O"][i] for i in rng.randint(0, 2, n_li)],
        "l_shipdate": ship.tolist(),
        "l_commitdate": commit.tolist(),
        "l_receiptdate": receipt.tolist(),
        "l_shipinstruct": [INSTRUCTS[i] for i in rng.randint(0, 4, n_li)],
        "l_shipmode": [SHIPMODES[i] for i in rng.randint(0, 7, n_li)],
        "l_comment": _comment(rng, n_li),
    }
    return out


def load_tables(session, sf: float = 0.001, seed: int = 42):
    """{name: DataFrame} on the given session (cached arrow tables)."""
    from .schema import SCHEMAS
    from .._cache import cached_load
    return cached_load("tpch", generate, SCHEMAS, session, sf, seed)
