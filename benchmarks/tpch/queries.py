"""All 22 TPC-H queries in the DataFrame API.

Reference counterpart: integration_tests/.../tpch/TpchLikeSpark.scala
(Q1-Q22 as DataFrame programs).  Correlated subqueries are expressed the
way Spark's optimizer would: aggregate-then-join; scalar subqueries are
evaluated driver-side (collect -> literal), mirroring Spark's scalar
subquery execution.  Distinct aggregates use two-level grouping rewrites.

Each `qN(t)` takes {table_name: DataFrame} (one session) and returns a
DataFrame.
"""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import SortOrder, col, functions as F, lit

from .datagen import days


def q1(t):
    li = t["lineitem"].filter(col("l_shipdate") <= "1998-09-02")
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (li.group_by(col("l_returnflag"), col("l_linestatus"))
            .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                 F.sum(col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(disc).alias("sum_disc_price"),
                 F.sum(disc * (lit(1.0) + col("l_tax"))).alias("sum_charge"),
                 F.avg(col("l_quantity")).alias("avg_qty"),
                 F.avg(col("l_extendedprice")).alias("avg_price"),
                 F.avg(col("l_discount")).alias("avg_disc"),
                 F.count(lit(1)).alias("count_order"))
            .order_by("l_returnflag", "l_linestatus"))


def q2(t):
    part = t["part"].filter((col("p_size") == 15)
                            & col("p_type").endswith("BRASS"))
    europe = (t["region"].filter(col("r_name") == "EUROPE")
              .join(t["nation"],
                    on=col("r_regionkey") == col("n_regionkey"))
              .join(t["supplier"],
                    on=col("n_nationkey") == col("s_nationkey")))
    ps = t["partsupp"].join(europe,
                            on=col("ps_suppkey") == col("s_suppkey"))
    joined = part.join(ps, on=col("p_partkey") == col("ps_partkey"))
    mins = (joined.group_by(col("p_partkey"))
            .agg(F.min(col("ps_supplycost")).alias("min_cost"))
            .select(col("p_partkey").alias("mk"), col("min_cost")))
    return (joined.join(mins, on=(col("p_partkey") == col("mk"))
                        & (col("ps_supplycost") == col("min_cost")))
            .select(col("s_acctbal"), col("s_name"), col("n_name"),
                    col("p_partkey"), col("p_mfgr"), col("s_address"),
                    col("s_phone"), col("s_comment"))
            .order_by(SortOrder(col("s_acctbal"), ascending=False),
                      "n_name", "s_name", "p_partkey")
            .limit(100))


def q3(t):
    cust = t["customer"].filter(col("c_mktsegment") == "BUILDING")
    orders = t["orders"].filter(col("o_orderdate") < "1995-03-15")
    li = t["lineitem"].filter(col("l_shipdate") > "1995-03-15")
    return (cust.join(orders, on=col("c_custkey") == col("o_custkey"))
            .join(li, on=col("o_orderkey") == col("l_orderkey"))
            .group_by(col("l_orderkey"), col("o_orderdate"),
                      col("o_shippriority"))
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .order_by(SortOrder(col("revenue"), ascending=False),
                      "o_orderdate")
            .limit(10))


def q4(t):
    orders = t["orders"].filter(
        (col("o_orderdate") >= "1993-07-01")
        & (col("o_orderdate") < "1993-10-01"))
    late = t["lineitem"].filter(col("l_commitdate") < col("l_receiptdate"))
    return (orders.join(late, on=col("o_orderkey") == col("l_orderkey"),
                        how="left_semi")
            .group_by(col("o_orderpriority"))
            .agg(F.count(lit(1)).alias("order_count"))
            .order_by("o_orderpriority"))


def q5(t):
    return (t["region"].filter(col("r_name") == "ASIA")
            .join(t["nation"], on=col("r_regionkey") == col("n_regionkey"))
            .join(t["supplier"], on=col("n_nationkey") == col("s_nationkey"))
            .join(t["lineitem"], on=col("s_suppkey") == col("l_suppkey"))
            .join(t["orders"].filter(
                (col("o_orderdate") >= "1994-01-01")
                & (col("o_orderdate") < "1995-01-01")),
                on=col("l_orderkey") == col("o_orderkey"))
            .join(t["customer"],
                  on=(col("o_custkey") == col("c_custkey"))
                  & (col("c_nationkey") == col("s_nationkey")))
            .group_by(col("n_name"))
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .order_by(SortOrder(col("revenue"), ascending=False)))


def q6(t):
    return (t["lineitem"]
            .filter((col("l_shipdate") >= "1994-01-01")
                    & (col("l_shipdate") < "1995-01-01")
                    & col("l_discount").between(0.05, 0.07)
                    & (col("l_quantity") < 24))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q7(t):
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_name").alias("supp_nation"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("cust_nation"))
    li = t["lineitem"].filter(col("l_shipdate").between("1995-01-01",
                                                        "1996-12-31"))
    joined = (li.join(t["supplier"], on=col("l_suppkey") == col("s_suppkey"))
              .join(t["orders"], on=col("l_orderkey") == col("o_orderkey"))
              .join(t["customer"], on=col("o_custkey") == col("c_custkey"))
              .join(n1, on=col("s_nationkey") == col("n1_key"))
              .join(n2, on=col("c_nationkey") == col("n2_key"))
              .filter(((col("supp_nation") == "FRANCE")
                       & (col("cust_nation") == "GERMANY"))
                      | ((col("supp_nation") == "GERMANY")
                         & (col("cust_nation") == "FRANCE"))))
    return (joined
            .with_column("l_year", F.year(col("l_shipdate")))
            .with_column("volume", col("l_extendedprice")
                         * (lit(1.0) - col("l_discount")))
            .group_by(col("supp_nation"), col("cust_nation"), col("l_year"))
            .agg(F.sum(col("volume")).alias("revenue"))
            .order_by("supp_nation", "cust_nation", "l_year"))


def q8(t):
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_regionkey").alias("n1_region"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("supp_nation"))
    america = t["region"].filter(col("r_name") == "AMERICA")
    part = t["part"].filter(col("p_type") == "ECONOMY ANODIZED STEEL")
    orders = t["orders"].filter(col("o_orderdate").between("1995-01-01",
                                                           "1996-12-31"))
    joined = (part.join(t["lineitem"],
                        on=col("p_partkey") == col("l_partkey"))
              .join(t["supplier"], on=col("l_suppkey") == col("s_suppkey"))
              .join(orders, on=col("l_orderkey") == col("o_orderkey"))
              .join(t["customer"], on=col("o_custkey") == col("c_custkey"))
              .join(n1, on=col("c_nationkey") == col("n1_key"))
              .join(america, on=col("n1_region") == col("r_regionkey"))
              .join(n2, on=col("s_nationkey") == col("n2_key")))
    vol = (joined
           .with_column("o_year", F.year(col("o_orderdate")))
           .with_column("volume", col("l_extendedprice")
                        * (lit(1.0) - col("l_discount")))
           .with_column("brazil_volume",
                        F.when(col("supp_nation") == "BRAZIL",
                               col("volume")).otherwise(0.0)))
    return (vol.group_by(col("o_year"))
            .agg((F.sum(col("brazil_volume"))
                  / F.sum(col("volume"))).alias("mkt_share"))
            .order_by("o_year"))


def q9(t):
    part = t["part"].filter(col("p_name").contains("green"))
    joined = (part.join(t["lineitem"],
                        on=col("p_partkey") == col("l_partkey"))
              .join(t["supplier"], on=col("l_suppkey") == col("s_suppkey"))
              .join(t["partsupp"],
                    on=(col("ps_partkey") == col("l_partkey"))
                    & (col("ps_suppkey") == col("l_suppkey")))
              .join(t["orders"], on=col("l_orderkey") == col("o_orderkey"))
              .join(t["nation"], on=col("s_nationkey") == col("n_nationkey")))
    return (joined
            .with_column("o_year", F.year(col("o_orderdate")))
            .with_column("amount",
                         col("l_extendedprice")
                         * (lit(1.0) - col("l_discount"))
                         - col("ps_supplycost") * col("l_quantity"))
            .group_by(col("n_name"), col("o_year"))
            .agg(F.sum(col("amount")).alias("sum_profit"))
            .order_by("n_name", SortOrder(col("o_year"), ascending=False)))


def q10(t):
    orders = t["orders"].filter((col("o_orderdate") >= "1993-10-01")
                                & (col("o_orderdate") < "1994-01-01"))
    li = t["lineitem"].filter(col("l_returnflag") == "R")
    return (t["customer"]
            .join(orders, on=col("c_custkey") == col("o_custkey"))
            .join(li, on=col("o_orderkey") == col("l_orderkey"))
            .join(t["nation"], on=col("c_nationkey") == col("n_nationkey"))
            .group_by(col("c_custkey"), col("c_name"), col("c_acctbal"),
                      col("c_phone"), col("n_name"), col("c_address"),
                      col("c_comment"))
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue"))
            .order_by(SortOrder(col("revenue"), ascending=False))
            .limit(20))


def q11(t):
    germany = t["nation"].filter(col("n_name") == "GERMANY")
    ps = (t["partsupp"]
          .join(t["supplier"], on=col("ps_suppkey") == col("s_suppkey"))
          .join(germany, on=col("s_nationkey") == col("n_nationkey"))
          .with_column("value", col("ps_supplycost") * col("ps_availqty")))
    total = ps.agg(F.sum(col("value")).alias("tv")).collect()[0][0] or 0.0
    return (ps.group_by(col("ps_partkey"))
            .agg(F.sum(col("value")).alias("value"))
            .filter(col("value") > total * 0.0001)
            .order_by(SortOrder(col("value"), ascending=False)))


def q12(t):
    li = t["lineitem"].filter(
        col("l_shipmode").isin("MAIL", "SHIP")
        & (col("l_commitdate") < col("l_receiptdate"))
        & (col("l_shipdate") < col("l_commitdate"))
        & (col("l_receiptdate") >= "1994-01-01")
        & (col("l_receiptdate") < "1995-01-01"))
    hi = F.when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                1).otherwise(0)
    lo = F.when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"),
                0).otherwise(1)
    return (t["orders"].join(li, on=col("o_orderkey") == col("l_orderkey"))
            .group_by(col("l_shipmode"))
            .agg(F.sum(hi).alias("high_line_count"),
                 F.sum(lo).alias("low_line_count"))
            .order_by("l_shipmode"))


def q13(t):
    orders = t["orders"].filter(
        ~(col("o_comment").contains("special")
          & col("o_comment").contains("requests")))
    per_cust = (t["customer"]
                .join(orders, on=col("c_custkey") == col("o_custkey"),
                      how="left")
                .with_column("has_order",
                             F.when(col("o_orderkey").is_null(), 0)
                             .otherwise(1))
                .group_by(col("c_custkey"))
                .agg(F.sum(col("has_order")).alias("c_count")))
    return (per_cust.group_by(col("c_count"))
            .agg(F.count(lit(1)).alias("custdist"))
            .order_by(SortOrder(col("custdist"), ascending=False),
                      SortOrder(col("c_count"), ascending=False)))


def q14(t):
    li = t["lineitem"].filter((col("l_shipdate") >= "1995-09-01")
                              & (col("l_shipdate") < "1995-10-01"))
    joined = li.join(t["part"], on=col("l_partkey") == col("p_partkey"))
    disc = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    promo = F.when(col("p_type").startswith("PROMO"), disc).otherwise(0.0)
    return joined.agg(
        ((F.sum(promo) * 100.0) / F.sum(disc)).alias("promo_revenue"))


def q15(t):
    li = t["lineitem"].filter((col("l_shipdate") >= "1996-01-01")
                              & (col("l_shipdate") < "1996-04-01"))
    revenue = (li.group_by(col("l_suppkey"))
               .agg(F.sum(col("l_extendedprice")
                          * (lit(1.0) - col("l_discount")))
                    .alias("total_revenue")))
    top = revenue.agg(F.max(col("total_revenue")).alias("m")) \
        .collect()[0][0] or 0.0
    return (t["supplier"]
            .join(revenue.filter(col("total_revenue") >= top - 1e-6),
                  on=col("s_suppkey") == col("l_suppkey"))
            .select(col("s_suppkey"), col("s_name"), col("s_address"),
                    col("s_phone"), col("total_revenue"))
            .order_by("s_suppkey"))


def q16(t):
    part = t["part"].filter(
        (col("p_brand") != "Brand#45")
        & ~col("p_type").startswith("MEDIUM POLISHED")
        & col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9))
    bad_supp = t["supplier"].filter(
        col("s_comment").contains("Customer")
        & col("s_comment").contains("Complaints"))
    ps = (t["partsupp"]
          .join(bad_supp, on=col("ps_suppkey") == col("s_suppkey"),
                how="left_anti")
          .join(part, on=col("ps_partkey") == col("p_partkey")))
    # distinct supplier count via two-level grouping (no distinct aggs)
    distinct_ps = (ps.group_by(col("p_brand"), col("p_type"), col("p_size"),
                               col("ps_suppkey"))
                   .agg(F.count(lit(1)).alias("_c")))
    return (distinct_ps.group_by(col("p_brand"), col("p_type"),
                                 col("p_size"))
            .agg(F.count(lit(1)).alias("supplier_cnt"))
            .order_by(SortOrder(col("supplier_cnt"), ascending=False),
                      "p_brand", "p_type", "p_size"))


def q17(t):
    part = t["part"].filter((col("p_brand") == "Brand#23")
                            & (col("p_container") == "MED BOX"))
    li = t["lineitem"].join(part,
                            on=col("l_partkey") == col("p_partkey"))
    avg_qty = (li.group_by(col("p_partkey"))
               .agg((F.avg(col("l_quantity")) * 0.2).alias("limit_qty"))
               .select(col("p_partkey").alias("ak"), col("limit_qty")))
    return (li.join(avg_qty, on=col("p_partkey") == col("ak"))
            .filter(col("l_quantity") < col("limit_qty"))
            .agg((F.sum(col("l_extendedprice")) / 7.0)
                 .alias("avg_yearly")))


def q18(t):
    big = (t["lineitem"].group_by(col("l_orderkey"))
           .agg(F.sum(col("l_quantity")).alias("sum_qty"))
           .filter(col("sum_qty") > 300)
           .select(col("l_orderkey").alias("big_key"), col("sum_qty")))
    return (t["orders"]
            .join(big, on=col("o_orderkey") == col("big_key"))
            .join(t["customer"], on=col("o_custkey") == col("c_custkey"))
            .select(col("c_name"), col("c_custkey"), col("o_orderkey"),
                    col("o_orderdate"), col("o_totalprice"), col("sum_qty"))
            .order_by(SortOrder(col("o_totalprice"), ascending=False),
                      "o_orderdate")
            .limit(100))


def q19(t):
    li = t["lineitem"].filter(
        col("l_shipmode").isin("AIR", "REG AIR")
        & (col("l_shipinstruct") == "DELIVER IN PERSON"))
    joined = li.join(t["part"], on=col("l_partkey") == col("p_partkey"))
    b1 = ((col("p_brand") == "Brand#12")
          & col("p_container").isin("SM CASE", "SM BOX", "SM PACK", "SM PKG")
          & col("l_quantity").between(1, 11) & (col("p_size").between(1, 5)))
    b2 = ((col("p_brand") == "Brand#23")
          & col("p_container").isin("MED BAG", "MED BOX", "MED PKG",
                                    "MED PACK")
          & col("l_quantity").between(10, 20)
          & (col("p_size").between(1, 10)))
    b3 = ((col("p_brand") == "Brand#34")
          & col("p_container").isin("LG CASE", "LG BOX", "LG PACK", "LG PKG")
          & col("l_quantity").between(20, 30)
          & (col("p_size").between(1, 15)))
    return (joined.filter(b1 | b2 | b3)
            .agg(F.sum(col("l_extendedprice")
                       * (lit(1.0) - col("l_discount"))).alias("revenue")))


def q20(t):
    forest_parts = t["part"].filter(col("p_name").startswith("forest")) \
        .select(col("p_partkey").alias("fp_key"))
    li94 = t["lineitem"].filter((col("l_shipdate") >= "1994-01-01")
                                & (col("l_shipdate") < "1995-01-01"))
    half_qty = (li94.group_by(col("l_partkey"), col("l_suppkey"))
                .agg((F.sum(col("l_quantity")) * 0.5).alias("half_qty")))
    ps = (t["partsupp"]
          .join(forest_parts, on=col("ps_partkey") == col("fp_key"),
                how="left_semi")
          .join(half_qty, on=(col("ps_partkey") == col("l_partkey"))
                & (col("ps_suppkey") == col("l_suppkey")))
          .filter(col("ps_availqty") > col("half_qty")))
    canada = t["nation"].filter(col("n_name") == "CANADA")
    return (t["supplier"]
            .join(ps, on=col("s_suppkey") == col("ps_suppkey"),
                  how="left_semi")
            .join(canada, on=col("s_nationkey") == col("n_nationkey"))
            .select(col("s_name"), col("s_address"))
            .order_by("s_name"))


def q21(t):
    nation = t["nation"].filter(col("n_name") == "SAUDI ARABIA")
    f_orders = t["orders"].filter(col("o_orderstatus") == "F") \
        .select(col("o_orderkey"))
    li = t["lineitem"].join(f_orders,
                            on=col("l_orderkey") == col("o_orderkey"),
                            how="left_semi")
    # per order: number of distinct suppliers, and of distinct LATE suppliers
    supp_per_order = (li.group_by(col("l_orderkey"), col("l_suppkey"))
                      .agg(F.count(lit(1)).alias("_c"))
                      .group_by(col("l_orderkey"))
                      .agg(F.count(lit(1)).alias("nsupp"))
                      .select(col("l_orderkey").alias("all_key"),
                              col("nsupp")))
    late = li.filter(col("l_receiptdate") > col("l_commitdate"))
    late_per_order = (late.group_by(col("l_orderkey"), col("l_suppkey"))
                      .agg(F.count(lit(1)).alias("_c"))
                      .group_by(col("l_orderkey"))
                      .agg(F.count(lit(1)).alias("nlate"))
                      .select(col("l_orderkey").alias("late_key"),
                              col("nlate")))
    blamed = (late
              .join(supp_per_order, on=col("l_orderkey") == col("all_key"))
              .join(late_per_order, on=col("l_orderkey") == col("late_key"))
              .filter((col("nsupp") > 1) & (col("nlate") == 1)))
    return (blamed
            .join(t["supplier"], on=col("l_suppkey") == col("s_suppkey"))
            .join(nation, on=col("s_nationkey") == col("n_nationkey"))
            .group_by(col("s_name"))
            .agg(F.count(lit(1)).alias("numwait"))
            .order_by(SortOrder(col("numwait"), ascending=False), "s_name")
            .limit(100))


def q22(t):
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cust = t["customer"].with_column("cntrycode",
                                     col("c_phone").substr(1, 2))
    cust = cust.filter(col("cntrycode").isin(*codes))
    avg_bal = cust.filter(col("c_acctbal") > 0.0) \
        .agg(F.avg(col("c_acctbal")).alias("a")).collect()[0][0] or 0.0
    rich = cust.filter(col("c_acctbal") > avg_bal)
    no_orders = rich.join(t["orders"],
                          on=col("c_custkey") == col("o_custkey"),
                          how="left_anti")
    return (no_orders.group_by(col("cntrycode"))
            .agg(F.count(lit(1)).alias("numcust"),
                 F.sum(col("c_acctbal")).alias("totacctbal"))
            .order_by("cntrycode"))


QUERIES = {i: globals()[f"q{i}"] for i in range(1, 23)}
