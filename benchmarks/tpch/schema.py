"""TPC-H table schemas (dates as DateType day numbers)."""
from spark_rapids_tpu.types import (DateType, DoubleType, LongType, Schema,
                                    StringType, StructField as F)

REGION = Schema([F("r_regionkey", LongType), F("r_name", StringType),
                 F("r_comment", StringType)])

NATION = Schema([F("n_nationkey", LongType), F("n_name", StringType),
                 F("n_regionkey", LongType), F("n_comment", StringType)])

SUPPLIER = Schema([F("s_suppkey", LongType), F("s_name", StringType),
                   F("s_address", StringType), F("s_nationkey", LongType),
                   F("s_phone", StringType), F("s_acctbal", DoubleType),
                   F("s_comment", StringType)])

CUSTOMER = Schema([F("c_custkey", LongType), F("c_name", StringType),
                   F("c_address", StringType), F("c_nationkey", LongType),
                   F("c_phone", StringType), F("c_acctbal", DoubleType),
                   F("c_mktsegment", StringType), F("c_comment", StringType)])

PART = Schema([F("p_partkey", LongType), F("p_name", StringType),
               F("p_mfgr", StringType), F("p_brand", StringType),
               F("p_type", StringType), F("p_size", LongType),
               F("p_container", StringType), F("p_retailprice", DoubleType),
               F("p_comment", StringType)])

PARTSUPP = Schema([F("ps_partkey", LongType), F("ps_suppkey", LongType),
                   F("ps_availqty", LongType), F("ps_supplycost", DoubleType),
                   F("ps_comment", StringType)])

ORDERS = Schema([F("o_orderkey", LongType), F("o_custkey", LongType),
                 F("o_orderstatus", StringType),
                 F("o_totalprice", DoubleType), F("o_orderdate", DateType),
                 F("o_orderpriority", StringType), F("o_clerk", StringType),
                 F("o_shippriority", LongType), F("o_comment", StringType)])

LINEITEM = Schema([F("l_orderkey", LongType), F("l_partkey", LongType),
                   F("l_suppkey", LongType), F("l_linenumber", LongType),
                   F("l_quantity", DoubleType),
                   F("l_extendedprice", DoubleType),
                   F("l_discount", DoubleType), F("l_tax", DoubleType),
                   F("l_returnflag", StringType), F("l_linestatus", StringType),
                   F("l_shipdate", DateType), F("l_commitdate", DateType),
                   F("l_receiptdate", DateType), F("l_shipinstruct", StringType),
                   F("l_shipmode", StringType), F("l_comment", StringType)])

SCHEMAS = {
    "region": REGION, "nation": NATION, "supplier": SUPPLIER,
    "customer": CUSTOMER, "part": PART, "partsupp": PARTSUPP,
    "orders": ORDERS, "lineitem": LINEITEM,
}
