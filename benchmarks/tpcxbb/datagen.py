"""TPCxBB-like data generator (structure-faithful, not bigbench-exact).

Row counts scale with `sf` like the benchmark (web_clickstreams is the
big fact; the reference's headline chart is SF10,000 on this schema).
Foreign keys and the value domains Q5/Q16/Q21/Q22 filter on (category
ids 1..7, the 2001-03-16 price-change window, the 2003 return chain, the
2001-05-08 inventory window) are generated so each query selects a
meaningful subset at tiny scale factors.  Reference counterpart:
TpcxbbLikeSpark.scala:49-290 + the four charted *Like query objects."""
from __future__ import annotations

import datetime

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
STATES = ["TN", "SD", "AL", "GA", "MI", "OH", "TX", "CA"]


def generate(sf: float = 0.001, seed: int = 13):
    """Returns {table_name: dict of column -> python list}."""
    rng = np.random.RandomState(seed)
    out = {}

    start = datetime.date(2001, 1, 1)
    end = datetime.date(2005, 12, 31)
    n_days = (end - start).days + 1
    dates = [start + datetime.timedelta(days=i) for i in range(n_days)]
    first_sk = 36890
    date_sks = np.arange(first_sk, first_sk + n_days)
    out["date_dim"] = {
        "d_date_sk": date_sks.tolist(),
        "d_date": [(d - _EPOCH).days for d in dates],
        "d_year": [d.year for d in dates],
        "d_moy": [d.month for d in dates],
    }

    n_item = max(50, int(18_000 * sf))
    cat_id = rng.randint(1, len(CATEGORIES) + 1, n_item)
    out["item"] = {
        "i_item_sk": list(range(1, n_item + 1)),
        "i_item_id": [f"AAAAAAAA{i:08d}" for i in range(1, n_item + 1)],
        "i_item_desc": [f"item description {i}" for i in range(n_item)],
        "i_category": [CATEGORIES[c - 1] for c in cat_id],
        "i_category_id": cat_id.tolist(),
        "i_current_price": np.round(rng.uniform(0.5, 5.0, n_item),
                                    2).tolist(),
    }

    n_cd = 70
    combos = [(g, e) for g in ["M", "F"] for e in EDUCATION]
    out["customer_demographics"] = {
        "cd_demo_sk": list(range(1, n_cd + 1)),
        "cd_gender": [combos[i % len(combos)][0] for i in range(n_cd)],
        "cd_education_status": [combos[i % len(combos)][1]
                                for i in range(n_cd)],
    }

    n_cust = max(40, int(100_000 * sf))
    out["customer"] = {
        "c_customer_sk": list(range(1, n_cust + 1)),
        "c_current_cdemo_sk": rng.randint(1, n_cd + 1, n_cust).tolist(),
    }

    # the big fact: one row per click (reference SF10000 has ~26B)
    n_wcs = max(500, int(5_000_000 * sf))
    user = rng.randint(1, n_cust + 1, n_wcs).astype(object)
    null_mask = rng.rand(n_wcs) < 0.05  # logged-out clicks
    user[null_mask] = None
    out["web_clickstreams"] = {
        "wcs_user_sk": user.tolist(),
        "wcs_item_sk": rng.randint(1, n_item + 1, n_wcs).tolist(),
    }

    n_store = max(4, int(1_002 * sf * 2))
    out["store"] = {
        "s_store_sk": list(range(1, n_store + 1)),
        "s_store_id": [f"STORE{i:08d}" for i in range(1, n_store + 1)],
        "s_store_name": [f"store {i}" for i in range(1, n_store + 1)],
    }

    n_ss = max(400, int(2_880_000 * sf))
    n_tick = (n_ss + 3) // 4
    per_tick = np.minimum(4, n_ss - 4 * np.arange(n_tick))

    def per_ticket(vals):
        return np.repeat(np.asarray(vals), per_tick)[:n_ss]
    out["store_sales"] = {
        "ss_sold_date_sk": per_ticket(rng.choice(date_sks,
                                                 n_tick)).tolist(),
        "ss_item_sk": rng.randint(1, n_item + 1, n_ss).tolist(),
        "ss_store_sk": per_ticket(rng.randint(1, n_store + 1,
                                              n_tick)).tolist(),
        "ss_customer_sk": per_ticket(rng.randint(1, n_cust + 1,
                                                 n_tick)).tolist(),
        "ss_ticket_number": per_ticket(np.arange(1, n_tick + 1)).tolist(),
        "ss_quantity": rng.randint(1, 100, n_ss).tolist(),
    }

    # returns reference sold tickets so Q21's chain resolves; returned
    # within ~6 months of the sale
    n_sr = max(100, int(287_000 * sf))
    sr_pick = rng.randint(0, n_ss, n_sr)
    sold = np.asarray(out["store_sales"]["ss_sold_date_sk"])[sr_pick]
    out["store_returns"] = {
        "sr_returned_date_sk": np.minimum(
            sold + rng.randint(1, 180, n_sr),
            int(date_sks[-1])).tolist(),
        "sr_item_sk": [out["store_sales"]["ss_item_sk"][i]
                       for i in sr_pick],
        "sr_customer_sk": [out["store_sales"]["ss_customer_sk"][i]
                           for i in sr_pick],
        "sr_ticket_number": [out["store_sales"]["ss_ticket_number"][i]
                             for i in sr_pick],
        "sr_return_quantity": rng.randint(1, 20, n_sr).tolist(),
    }

    n_wh = max(3, int(20 * sf * 5))
    out["warehouse"] = {
        "w_warehouse_sk": list(range(1, n_wh + 1)),
        "w_warehouse_name": [f"warehouse {i}" for i in range(1, n_wh + 1)],
        "w_state": [STATES[i % len(STATES)] for i in range(n_wh)],
    }

    n_ws = max(300, int(720_000 * sf))
    out["web_sales"] = {
        "ws_sold_date_sk": rng.choice(date_sks, n_ws).tolist(),
        "ws_item_sk": rng.randint(1, n_item + 1, n_ws).tolist(),
        "ws_bill_customer_sk": rng.randint(1, n_cust + 1, n_ws).tolist(),
        "ws_order_number": list(range(1, n_ws + 1)),
        "ws_quantity": rng.randint(1, 100, n_ws).tolist(),
        "ws_sales_price": np.round(rng.uniform(0.5, 300.0, n_ws),
                                   2).tolist(),
        "ws_warehouse_sk": rng.randint(1, n_wh + 1, n_ws).tolist(),
    }

    n_wr = max(60, int(72_000 * sf))
    wr_pick = rng.randint(0, n_ws, n_wr)
    out["web_returns"] = {
        "wr_order_number": [out["web_sales"]["ws_order_number"][i]
                            for i in wr_pick],
        "wr_item_sk": [out["web_sales"]["ws_item_sk"][i]
                       for i in wr_pick],
        "wr_refunded_cash": np.round(rng.uniform(0.5, 200.0, n_wr),
                                     2).tolist(),
    }

    # inventory snapshots around the Q22 price-change date (the spec has
    # weekly snapshots for every item x warehouse; sample that grid)
    n_inv = max(400, int(1_000_000 * sf))
    out["inventory"] = {
        "inv_date_sk": rng.choice(date_sks[:730], n_inv).tolist(),
        "inv_item_sk": rng.randint(1, n_item + 1, n_inv).tolist(),
        "inv_warehouse_sk": rng.randint(1, n_wh + 1, n_inv).tolist(),
        "inv_quantity_on_hand": rng.randint(0, 1000, n_inv).tolist(),
    }
    return out


def load_tables(session, sf: float = 0.001, seed: int = 13):
    """{name: DataFrame} on the given session (cached arrow tables)."""
    from .schema import SCHEMAS
    from .._cache import cached_load
    return cached_load("tpcxbb", generate, SCHEMAS, session, sf, seed)
