"""The reference's four CHARTED TPCxBB-like queries in this repo's DSL —
the headline benchmark (reference README.md:7-15: Q5 19.8x, Q16 5.3x,
Q21 12.7x, Q22 27.1x on SF10,000).  Behavior follows
TpcxbbLikeSpark.scala's SQL (Q5Like:809-864, Q16Like:1377-1417,
Q21Like:1542-1628, Q22Like:1630-1682); each `qN(t)` takes
{table_name: DataFrame} and returns a DataFrame.
"""
from __future__ import annotations

from spark_rapids_tpu.plan.logical import col, functions as F, lit


def q5(t):
    """Per-user clicks-in-category feature matrix joined to demographics
    (the logistic-regression input; the ml handoff consumes the result)."""
    clicks = (t["web_clickstreams"]
              .filter(~col("wcs_user_sk").is_null())
              .join(t["item"], on=col("wcs_item_sk") == col("i_item_sk")))
    aggs = [F.sum(F.when(col("i_category") == "Books", 1).otherwise(0))
            .alias("clicks_in_category")]
    for c in range(1, 8):
        aggs.append(F.sum(F.when(col("i_category_id") == c, 1)
                          .otherwise(0)).alias(f"clicks_in_{c}"))
    per_user = (clicks.group_by(col("wcs_user_sk")).agg(*aggs))
    college = col("cd_education_status").isin(
        "Advanced Degree", "College", "4 yr Degree", "2 yr Degree")
    return (per_user
            .join(t["customer"],
                  on=col("wcs_user_sk") == col("c_customer_sk"))
            .join(t["customer_demographics"],
                  on=col("c_current_cdemo_sk") == col("cd_demo_sk"))
            .select(col("clicks_in_category"),
                    F.when(college, 1).otherwise(0)
                    .alias("college_education"),
                    F.when(col("cd_gender") == "M", 1).otherwise(0)
                    .alias("male"),
                    *[col(f"clicks_in_{c}") for c in range(1, 8)]))


def q16(t):
    """Sales impact of a price change: web sales net of refunds in the 30
    days before/after 2001-03-16, by warehouse state and item."""
    dd = t["date_dim"].filter(col("d_date").between("2001-02-14",
                                                    "2001-04-15"))
    net = col("ws_sales_price") - F.coalesce(col("wr_refunded_cash"),
                                             lit(0.0))
    return (t["web_sales"]
            .join(t["web_returns"],
                  on=(col("ws_order_number") == col("wr_order_number"))
                  & (col("ws_item_sk") == col("wr_item_sk")), how="left")
            .join(t["item"], on=col("ws_item_sk") == col("i_item_sk"))
            .join(t["warehouse"],
                  on=col("ws_warehouse_sk") == col("w_warehouse_sk"))
            .join(dd, on=col("ws_sold_date_sk") == col("d_date_sk"))
            .group_by(col("w_state"), col("i_item_id"))
            .agg(F.sum(F.when(col("d_date") < "2001-03-16", net)
                       .otherwise(0.0)).alias("sales_before"),
                 F.sum(F.when(col("d_date") >= "2001-03-16", net)
                       .otherwise(0.0)).alias("sales_after"))
            .order_by(col("w_state"), col("i_item_id"))
            .limit(100))


def q21(t):
    """Items sold in a month, returned within 6 months, re-purchased on
    the web by the same customer — quantities by item and store."""
    d1 = t["date_dim"].filter((col("d_year") == 2003)
                              & (col("d_moy") == 1)) \
        .select(col("d_date_sk").alias("d1_sk"))
    d2 = t["date_dim"].filter((col("d_year") == 2003)
                              & col("d_moy").between(1, 7)) \
        .select(col("d_date_sk").alias("d2_sk"))
    d3 = t["date_dim"].filter(col("d_year").between(2003, 2005)) \
        .select(col("d_date_sk").alias("d3_sk"))
    part_sr = (t["store_returns"]
               .join(d2, on=col("sr_returned_date_sk") == col("d2_sk")))
    part_ws = (t["web_sales"]
               .join(d3, on=col("ws_sold_date_sk") == col("d3_sk"))
               .select(col("ws_item_sk"), col("ws_bill_customer_sk"),
                       col("ws_quantity")))
    part_ss = (t["store_sales"]
               .join(d1, on=col("ss_sold_date_sk") == col("d1_sk")))
    return (part_sr
            .join(part_ws,
                  on=(col("sr_item_sk") == col("ws_item_sk"))
                  & (col("sr_customer_sk") == col("ws_bill_customer_sk")))
            .join(part_ss,
                  on=(col("ss_ticket_number") == col("sr_ticket_number"))
                  & (col("ss_item_sk") == col("sr_item_sk"))
                  & (col("ss_customer_sk") == col("sr_customer_sk")))
            .join(t["store"], on=col("s_store_sk") == col("ss_store_sk"))
            .join(t["item"], on=col("i_item_sk") == col("ss_item_sk"))
            .group_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_id"), col("s_store_name"))
            .agg(F.sum(col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(col("sr_return_quantity"))
                 .alias("store_returns_quantity"),
                 F.sum(col("ws_quantity")).alias("web_sales_quantity"))
            .order_by(col("i_item_id"), col("i_item_desc"),
                      col("s_store_id"), col("s_store_name"))
            .limit(100))


def q22(t):
    """Inventory change around a price change (2001-05-08 +/- 30 days) by
    warehouse, for items in a price band; keep items whose after/before
    ratio is within [2/3, 3/2]."""
    it = t["item"].filter(col("i_current_price").between(0.98, 1.5))
    dd = t["date_dim"].filter(col("d_date").between("2001-04-08",
                                                    "2001-06-07"))
    grouped = (t["inventory"]
               .join(it, on=col("i_item_sk") == col("inv_item_sk"))
               .join(t["warehouse"],
                     on=col("inv_warehouse_sk") == col("w_warehouse_sk"))
               .join(dd, on=col("inv_date_sk") == col("d_date_sk"))
               .group_by(col("w_warehouse_name"), col("i_item_id"))
               .agg(F.sum(F.when(col("d_date") < "2001-05-08",
                                 col("inv_quantity_on_hand"))
                          .otherwise(0)).alias("inv_before"),
                    F.sum(F.when(col("d_date") >= "2001-05-08",
                                 col("inv_quantity_on_hand"))
                          .otherwise(0)).alias("inv_after")))
    ratio = col("inv_after") / col("inv_before")
    return (grouped
            .filter((col("inv_before") > 0)
                    & (ratio >= lit(2.0) / 3.0)
                    & (ratio <= lit(3.0) / 2.0))
            .order_by(col("w_warehouse_name"), col("i_item_id"))
            .limit(100))


QUERIES = {5: q5, 16: q16, 21: q21, 22: q22}
