"""TPCxBB-like table schemas (the subset backing the reference's four
charted queries Q5/Q16/Q21/Q22 — BASELINE.md headline: Q5 19.8x).
Reference counterpart: TpcxbbLikeSpark.scala:49-290 (csv/parquet
converters + table registration)."""
from spark_rapids_tpu.types import (DateType, DoubleType, LongType, Schema,
                                    StringType, StructField as F)

DATE_DIM = Schema([
    F("d_date_sk", LongType), F("d_date", DateType),
    F("d_year", LongType), F("d_moy", LongType)])

ITEM = Schema([
    F("i_item_sk", LongType), F("i_item_id", StringType),
    F("i_item_desc", StringType), F("i_category", StringType),
    F("i_category_id", LongType), F("i_current_price", DoubleType)])

CUSTOMER = Schema([
    F("c_customer_sk", LongType), F("c_current_cdemo_sk", LongType)])

CUSTOMER_DEMOGRAPHICS = Schema([
    F("cd_demo_sk", LongType), F("cd_gender", StringType),
    F("cd_education_status", StringType)])

WEB_CLICKSTREAMS = Schema([
    F("wcs_user_sk", LongType), F("wcs_item_sk", LongType)])

STORE = Schema([
    F("s_store_sk", LongType), F("s_store_id", StringType),
    F("s_store_name", StringType)])

STORE_SALES = Schema([
    F("ss_sold_date_sk", LongType), F("ss_item_sk", LongType),
    F("ss_store_sk", LongType), F("ss_customer_sk", LongType),
    F("ss_ticket_number", LongType), F("ss_quantity", LongType)])

STORE_RETURNS = Schema([
    F("sr_returned_date_sk", LongType), F("sr_item_sk", LongType),
    F("sr_customer_sk", LongType), F("sr_ticket_number", LongType),
    F("sr_return_quantity", LongType)])

WEB_SALES = Schema([
    F("ws_sold_date_sk", LongType), F("ws_item_sk", LongType),
    F("ws_bill_customer_sk", LongType), F("ws_order_number", LongType),
    F("ws_quantity", LongType), F("ws_sales_price", DoubleType),
    F("ws_warehouse_sk", LongType)])

WEB_RETURNS = Schema([
    F("wr_order_number", LongType), F("wr_item_sk", LongType),
    F("wr_refunded_cash", DoubleType)])

WAREHOUSE = Schema([
    F("w_warehouse_sk", LongType), F("w_warehouse_name", StringType),
    F("w_state", StringType)])

INVENTORY = Schema([
    F("inv_date_sk", LongType), F("inv_item_sk", LongType),
    F("inv_warehouse_sk", LongType), F("inv_quantity_on_hand", LongType)])

SCHEMAS = {
    "date_dim": DATE_DIM, "item": ITEM, "customer": CUSTOMER,
    "customer_demographics": CUSTOMER_DEMOGRAPHICS,
    "web_clickstreams": WEB_CLICKSTREAMS, "store": STORE,
    "store_sales": STORE_SALES, "store_returns": STORE_RETURNS,
    "web_sales": WEB_SALES, "web_returns": WEB_RETURNS,
    "warehouse": WAREHOUSE, "inventory": INVENTORY,
}
