#!/bin/sh
# Build the native host runtime (called automatically from
# spark_rapids_tpu/native.py on first import; safe to run by hand).
set -e
cd "$(dirname "$0")"
g++ -O3 -std=c++17 -shared -fPIC -pthread \
    -o libtpu_host_runtime.so src/host_runtime.cpp
echo "built $(pwd)/libtpu_host_runtime.so"
