// Native host runtime for spark_rapids_tpu.
//
// The reference delegates its performance-critical host paths to native
// libraries (RMM's C++ allocator, libcudf's host scaffolding, UCX).  The
// TPU build keeps the same split: JAX/XLA owns device compute, and this
// C++ library owns the host runtime hot paths, exposed over a plain C ABI
// consumed via ctypes (no pybind11 in the image):
//
//   * best-fit address-space sub-allocator (AddressSpaceAllocator.scala
//     equivalent) for bounce-buffer pools
//   * spill file I/O: O_DIRECT-friendly whole-buffer pwrite/pread with
//     full-write loops (RapidsDiskStore equivalent)
//   * multi-threaded gather/scatter memcpy for host columnar compaction
//     (the serialize path of shuffle spill: contiguous per-partition
//     reassembly)
//   * murmur3-32 (Spark variant) batch hashing for host-side fallbacks
//
// Build: g++ -O3 -shared -fPIC (see native/build.sh).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// Best-fit address-space allocator
// ---------------------------------------------------------------------------

struct AsAllocator {
  std::mutex mu;
  std::map<int64_t, int64_t> free_blocks;  // start -> len (coalesced)
  std::map<int64_t, int64_t> allocated;    // start -> len
  int64_t size;
};

void* asalloc_create(int64_t size) {
  auto* a = new AsAllocator();
  a->size = size;
  a->free_blocks[0] = size;
  return a;
}

void asalloc_destroy(void* h) { delete static_cast<AsAllocator*>(h); }

int64_t asalloc_allocate(void* h, int64_t length) {
  auto* a = static_cast<AsAllocator*>(h);
  if (length <= 0) return -1;
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t best = -1, best_len = 0;
  for (auto& kv : a->free_blocks) {
    if (kv.second >= length && (best < 0 || kv.second < best_len)) {
      best = kv.first;
      best_len = kv.second;
    }
  }
  if (best < 0) return -1;
  a->free_blocks.erase(best);
  if (best_len > length) a->free_blocks[best + length] = best_len - length;
  a->allocated[best] = length;
  return best;
}

int64_t asalloc_free(void* h, int64_t address) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->allocated.find(address);
  if (it == a->allocated.end()) return -1;
  int64_t start = address, len = it->second, freed = len;
  a->allocated.erase(it);
  auto next = a->free_blocks.find(start + len);
  if (next != a->free_blocks.end()) {
    len += next->second;
    a->free_blocks.erase(next);
  }
  auto prev = a->free_blocks.lower_bound(start);
  if (prev != a->free_blocks.begin()) {
    --prev;
    if (prev->first + prev->second == start) {
      start = prev->first;
      len += prev->second;
      a->free_blocks.erase(prev);
    }
  }
  a->free_blocks[start] = len;
  return freed;
}

int64_t asalloc_allocated_bytes(void* h) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t total = 0;
  for (auto& kv : a->allocated) total += kv.second;
  return total;
}

int64_t asalloc_largest_free(void* h) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t best = 0;
  for (auto& kv : a->free_blocks) best = std::max(best, kv.second);
  return best;
}

// ---------------------------------------------------------------------------
// Spill file I/O (RapidsDiskStore equivalent)
// ---------------------------------------------------------------------------

// Write the full buffer to `path`; returns bytes written or -errno.
int64_t spill_write(const char* path, const uint8_t* data, int64_t nbytes) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  int64_t off = 0;
  while (off < nbytes) {
    ssize_t w = ::pwrite(fd, data + off, nbytes - off, off);
    if (w <= 0) {
      ::close(fd);
      return -2;
    }
    off += w;
  }
  ::close(fd);
  return off;
}

// Read exactly nbytes from `path` at `offset` into data.
int64_t spill_read(const char* path, uint8_t* data, int64_t nbytes,
                   int64_t offset) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  int64_t off = 0;
  while (off < nbytes) {
    ssize_t r = ::pread(fd, data + off, nbytes - off, offset + off);
    if (r <= 0) {
      ::close(fd);
      return -2;
    }
    off += r;
  }
  ::close(fd);
  return off;
}

// ---------------------------------------------------------------------------
// Multi-threaded row gather (host columnar compaction)
// ---------------------------------------------------------------------------

// out[i, :] = src[idx[i], :] for fixed-width rows of `row_bytes` each.
void gather_rows(const uint8_t* src, uint8_t* out, const int32_t* idx,
                 int64_t n_out, int64_t row_bytes, int32_t n_threads) {
  if (n_threads <= 1 || n_out < 4096) {
    for (int64_t i = 0; i < n_out; ++i)
      std::memcpy(out + i * row_bytes, src + (int64_t)idx[i] * row_bytes,
                  row_bytes);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n_out + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n_out, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(out + i * row_bytes, src + (int64_t)idx[i] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& w : workers) w.join();
}

// ---------------------------------------------------------------------------
// Spark murmur3-32 over int64 values (host-side hash partition fallback)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k(uint32_t k) {
  k *= 0xcc9e2d51u;
  k = rotl32(k, 15);
  return k * 0x1b873593u;
}

static inline uint32_t mix_h(uint32_t h, uint32_t k) {
  h ^= mix_k(k);
  h = rotl32(h, 13);
  return h * 5u + 0xe6546b64u;
}

static inline uint32_t fmix(uint32_t h, uint32_t len) {
  h ^= len;
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// Spark hashLong per element (low word then high word), seed 42 chainable.
void murmur3_long_batch(const int64_t* vals, const uint8_t* valid,
                        int32_t* out, int64_t n, int32_t seed) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = seed;
      continue;
    }
    uint64_t u = (uint64_t)vals[i];
    uint32_t h = (uint32_t)seed;
    h = mix_h(h, (uint32_t)(u & 0xffffffffu));
    h = mix_h(h, (uint32_t)(u >> 32));
    out[i] = (int32_t)fmix(h, 8);
  }
}

// Quote-aware CSV tokenizer (RFC-4180 subset: double-quote quoting with
// "" escapes; LF row terminators).  The numpy delimiter scan in
// io/csv_device.py cannot see quoting state — this single native pass
// can, which is what extends the device CSV decode path to quoted files.
//
// Per field i (< cap_fields): starts[i]/lens[i] describe the value bytes.
// Unquoted fields point at the raw span; quoted fields point INSIDE the
// quotes.  flags[i] low bits: 0 = unquoted, 1 = quoted clean, 2 = quoted
// with doubled-quote escapes still embedded (the caller rewrites those
// few); bit 2 (value 4) marks the LAST field of a row.  CRLF row
// endings are accepted in unquoted context (the CR is excluded from the
// field); returns the field count, or -1 on malformed quoting / field
// overflow / bare CR (caller falls back to the host reader).
int64_t csv_tokenize(const uint8_t* data, int64_t n, uint8_t sep,
                     int64_t* starts, int64_t* lens, uint8_t* flags,
                     int64_t cap_fields) {
  int64_t nf = 0;
  int64_t i = 0;
  while (i < n) {
    if (nf >= cap_fields) return -1;
    uint8_t flag;
    if (data[i] == '"') {  // quoted field
      int64_t start = ++i;
      flag = 1;
      for (;;) {
        if (i >= n) return -1;           // unterminated quote
        if (data[i] == '"') {
          if (i + 1 < n && data[i + 1] == '"') {  // escaped quote
            flag = 2;
            i += 2;
            continue;
          }
          break;
        }
        ++i;
      }
      starts[nf] = start;
      lens[nf] = i - start;
      ++i;  // past closing quote
      if (i + 1 < n && data[i] == '\r' && data[i + 1] == '\n') ++i;  // CRLF
      if (i < n && data[i] != sep && data[i] != '\n') return -1;
    } else {  // unquoted field: runs to sep/newline (CRLF = newline)
      int64_t start = i;
      flag = 0;
      while (i < n && data[i] != sep && data[i] != '\n') {
        if (data[i] == '\r') {
          if (i + 1 < n && data[i + 1] == '\n') break;  // CRLF row end
          return -1;  // bare CR (old-Mac line ending): out of scope
        }
        if (data[i] == '"') return -1;
        ++i;
      }
      starts[nf] = start;
      lens[nf] = i - start;
      if (i < n && data[i] == '\r') ++i;  // settle on the NL
    }
    if (i >= n || data[i] == '\n') flag |= 4;  // last field of its row
    flags[nf] = flag;
    ++nf;
    if (i < n) ++i;  // past sep or newline
  }
  return nf;
}

// Parquet hybrid RLE / bit-packed decode (dictionary indices, def levels):
// [varint header][run payload]... -> int32 values.  This is the per-page
// control plane the reference hands to libcudf's gpuDecodePages; here it
// is host work feeding the device dictionary gather, and the python walk
// of the same structure was the q6_scan profile's #1 cost (1.6s of 4.8s
// over ~2200 pages).  `buf` starts AFTER the leading bit-width byte.
// Returns bytes consumed, or -1 on malformed input (caller falls back).
int64_t pq_rle_decode(const uint8_t* buf, int64_t len, int32_t bw,
                      int64_t n_values, int32_t* out) {
  if (bw <= 0 || bw > 24) return -1;
  const uint32_t mask = (1u << bw) - 1u;
  const int vw = (bw + 7) / 8;
  int64_t pos = 0, got = 0;
  while (got < n_values) {
    uint64_t header = 0;
    int shift = 0;
    for (;;) {
      if (pos >= len || shift > 56) return -1;
      uint8_t b = buf[pos++];
      header |= (uint64_t)(b & 0x7Fu) << shift;
      if (!(b & 0x80u)) break;
      shift += 7;
    }
    if (header & 1) {  // bit-packed groups of 8 values
      int64_t count = (int64_t)(header >> 1) * 8;
      int64_t blen = (int64_t)(header >> 1) * bw;
      if (count == 0 || pos + blen > len) return -1;
      int64_t take = std::min(count, n_values - got);
      // values whose 4-byte window is fully in-bounds go through the
      // fast unaligned-load path; the tail few go byte by byte
      int64_t fast = take;
      while (fast > 0 &&
             pos + (((fast - 1) * (int64_t)bw) >> 3) + 4 > len)
        --fast;
      for (int64_t i = 0; i < fast; ++i) {
        int64_t bitpos = i * (int64_t)bw;
        uint32_t w;
        std::memcpy(&w, buf + pos + (bitpos >> 3), 4);
        out[got + i] = (int32_t)((w >> (bitpos & 7)) & mask);
      }
      for (int64_t i = fast; i < take; ++i) {
        int64_t bitpos = i * (int64_t)bw;
        int64_t b0 = pos + (bitpos >> 3);
        uint32_t w = 0;
        for (int k = 0; k < 4 && b0 + k < len; ++k)
          w |= (uint32_t)buf[b0 + k] << (8 * k);
        out[got + i] = (int32_t)((w >> (bitpos & 7)) & mask);
      }
      pos += blen;
      got += take;
    } else {  // RLE run: vw-byte little-endian value repeated `count`
      int64_t count = (int64_t)(header >> 1);
      if (count == 0 || pos + vw > len) return -1;
      uint32_t value = 0;
      for (int k = 0; k < vw; ++k) value |= (uint32_t)buf[pos + k] << (8 * k);
      pos += vw;
      int64_t take = std::min(count, n_values - got);
      std::fill(out + got, out + got + take, (int32_t)(value & mask));
      got += take;
    }
  }
  return pos;
}

// ---------------------------------------------------------------------------
// Parquet page-header walk (thrift compact protocol, just enough for
// PageHeader).  One native call parses EVERY page header in a column
// chunk — the per-page python thrift walk was ~0.2s of a 1.1s q6 scan.
// ---------------------------------------------------------------------------

struct TR {
  const uint8_t* b;
  int64_t len, pos;
  bool err;
};

static inline uint8_t tr_byte(TR& t) {
  if (t.pos >= t.len) {
    t.err = true;
    return 0;
  }
  return t.b[t.pos++];
}

static uint64_t tr_varint(TR& t) {
  uint64_t out = 0;
  int sh = 0;
  for (;;) {
    uint8_t c = tr_byte(t);
    if (t.err) return 0;
    out |= (uint64_t)(c & 0x7Fu) << sh;
    if (!(c & 0x80u)) return out;
    sh += 7;
    if (sh > 63) {
      t.err = true;
      return 0;
    }
  }
}

static int64_t tr_zigzag(TR& t) {
  uint64_t v = tr_varint(t);
  return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

static void tr_skip_struct(TR& t);

static void tr_skip(TR& t, int ft) {
  switch (ft) {
    case 1:
    case 2:
      break;  // bool encoded in the type nibble
    case 3:
    case 4:
    case 5:
    case 6:
      tr_zigzag(t);
      break;
    case 7:
      t.pos += 8;
      break;
    case 8: {
      uint64_t n = tr_varint(t);
      if (n > (uint64_t)t.len) {  // unvalidated add could wrap pos
        t.err = true;             // negative and defeat bounds checks
        break;
      }
      t.pos += (int64_t)n;
      break;
    }
    case 9:
    case 10: {
      uint8_t h = tr_byte(t);
      if (t.err) return;
      int64_t n = h >> 4;
      int et = h & 0xF;
      if (n == 15) n = (int64_t)tr_varint(t);
      for (int64_t i = 0; i < n && !t.err; ++i) tr_skip(t, et);
      break;
    }
    case 12:
      tr_skip_struct(t);
      break;
    default:
      t.err = true;
  }
  if (t.pos > t.len) t.err = true;
}

static void tr_skip_struct(TR& t) {
  int16_t fid = 0;
  for (;;) {
    uint8_t head = tr_byte(t);
    if (t.err || !head) return;
    int delta = head >> 4, ft = head & 0xF;
    fid = delta ? (int16_t)(fid + delta) : (int16_t)tr_zigzag(t);
    tr_skip(t, ft);
    if (t.err) return;
  }
}

struct PageRec {
  int32_t type = -1, comp = -1, uncomp = -1, n_vals = -1, enc = -1,
          dl_enc = -1, dl_len = -1, rl_len = 0, comp_flag = 1, dict_n = -1;
};

// DataPageHeader (v2=false) / DataPageHeaderV2 (v2=true)
static void parse_dph(TR& t, PageRec& p, bool v2) {
  int16_t fid = 0;
  for (;;) {
    uint8_t head = tr_byte(t);
    if (t.err || !head) return;
    int delta = head >> 4, ft = head & 0xF;
    fid = delta ? (int16_t)(fid + delta) : (int16_t)tr_zigzag(t);
    bool i32 = ft >= 3 && ft <= 6;
    if (!v2) {
      if (fid == 1 && i32) p.n_vals = (int32_t)tr_zigzag(t);
      else if (fid == 2 && i32) p.enc = (int32_t)tr_zigzag(t);
      else if (fid == 3 && i32) p.dl_enc = (int32_t)tr_zigzag(t);
      else tr_skip(t, ft);
    } else {
      if (fid == 1 && i32) p.n_vals = (int32_t)tr_zigzag(t);
      else if (fid == 4 && i32) p.enc = (int32_t)tr_zigzag(t);
      else if (fid == 5 && i32) p.dl_len = (int32_t)tr_zigzag(t);
      else if (fid == 6 && i32) p.rl_len = (int32_t)tr_zigzag(t);
      else if (fid == 7 && (ft == 1 || ft == 2)) p.comp_flag = (ft == 1);
      else tr_skip(t, ft);
    }
    if (t.err) return;
  }
}

// Walk page headers until `target_values` data values are covered (or the
// buffer ends).  Per page i: ptype/data_off (payload start)/comp_size/
// uncomp_size/n_vals/enc/dl_enc (v1)/dl_len+rl_len+comp_flag (v2, dl_len
// is -1 for v1)/dict_n (dictionary pages).  Returns the page count, -2
// when cap_pages is too small (caller grows and retries), -1 on any
// parse error (caller falls back to the python walk).
int64_t pq_page_walk(const uint8_t* buf, int64_t len, int64_t target_values,
                     int64_t cap_pages, int32_t* ptype, int64_t* data_off,
                     int32_t* comp_size, int32_t* uncomp_size,
                     int32_t* n_vals, int32_t* enc, int32_t* dl_enc,
                     int32_t* dl_len, int32_t* rl_len, int32_t* comp_flag,
                     int32_t* dict_n) {
  TR t{buf, len, 0, false};
  int64_t np = 0, rows = 0;
  while (rows < target_values && t.pos < len) {
    if (np >= cap_pages) return -2;
    PageRec p;
    int16_t fid = 0;
    for (;;) {
      uint8_t head = tr_byte(t);
      if (t.err) return -1;
      if (!head) break;
      int delta = head >> 4, ft = head & 0xF;
      fid = delta ? (int16_t)(fid + delta) : (int16_t)tr_zigzag(t);
      bool i32 = ft >= 3 && ft <= 6;
      if (fid == 1 && i32) p.type = (int32_t)tr_zigzag(t);
      else if (fid == 2 && i32) p.uncomp = (int32_t)tr_zigzag(t);
      else if (fid == 3 && i32) p.comp = (int32_t)tr_zigzag(t);
      else if (fid == 5 && ft == 12) parse_dph(t, p, false);
      else if (fid == 8 && ft == 12) parse_dph(t, p, true);
      else if (fid == 7 && ft == 12) {  // DictionaryPageHeader
        int16_t f2 = 0;
        for (;;) {
          uint8_t h2 = tr_byte(t);
          if (t.err || !h2) break;
          int d2 = h2 >> 4, ft2 = h2 & 0xF;
          f2 = d2 ? (int16_t)(f2 + d2) : (int16_t)tr_zigzag(t);
          if (f2 == 1 && ft2 >= 3 && ft2 <= 6)
            p.dict_n = (int32_t)tr_zigzag(t);
          else
            tr_skip(t, ft2);
          if (t.err) break;
        }
      } else {
        tr_skip(t, ft);
      }
      if (t.err) return -1;
    }
    if (p.comp < 0 || p.type < 0) return -1;
    ptype[np] = p.type;
    data_off[np] = t.pos;
    comp_size[np] = p.comp;
    uncomp_size[np] = p.uncomp;
    n_vals[np] = p.n_vals;
    enc[np] = p.enc;
    dl_enc[np] = p.dl_enc;
    dl_len[np] = p.dl_len;
    rl_len[np] = p.rl_len;
    comp_flag[np] = p.comp_flag;
    dict_n[np] = p.dict_n;
    t.pos += p.comp;
    if (t.pos > len) return -1;
    if (p.type == 0 || p.type == 3) {  // data page v1/v2
      if (p.n_vals < 0) return -1;
      rows += p.n_vals;
    }
    ++np;
  }
  return np;
}

// Definition levels -> validity bytes in one call: decode the hybrid
// stream, write valid_out[i] = (level == max_def), return the non-null
// count (or -1: caller falls back).  Replaces a python decode + eq +
// sum triple per page.
int64_t pq_def_levels(const uint8_t* buf, int64_t len, int32_t bw,
                      int64_t n_values, int32_t max_def,
                      uint8_t* valid_out) {
  std::vector<int32_t> tmp((size_t)n_values);
  if (pq_rle_decode(buf, len, bw, n_values, tmp.data()) < 0) return -1;
  int64_t nn = 0;
  for (int64_t i = 0; i < n_values; ++i) {
    uint8_t v = tmp[i] == max_def;
    valid_out[i] = v;
    nn += v;
  }
  return nn;
}

// ---------------------------------------------------------------------------
// ORC RLEv2 decode (all four sub-encodings) — the ORC twin of
// pq_rle_decode: the python run walk was the top cost of the ORC scan
// (0.2s of a 0.65s q6-shaped scan at 2M rows).
// ---------------------------------------------------------------------------

static const int kW5[32] = {1,  2,  3,  4,  5,  6,  7,  8,  9,  10, 11,
                            12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22,
                            23, 24, 26, 28, 30, 32, 40, 48, 56, 64};

static inline int64_t orc_zz(uint64_t u) {
  return (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
}

// big-endian bit-packed read: `w` bits starting at absolute bit `bitpos`
static inline uint64_t orc_rd_bits(const uint8_t* b, int64_t len,
                                   int64_t bitpos, int w) {
  if (w <= 0) return 0;
  int64_t byte0 = bitpos >> 3;
  int off = (int)(bitpos & 7);
  int need = (off + w + 7) / 8;  // <= 9 bytes for w <= 64
  unsigned __int128 win = 0;
  for (int k = 0; k < need; ++k) {
    uint8_t byte = (byte0 + k < len) ? b[byte0 + k] : 0;
    win = (win << 8) | byte;
  }
  int shift = need * 8 - off - w;
  unsigned __int128 mask =
      (w >= 64) ? (unsigned __int128)(~(uint64_t)0)
                : ((unsigned __int128)1 << w) - 1;
  return (uint64_t)((win >> shift) & mask);
}

static inline int orc_varint(const uint8_t* b, int64_t len, int64_t* pos,
                             uint64_t* out) {
  uint64_t v = 0;
  int sh = 0;
  for (;;) {
    if (*pos >= len || sh > 63) return -1;
    uint8_t c = b[(*pos)++];
    v |= (uint64_t)(c & 0x7Fu) << sh;
    if (!(c & 0x80u)) break;
    sh += 7;
  }
  *out = v;
  return 0;
}

// RLEv2 stream -> int64[n_values].  `is_signed` selects zigzag for
// SHORT_REPEAT/DIRECT (value streams) vs raw unsigned (LENGTH /
// dictionary-index streams); DELTA's first delta stays zigzag either
// way, PATCHED_BASE payloads are raw + sign-magnitude base (patch high
// bits fold additively above the packed width).  Returns consumed bytes
// or -1 on malformed input (caller falls back to the python walk).
int64_t orc_rlev2_decode(const uint8_t* body, int64_t len,
                         int64_t n_values, int32_t is_signed,
                         int64_t* out) {
  int64_t pos = 0, o = 0;
  while (o < n_values && pos < len) {
    uint8_t h = body[pos];
    int enc = h >> 6;
    if (enc == 0) {  // SHORT_REPEAT
      int w = ((h >> 3) & 7) + 1;
      int rep = (h & 7) + 3;
      if (pos + 1 + w > len) return -1;
      uint64_t v = 0;
      for (int k = 0; k < w; ++k) v = (v << 8) | body[pos + 1 + k];
      int64_t val = is_signed ? orc_zz(v) : (int64_t)v;
      for (int r = 0; r < rep && o + r < n_values; ++r) out[o + r] = val;
      pos += 1 + w;
      o += rep;
    } else if (enc == 1) {  // DIRECT: bit-packed (zigzag when signed)
      int width = kW5[(h >> 1) & 31];
      if (pos + 1 >= len) return -1;
      int ln = (((h & 1) << 8) | body[pos + 1]) + 1;
      pos += 2;
      for (int i = 0; i < ln && o + i < n_values; ++i) {
        uint64_t u = orc_rd_bits(body, len, pos * 8 + (int64_t)i * width,
                                 width);
        out[o + i] = is_signed ? orc_zz(u) : (int64_t)u;
      }
      pos += ((int64_t)ln * width + 7) / 8;
      o += ln;
    } else if (enc == 3) {  // DELTA
      int w5 = (h >> 1) & 31;
      int width = (w5 == 0) ? 0 : kW5[w5];
      if (pos + 1 >= len) return -1;
      int ln = (((h & 1) << 8) | body[pos + 1]) + 1;
      pos += 2;
      uint64_t bu, du;
      if (orc_varint(body, len, &pos, &bu)) return -1;
      int64_t base = is_signed ? orc_zz(bu) : (int64_t)bu;
      if (orc_varint(body, len, &pos, &du)) return -1;
      int64_t delta0 = orc_zz(du);
      if (o < n_values) out[o] = base;
      if (ln > 1 && o + 1 < n_values) out[o + 1] = base + delta0;
      if (ln > 2) {
        int64_t sign = delta0 >= 0 ? 1 : -1;
        int64_t run = base + delta0;
        if (width == 0) {
          int64_t d = delta0 >= 0 ? delta0 : -delta0;
          for (int i = 2; i < ln && o + i < n_values; ++i) {
            run += sign * d;
            out[o + i] = run;
          }
        } else {
          for (int i = 2; i < ln; ++i) {
            uint64_t d = orc_rd_bits(
                body, len, pos * 8 + (int64_t)(i - 2) * width, width);
            run += sign * (int64_t)d;
            if (o + i < n_values) out[o + i] = run;
          }
          pos += ((int64_t)(ln - 2) * width + 7) / 8;
        }
      }
      o += ln;
    } else {  // PATCHED_BASE
      int width = kW5[(h >> 1) & 31];
      if (pos + 3 >= len) return -1;
      int ln = (((h & 1) << 8) | body[pos + 1]) + 1;
      uint8_t b3 = body[pos + 2], b4 = body[pos + 3];
      int bw = ((b3 >> 5) & 7) + 1;
      int pw = kW5[b3 & 31];
      int pgw = ((b4 >> 5) & 7) + 1;
      int pll = b4 & 31;
      pos += 4;
      if (pos + bw > len) return -1;
      uint64_t ub = 0;
      for (int k = 0; k < bw; ++k) ub = (ub << 8) | body[pos + k];
      uint64_t msb = (uint64_t)1 << (bw * 8 - 1);
      int64_t base = (ub & msb) ? -(int64_t)(ub & (msb - 1))
                                : (int64_t)ub;
      int64_t payload_off = pos + bw;
      pos = payload_off + ((int64_t)ln * width + 7) / 8;
      int pwt = 64;
      for (int wi = 0; wi < 32; ++wi)
        if (kW5[wi] >= pgw + pw) {
          pwt = kW5[wi];
          break;
        }
      for (int i = 0; i < ln && o + i < n_values; ++i) {
        uint64_t u = orc_rd_bits(
            body, len, payload_off * 8 + (int64_t)i * width, width);
        out[o + i] = base + (int64_t)u;
      }
      int64_t gap = 0;
      uint64_t pmask = (pw >= 64) ? ~(uint64_t)0
                                  : (((uint64_t)1 << pw) - 1);
      for (int p = 0; p < pll; ++p) {
        uint64_t pe = orc_rd_bits(body, len,
                                  pos * 8 + (int64_t)p * pwt, pwt);
        gap += (int64_t)(pe >> pw);
        uint64_t pval = pe & pmask;
        if (pval && gap < ln && o + gap < n_values)
          out[o + gap] += (int64_t)(pval << width);
      }
      pos += ((int64_t)pll * pwt + 7) / 8;
      o += ln;
    }
  }
  return (o == n_values) ? pos : -1;
}

// Parquet PLAIN BYTE_ARRAY layout scan: [u32-le length][bytes]... -> value
// offsets/lengths.  The walk is inherently sequential (each length
// determines the next offset), which is exactly the scalar control-plane
// work the host keeps while the device gathers the payload bytes
// (io/parquet_device.py).  Returns bytes consumed, or -1 on truncation.
int64_t pq_byte_array_scan(const uint8_t* data, int64_t n, int64_t n_values,
                           int64_t* offsets, int64_t* lens) {
  int64_t pos = 0;
  for (int64_t v = 0; v < n_values; ++v) {
    if (pos + 4 > n) return -1;
    uint32_t ln = (uint32_t)data[pos] | ((uint32_t)data[pos + 1] << 8) |
                  ((uint32_t)data[pos + 2] << 16) |
                  ((uint32_t)data[pos + 3] << 24);
    pos += 4;
    if (pos + (int64_t)ln > n) return -1;
    offsets[v] = pos;
    lens[v] = (int64_t)ln;
    pos += ln;
  }
  return pos;
}

}  // extern "C"
