// Native host runtime for spark_rapids_tpu.
//
// The reference delegates its performance-critical host paths to native
// libraries (RMM's C++ allocator, libcudf's host scaffolding, UCX).  The
// TPU build keeps the same split: JAX/XLA owns device compute, and this
// C++ library owns the host runtime hot paths, exposed over a plain C ABI
// consumed via ctypes (no pybind11 in the image):
//
//   * best-fit address-space sub-allocator (AddressSpaceAllocator.scala
//     equivalent) for bounce-buffer pools
//   * spill file I/O: O_DIRECT-friendly whole-buffer pwrite/pread with
//     full-write loops (RapidsDiskStore equivalent)
//   * multi-threaded gather/scatter memcpy for host columnar compaction
//     (the serialize path of shuffle spill: contiguous per-partition
//     reassembly)
//   * murmur3-32 (Spark variant) batch hashing for host-side fallbacks
//
// Build: g++ -O3 -shared -fPIC (see native/build.sh).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// Best-fit address-space allocator
// ---------------------------------------------------------------------------

struct AsAllocator {
  std::mutex mu;
  std::map<int64_t, int64_t> free_blocks;  // start -> len (coalesced)
  std::map<int64_t, int64_t> allocated;    // start -> len
  int64_t size;
};

void* asalloc_create(int64_t size) {
  auto* a = new AsAllocator();
  a->size = size;
  a->free_blocks[0] = size;
  return a;
}

void asalloc_destroy(void* h) { delete static_cast<AsAllocator*>(h); }

int64_t asalloc_allocate(void* h, int64_t length) {
  auto* a = static_cast<AsAllocator*>(h);
  if (length <= 0) return -1;
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t best = -1, best_len = 0;
  for (auto& kv : a->free_blocks) {
    if (kv.second >= length && (best < 0 || kv.second < best_len)) {
      best = kv.first;
      best_len = kv.second;
    }
  }
  if (best < 0) return -1;
  a->free_blocks.erase(best);
  if (best_len > length) a->free_blocks[best + length] = best_len - length;
  a->allocated[best] = length;
  return best;
}

int64_t asalloc_free(void* h, int64_t address) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->allocated.find(address);
  if (it == a->allocated.end()) return -1;
  int64_t start = address, len = it->second, freed = len;
  a->allocated.erase(it);
  auto next = a->free_blocks.find(start + len);
  if (next != a->free_blocks.end()) {
    len += next->second;
    a->free_blocks.erase(next);
  }
  auto prev = a->free_blocks.lower_bound(start);
  if (prev != a->free_blocks.begin()) {
    --prev;
    if (prev->first + prev->second == start) {
      start = prev->first;
      len += prev->second;
      a->free_blocks.erase(prev);
    }
  }
  a->free_blocks[start] = len;
  return freed;
}

int64_t asalloc_allocated_bytes(void* h) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t total = 0;
  for (auto& kv : a->allocated) total += kv.second;
  return total;
}

int64_t asalloc_largest_free(void* h) {
  auto* a = static_cast<AsAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int64_t best = 0;
  for (auto& kv : a->free_blocks) best = std::max(best, kv.second);
  return best;
}

// ---------------------------------------------------------------------------
// Spill file I/O (RapidsDiskStore equivalent)
// ---------------------------------------------------------------------------

// Write the full buffer to `path`; returns bytes written or -errno.
int64_t spill_write(const char* path, const uint8_t* data, int64_t nbytes) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  int64_t off = 0;
  while (off < nbytes) {
    ssize_t w = ::pwrite(fd, data + off, nbytes - off, off);
    if (w <= 0) {
      ::close(fd);
      return -2;
    }
    off += w;
  }
  ::close(fd);
  return off;
}

// Read exactly nbytes from `path` at `offset` into data.
int64_t spill_read(const char* path, uint8_t* data, int64_t nbytes,
                   int64_t offset) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  int64_t off = 0;
  while (off < nbytes) {
    ssize_t r = ::pread(fd, data + off, nbytes - off, offset + off);
    if (r <= 0) {
      ::close(fd);
      return -2;
    }
    off += r;
  }
  ::close(fd);
  return off;
}

// ---------------------------------------------------------------------------
// Multi-threaded row gather (host columnar compaction)
// ---------------------------------------------------------------------------

// out[i, :] = src[idx[i], :] for fixed-width rows of `row_bytes` each.
void gather_rows(const uint8_t* src, uint8_t* out, const int32_t* idx,
                 int64_t n_out, int64_t row_bytes, int32_t n_threads) {
  if (n_threads <= 1 || n_out < 4096) {
    for (int64_t i = 0; i < n_out; ++i)
      std::memcpy(out + i * row_bytes, src + (int64_t)idx[i] * row_bytes,
                  row_bytes);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n_out + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n_out, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(out + i * row_bytes, src + (int64_t)idx[i] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& w : workers) w.join();
}

// ---------------------------------------------------------------------------
// Spark murmur3-32 over int64 values (host-side hash partition fallback)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mix_k(uint32_t k) {
  k *= 0xcc9e2d51u;
  k = rotl32(k, 15);
  return k * 0x1b873593u;
}

static inline uint32_t mix_h(uint32_t h, uint32_t k) {
  h ^= mix_k(k);
  h = rotl32(h, 13);
  return h * 5u + 0xe6546b64u;
}

static inline uint32_t fmix(uint32_t h, uint32_t len) {
  h ^= len;
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// Spark hashLong per element (low word then high word), seed 42 chainable.
void murmur3_long_batch(const int64_t* vals, const uint8_t* valid,
                        int32_t* out, int64_t n, int32_t seed) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = seed;
      continue;
    }
    uint64_t u = (uint64_t)vals[i];
    uint32_t h = (uint32_t)seed;
    h = mix_h(h, (uint32_t)(u & 0xffffffffu));
    h = mix_h(h, (uint32_t)(u >> 32));
    out[i] = (int32_t)fmix(h, 8);
  }
}

// Quote-aware CSV tokenizer (RFC-4180 subset: double-quote quoting with
// "" escapes; LF row terminators).  The numpy delimiter scan in
// io/csv_device.py cannot see quoting state — this single native pass
// can, which is what extends the device CSV decode path to quoted files.
//
// Per field i (< cap_fields): starts[i]/lens[i] describe the value bytes.
// Unquoted fields point at the raw span; quoted fields point INSIDE the
// quotes.  flags[i] low bits: 0 = unquoted, 1 = quoted clean, 2 = quoted
// with doubled-quote escapes still embedded (the caller rewrites those
// few); bit 2 (value 4) marks the LAST field of a row.  CRLF row
// endings are accepted in unquoted context (the CR is excluded from the
// field); returns the field count, or -1 on malformed quoting / field
// overflow / bare CR (caller falls back to the host reader).
int64_t csv_tokenize(const uint8_t* data, int64_t n, uint8_t sep,
                     int64_t* starts, int64_t* lens, uint8_t* flags,
                     int64_t cap_fields) {
  int64_t nf = 0;
  int64_t i = 0;
  while (i < n) {
    if (nf >= cap_fields) return -1;
    uint8_t flag;
    if (data[i] == '"') {  // quoted field
      int64_t start = ++i;
      flag = 1;
      for (;;) {
        if (i >= n) return -1;           // unterminated quote
        if (data[i] == '"') {
          if (i + 1 < n && data[i + 1] == '"') {  // escaped quote
            flag = 2;
            i += 2;
            continue;
          }
          break;
        }
        ++i;
      }
      starts[nf] = start;
      lens[nf] = i - start;
      ++i;  // past closing quote
      if (i + 1 < n && data[i] == '\r' && data[i + 1] == '\n') ++i;  // CRLF
      if (i < n && data[i] != sep && data[i] != '\n') return -1;
    } else {  // unquoted field: runs to sep/newline (CRLF = newline)
      int64_t start = i;
      flag = 0;
      while (i < n && data[i] != sep && data[i] != '\n') {
        if (data[i] == '\r') {
          if (i + 1 < n && data[i + 1] == '\n') break;  // CRLF row end
          return -1;  // bare CR (old-Mac line ending): out of scope
        }
        if (data[i] == '"') return -1;
        ++i;
      }
      starts[nf] = start;
      lens[nf] = i - start;
      if (i < n && data[i] == '\r') ++i;  // settle on the NL
    }
    if (i >= n || data[i] == '\n') flag |= 4;  // last field of its row
    flags[nf] = flag;
    ++nf;
    if (i < n) ++i;  // past sep or newline
  }
  return nf;
}

// Parquet PLAIN BYTE_ARRAY layout scan: [u32-le length][bytes]... -> value
// offsets/lengths.  The walk is inherently sequential (each length
// determines the next offset), which is exactly the scalar control-plane
// work the host keeps while the device gathers the payload bytes
// (io/parquet_device.py).  Returns bytes consumed, or -1 on truncation.
int64_t pq_byte_array_scan(const uint8_t* data, int64_t n, int64_t n_values,
                           int64_t* offsets, int64_t* lens) {
  int64_t pos = 0;
  for (int64_t v = 0; v < n_values; ++v) {
    if (pos + 4 > n) return -1;
    uint32_t ln = (uint32_t)data[pos] | ((uint32_t)data[pos + 1] << 8) |
                  ((uint32_t)data[pos + 2] << 16) |
                  ((uint32_t)data[pos + 3] << 24);
    pos += 4;
    if (pos + (int64_t)ln > n) return -1;
    offsets[v] = pos;
    lens[v] = (int64_t)ln;
    pos += ln;
  }
  return pos;
}

}  // extern "C"
