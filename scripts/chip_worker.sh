#!/bin/bash
# Chip worker: whenever the machine-wide TPU lease grants a window, spend
# it on the round's full on-chip evidence list, in priority order:
#   1. bench.py          -> BENCH_ONCHIP.json (5 core + 4 SF1 queries)
#   2. pallas_micro.py   -> BENCH_PALLAS.json (settle pallas.enabled)
#   3. profile_device.py -> PROFILE_ONCHIP.json (roofline-gap profile)
#   4. pressure_onchip   -> BENCH_PRESSURE.json (spill cascade on chip)
# Each stage is bounded; a stage that can't get the chip exits cleanly and
# the loop retries.  Stages 2-4 only run after stage 1 has succeeded at
# least once this round (the lease is clearly grantable then).
#
# Usage: nohup bash scripts/chip_worker.sh > /tmp/chip_worker.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
MAX_ITERS=${MAX_ITERS:-12}
export CAPTURE_START=${CAPTURE_START:-$(date +%s)}

fresh() {  # fresh() FILE -> 0 when the artifact is from this round
  python - "$1" <<'EOF'
import json, os, sys
try:
    d = json.load(open(sys.argv[1]))
    start = int(os.environ.get("CAPTURE_START", 0))
    ok = int(d.get("recorded_unix", 0)) >= start and (
        d.get("platform") is None or "tpu" in str(d.get("platform", "")))
    sys.exit(0 if ok and d.get("platform") else 1)
except Exception:
    sys.exit(1)
EOF
}

bench_fresh() {
  python - <<'EOF'
import json, os, sys
try:
    start = int(os.environ.get("CAPTURE_START", 0))
    pq = json.load(open("BENCH_ONCHIP.json"))["extra"]["per_query"]
    want = ["q1", "q6", "q6_scan", "tpcds_q5", "tpcxbb_q5"]
    fresh = [q for q in want
             if pq.get(q, {}).get("dev_s") is not None
             and int(pq.get(q, {}).get("recorded_unix", 0)) >= start]
    print(len(fresh), flush=True)
    sys.exit(0 if len(fresh) == len(want) else 1)
except Exception:
    print(0, flush=True)
    sys.exit(1)
EOF
}

for i in $(seq 1 "$MAX_ITERS"); do
  echo "=== chip worker iteration $i $(date -u +%H:%M:%S) ==="
  if n=$(bench_fresh); then
    echo "bench suite complete on chip ($n/5 fresh)"
  else
    echo "bench incomplete ($n/5 fresh); running bench.py"
    BENCH_GLOBAL_S=${BENCH_GLOBAL_S:-2800} BENCH_TPU_PROBE_S=${BENCH_TPU_PROBE_S:-2000} \
      BENCH_ORACLE_CACHE=1 BENCH_SF1=1 timeout -k 5 3300 python bench.py
    echo "--- bench rc=$? ---"
    if ! n=$(bench_fresh); then
      echo "still incomplete ($n/5); retrying next iteration"
      sleep 30
      continue
    fi
  fi
  # lease is grantable: spend the window on the remaining evidence
  if ! fresh BENCH_PALLAS.json; then
    echo "running pallas_micro"
    timeout -k 5 1200 python benchmarks/pallas_micro.py
    echo "--- pallas rc=$? ---"
  fi
  if ! fresh PROFILE_ONCHIP.json; then
    echo "running profile_device"
    timeout -k 5 1200 python benchmarks/profile_device.py
    echo "--- profile rc=$? ---"
  fi
  if ! fresh BENCH_PRESSURE.json; then
    echo "running pressure_onchip"
    timeout -k 5 1800 python scripts/pressure_onchip.py
    echo "--- pressure rc=$? ---"
  fi
  if fresh BENCH_PALLAS.json && fresh PROFILE_ONCHIP.json \
      && fresh BENCH_PRESSURE.json; then
    echo "all on-chip evidence captured; exiting"
    exit 0
  fi
  sleep 30
done
echo "chip worker exhausted $MAX_ITERS iterations"
