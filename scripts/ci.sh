#!/usr/bin/env bash
# CI entry point: lint + fast test tier (the reference's analogue is the
# maven multi-module verify + jenkins pipelines, SURVEY.md §2.11).
# Usage: scripts/ci.sh [--slow]   (--slow adds the SF0.05 TPC-H tier)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint (pyflakes-level) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check spark_rapids_tpu tests benchmarks bench.py __graft_entry__.py
else
    python -m pyflakes spark_rapids_tpu tests benchmarks bench.py \
        __graft_entry__.py 2>/dev/null || \
    python -m flake8 --select=E9,F spark_rapids_tpu tests benchmarks \
        bench.py __graft_entry__.py 2>/dev/null || \
    echo "(no ruff/pyflakes/flake8 in image; syntax-checking instead)" && \
    python -m compileall -q spark_rapids_tpu tests benchmarks bench.py \
        __graft_entry__.py
fi

echo "== tpulint (ISSUE 9/12: project contract gate) =="
# Two-phase static analysis over the whole tree — the per-file passes
# (host-sync TPU001, jit purity TPU002, conf hygiene TPU003,
# metric/journal contracts TPU004, retry-site sweep TPU005, exception
# hygiene TPU006, lock order TPU007, use-after-donate TPU008, pallas
# kernel contracts TPU010) plus the cross-module project-model passes
# (serving concurrency audit TPU009, metric/journal flow coverage
# TPU011).  Runs BEFORE the test tiers so a contract break fails in
# seconds, not after a 30-minute compile-bound suite.  docs/lint.md
# documents every rule, `--explain TPUxxx` prints one rule's reference.
#
# COLD-RUN BUDGET: the full analysis from an empty cache must stay
# under 60s on the CI host — the analysis tier must never become the
# slowest gate.  The second (warm) run exercises the incremental cache
# (.tpulint-cache/, content-hash keyed; --stats prints cold vs warm).
T_LINT=$SECONDS
rm -rf .tpulint-cache
T_COLD=$SECONDS
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.lint --stats
DT_COLD=$((SECONDS - T_COLD))
if [ "$DT_COLD" -ge 60 ]; then
    echo "tpulint cold run took ${DT_COLD}s (budget: <60s) — the"
    echo "analysis tier may not become the slowest gate; profile the"
    echo "passes or tighten the project-model extraction"
    exit 1
fi
# warm run: only changed files re-analyze (here: none)
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.lint --stats
# generated docs must match their registries (the TPU003 doc half)
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.lint --check-docs
# fixture tests: every pass proves a true positive + clean negative,
# suppressions and the baseline silence what they claim to
python -m pytest tests/test_lint.py -q -m "not slow" -p no:cacheprovider
echo "== tpulint tier took $((SECONDS - T_LINT))s =="

echo "== metric-name lint (back-compat alias) =="
# every metrics.add/add_lazy/timer call site must use a name registered in
# spark_rapids_tpu/metrics/names.py (catches typo'd keys like numOutputRow);
# delegates to tpulint TPU004 — kept as the documented entry point
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.metrics --lint

echo "== observability tier =="
T_OBS=$SECONDS
python -m pytest tests/test_metrics.py tests/test_observability_e2e.py \
    tests/test_telemetry.py -q -m "not slow" -p no:cacheprovider
# post-mortem smoke (ISSUE 17): dump a diagnostics bundle from a live
# session, then the CLI renderer must parse it back completely
PM_DIR=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$PM_DIR" <<'EOF'
import sys
from spark_rapids_tpu.engine import TpuSession
from spark_rapids_tpu.plan.logical import col
s = TpuSession()
assert len(s.from_pydict({"a": [1, 2, 3]}).filter(col("a") > 1)
           .collect()) == 2
print("bundle:", s.dump_diagnostics(out_dir=sys.argv[1] + "/smoke",
                                    reason="ci-smoke"))
EOF
JAX_PLATFORMS=cpu python -m spark_rapids_tpu.metrics postmortem \
    "$PM_DIR/smoke" > /dev/null
rm -rf "$PM_DIR"
# always-on ring+sampler overhead gate: <=2% wall time (or the absolute
# noise floor) on the representative query slice; writes BENCH_OBS.json
JAX_PLATFORMS=cpu python scripts/obs_overhead.py --reps 5
echo "== observability tier took $((SECONDS - T_OBS))s =="

echo "== adaptive tier =="
# adaptive query execution (ISSUE 3): AQE-on must match AQE-off while the
# coalesce/skew/strategy rules demonstrably fire and land in the journal
T_AQE=$SECONDS
python -m pytest tests/test_adaptive.py -q -m "not slow" -p no:cacheprovider
echo "== adaptive tier took $((SECONDS - T_AQE))s =="

echo "== integrity tier =="
# shuffle/spill data integrity (ISSUE 4): injected single-bit corruption
# at every transfer/spill path must be detected, classified
# (writer/wire/reader) and recovered — refetch for transient faults,
# map-fragment recompute for persistent ones.  The in-process suite runs
# fast; the -m integrity sweep adds the multi-process ProcCluster
# corruption-recovery tests (slow-marked, so tier-1 skips them).
T_INT=$SECONDS
python -m pytest tests/test_integrity.py -q -p no:cacheprovider
python -m pytest tests/test_proc_cluster.py -q -m integrity \
    -p no:cacheprovider
echo "== integrity tier took $((SECONDS - T_INT))s =="

echo "== compress tier =="
# shuffle/spill compression (ISSUE 5): framed codec round-trip fuzz,
# bit-for-bit wire/spill integration per codec, negotiation fallback,
# and corruption injection with compression on (flipped compressed
# bytes must fail the frame digest before any decompressor runs)
T_CMP=$SECONDS
python -m pytest tests/test_compress.py -q -p no:cacheprovider
echo "== compress tier took $((SECONDS - T_CMP))s =="

echo "== fusion tier =="
# whole-stage fusion (ISSUE 6): fused == unfused bit-for-bit across the
# dtype surface and around every fusion boundary, the stage-level OOM
# ladder (split-retry -> operator-at-a-time -> per-op CPU fallback),
# AQE-on fused reduce stages, *(N) EXPLAIN rendering, and the >=2x
# compile-count reduction acceptance
T_FUS=$SECONDS
python -m pytest tests/test_fusion.py -q -p no:cacheprovider
echo "== fusion tier took $((SECONDS - T_FUS))s =="

echo "== tracing tier =="
# distributed tracing (ISSUE 7): trace-context wire propagation, journal
# shard merge + wall-clock/probe alignment, critical-path + straggler
# analysis, torn-line-free concurrent journal writes, chrome flow
# events.  The fast subset runs here; -m "tracing and slow" adds the
# 3-executor ProcCluster acceptance (merged timeline from every worker,
# fetch<->serve flow links, injected-straggler flagging, monotonic
# session.progress(), hung-task watchdog).
T_TRC=$SECONDS
python -m pytest tests/test_tracing.py -q -m "not slow" \
    -p no:cacheprovider
echo "== tracing tier took $((SECONDS - T_TRC))s =="

echo "== memledger tier =="
# memory-pressure observability (ISSUE 8): the allocation ledger's
# causal chains (reserve -> oomSpill -> victim buffer ids), watermark
# monotonicity, churn/victim-quality analysis, the --memory CLI offline
# from journal files, and the heartbeat peak roll-up.  -m "memledger and
# slow" adds the 2-worker ProcCluster acceptance (worker-side mem events
# stamped with the driver query, cluster peak_memory over real
# heartbeats).
T_MEM=$SECONDS
python -m pytest tests/test_memledger.py -q -m "not slow" \
    -p no:cacheprovider
echo "== memledger tier took $((SECONDS - T_MEM))s =="

echo "== serve tier =="
# serving tier (ISSUE 10): parameterized plan-cache hits must compile
# nothing new on literal-variant re-submission, concurrent submissions
# (including under OOM injection) must be bit-for-bit identical to
# serial runs, per-query budgets must confine spill causality to the
# over-budget query, and the scheduler's priority/admission/rejection
# discipline + per-query semaphore attribution + journal routing hold
T_SRV=$SECONDS
python -m pytest tests/test_serve.py -q -m "not slow" -p no:cacheprovider
echo "== serve tier took $((SECONDS - T_SRV))s =="

echo "== lifecycle tier =="
# query lifecycle robustness (ISSUE 19): cooperative cancellation
# (queued dequeues free, running stops at the next checkpoint with
# owner-confined cleanup — zero residual owner bytes across all tiers),
# per-query deadlines (typed QueryDeadlineExceeded into the query's own
# failure path, queue-side shedding), SLO-aware preemption (suspended
# victim resumes bit-for-bit across plan shapes), typed QueryTimeout on
# result()/exception() waits, token-routed scheduler shutdown, and the
# kill-switch no-op guarantee.  The fast half runs here; -m "lifecycle
# and slow" adds the >=20-round mixed-priority serving chaos soak
# (random cancels/deadlines/preemptions + injectOom, survivors
# bit-for-bit, zero leaked owner bytes — CHAOS_ROUNDS/CHAOS_SEED
# tunable).
T_LC=$SECONDS
python -m pytest tests/test_lifecycle.py -q -m "not slow" \
    -p no:cacheprovider
echo "== lifecycle tier took $((SECONDS - T_LC))s =="

echo "== streaming tier =="
# streaming micro-batch engine (ISSUE 20): incremental results must be
# BIT-FOR-BIT identical to a full batch re-query at every epoch (across
# agg shapes, rollup, and every dtype as a state key — the epoch-row /
# reader-batch alignment contract), every epoch after the first a
# plan-cache hit with ZERO warm-epoch kernel/stage compiles, injectOom
# forced at the stream.fold/stream.restore reserve sites, kill-and-
# restart checkpoint recovery (partial epoch dirs ignored), and
# stop()/deadline shutdowns leaving zero leaked owner bytes.
T_STRM=$SECONDS
python -m pytest tests/test_streaming.py -q -m "not slow" \
    -p no:cacheprovider
echo "== streaming tier took $((SECONDS - T_STRM))s =="

echo "== roofline tier =="
# roofline-attribution profiler (ISSUE 13): cost-declaration coverage
# (every plan node of the q1/q6 shapes names a bottleneck resource),
# profile-tree invariants (op-row bytes never exceed the stage
# declaration), the prometheus round-trip property (histogram buckets,
# _sum/_count, escaped label values), SLO histogram percentiles,
# scheduler fairness visibility, and the profiler-overhead ceiling
T_ROOF=$SECONDS
python -m pytest tests/test_roofline.py -q -m "not slow" \
    -p no:cacheprovider
echo "== roofline tier took $((SECONDS - T_ROOF))s =="

echo "== chaos tier =="
# fault-recovery chaos (ISSUE 15): injectCrash grammar (site/scope
# ordinals, seed-deterministic p=), injectNetFault per-site addressing,
# the stale-spill-dir bootstrap sweep, attempt-id-guarded map-output
# registration, and per-task retry-budget semantics.  The fast half runs
# here; -m "chaos and slow" adds the 3-worker ProcCluster acceptance
# (mid-task kills bit-for-bit, deadline abandonment + wedged-worker
# eviction, speculation beating an injected straggler, graceful shrink,
# and the seeded >=20-round chaos soak — CHAOS_ROUNDS/CHAOS_SEED env
# knobs keep it deterministic and tunable).
T_CHAOS=$SECONDS
python -m pytest tests/test_chaos.py -q -m "not slow" -p no:cacheprovider
echo "== chaos tier took $((SECONDS - T_CHAOS))s =="

echo "== policy tier =="
# data-movement policy engine (ISSUE 18): policy ON must equal policy
# OFF bit-for-bit across every dtype and under genuine pressure (the
# kill switch is the contract), injected OOMs at every reserve site
# must recover identically with the scorer live, proactive unspill must
# stay inside the owning query's budget, flow-control stalls must stay
# bounded (never a deadlock), and codec re-selection must round-trip
# the PR 5 negotiation
T_POL=$SECONDS
python -m pytest tests/test_policy.py -q -m "not slow" -p no:cacheprovider
echo "== policy tier took $((SECONDS - T_POL))s =="

echo "== mesh exchange tier =="
# mesh-native ICI shuffle (ISSUE 14): the generic exchange lowered into
# jitted shard_map collectives must be bit-for-bit with the socket tier
# across partitioning modes and the dtype surface, produce IDENTICAL
# AQE map statistics, survive injectOom at every collective reserve
# site, and de-lower to the socket tier on exhaustion.  The forced
# host-device count makes the 4-device meshes real even outside the
# conftest (tests force 8 virtual CPU devices themselves; the explicit
# XLA_FLAGS keeps this tier honest if run standalone).
T_MESH=$SECONDS
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
    python -m pytest tests/test_mesh_exchange.py -q -m "not slow" \
    -p no:cacheprovider
echo "== mesh exchange tier took $((SECONDS - T_MESH))s =="

echo "== pallas/donation tier =="
# on-chip kernels + buffer donation (ISSUE 11): interpret-mode pallas
# kernel tests (fused segmented aggregation, tiled bitonic sort, the
# carry-pattern cumsum), the fused-dispatcher parity checks, the
# packed-key argsort vs lexsort permutation equality, and the donation
# parity sweep (donation ON vs OFF bit-for-bit across every dtype,
# retry/checkpoint exclusion, multi-consumer pins)
T_PAL=$SECONDS
python -m pytest tests/test_pallas.py tests/test_donation.py -q \
    -m "not slow" -p no:cacheprovider
echo "== pallas/donation tier took $((SECONDS - T_PAL))s =="

echo "== tests (fast tier) =="
T_TESTS=$SECONDS
MARK="not slow"
if [[ "${1:-}" == "--slow" ]]; then MARK=""; fi
if [[ "${1:-}" == "--parallel" ]]; then
    # file-sharded concurrent pytest: the fast tier is XLA:CPU
    # compile-bound (~30 CPU-minutes), so on a multi-core host N
    # processes cut wall clock ~N-fold.  (The round-5 build image
    # exposes ONE core — os.cpu_count()==1 — so there this mode only
    # interleaves; the ~30min floor is single-core compile time.)
    # Each shard holds ~1/N of the tests, which keeps the per-process
    # compiled-executable count far below the XLA:CPU segfault
    # threshold the conftest cache-clears guard against.
    N="${2:-6}"
    # size-descending order before round-robin: file size tracks test
    # count/cost well enough to spread the heavy suites across shards
    mapfile -t FILES < <(ls -S tests/test_*.py)
    pids=()
    for ((i = 0; i < N; i++)); do
        shard=()
        for ((j = i; j < ${#FILES[@]}; j += N)); do
            shard+=("${FILES[$j]}")
        done
        python -m pytest "${shard[@]}" -q -m "not slow" \
            -p no:cacheprovider > "/tmp/ci_shard_$i.log" 2>&1 &
        pids+=($!)
    done
    rc=0
    for ((i = 0; i < N; i++)); do
        if ! wait "${pids[$i]}"; then
            rc=1
            echo "shard $i FAILED:"
            tail -20 "/tmp/ci_shard_$i.log"
        else
            tail -1 "/tmp/ci_shard_$i.log"
        fi
    done
    [[ $rc -eq 0 ]]
elif [[ -n "$MARK" ]]; then
    python -m pytest tests/ -q -m "$MARK"
else
    python -m pytest tests/ -q
fi
echo "== fast tier took $((SECONDS - T_TESTS))s =="

echo "== profile-regression gate =="
# ISSUE 13: a fresh roofline capture (per-operator achieved-vs-peak
# ledgers for q1/q6 + serving SLO phase p95s + the profiler's own
# overhead) is diffed against the checked-in BASELINE_PROFILE.json at a
# generous (5x) tolerance — catches an operator falling off its fused
# path or a phase exploding, not single-digit noise.  After a
# deliberate perf change: scripts/profile_regression.py --bless
T_PROF=$SECONDS
JAX_PLATFORMS=cpu python scripts/profile_regression.py
echo "== profile-regression gate took $((SECONDS - T_PROF))s =="

echo "== multichip dryrun =="
T_DRY=$SECONDS
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
echo "== dryrun took $((SECONDS - T_DRY))s; total $((SECONDS))s =="
echo "CI OK"
