#!/usr/bin/env python
"""Telemetry-plane overhead gate (ISSUE 17 CI satellite).

Runs the representative streaming query slice twice in CHILD processes —
telemetry plane ON (flight-recorder ring + gauge sampler) vs OFF — and
gates the median wall-time delta at <= --budget-pct (default 2%).  Child
processes because the telemetry singleton is per-process: only a fresh
interpreter measures a true off state.

A relative gate on a sub-second query is noise-dominated, so the gate
passes when EITHER the relative overhead is within budget OR the
absolute delta is under --floor-s (default 80ms): a 3% blip on a 0.4s
query is scheduler jitter, not a regression.  Results land in
BENCH_OBS.json next to the other committed bench artifacts.

Usage: python scripts/obs_overhead.py [--rows N] [--reps K]
       [--budget-pct P] [--floor-s S] [--out BENCH_OBS.json]
       (internal: --child on|off)
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child(mode: str, rows: int, reps: int) -> None:
    """One measured process: warm the compile, then time `reps` runs of
    the query slice; emits one JSON line on stdout."""
    sys.path.insert(0, _REPO)
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.plan.logical import col, functions as F, lit

    conf = {
        "spark.rapids.sql.tpu.telemetry.enabled":
            "true" if mode == "on" else "false",
        # the gate targets ring+sampler; the http listener is one idle
        # accept thread and would only add port-collision flake here
        "spark.rapids.sql.tpu.telemetry.http.enabled": "false",
        # streaming path: per-operator spans make the journal tap hot
        "spark.rapids.sql.tpu.wholeStage.enabled": "false",
        "spark.rapids.sql.tpu.shuffle.partitions": "4",
        "spark.rapids.sql.variableFloatAgg.enabled": "true",
    }
    s = TpuSession(conf)
    if mode == "on":
        from spark_rapids_tpu.metrics.ring import get_telemetry
        assert get_telemetry() is not None, \
            "telemetry=on child has no live plane"
    fact = s.from_pydict({"k": [i % 7 for i in range(rows)],
                          "v": [float(i) for i in range(rows)],
                          "q": [i % 3 for i in range(rows)]})
    dim = s.from_pydict({"k": list(range(7)),
                         "name": [f"g{j}" for j in range(7)]})

    def run():
        df = (fact.join(dim, on="k")
              .filter(col("q") < 2)
              .group_by(col("name"))
              .agg(F.sum(col("v")).alias("sv"),
                   F.count(lit(1)).alias("c"))
              .order_by(col("name")))
        return df.collect()

    assert len(run()) == 7  # warm compile outside the timed region
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    print(json.dumps({"mode": mode, "times": times,
                      "median_s": statistics.median(times)}))


def measure(mode: str, rows: int, reps: int) -> dict:
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         "--rows", str(rows), "--reps", str(reps)],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS":
             os.environ.get("JAX_PLATFORMS", "cpu")})
    if proc.returncode != 0:
        raise RuntimeError(f"child ({mode}) failed:\n{proc.stderr}")
    # last stdout line is the payload (library banners may precede it)
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", choices=["on", "off"])
    ap.add_argument("--rows", type=int, default=60_000)
    ap.add_argument("--reps", type=int, default=7)
    ap.add_argument("--budget-pct", type=float, default=2.0)
    ap.add_argument("--floor-s", type=float, default=0.08)
    ap.add_argument("--out", default=os.path.join(_REPO,
                                                  "BENCH_OBS.json"))
    args = ap.parse_args()
    if args.child:
        child(args.child, args.rows, args.reps)
        return 0

    off = measure("off", args.rows, args.reps)
    on = measure("on", args.rows, args.reps)
    delta_s = on["median_s"] - off["median_s"]
    overhead_pct = 100.0 * delta_s / off["median_s"]
    within_budget = (overhead_pct <= args.budget_pct
                     or delta_s <= args.floor_s)
    result = {
        "bench": "telemetry-overhead",
        "rows": args.rows,
        "reps": args.reps,
        "off_median_s": round(off["median_s"], 5),
        "on_median_s": round(on["median_s"], 5),
        "delta_s": round(delta_s, 5),
        "overhead_pct": round(overhead_pct, 3),
        "budget_pct": args.budget_pct,
        "floor_s": args.floor_s,
        "pass": within_budget,
        "off_times": [round(t, 5) for t in off["times"]],
        "on_times": [round(t, 5) for t in on["times"]],
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"telemetry overhead: off={off['median_s']:.4f}s "
          f"on={on['median_s']:.4f}s delta={delta_s * 1000:.1f}ms "
          f"({overhead_pct:+.2f}%; budget {args.budget_pct}% or "
          f"<{args.floor_s * 1000:.0f}ms) -> "
          f"{'PASS' if within_budget else 'FAIL'}  [{args.out}]")
    if not within_budget:
        print("the always-on ring+sampler exceeded its overhead budget; "
              "profile metrics/ring.py (tap + tick cost) before raising "
              "the budget", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
