#!/bin/bash
# On-chip bench capture loop: run the full bench suite against the real TPU
# whenever the machine-wide lease grants a window.  Each iteration runs
# bench.py with a generous TPU probe budget; bench.py merges any on-chip
# per-query timings into BENCH_ONCHIP.json (partial windows accumulate).
# Stops once all five queries have non-null dev_s, or after MAX_ITERS.
#
# Usage: nohup bash scripts/onchip_capture.sh > /tmp/onchip_capture.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
MAX_ITERS=${MAX_ITERS:-12}
# entries recorded after the loop started count as fresh — bench.py merges
# earlier windows forward (stale flag) with their original recorded_unix,
# so coverage ACCUMULATES across partial lease windows
export CAPTURE_START=${CAPTURE_START:-$(date +%s)}
for i in $(seq 1 "$MAX_ITERS"); do
  echo "=== capture iteration $i $(date -u +%H:%M:%S) ==="
  complete=$(python - <<'EOF'
import json, os
try:
    start = int(os.environ.get("CAPTURE_START", 0))
    pq = json.load(open("BENCH_ONCHIP.json"))["extra"]["per_query"]
    want = ["q1", "q6", "q6_scan", "tpcds_q5", "tpcxbb_q5"]
    fresh = [q for q in want
             if pq.get(q, {}).get("dev_s") is not None
             and int(pq.get(q, {}).get("recorded_unix", 0)) >= start]
    print("yes" if len(fresh) == len(want) else "no", len(fresh))
except Exception:
    print("no", 0)
EOF
)
  echo "onchip completeness: $complete"
  if [[ "$complete" == yes* ]]; then
    echo "all five queries captured on chip; exiting"
    exit 0
  fi
  BENCH_GLOBAL_S=${BENCH_GLOBAL_S:-2800} BENCH_TPU_PROBE_S=${BENCH_TPU_PROBE_S:-2000} \
    BENCH_ORACLE_CACHE=1 BENCH_SF1=1 timeout -k 5 3300 python bench.py
  echo "--- iteration $i done rc=$? ---"
  sleep 30
done
echo "capture loop exhausted $MAX_ITERS iterations"
