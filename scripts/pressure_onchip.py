"""On-chip memory-pressure run (VERDICT r4 item 5): the composed
join->agg->sort query under budgets that force device->host->disk spill,
executed on the REAL chip, oracle-checked, with spill counters recorded.

Reference behavior being matched: RapidsBufferStore.scala:141-241 (the
synchronous spill cascade under allocation pressure).  The accounted-pool
caveat (XLA's own temporaries are invisible to the accounting) is
documented in docs/tuning-guide.md.

Run: timeout 900 python scripts/pressure_onchip.py   (ambient env; one
jax process at a time).  Writes BENCH_PRESSURE.json at the repo root."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if "--cpu" in sys.argv:
    # mechanics self-test off-chip (spill accounting is backend-agnostic)
    from spark_rapids_tpu.utils.cpu_backend import force_cpu_backend
    force_cpu_backend()


def main() -> None:
    import jax
    try:
        platform = jax.devices()[0].platform
    except Exception as e:
        print(json.dumps({"platform": None, "error": repr(e)[:200]}))
        return

    from data_gen import gen_table
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.engine import TpuSession
    from spark_rapids_tpu.mem import stores
    from spark_rapids_tpu.plan.logical import col, functions as F, lit

    spills = {"device": 0}
    orig = stores.BufferStore._spill_one

    def counting(self, *a, **kw):
        spills["device"] += 1
        return orig(self, *a, **kw)
    stores.BufferStore._spill_one = counting

    conf = {
        "spark.rapids.sql.variableFloatAgg.enabled": "true",
        # ~0.2% of HBM: a handful of 2MB batches overflow it immediately
        "spark.rapids.memory.tpu.allocFraction": "0.002",
        "spark.rapids.memory.host.spillStorageSize": str(1 << 20),
        "spark.rapids.sql.batchSizeBytes": str(2 << 20),
        "spark.rapids.sql.reader.batchSizeRows": "16384",
        "spark.sql.autoBroadcastJoinThreshold": "-1",
        "spark.rapids.sql.tpu.join.partitioned.threshold": "1",
        "spark.rapids.sql.tpu.shuffle.partitions": "8",
    }

    def q(s):
        fdata, fschema = gen_table(71, 120_000, k=T.IntegerType,
                                   g=T.LongType, v=T.DoubleType,
                                   w=T.DoubleType)
        ddata, dschema = gen_table(72, 15_000, k=T.IntegerType,
                                   name=T.StringType, m=T.DoubleType)
        fact = s.from_pydict(fdata, fschema)
        dim = s.from_pydict(ddata, dschema)
        return (fact.join(dim, on="k")
                .group_by(col("k"), col("name"))
                .agg(F.sum(col("v")).alias("sv"),
                     F.count(lit(1)).alias("c"),
                     F.min(col("w")).alias("mw"))
                .order_by(col("sv").desc(), col("k")))

    def q_sort(s):
        # the spill driver: the full joined table through the external
        # sort (the agg query's whole-stage path reduces too early to
        # pressure the store by itself)
        fdata, fschema = gen_table(71, 120_000, k=T.IntegerType,
                                   g=T.LongType, v=T.DoubleType,
                                   w=T.DoubleType)
        ddata, dschema = gen_table(72, 15_000, k=T.IntegerType,
                                   name=T.StringType, m=T.DoubleType)
        return (s.from_pydict(fdata, fschema)
                .join(s.from_pydict(ddata, dschema), on="k")
                .order_by(col("v").desc()).limit(50))

    t0 = time.time()
    s_dev = TpuSession(conf)
    got = q(s_dev).collect()
    sorted_rows = q_sort(s_dev).collect()
    assert len(sorted_rows) == 50, len(sorted_rows)
    dev_s = time.time() - t0
    dev_spills = spills["device"]

    stores.BufferStore._spill_one = orig
    want = q(TpuSession({"spark.rapids.sql.enabled": "false"})).collect()

    from compare import assert_rows_equal
    assert len(got) == len(want), (len(got), len(want))
    # ignore_order: rows tied on the sort key (NaN sums from the float
    # domain) are legitimately emitted in either order
    assert_rows_equal(want, got, ignore_order=True, approx_float=True)
    n_match = len(got)

    out = {"platform": platform, "recorded_unix": int(time.time()),
           "device_spills": dev_spills, "rows_checked": n_match,
           "elapsed_s": round(dev_s, 2),
           "conf": {"allocFraction": "0.002",
                    "hostSpillStorage": "1MB", "batchSize": "2MB"},
           "note": "join->agg->sort with device->host->disk spill "
                   "cascade engaged; results row-identical to the "
                   "unconstrained CPU oracle "
                   "(RapidsBufferStore.scala:141-241 analogue)"}
    with open(os.path.join(REPO, "BENCH_PRESSURE.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    assert dev_spills > 0, "pressure run completed without any spill"


if __name__ == "__main__":
    main()
