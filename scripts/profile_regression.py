#!/usr/bin/env python
"""Profile-regression gate: diff a fresh roofline capture against the
checked-in baseline (ISSUE 13).

A fresh BENCH_PROFILE.json capture (bench.profile_microbench: per-
operator roofline ledgers for the representative query set + serving
SLO phase histograms + the profiler overhead gate) is compared against
BASELINE_PROFILE.json:

  * structure — every baseline query present; every plan node names a
    bottleneck resource; every operator class the baseline saw still
    appears in the capture's ledger (a silently vanished cost
    declaration is a coverage regression, not a perf one);
  * achieved bandwidth — per query, the effective HBM rate (declared
    hbm bytes / measured seconds) and each operator class's best
    achieved rate on its bottleneck resource must not fall below
    baseline / tolerance;
  * phase latencies — each serving phase's per-priority p95 must not
    exceed baseline x tolerance;
  * the profiler's own overhead gate must hold (<5% on q1).

Tolerance is deliberately generous (default 5x, --tolerance/-t or env
PROFILE_TOLERANCE): CI hosts vary wildly, and this gate exists to catch
order-of-magnitude regressions (an operator silently falling off its
fused path, a phase exploding), not single-digit noise.

Usage:
  python scripts/profile_regression.py            # capture + compare
  python scripts/profile_regression.py --bless    # update the baseline
  python scripts/profile_regression.py --from-artifact   # reuse
      BENCH_PROFILE.json instead of re-running the capture
Exit: 0 ok, 1 regression, 2 usage/missing baseline.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPTURE_PATH = os.path.join(REPO, "BENCH_PROFILE.json")
BASELINE_PATH = os.path.join(REPO, "BASELINE_PROFILE.json")


def capture(from_artifact: bool) -> dict:
    if from_artifact:
        with open(CAPTURE_PATH) as f:
            return json.load(f)
    sys.path.insert(0, REPO)
    import bench
    return bench.profile_microbench(write_artifact=True)


def _per_op_best_rates(query_rec: dict) -> dict:
    """{op: best achieved rate (GB/s or GFLOP/s) on its bottleneck}
    over a query's ledger rows that have a measured utilization."""
    out: dict = {}
    for row in query_rec.get("ledger", []):
        b = row.get("bottleneck")
        if b in (None, "host"):
            continue
        rate = (row.get("achieved_gflops") if b == "flops"
                else row.get("achieved_gb_s", {}).get(b))
        if rate is None:
            continue
        op = row.get("op", "?")
        if rate > out.get(op, 0.0):
            out[op] = rate
    return out


def _effective_hbm_rate(query_rec: dict):
    s = query_rec.get("summary", {})
    secs = s.get("measured_seconds") or 0.0
    hbm = s.get("cost_totals", {}).get("hbm", 0)
    return (hbm / secs / 1e9) if secs > 0 and hbm else None


def compare(base: dict, cur: dict, tolerance: float) -> list:
    """List of regression strings (empty = gate passes)."""
    problems = []
    for qname, brec in sorted(base.get("queries", {}).items()):
        crec = cur.get("queries", {}).get(qname)
        if crec is None:
            problems.append(f"{qname}: query missing from capture")
            continue
        if not crec.get("all_nodes_attributed", False):
            problems.append(
                f"{qname}: a plan node has no bottleneck attribution")
        b_ops = {r.get("op") for r in brec.get("ledger", [])}
        c_ops = {r.get("op") for r in crec.get("ledger", [])}
        for op in sorted(b_ops - c_ops):
            problems.append(
                f"{qname}: operator {op} vanished from the ledger "
                "(cost-declaration coverage regression)")
        b_eff, c_eff = _effective_hbm_rate(brec), _effective_hbm_rate(crec)
        if b_eff and c_eff is not None and c_eff < b_eff / tolerance:
            problems.append(
                f"{qname}: effective HBM rate {c_eff:.4f} GB/s < "
                f"baseline {b_eff:.4f} / {tolerance:g}")
        c_rates = _per_op_best_rates(crec)
        for op, b_rate in sorted(_per_op_best_rates(brec).items()):
            c_rate = c_rates.get(op)
            if c_rate is not None and c_rate < b_rate / tolerance:
                problems.append(
                    f"{qname}/{op}: achieved {c_rate:.4f} < baseline "
                    f"{b_rate:.4f} / {tolerance:g}")
        b_t, c_t = brec.get("time_s"), crec.get("time_s")
        if b_t and c_t and c_t > b_t * tolerance:
            problems.append(f"{qname}: time_s {c_t:.3f} > baseline "
                            f"{b_t:.3f} x {tolerance:g}")
    # serving SLO phase latencies: per-(phase, priority) p95
    for phase, by_prio in sorted(base.get("slo", {}).items()):
        for prio, bh in sorted(by_prio.items()):
            ch = cur.get("slo", {}).get(phase, {}).get(prio)
            b95 = (bh or {}).get("p95_s")
            c95 = (ch or {}).get("p95_s")
            if ch is None or (ch.get("count", 0) or 0) == 0:
                continue  # phase not exercised in this capture
            if b95 and c95 is not None and c95 > b95 * tolerance:
                problems.append(
                    f"slo {phase}/p{prio}: p95 {c95:.4f}s > baseline "
                    f"{b95:.4f}s x {tolerance:g}")
    # the bench records the honest <5% target in gate_ok; the CI gate
    # uses a noise-proof ceiling (shared hosts jitter single digits)
    ovh = cur.get("profiler_overhead", {})
    pct = ovh.get("overhead_pct")
    if pct is not None and pct > 15.0:
        problems.append(
            f"profiler overhead {pct}% on q1 (>15% CI ceiling; "
            "target <5%)")
    return problems


def main(argv) -> int:
    bless = "--bless" in argv
    from_artifact = "--from-artifact" in argv
    tolerance = float(os.environ.get("PROFILE_TOLERANCE", 5.0))
    for flag in ("--tolerance", "-t"):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                print(__doc__, file=sys.stderr)
                return 2
            tolerance = float(argv[i + 1])
    cur = capture(from_artifact)
    if bless:
        with open(BASELINE_PATH, "w") as f:
            json.dump(cur, f, indent=1)
        print(f"blessed: {BASELINE_PATH} updated from "
              f"{'artifact' if from_artifact else 'fresh capture'} "
              f"({len(cur.get('queries', {}))} queries)")
        return 0
    if not os.path.exists(BASELINE_PATH):
        print(f"no baseline at {BASELINE_PATH}; run with --bless to "
              "create one", file=sys.stderr)
        return 2
    with open(BASELINE_PATH) as f:
        base = json.load(f)
    problems = compare(base, cur, tolerance)
    if problems:
        print(f"profile-regression gate FAILED ({len(problems)} "
              f"problem(s), tolerance {tolerance:g}x):", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        print("intentional change? scripts/profile_regression.py "
              "--bless updates the baseline", file=sys.stderr)
        return 1
    n_ops = sum(len(q.get("ledger", []))
                for q in cur.get("queries", {}).values())
    print(f"profile-regression gate OK: {len(cur.get('queries', {}))} "
          f"queries, {n_ops} ledger rows, tolerance {tolerance:g}x, "
          f"profiler overhead {cur.get('profiler_overhead', {}).get('overhead_pct')}%")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
