#!/usr/bin/env python
"""TPC-DS triage sweep: classify every query in the 99-query tier.

Runs the full `benchmarks.tpcds.QUERIES` list at one scale factor
(default SF0.1) and classifies each query:

    ok           fully on-device plan, result matches the CPU oracle
    fallback     result matches but the physical plan contains Cpu*
                 nodes (named in the table) — perf work, not correctness
    wrong        device result does NOT match the CPU oracle — a
                 correctness bug to file
    unsupported  the query raises while planning or executing

Each row also records wall time on the device path vs the CPU oracle
(single run each, shared session + tables, so times include first-run
compiles — the honest "what would a user see" number at this scale).

Outputs: a JSON table (--json, default TPCDS_TRIAGE.json at the repo
root, the artifact bench tooling diffs) and a markdown table (--md,
default docs/tpcds-triage.md, the checked-in triage board).

The sweep runs in chunks of --chunk queries, each in a fresh
subprocess: XLA's JIT keeps every compiled executable mapped for the
life of the process, and ~40 queries' worth of stages exhausts
vm.max_map_count (LLVM reports it as "Cannot allocate memory").
Chunking bounds the per-process map count; --chunk 0 runs in-process.

    python scripts/tpcds_triage.py                # full sweep, SF0.1
    python scripts/tpcds_triage.py --sf 0.01 --queries 3,5,96
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

DEVICE_CONF = {"spark.rapids.sql.variableFloatAgg.enabled": "true"}
CPU_CONF = {"spark.rapids.sql.enabled": "false"}


def _cpu_fallback_nodes(session, df) -> list:
    """Names of Cpu*-prefixed physical nodes in the query's plan."""
    plan = session.plan(df.plan)
    bad = set()

    def walk(n):
        if type(n).__name__.startswith("Cpu"):
            bad.add(type(n).__name__)
        for c in n.children:
            walk(c)
    walk(plan)
    return sorted(bad)


def triage(sf: float, qnums=None) -> dict:
    from benchmarks.tpcds import QUERIES, load_tables
    from compare import assert_rows_equal
    from spark_rapids_tpu.engine import TpuSession

    qnums = sorted(QUERIES) if not qnums else sorted(qnums)
    t0 = time.time()
    dev_s = TpuSession(dict(DEVICE_CONF))
    dev_tables = load_tables(dev_s, sf=sf)
    cpu_s = TpuSession(dict(CPU_CONF))
    cpu_tables = load_tables(cpu_s, sf=sf)
    load_seconds = round(time.time() - t0, 2)

    rows = []
    for qnum in qnums:
        rec = {"query": qnum, "status": None, "device_s": None,
               "cpu_s": None, "ratio": None, "rows": None,
               "fallback_nodes": [], "error": None}
        try:
            df = QUERIES[qnum](dev_tables)
            rec["fallback_nodes"] = _cpu_fallback_nodes(dev_s, df)
            t = time.time()
            got = df.collect()
            rec["device_s"] = round(time.time() - t, 3)
            rec["rows"] = len(got)
        except Exception as e:  # noqa: BLE001 — triage, not a test
            rec["status"] = "unsupported"
            rec["error"] = repr(e)[:200]
            rows.append(rec)
            print(f"q{qnum}: unsupported ({rec['error'][:60]})",
                  flush=True)
            continue
        t = time.time()
        want = QUERIES[qnum](cpu_tables).collect()
        rec["cpu_s"] = round(time.time() - t, 3)
        rec["ratio"] = round(rec["device_s"] / max(1e-9, rec["cpu_s"]), 2)
        try:
            assert_rows_equal(want, got, ignore_order=True,
                              approx_float=True)
        except AssertionError as e:
            rec["status"] = "wrong"
            rec["error"] = repr(e)[:200]
        else:
            rec["status"] = "fallback" if rec["fallback_nodes"] else "ok"
        rows.append(rec)
        print(f"q{qnum}: {rec['status']} dev={rec['device_s']}s "
              f"cpu={rec['cpu_s']}s", flush=True)

    counts = {}
    for r in rows:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    return {"sf": sf, "queries": len(rows), "counts": counts,
            "load_seconds": load_seconds,
            "total_device_s": round(sum(r["device_s"] or 0.0
                                        for r in rows), 1),
            "total_cpu_s": round(sum(r["cpu_s"] or 0.0
                                     for r in rows), 1),
            "rows": rows}


def _merge(parts: list) -> dict:
    rows = [r for p in parts for r in p["rows"]]
    counts = {}
    for r in rows:
        counts[r["status"]] = counts.get(r["status"], 0) + 1
    return {"sf": parts[0]["sf"], "queries": len(rows), "counts": counts,
            "load_seconds": round(sum(p["load_seconds"] for p in parts), 2),
            "total_device_s": round(sum(p["total_device_s"]
                                        for p in parts), 1),
            "total_cpu_s": round(sum(p["total_cpu_s"] for p in parts), 1),
            "rows": rows}


def triage_chunked(sf: float, qnums, chunk: int) -> dict:
    """Run the sweep `chunk` queries per fresh subprocess and merge."""
    parts = []
    with tempfile.TemporaryDirectory(prefix="tpcds_triage_") as tmp:
        for i in range(0, len(qnums), chunk):
            part = qnums[i:i + chunk]
            out = os.path.join(tmp, f"part-{i}.json")
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--sf", str(sf),
                   "--queries", ",".join(str(q) for q in part),
                   "--json", out, "--md", os.devnull, "--chunk", "0"]
            subprocess.run(cmd, check=True)
            with open(out) as f:
                parts.append(json.load(f))
    return _merge(parts)


def to_markdown(result: dict) -> str:
    counts = result["counts"]
    lines = [
        "# TPC-DS triage",
        "",
        f"Generated by `scripts/tpcds_triage.py` at SF{result['sf']} — "
        "the full 99-query tier, each query classified "
        "ok / fallback / wrong / unsupported with single-run wall time "
        "vs the CPU oracle (shared session and tables; device times "
        "include first-run compiles).",
        "",
        "| status | queries |",
        "|---|---|",
    ]
    for st in ("ok", "fallback", "wrong", "unsupported"):
        if counts.get(st):
            lines.append(f"| {st} | {counts[st]} |")
    lines += [
        "",
        f"Table load: {result['load_seconds']}s.  Total device time: "
        f"{result['total_device_s']}s; total CPU-oracle time: "
        f"{result['total_cpu_s']}s.",
        "",
        "| query | status | device s | cpu s | dev/cpu | rows | notes |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in result["rows"]:
        notes = ""
        if r["fallback_nodes"]:
            notes = ", ".join(r["fallback_nodes"])
        elif r["error"]:
            notes = r["error"][:80].replace("|", "\\|")
        lines.append(
            f"| q{r['query']} | {r['status']} "
            f"| {r['device_s'] if r['device_s'] is not None else '—'} "
            f"| {r['cpu_s'] if r['cpu_s'] is not None else '—'} "
            f"| {r['ratio'] if r['ratio'] is not None else '—'} "
            f"| {r['rows'] if r['rows'] is not None else '—'} "
            f"| {notes} |")
    lines.append("")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--queries", type=str, default="",
                    help="comma-separated query numbers (default: all)")
    ap.add_argument("--json", type=str,
                    default=os.path.join(REPO, "TPCDS_TRIAGE.json"))
    ap.add_argument("--md", type=str,
                    default=os.path.join(REPO, "docs", "tpcds-triage.md"))
    ap.add_argument("--chunk", type=int, default=20,
                    help="queries per fresh subprocess (0 = in-process)")
    args = ap.parse_args()
    qnums = ([int(x) for x in args.queries.split(",") if x.strip()]
             if args.queries else None)
    if args.chunk > 0:
        from benchmarks.tpcds import QUERIES
        qnums = sorted(QUERIES) if not qnums else sorted(qnums)
        if len(qnums) > args.chunk:
            result = triage_chunked(args.sf, qnums, args.chunk)
        else:
            result = triage(args.sf, qnums)
    else:
        result = triage(args.sf, qnums)
    with open(args.json, "w") as f:
        json.dump(result, f, indent=1)
    with open(args.md, "w") as f:
        f.write(to_markdown(result))
    print(json.dumps({"counts": result["counts"],
                      "json": args.json, "md": args.md}, indent=1))


if __name__ == "__main__":
    main()
