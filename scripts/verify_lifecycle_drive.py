"""End-to-end /verify drive for the query lifecycle layer (PR 19).

Drives the PUBLIC serving API against a hand-computed numpy oracle:
a submitted aggregation must match the oracle bit-for-bit; a cancelled
running query must fail with the typed QueryCancelled and leave zero
owner-stamped bytes in any tier; an expired deadline must shed at
admission with the typed QueryDeadlineExceeded; with preemption on, a
high-priority arrival must suspend the low-priority victim and the
victim must still produce the oracle's bytes after resuming; with the
lifecycle kill switch off, cancel() is a False no-op and results are
identical.

CPU-forced standalone (never touches the TPU lease); safe under
`timeout 600`.  Run: `python scripts/verify_lifecycle_drive.py`.
"""
import sys
import os
import time

import jax._src.xla_bridge as xb
for p in ("axon", "tpu"):
    xb._backend_factories.pop(p, None)
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.engine import TpuSession
from spark_rapids_tpu.plan.logical import col, functions as F, lit
from spark_rapids_tpu.serve.lifecycle import (QueryCancelled,
                                              QueryDeadlineExceeded)

N = 200_000
rng = np.random.RandomState(11)
A = rng.uniform(0.0, 100.0, N)
B = rng.randint(0, 50, N).astype(np.int64)
TABLE = pa.table({"a": A, "b": B})

CONF = {
    "spark.rapids.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.sql.reader.batchSizeRows": "2000",
}


def q_agg(df):
    return (df.filter(col("a") > 5.0)
            .group_by(col("b")).agg(F.count(lit(1)).alias("n"))
            .order_by("b"))


def hand_oracle():
    mask = A > 5.0
    keys, counts = np.unique(B[mask], return_counts=True)
    return pa.table({"b": keys, "n": counts.astype(np.int64)})


def owner_bytes(s, qid):
    rt = s.runtime
    return sum(st.owner_size(f"q{qid}") for st in
               (rt.device_store, rt.host_store, rt.disk_store))


def main():
    oracle = hand_oracle()

    # 1. submitted query vs hand oracle (exact: int64 counts)
    s = TpuSession(dict(CONF))
    got = s.submit(q_agg(s.from_arrow(TABLE))).result(300)
    assert got.equals(oracle), "submit() result diverged from hand oracle"
    print("1. submit vs hand oracle: bit-for-bit OK")

    # 2. cancel a running query: typed error, zero residual owner bytes
    df = s.from_arrow(TABLE)
    f = s.submit(df.select((col("a") * lit(2.0)).alias("x"), col("b")))
    while f.admitted_ns is None:
        time.sleep(0.002)
    time.sleep(0.03)
    f.cancel("verify drive")
    err = f.exception(120)
    assert err is None or isinstance(err, QueryCancelled), repr(err)
    assert owner_bytes(s, f.query_id) == 0, "residual owner bytes"
    print(f"2. cancel running: typed={type(err).__name__ if err else 'finished first'}, owner bytes 0 OK")

    # 3. expired deadline sheds at admission, typed
    f = s.submit(q_agg(df), deadline_ms=0.001)
    err = f.exception(60)
    assert isinstance(err, QueryDeadlineExceeded), repr(err)
    assert "shed at admission" in str(err)
    print("3. deadline shed: typed QueryDeadlineExceeded OK")
    s.shutdown_serving()

    # 4. preemption: victim suspends for the high-priority arrival and
    # still returns the oracle's bytes
    # wholeStage off keeps the agg victim on its streaming per-batch
    # update loop — the fused probe drain's suspend window is too narrow
    # to hit deterministically (same shape tests/test_lifecycle.py uses)
    s = TpuSession({**CONF,
                    "spark.rapids.sql.tpu.serve.maxConcurrentQueries": "2",
                    "spark.rapids.sql.concurrentTpuTasks": "1",
                    "spark.rapids.sql.tpu.serve.preemption.enabled": "true",
                    "spark.rapids.sql.tpu.wholeStage.enabled": "false"})
    df = s.from_arrow(TABLE)
    preempted = False
    for _ in range(3):
        victim = s.submit(q_agg(df), priority=0)
        while victim.admitted_ns is None:
            time.sleep(0.002)
        hi = s.submit(df.limit(5), priority=10)
        hi.result(300)
        assert victim.result(300).equals(oracle), \
            "preempted victim diverged from hand oracle"
        st = s.scheduler.stats()["lifecycle"]
        if st["preemptions"] > 0:
            assert st["preemption_resumes"] == st["preemptions"]
            preempted = True
            break
    assert preempted, "no preemption observed in 3 attempts"
    print(f"4. preemption: {st['preemptions']} suspend/resume, victim bit-for-bit OK")
    s.shutdown_serving()

    # 5. kill switch: no token, cancel() False, identical bytes
    s = TpuSession({**CONF,
                    "spark.rapids.sql.tpu.serve.lifecycle.enabled": "false"})
    f = s.submit(q_agg(s.from_arrow(TABLE)), deadline_ms=0.001)
    assert f.lifecycle is None
    assert f.cancel("ignored") is False
    assert f.result(300).equals(oracle), "kill-switch result diverged"
    print("5. kill switch: no token, cancel()=False, bit-for-bit OK")
    s.shutdown_serving()

    print("verify_lifecycle_drive: ALL OK")


if __name__ == "__main__":
    main()
