"""On-chip verify drive: a small end-to-end query through the public API
on the real TPU, checked against a hand-computed oracle.

Run from /root/repo with the ambient env (JAX_PLATFORMS=axon), one jax
process at a time:  timeout 600 python scripts/verify_onchip.py

Exit 0 prints VERIFY-ONCHIP-OK; any mismatch raises.  Floats compare with
tolerance: the axon backend emulates f64 as an f32 pair (~49-bit
mantissa), so doubles can move ~4e-16 rel per transfer.
"""
import sys

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402

from spark_rapids_tpu.engine import TpuSession  # noqa: E402
from spark_rapids_tpu.plan.logical import col, functions as F  # noqa: E402


def main():
    platform = jax.devices()[0].platform
    print(f"platform: {platform}")
    s = TpuSession({"spark.rapids.sql.variableFloatAgg.enabled": "true"})
    n = 10_000
    data = {
        "k": [i % 7 for i in range(n)],
        "v": [float(i) for i in range(n)],
        "w": [i % 3 for i in range(n)],
    }
    df = s.from_pydict(data)
    got = dict(
        (r[0], (r[1], r[2]))
        for r in (df.filter(col("w") != 0)
                  .group_by(col("k"))
                  .agg(F.sum(col("v")).alias("s"),
                       F.count(col("v")).alias("c"))
                  .collect()))
    # hand-computed oracle
    want = {}
    for i in range(n):
        if i % 3 == 0:
            continue
        sm, c = want.get(i % 7, (0.0, 0))
        want[i % 7] = (sm + float(i), c + 1)
    assert set(got) == set(want), (sorted(got), sorted(want))
    for k, (sm, c) in want.items():
        gs, gc = got[k]
        assert gc == c, (k, gc, c)
        assert abs(gs - sm) <= 1e-9 * max(1.0, abs(sm)), (k, gs, sm)
    print(f"VERIFY-ONCHIP-OK platform={platform} groups={len(got)}")


if __name__ == "__main__":
    main()
