"""End-to-end /verify drive for the data-movement policy engine (PR 18).

Runs the spill-cascade slice (join+filter+agg+sort under a 2MB pool)
three ways — policy ON, policy OFF, and unconstrained oracle — asserts
bit-for-bit equality, live policy counters, and that the --memory CLI
replays the decision stream from journal shards alone.

CPU-forced standalone (never touches the TPU lease); safe under
`timeout 300`.  Run: `python scripts/verify_policy_drive.py`.
"""
import sys
import os
import subprocess
import tempfile
import time

import jax._src.xla_bridge as xb
for p in ("axon", "tpu"):
    xb._backend_factories.pop(p, None)
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_tpu.engine import TpuSession
from spark_rapids_tpu.metrics import names as MN
from spark_rapids_tpu.metrics.export import session_observability
from spark_rapids_tpu.plan.logical import col, functions as F, lit

CASCADE = {
    "spark.rapids.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.memory.tpu.poolSizeBytes": str(2 << 20),
    "spark.rapids.memory.host.spillStorageSize": str(1 << 20),
    "spark.rapids.sql.batchSizeBytes": str(512 << 10),
    "spark.rapids.sql.reader.batchSizeRows": "16384",
    "spark.sql.autoBroadcastJoinThreshold": "-1",
    "spark.rapids.sql.tpu.join.partitioned.threshold": "1",
    "spark.rapids.sql.tpu.shuffle.partitions": "8",
}
N = 60_000


def run(conf):
    s = TpuSession(conf)
    fact = s.from_pydict({"k": [i % 7 for i in range(N)],
                          "v": [float(i) for i in range(N)],
                          "q": [i % 3 for i in range(N)]})
    dim = s.from_pydict({"k": list(range(7)),
                         "name": [f"g{j}" for j in range(7)]})
    rows = (fact.join(dim, on="k").filter(col("q") < 2)
            .group_by(col("name"))
            .agg(F.sum(col("v")).alias("sv"), F.count(lit(1)).alias("c"))
            .order_by(col("name")).collect())
    return rows, s


def main():
    with tempfile.TemporaryDirectory() as td:
        jdir = os.path.join(td, "journal")
        on_conf = dict(CASCADE, **{
            "spark.rapids.sql.tpu.metrics.journal.dir": jdir})
        rows_on, s_on = run(on_conf)
        rows_off, s_off = run(dict(
            CASCADE, **{"spark.rapids.sql.tpu.policy.enabled": "false"}))
        rows_oracle, _ = run({})

        assert rows_on == rows_off == rows_oracle, "results diverge"
        print(f"bit-for-bit: OK ({len(rows_on)} rows, sv[0]={rows_on[0]})")

        # hand oracle on the aggregate itself
        sv = {}
        cnt = {}
        for i in range(N):
            if i % 3 < 2:
                g = f"g{i % 7}"
                sv[g] = sv.get(g, 0.0) + float(i)
                cnt[g] = cnt.get(g, 0) + 1
        for name, got_sv, got_c in rows_on:
            assert abs(got_sv - sv[name]) < 1e-6, (name, got_sv)
            assert got_c == cnt[name], (name, got_c)
        print("hand oracle: OK")

        obs = session_observability(s_on)
        assert obs["numPolicyVictimPicks"] > 0, obs
        obs_off = session_observability(s_off)
        assert obs_off["numPolicyVictimPicks"] == 0, obs_off
        print(f"policy counters: victimPicks={obs['numPolicyVictimPicks']} "
              f"earlyReleases={obs['numPolicyEarlyReleases']} "
              f"unspills={obs['numProactiveUnspills']} (OFF session: all 0)")

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        t0 = time.monotonic()
        cp = subprocess.run(
            [sys.executable, "-m", "spark_rapids_tpu.metrics",
             "--memory", jdir],
            capture_output=True, text=True, env=env, timeout=240)
        assert cp.returncode == 0, cp.stderr
        assert "policy decisions:" in cp.stdout, cp.stdout[-2000:]
        assert "scored picks" in cp.stdout, cp.stdout[-2000:]
        print(f"--memory replay: OK ({time.monotonic() - t0:.1f}s)")
        for line in cp.stdout.splitlines():
            if "policy" in line or "scored" in line or "release" in line:
                print("  " + line.strip())
    print("VERIFY_POLICY_DRIVE_PASS")


if __name__ == "__main__":
    main()
