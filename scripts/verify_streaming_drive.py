"""End-to-end /verify drive for the streaming micro-batch engine (PR 20).

Drives the PUBLIC streaming API against both oracles at once: an
incremental grouped aggregation over a MemoryStream must, at EVERY
epoch, match (a) the batch query over all rows appended so far run
through the same engine, bit-for-bit, and (b) a numpy hand oracle
(exact on int64 sum/count, 1e-12 relative on the float average).  Warm
epochs must compile zero new kernels or stages and hit the plan cache.
A query killed mid-stream and restarted from its checkpoint must drain
the remaining epochs and land bit-for-bit on the uninterrupted result,
bumping numStateRecoveries.  stop() must free every owner-stamped
state byte in every tier.

CPU-forced standalone (never touches the TPU lease); safe under
`timeout 600`.  Run: `python scripts/verify_streaming_drive.py`.
"""
import os
import struct
import sys
import tempfile

import jax._src.xla_bridge as xb
for p in ("axon", "tpu"):
    xb._backend_factories.pop(p, None)
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.engine import DataFrame, TpuSession
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import col, functions as F
from spark_rapids_tpu.streaming import MemoryStream, stream_query
from spark_rapids_tpu.utils import kernel_cache as KC

EPOCH_ROWS = 500
N_EPOCHS = 6
CONF = {
    "spark.rapids.sql.variableFloatAgg.enabled": "true",
    "spark.rapids.sql.reader.batchSizeRows": str(EPOCH_ROWS),
    "spark.rapids.sql.tpu.streaming.maxBatchRows": str(EPOCH_ROWS),
}

rng = np.random.RandomState(7)
K = rng.randint(0, 13, EPOCH_ROWS * N_EPOCHS).astype(np.int64)
V = rng.randint(-1000, 1000, EPOCH_ROWS * N_EPOCHS).astype(np.int64)
X = rng.uniform(-10.0, 10.0, EPOCH_ROWS * N_EPOCHS)
CHUNKS = [pa.table({"k": K[i * EPOCH_ROWS:(i + 1) * EPOCH_ROWS],
                    "v": V[i * EPOCH_ROWS:(i + 1) * EPOCH_ROWS],
                    "x": X[i * EPOCH_ROWS:(i + 1) * EPOCH_ROWS]})
          for i in range(N_EPOCHS)]


def build(df):
    return df.group_by(col("k")).agg(
        F.sum(col("v")).alias("sv"),
        F.count(col("v")).alias("cv"),
        F.avg(col("x")).alias("ax"))


def canon(table):
    rows = []
    for row in table.to_pylist():
        rows.append(tuple(
            struct.pack("<d", v) if isinstance(v, float) else v
            for v in (row[name] for name in sorted(row))))
    return sorted(rows, key=repr)


def batch_oracle(session, source):
    scan = L.LogicalScan(source.rows_between(0, source.latest_offset()),
                         source.schema, "memory")
    return build(DataFrame(session, scan)).to_arrow()


def hand_oracle(n_rows):
    k, v, x = K[:n_rows], V[:n_rows], X[:n_rows]
    out = {}
    for key in np.unique(k):
        m = k == key
        out[int(key)] = (int(v[m].sum()), int(m.sum()), float(x[m].mean()))
    return out


def check_hand(table, n_rows):
    want = hand_oracle(n_rows)
    got = {row["k"]: (row["sv"], row["cv"], row["ax"])
           for row in table.to_pylist()}
    assert set(got) == set(want), (set(got), set(want))
    for key, (sv, cv, ax) in want.items():
        gsv, gcv, gax = got[key]
        assert gsv == sv and gcv == cv, (key, got[key], want[key])
        assert abs(gax - ax) <= 1e-12 * max(1.0, abs(ax)), (key, gax, ax)


def owner_bytes(session, owner):
    rt = session.runtime
    return sum(st.owner_size(owner) for st in
               (rt.device_store, rt.host_store, rt.disk_store))


def main():
    ckpt = tempfile.mkdtemp(prefix="verify_stream_ck_")

    # -- incremental vs both oracles at every epoch, zero warm compiles --
    s = TpuSession(dict(CONF))
    src = MemoryStream(CHUNKS[0].slice(0, 0), name="drive")
    q = stream_query(s, src, build, name="drive", checkpoint_dir=ckpt)
    warm_deltas = []
    for i, chunk in enumerate(CHUNKS[:4]):
        src.append(chunk)
        before = KC.stats()
        assert q.trigger_once(), f"epoch {i + 1} did not commit"
        after = KC.stats()
        if i >= 1:
            warm_deltas.append(
                (after["builds"] - before["builds"],
                 after["stage_compiles"] - before["stage_compiles"]))
        inc = q.result()
        assert canon(inc) == canon(batch_oracle(s, src)), f"epoch {i + 1}"
        check_hand(inc, (i + 1) * EPOCH_ROWS)
    assert warm_deltas and all(d == (0, 0) for d in warm_deltas), warm_deltas
    pc = s.scheduler.stats()["plan_cache"]
    assert pc["hits"] >= 3, pc
    print(f"epochs 1-4 bit-for-bit vs engine + numpy oracles; warm "
          f"compile deltas {warm_deltas}, plan cache {pc['hits']} hits")

    # -- kill mid-stream, restart from checkpoint, drain the rest --------
    owner = q._state.owner
    assert owner_bytes(s, owner) > 0
    q._state.release()          # simulate a hard kill: no stop() cleanup
    del q
    s2 = TpuSession(dict(CONF))
    before_rec = s2.runtime.metrics.snapshot().get("numStateRecoveries", 0)
    q2 = stream_query(s2, src, build, name="drive", checkpoint_dir=ckpt)
    assert s2.runtime.metrics.snapshot()["numStateRecoveries"] == \
        before_rec + 1
    for chunk in CHUNKS[4:]:
        src.append(chunk)
    assert q2.process_available() == 2
    final = q2.result()
    assert canon(final) == canon(batch_oracle(s2, src)), "post-restart"
    check_hand(final, N_EPOCHS * EPOCH_ROWS)
    print(f"restart recovered epoch 4, drained 2 more epochs, final "
          f"bit-for-bit over {N_EPOCHS * EPOCH_ROWS} rows")

    # -- stop() frees every owner byte in every tier ---------------------
    owner2 = q2._state.owner
    held = owner_bytes(s2, owner2)
    freed = q2.stop()
    assert freed > 0 and held > 0 and owner_bytes(s2, owner2) == 0, \
        (held, freed)
    print(f"stop() freed {freed} owner bytes; zero residual")

    s.shutdown_serving()
    s2.shutdown_serving()
    print("VERIFY STREAMING DRIVE OK")


if __name__ == "__main__":
    main()
