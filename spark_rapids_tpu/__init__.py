"""spark_rapids_tpu — a TPU-native columnar SQL acceleration framework.

A from-scratch JAX/XLA/Pallas implementation of the capability surface of the
RAPIDS Accelerator for Apache Spark (plan rewrite -> columnar device operators
-> tiered device memory -> columnar file I/O -> device-resident shuffle),
designed for TPU: static-shape bucketed batches, whole-pipeline jit
compilation, sort-based joins/aggregations, and ICI all-to-all shuffle over a
`jax.sharding.Mesh`.
"""
__version__ = "0.1.0"

import jax as _jax

# LongType/DoubleType columns require real int64/float64 semantics; without
# x64 JAX silently truncates to 32-bit and the CPU-vs-TPU oracle diverges.
_jax.config.update("jax_enable_x64", True)

from . import types  # noqa: F401
from .config import TpuConf  # noqa: F401
from .columnar import Column, ColumnarBatch  # noqa: F401
from .plan.logical import Window, WindowSpec  # noqa: F401
