"""Adaptive query execution: runtime re-planning from shuffle map statistics.

The query runs as a DAG of stages split at `TpuShuffleExchangeExec`
boundaries: map stages are materialized first, their OBSERVED per-partition
output sizes (stats.py) replace the planner's schema-width guesses, and the
reduce side is re-planned (rules.py) before it is instantiated
(executor.py).  Reference analogue: Spark 3 AQE driving
GpuShuffleExchangeExec + GpuCustomShuffleReaderExec.

Submodule imports stay lazy: exec/ imports `adaptive.stats` for the
partition-spec types, and an eager package __init__ would cycle back into
exec/ through executor.py.
"""
from __future__ import annotations

__all__ = [
    "CoalescedPartitionSpec", "PartialReducerPartitionSpec",
    "MapOutputStatistics", "MapOutputTracker", "merge_cluster_stats",
    "TpuAdaptivePlanExec", "maybe_wrap_adaptive",
]


def __getattr__(name):
    if name in ("CoalescedPartitionSpec", "PartialReducerPartitionSpec",
                "MapOutputStatistics", "MapOutputTracker",
                "merge_cluster_stats"):
        from . import stats
        return getattr(stats, name)
    if name in ("TpuAdaptivePlanExec", "maybe_wrap_adaptive"):
        from . import executor
        return getattr(executor, name)
    raise AttributeError(name)
