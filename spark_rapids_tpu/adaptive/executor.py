"""Adaptive stage-graph executor.

`TpuAdaptivePlanExec` wraps an about-to-run physical tree (the engine
inserts it at execution time, never in `physical_plan()` output): its
`execute` walks the tree bottom-up, MATERIALIZES each shuffle exchange's
map stage (write phase; `TpuShuffleExchangeExec.materialize`), then applies
the re-planning rules (rules.py) over the observed `MapOutputStatistics`
before the reduce side is instantiated — Spark AQE's
query-stage-by-query-stage loop collapsed into one recursive pass, because
stage dependencies here ARE the tree structure: materializing an exchange
executes its (already adapted) subtree.

The rewritten tree is re-registered with the live QueryExecution
(`QueryExecution.adopt`) so EXPLAIN METRICS, the journal's per-node metric
dump and the Prometheus export all show the FINAL (re-planned) plan.

Failure containment: if a stage materialization exhausts its OOM retries,
the node is left un-adapted and normal execution — with its operator-local
CPU fallback machinery (exec/retryable.py) — takes over.
"""
from __future__ import annotations

from typing import Iterator

from .. import config as C
from ..columnar import ColumnarBatch
from ..exec.base import ExecContext, ExecNode, TpuExec
from ..metrics import names as MN


class TpuAdaptivePlanExec(TpuExec):
    """AQE driver node (AdaptiveSparkPlanExec analogue): re-plans its
    subtree from runtime statistics at execute time, then delegates."""

    def __init__(self, child: ExecNode):
        super().__init__(child)
        self._replanned = False

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        suffix = "final" if self._replanned else "initial"
        return f"TpuAdaptivePlanExec[{suffix}]"

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        final = self._replan(ctx)
        yield from final.execute(ctx)

    # ---- re-planning -------------------------------------------------------

    def _replan(self, ctx: ExecContext) -> ExecNode:
        if self._replanned or not ctx.conf.get(C.ADAPTIVE_ENABLED):
            return self.children[0]
        new_root = self._adapt(self.children[0], ctx)
        # ICI-lowering idempotence: exchanges the rules created (a
        # demoted broadcast's replacement repartition) must get the same
        # mesh-vs-socket decision as planner-built ones — re-run the
        # (idempotent) marking pass over the re-planned tree
        from ..exec.distributed import resolve_mesh
        mesh = resolve_mesh(ctx.conf)
        if mesh is not None:
            from ..plan.transitions import mark_ici_exchanges
            mark_ici_exchanges(new_root, mesh)
        if ctx.conf.get(C.FUSION_ENABLED):
            # re-planned reduce sides fuse too: the pass is idempotent on
            # already-fused subtrees (identity preserved, plan/fusion.py),
            # so only chains the rules introduced become new stages; fresh
            # stages get *(N) ids above the existing numbering
            from ..plan import fusion as F
            new_root = F._fuse(new_root,
                               max(1, int(ctx.conf.get(C.FUSION_MAX_OPS))))
            F.number_stages(new_root,
                            start=F.max_stage_id(new_root) + 1)
        self._replanned = True
        self.children = [new_root]
        qe = getattr(ctx, "query_execution", None)
        if qe is not None:
            # EXPLAIN METRICS / journal / prometheus must show the FINAL
            # stage plan: register any nodes the rules created
            qe.adopt(self)
        return new_root

    def _adapt(self, node: ExecNode, ctx: ExecContext) -> ExecNode:
        from ..exec.broadcast import (TpuBroadcastExchangeExec,
                                      TpuBroadcastHashJoinExec)
        from ..exec.exchange import TpuShuffleExchangeExec
        from ..exec.join import TpuShuffledHashJoinExec
        from ..exec.shuffle_reader import TpuCoalescedShuffleReaderExec
        from ..mem.retry import RetryExhausted
        from . import rules

        if isinstance(node, TpuCoalescedShuffleReaderExec):
            # already re-planned in an earlier pass of this walk (a
            # demoted broadcast's replacement join re-walks its adapted
            # probe subtree): re-entering the exchange below would re-fire
            # the coalesce rule on the same cached stats and nest a second
            # reader around the first
            return node

        if isinstance(node, TpuShuffledHashJoinExec) \
                and all(isinstance(c, TpuShuffleExchangeExec)
                        for c in node.children):
            lex, rex = node.children
            lex.children = [self._adapt(lex.children[0], ctx)]
            rex.children = [self._adapt(rex.children[0], ctx)]
            try:
                lex.materialize(ctx)
                rex.materialize(ctx)
                with self.metrics.timer(MN.REPLAN_TIME):
                    return rules.replan_shuffled_join(node, ctx,
                                                      self.metrics)
            except RetryExhausted:
                return node  # normal execution owns the fallback path

        if isinstance(node, TpuBroadcastHashJoinExec) \
                and isinstance(node.children[1], TpuBroadcastExchangeExec):
            bx = node.children[1]
            probe = self._adapt(node.children[0], ctx)
            bx.children = [self._adapt(bx.children[0], ctx)]
            node.children = [probe, bx]
            thr = ctx.conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
            if not ctx.conf.get(C.ADAPTIVE_JOIN_STRATEGY_ENABLED) \
                    or thr is None or int(thr) < 0:
                return node
            try:
                # collect the build once, OUTSIDE the replan timer (a kept
                # broadcast reuses the cached collect at probe time); the
                # demotion check then reads its observed size
                bx.materialize_host(ctx)
                with self.metrics.timer(MN.REPLAN_TIME):
                    new = rules.demote_broadcast_join(node, ctx,
                                                      self.metrics)
            except RetryExhausted:
                return node
            if new is not node:
                return self._adapt(new, ctx)  # adapt the replacement join
            return node

        node.children = [self._adapt(c, ctx) for c in node.children]
        if isinstance(node, TpuShuffleExchangeExec) \
                and node.num_partitions > 1 and node.mode != "single":
            try:
                node.materialize(ctx)
                with self.metrics.timer(MN.REPLAN_TIME):
                    return rules.replan_exchange(node, ctx, self.metrics)
            except RetryExhausted:
                return node
        return node


def has_adaptive_target(node: ExecNode) -> bool:
    """Anything in the tree adaptive execution could improve?"""
    from ..exec.broadcast import TpuBroadcastHashJoinExec
    from ..exec.exchange import TpuShuffleExchangeExec
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (TpuShuffleExchangeExec,
                          TpuBroadcastHashJoinExec)):
            return True
        stack.extend(n.children)
    return False


def maybe_wrap_adaptive(physical: ExecNode, conf) -> ExecNode:
    """Engine hook (engine.py to_arrow/_write/to_device_batches): wrap a
    device tree in the AQE driver when enabled and worthwhile.  Applied at
    EXECUTE time only, so `DataFrame.physical_plan()` keeps showing the
    static plan the planner chose."""
    if not conf.get(C.ADAPTIVE_ENABLED):
        return physical
    if not isinstance(physical, TpuExec):
        return physical
    if not has_adaptive_target(physical):
        return physical
    return TpuAdaptivePlanExec(physical)
