"""Adaptive re-planning rules: what changes between map stage and reduce
side once observed sizes replace estimates.

Three rules, mirroring Spark AQE's reduce-side optimizations over
GpuShuffleExchangeExec / GpuCustomShuffleReaderExec:

  * coalesce small partitions — merge contiguous reduce partitions up to
    `spark.rapids.sql.tpu.adaptive.advisoryPartitionSizeBytes`, served by
    one TpuCoalescedShuffleReaderExec spec per merged range;
  * skew-join split — a stream-side partition larger than
    `skewedPartitionFactor x median` (and the size floor) is split into
    map-id-range slices, each paired with a replicated read of the full
    build-side partition;
  * dynamic join strategy — a partitioned join whose OBSERVED build side
    fits under spark.sql.autoBroadcastJoinThreshold is promoted to a
    single-build join; a planned broadcast whose observed collect blew
    past the threshold is demoted to a partitioned join over the
    already-collected build (overriding the static
    `_should_broadcast_build` choice, plan/physical.py).

Every decision appends a `replan` journal event and bumps the adaptive
metric counters (numCoalescedPartitions / numSkewSplits /
numJoinStrategyChanges), so EXPLAIN METRICS, the event journal and the
Prometheus export all show what actually ran.
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple

from .. import config as C
from ..metrics import names as MN
from ..metrics.journal import journal_event
from .stats import (CoalescedPartitionSpec, PartialReducerPartitionSpec,
                    is_identity)

# stream-side row slices compose by concatenation for these join types
# (each left row's matches depend only on the resident build side).  FULL
# outer stays whole: its never-matched-build tail is emitted once per
# probe stream, so slicing would duplicate it per slice.
SKEW_SPLITTABLE_JOINS = ("inner", "left", "left_semi", "left_anti")


def coalesce_specs(n: int, size_lists: List[List[int]],
                   bounds: List[int]) -> List[CoalescedPartitionSpec]:
    """Greedy contiguous merge: partitions accumulate into one spec while
    EVERY tracked size sum stays within its bound (a join tracks the
    combined l+r bytes against the advisory size AND the build side
    against the partitioned-join threshold, so coalescing never un-bounds
    the single-build-batch contract the exchange was inserted for)."""
    specs: List[CoalescedPartitionSpec] = []
    start = 0
    accs = [0] * len(size_lists)
    for p in range(n):
        cur = [sl[p] for sl in size_lists]
        if p > start and any(a + c > b
                             for a, c, b in zip(accs, cur, bounds)):
            specs.append(CoalescedPartitionSpec(start, p))
            start = p
            accs = [0] * len(size_lists)
        accs = [a + c for a, c in zip(accs, cur)]
    if n > 0:
        specs.append(CoalescedPartitionSpec(start, n))
    return specs


def detect_skew(sizes: List[int], factor: float,
                threshold: int) -> Set[int]:
    """Partitions whose bytes exceed max(factor x median non-empty size,
    threshold floor)."""
    nonzero = sorted(s for s in sizes if s > 0)
    if not nonzero:
        return set()
    median = nonzero[len(nonzero) // 2]
    bound = max(median * factor, threshold)
    return {p for p, s in enumerate(sizes) if s > bound}


def map_range_slices(map_bytes: Dict[int, int],
                     target: int) -> List[Tuple[int, int]]:
    """Split one partition's per-map-task sizes into contiguous map-id
    ranges of roughly `target` bytes.  A single-map partition returns one
    slice (unsplittable — the map output is one block)."""
    if not map_bytes:
        return []
    mids = sorted(map_bytes)
    slices: List[Tuple[int, int]] = []
    lo = 0  # cover from map 0: unseen low ids wrote nothing, cost nothing
    acc = 0
    for m in mids:
        b = map_bytes[m]
        if acc > 0 and acc + b > target:
            slices.append((lo, m))
            lo = m
            acc = 0
        acc += b
    slices.append((lo, mids[-1] + 1))
    return slices


def replan_shuffled_join(join, ctx, adaptive_metrics):
    """Re-plan one TpuShuffledHashJoinExec whose exchanges are already
    materialized; returns the node to execute (possibly a different join
    operator, possibly the same node re-wired onto paired readers,
    possibly untouched)."""
    from ..exec.join import TpuHashJoinExec
    from ..exec.shuffle_reader import TpuCoalescedShuffleReaderExec
    conf = ctx.conf
    am = adaptive_metrics
    lex, rex = join.children
    lh, rh = lex._handle, rex._handle
    n = lh.num_partitions
    lst, rst = lh.stats(), rh.stats()
    lbytes, rbytes = lst.bytes_by_partition, rst.bytes_by_partition
    advisory = int(conf.get(C.ADAPTIVE_ADVISORY_PARTITION_SIZE))
    coalesce_on = bool(conf.get(C.ADAPTIVE_COALESCE_ENABLED))

    # --- dynamic join strategy: promote to a single-build join ----------
    thr = conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    if bool(conf.get(C.ADAPTIVE_JOIN_STRATEGY_ENABLED)) \
            and join.join_type != "full" \
            and not getattr(join, "_adaptive_no_promote", False) \
            and thr is not None and int(thr) >= 0 \
            and rst.total_bytes <= int(thr):
        am.add(MN.NUM_JOIN_STRATEGY_CHANGES, 1)
        journal_event("replan", "promoteToBroadcast",
                      shuffle=rh.sid, build_bytes=rst.total_bytes,
                      threshold=int(thr))
        if coalesce_on:
            lspecs = coalesce_specs(n, [lbytes], [advisory])
            merged = n - len(lspecs)
            if merged:
                am.add(MN.NUM_COALESCED_PARTITIONS, merged)
        else:
            from .stats import identity_specs
            lspecs = identity_specs(n)
        left = TpuCoalescedShuffleReaderExec(lex, lspecs, kind="coalesced")
        right = TpuCoalescedShuffleReaderExec(
            rex, [CoalescedPartitionSpec(0, n)], kind="build")
        return TpuHashJoinExec(left, right, join.join_type,
                               join.left_keys, join.right_keys,
                               join.condition, join.schema,
                               join.using_drop)

    # --- paired skew split + coalesce -----------------------------------
    skew_on = bool(conf.get(C.ADAPTIVE_SKEW_ENABLED)) \
        and join.join_type in SKEW_SPLITTABLE_JOINS
    skewed: Set[int] = set()
    if skew_on:
        skewed = detect_skew(
            lbytes, float(conf.get(C.ADAPTIVE_SKEW_FACTOR)),
            int(conf.get(C.ADAPTIVE_SKEW_THRESHOLD)))
    build_bound = int(conf.get(C.PARTITIONED_JOIN_THRESHOLD))

    pairs: List[tuple] = []
    n_coal = 0
    n_skew = 0
    cur_start = None
    acc_comb = acc_build = 0

    def flush(end: int) -> None:
        nonlocal cur_start, n_coal
        if cur_start is None:
            return
        spec = CoalescedPartitionSpec(cur_start, end)
        pairs.append((spec, spec))
        n_coal += (end - cur_start) - 1
        cur_start = None

    for p in range(n):
        if p in skewed:
            slices = map_range_slices(lst.map_bytes_by_partition[p],
                                      advisory)
            if len(slices) > 1:
                flush(p)
                for mlo, mhi in slices:
                    pairs.append((PartialReducerPartitionSpec(p, mlo, mhi),
                                  CoalescedPartitionSpec(p, p + 1)))
                n_skew += len(slices) - 1
                journal_event("replan", "skewSplit", shuffle=lh.sid,
                              partition=p, slices=len(slices),
                              bytes=lbytes[p])
                continue
            # one map block holds the whole partition: unsplittable
        combined = lbytes[p] + rbytes[p]
        if cur_start is None:
            cur_start, acc_comb, acc_build = p, combined, rbytes[p]
        elif (not coalesce_on) or acc_comb + combined > advisory \
                or acc_build + rbytes[p] > build_bound:
            flush(p)
            cur_start, acc_comb, acc_build = p, combined, rbytes[p]
        else:
            acc_comb += combined
            acc_build += rbytes[p]
    flush(n)

    if not n_skew and is_identity([a for a, _ in pairs], n):
        return join  # nothing observed that the static plan got wrong

    if n_coal:
        am.add(MN.NUM_COALESCED_PARTITIONS, n_coal)
        journal_event("replan", "coalescePartitions", shuffle=lh.sid,
                      before=n, after=len(pairs), merged=n_coal)
    if n_skew:
        am.add(MN.NUM_SKEW_SPLITS, n_skew)
    kind = "skew" if n_skew else "coalesced"
    join.children = [
        TpuCoalescedShuffleReaderExec(lex, [a for a, _ in pairs], kind),
        TpuCoalescedShuffleReaderExec(rex, [b for _, b in pairs], kind)]
    return join


def replan_exchange(exch, ctx, adaptive_metrics):
    """Coalesce a standalone (non-join) exchange's reduce partitions;
    returns a reader over the merged ranges, or the exchange untouched.
    Contiguous merges preserve partition order, so RANGE exchanges (whose
    partition order IS the global sort order) stay correct."""
    from ..exec.shuffle_reader import TpuCoalescedShuffleReaderExec
    conf = ctx.conf
    if not bool(conf.get(C.ADAPTIVE_COALESCE_ENABLED)):
        return exch
    h = exch._handle
    st = h.stats()
    advisory = int(conf.get(C.ADAPTIVE_ADVISORY_PARTITION_SIZE))
    specs = coalesce_specs(h.num_partitions, [st.bytes_by_partition],
                           [advisory])
    if is_identity(specs, h.num_partitions):
        return exch
    merged = h.num_partitions - len(specs)
    adaptive_metrics.add(MN.NUM_COALESCED_PARTITIONS, merged)
    journal_event("replan", "coalescePartitions", shuffle=h.sid,
                  before=h.num_partitions, after=len(specs), merged=merged)
    return TpuCoalescedShuffleReaderExec(exch, specs)


def demote_broadcast_join(join, ctx, adaptive_metrics):
    """TpuBroadcastHashJoinExec whose OBSERVED build side exceeds the
    broadcast threshold: replace with a partitioned join fed by the
    already-collected build (never re-executes the build subtree).
    Threshold -1 (broadcast disabled) means the plan got here via an
    explicit hint — the user's choice stands."""
    from ..exec.broadcast import TpuBroadcastExchangeExec
    conf = ctx.conf
    if not bool(conf.get(C.ADAPTIVE_JOIN_STRATEGY_ENABLED)):
        return join
    thr = conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    if thr is None or int(thr) < 0:
        return join
    bx = join.children[1]
    if not isinstance(bx, TpuBroadcastExchangeExec):
        return join
    leaves, meta = bx.materialize_host(ctx)
    if meta.size_bytes <= int(thr):
        return join
    from ..exec.exchange import TpuShuffleExchangeExec
    from ..exec.join import TpuShuffledHashJoinExec
    from ..exec.shuffle_reader import TpuHostCollectedSource
    adaptive_metrics.add(MN.NUM_JOIN_STRATEGY_CHANGES, 1)
    journal_event("replan", "demoteBroadcastJoin",
                  observed_bytes=meta.size_bytes, threshold=int(thr))
    n = int(conf.get(C.SHUFFLE_PARTITIONS))
    src = TpuHostCollectedSource(bx.schema, leaves, meta)
    lex = TpuShuffleExchangeExec("hash", join.left_keys, n,
                                 join.children[0])
    rex = TpuShuffleExchangeExec("hash", join.right_keys, n, src)
    new = TpuShuffledHashJoinExec(lex, rex, join.join_type,
                                  join.left_keys, join.right_keys,
                                  join.condition, join.schema,
                                  join.using_drop)
    # the observed build is ALREADY past the broadcast threshold: without
    # this mark, the promote rule could read the (selection-aware, often
    # smaller) data-byte stats and flip the join straight back
    new._adaptive_no_promote = True
    return new
