"""Map-output statistics: the runtime numbers adaptive re-planning runs on.

Reference analogue: Spark's MapOutputStatistics / MapOutputTrackerMaster —
every shuffle map task reports per-reduce-partition output sizes, and AQE
(GpuCustomShuffleReaderExec's planning side) reads the aggregated view to
coalesce small partitions, split skewed ones, and re-pick join strategies.

Here the tracker lives on each `ShuffleEnv` (one per executor) and is
populated synchronously at `write_partition` time: the write path already
holds the sub-batch's host-known row count (shuffle/partition.py
split_by_partition stamps it), so recording costs two dict updates — no
device sync.  Cluster-wide aggregation merges per-executor snapshots:
in-process for `plugin.TpuCluster`, over the control RPC
(`rpc_map_output_stats`, alongside `rpc_pool_stats`) for
`cluster.ProcCluster`.

Partition specs (the reduce-side re-planning vocabulary, Spark's
ShufflePartitionSpec family) also live here so exec/ and adaptive/ can
share them without import cycles:

  * `CoalescedPartitionSpec(start, end)` — serve reduce partitions
    [start, end) as ONE coalesced batch;
  * `PartialReducerPartitionSpec(reduce_id, map_lo, map_hi)` — serve only
    the blocks of `reduce_id` written by map tasks in [map_lo, map_hi)
    (a skew slice; the other join side replicates the full partition).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class CoalescedPartitionSpec:
    """Reduce partitions [start, end) read as one coalesced batch."""
    start: int
    end: int

    def units(self) -> List[Tuple[int, Optional[Tuple[int, int]]]]:
        return [(p, None) for p in range(self.start, self.end)]

    def describe(self) -> str:
        if self.end == self.start + 1:
            return str(self.start)
        return f"{self.start}..{self.end - 1}"


@dataclass(frozen=True)
class PartialReducerPartitionSpec:
    """One reduce partition restricted to map ids [map_lo, map_hi) — a
    skew-join slice of the stream side."""
    reduce_id: int
    map_lo: int
    map_hi: int

    def units(self) -> List[Tuple[int, Optional[Tuple[int, int]]]]:
        return [(self.reduce_id, (self.map_lo, self.map_hi))]

    def describe(self) -> str:
        return f"{self.reduce_id}[m{self.map_lo}:m{self.map_hi}]"


def identity_specs(n: int) -> List[CoalescedPartitionSpec]:
    """The no-op re-plan: one spec per reduce partition."""
    return [CoalescedPartitionSpec(p, p + 1) for p in range(n)]


def is_identity(specs, n: int) -> bool:
    return (len(specs) == n
            and all(isinstance(s, CoalescedPartitionSpec)
                    and s.start == i and s.end == i + 1
                    for i, s in enumerate(specs)))


class MapOutputStatistics:
    """Aggregated per-reduce-partition sizes of one materialized shuffle."""

    __slots__ = ("shuffle_id", "num_partitions", "bytes_by_partition",
                 "rows_by_partition", "map_bytes_by_partition",
                 "num_map_tasks")

    def __init__(self, shuffle_id: int, num_partitions: int):
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        self.bytes_by_partition = [0] * num_partitions
        self.rows_by_partition = [0] * num_partitions
        # per-partition {map_id: bytes} — what the skew rule slices on
        self.map_bytes_by_partition: List[Dict[int, int]] = \
            [dict() for _ in range(num_partitions)]
        self.num_map_tasks = 0

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes_by_partition)

    @property
    def total_rows(self) -> int:
        return sum(self.rows_by_partition)

    def merge_snapshot(self, snap: dict) -> None:
        """Fold one executor's tracker snapshot (see
        MapOutputTracker.snapshot) into this aggregate."""
        maps_seen = set()
        for rid_s, rec in snap.items():
            rid = int(rid_s)
            if not 0 <= rid < self.num_partitions:
                continue  # stale/foreign record; never index out of range
            self.bytes_by_partition[rid] += int(rec["bytes"])
            self.rows_by_partition[rid] += int(rec["rows"])
            per_map = self.map_bytes_by_partition[rid]
            for mid_s, b in rec["maps"].items():
                mid = int(mid_s)
                per_map[mid] = per_map.get(mid, 0) + int(b)
                maps_seen.add(mid)
        if maps_seen:
            self.num_map_tasks = max(self.num_map_tasks,
                                     max(maps_seen) + 1)


class MapOutputTracker:
    """Per-executor record of map-output sizes, keyed by shuffle id.

    `remove_shuffle` MUST be called when the shuffle's buffers are dropped
    (ShuffleEnv.remove_shuffle does) or statistics accumulate forever in a
    long-lived session — the regression tests pin this down."""

    def __init__(self):
        self._by_shuffle: Dict[int, Dict[int, dict]] = {}
        self._lock = threading.Lock()
        # bumped whenever previously-recorded map output is invalidated
        # (lost to corruption / a dead peer).  Stats consumers (the
        # exchange's _ShuffleHandle cache) compare epochs so AQE re-plan
        # rules never act on statistics from a dead map stage.
        self._epoch = 0

    def record(self, shuffle_id: int, map_id: int, reduce_id: int,
               nbytes: int, nrows: int) -> None:
        with self._lock:
            shuffle = self._by_shuffle.setdefault(shuffle_id, {})
            rec = shuffle.get(reduce_id)
            if rec is None:
                rec = shuffle[reduce_id] = \
                    {"bytes": 0, "rows": 0, "maps": {}}
            rec["bytes"] += int(nbytes)
            rec["rows"] += int(nrows)
            rec["maps"][map_id] = rec["maps"].get(map_id, 0) + int(nbytes)
            # per-map ROWS ride along internally (not in the snapshot
            # wire shape) so mark_lost/remove_map_range keep the row
            # totals exact, not just the byte totals
            rows = rec.setdefault("map_rows", {})
            rows[map_id] = rows.get(map_id, 0) + int(nrows)

    def snapshot(self, shuffle_id: int) -> dict:
        """JSON-safe {reduce_id: {bytes, rows, maps:{map_id: bytes}}} —
        the payload `rpc_map_output_stats` ships driver-ward."""
        with self._lock:
            shuffle = self._by_shuffle.get(shuffle_id, {})
            return {rid: {"bytes": rec["bytes"], "rows": rec["rows"],
                          "maps": dict(rec["maps"])}
                    for rid, rec in shuffle.items()}

    def stats(self, shuffle_id: int,
              num_partitions: int) -> MapOutputStatistics:
        st = MapOutputStatistics(shuffle_id, num_partitions)
        st.merge_snapshot(self.snapshot(shuffle_id))
        return st

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def bump_epoch(self) -> int:
        """Invalidate every captured statistics view (cheap: consumers
        re-aggregate lazily on their next stats() read)."""
        with self._lock:
            self._epoch += 1
            return self._epoch

    def mark_lost(self, shuffle_id: int,
                  map_id: Optional[int] = None) -> None:
        """Drop the records of a lost map output (one map task's, or the
        whole shuffle's) and bump the epoch: the recompute repopulates
        them via `record`, and stale AQE stats can never be read in
        between."""
        with self._lock:
            shuffle = self._by_shuffle.get(shuffle_id)
            if shuffle is not None:
                if map_id is None:
                    self._by_shuffle.pop(shuffle_id, None)
                else:
                    for rec in shuffle.values():
                        dropped = rec["maps"].pop(map_id, None)
                        if dropped is not None:
                            rec["bytes"] -= int(dropped)
                        rows = rec.get("map_rows", {}).pop(map_id, None)
                        if rows is not None:
                            rec["rows"] -= int(rows)
            self._epoch += 1

    def remove_map_range(self, shuffle_id: int, map_lo: int,
                         map_hi: int) -> None:
        """Drop the records of every map id in [map_lo, map_hi) — the
        statistics half of the attempt-id guard (ShuffleBufferCatalog
        .remove_map_range): a superseded attempt's bytes must not stay in
        the AQE view the winner's re-record will add to.  Bumps the epoch
        once when anything was dropped (same contract as mark_lost)."""
        with self._lock:
            shuffle = self._by_shuffle.get(shuffle_id)
            dropped_any = False
            if shuffle is not None:
                for rec in shuffle.values():
                    for mid in [m for m in rec["maps"]
                                if map_lo <= m < map_hi]:
                        rec["bytes"] -= int(rec["maps"].pop(mid))
                        rows = rec.get("map_rows", {}).pop(mid, None)
                        if rows is not None:
                            rec["rows"] -= int(rows)
                        dropped_any = True
            if dropped_any:
                self._epoch += 1

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._by_shuffle.pop(shuffle_id, None)

    def tracked_shuffles(self) -> List[int]:
        with self._lock:
            return sorted(self._by_shuffle)


def merge_cluster_stats(shuffle_id: int, num_partitions: int,
                        snapshots) -> MapOutputStatistics:
    """Aggregate per-executor snapshots into one cluster-wide view (the
    MapOutputTrackerMaster step)."""
    st = MapOutputStatistics(shuffle_id, num_partitions)
    for snap in snapshots:
        st.merge_snapshot(snap or {})
    return st
