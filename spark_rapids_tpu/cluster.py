"""Multi-process cluster driver: spawn executor workers, ship plan
fragments, run distributed map/shuffle/reduce over the socket wire.

This is the PROCESS-level deployment of the shuffle stack — the analogue
of a Spark cluster running the reference's UCX shuffle
(shuffle-plugin/.../RapidsShuffleInternalManager.scala + UCX transport):
`ProcCluster` spawns N worker processes (shuffle/worker.py), each with its
own runtime + ShuffleEnv + SocketTransport server; the driver distributes
the peer address map (the management handshake), sends map fragments to
every worker, assigns reduce partitions round-robin, and concatenates the
arrow IPC results.  Shuffle bytes cross real process boundaries over TCP;
on a TPU pod the same wire is the DCN path between hosts while ICI
collectives handle the in-mesh exchange (shuffle/ici.py).

In-process `plugin.TpuCluster` remains the single-interpreter deployment
for tests and one-host runs; `ProcCluster` is its multi-process twin.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import random
import re
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from .config import TpuConf
from .metrics.journal import journal_event
from .metrics.registry import count_swallowed

log = logging.getLogger("spark_rapids_tpu.cluster")


class HeartbeatMonitor:
    """Driver-side live progress: polls every worker's `rpc_heartbeat`
    on an interval over DEDICATED SocketClients — a long-running task rpc
    holds its own client's lock for the whole call, so liveness must ride
    separate sockets (the worker server threads answer concurrently).

    What one heartbeat buys:
      * progress: monotonic cluster totals (tasks completed, rows
        written, wire bytes) accumulated restart-aware, surfaced as
        `cluster.progress()` / `session.progress()`;
      * liveness: per-worker heartbeat lag (`heartbeatLag`) + missed-poll
        counting (`numMissedHeartbeats`);
      * the hung-task watchdog: a task active past
        `spark.rapids.sql.tpu.trace.hungTaskTimeoutMs` in successive
        snapshots is logged once and counted (`numHungTasks`);
      * clock probes: every round trip is an NTP-style sample
        (local-before, worker wall, local-after) feeding the merged
        timeline's per-worker offset estimation (metrics/timeline.py).
    """

    def __init__(self, cluster: "ProcCluster", interval_s: float,
                 hung_timeout_s: float):
        self.cluster = cluster
        self.interval_s = max(float(interval_s), 0.05)
        self.hung_timeout_s = float(hung_timeout_s)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._clients: Dict[str, tuple] = {}
        self.latest: Dict[str, dict] = {}
        self.last_ok_mono: Dict[str, float] = {}
        self.clock_probes: Dict[str, deque] = {}
        self._last_seen: Dict[str, dict] = {}
        self._warned_hung = set()
        self.started_mono = time.monotonic()
        self.missed_heartbeats = 0
        self.hung_tasks = 0
        self.max_lag_s = 0.0
        # watchdog hook: called with each newly-flagged hung task's
        # snapshot AFTER the monitor lock is released (_ingest) — the
        # post-mortem trigger behind it does rpc sweeps and must never
        # run under (or deadlock against) the monitor's own lock
        self.on_hung = None
        self.totals = {"heartbeats": 0, "tasks_completed": 0,
                       "tasks_failed": 0, "rows_written": 0,
                       "wire_bytes": 0}
        # per-executor memory high-waters from heartbeat pool stats,
        # accumulated max-monotonic across restarts: a replaced worker's
        # reset peaks never regress the cluster roll-up (same contract
        # as the monotonic counter totals above)
        self._peak_seen: Dict[str, Dict[str, int]] = {}
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="heartbeat-monitor")
        self._thread.start()

    # -- polling -------------------------------------------------------------

    def _client_for(self, worker):
        from .shuffle.net import SocketClient
        addr = tuple(worker.address)
        stale = None
        with self._lock:
            if self._stop.is_set():
                # stop() already closed + cleared the clients; never
                # re-create one behind its back (fd leak on shutdown)
                return None
            cur = self._clients.get(worker.executor_id)
            if cur is not None and cur[0] == addr:
                return cur[1]
            stale = cur[1] if cur is not None else None
            # inject_faults=False: liveness polls must not consume the
            # deterministic net-fault ordinals a test armed for the data
            # plane.  The connect bound mirrors the poll's rpc timeout —
            # one blackholed worker must not starve the other workers'
            # heartbeats behind the transport's 30s data-plane default.
            client = SocketClient(self.cluster._transport, addr,
                                  inject_faults=False,
                                  connect_timeout=max(
                                      self.interval_s * 2, 2.0))
            self._clients[worker.executor_id] = (addr, client)
        if stale is not None:
            stale.close()  # worker was replaced on a new port
        return client

    def poll_once(self) -> None:
        for worker in list(self.cluster.workers):
            if self._stop.is_set():
                return
            try:
                client = self._client_for(worker)
                if client is None:
                    return
                t0 = time.time_ns()
                hb = client.rpc(
                    "heartbeat",
                    _rpc_timeout=max(self.interval_s * 2, 2.0))
                t1 = time.time_ns()
            except Exception as e:  # noqa: BLE001 — liveness, not control
                with self._lock:
                    self.missed_heartbeats += 1
                    stale = self._clients.pop(worker.executor_id, None)
                if stale is not None:
                    try:
                        stale[1].close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass  # tpulint: disable=TPU006 closing an already-broken heartbeat client; the poll failure itself is logged+counted just below
                log.debug("heartbeat poll of %s failed: %r",
                          worker.executor_id, e)
                continue
            self._ingest(worker.executor_id, hb, t0, t1)

    def _ingest(self, executor: str, hb: dict, t0: int, t1: int) -> None:
        newly_hung: List[dict] = []
        with self._lock:
            self.latest[executor] = hb
            self.last_ok_mono[executor] = time.monotonic()
            self.clock_probes.setdefault(executor, deque(maxlen=64)) \
                .append((t0, hb.get("wall_ns", t0), t1))
            # restart-aware monotonic accumulation: a replaced worker's
            # counters reset to zero — its full new value is the delta,
            # so cluster totals NEVER go backwards (progress() contract)
            last = self._last_seen.get(executor)
            fresh = last is None or last.get("pid") != hb.get("pid")

            def delta(field, new):
                return new if fresh else max(0, new - last.get(field, 0))

            counters = hb.get("counters", {}) or {}
            wire = (int(counters.get("bytes_sent", 0))
                    + int(counters.get("bytes_received", 0)))
            self.totals["heartbeats"] += 1
            self.totals["tasks_completed"] += delta(
                "tasks_completed", int(hb.get("tasks_completed", 0)))
            self.totals["tasks_failed"] += delta(
                "tasks_failed", int(hb.get("tasks_failed", 0)))
            self.totals["rows_written"] += delta(
                "rows_written", int(hb.get("rows_written", 0)))
            self.totals["wire_bytes"] += delta("wire_bytes", wire)
            self._last_seen[executor] = {
                "pid": hb.get("pid"),
                "tasks_completed": int(hb.get("tasks_completed", 0)),
                "tasks_failed": int(hb.get("tasks_failed", 0)),
                "rows_written": int(hb.get("rows_written", 0)),
                "wire_bytes": wire}
            pool = hb.get("pool", {}) or {}
            peaks = self._peak_seen.setdefault(executor, {})
            for field in ("device_peak", "host_peak", "disk_peak"):
                v = int(pool.get(field, 0) or 0)
                if v > peaks.get(field, 0):
                    peaks[field] = v
            if self.hung_timeout_s > 0:
                for task in hb.get("active_tasks", []) or []:
                    if task.get("elapsed_s", 0) <= self.hung_timeout_s:
                        continue
                    key = (executor, hb.get("pid"), task.get("span"),
                           task.get("name"))
                    if key in self._warned_hung:
                        continue
                    self._warned_hung.add(key)
                    self.hung_tasks += 1
                    log.warning(
                        "hung-task watchdog: %s task %r (stage %s) "
                        "active for %.1fs (> %.1fs)", executor,
                        task.get("name"), task.get("stage"),
                        task.get("elapsed_s", 0), self.hung_timeout_s)
                    newly_hung.append(dict(task, executor=executor))
        # watchdog hook outside the lock: the post-mortem dump it
        # triggers sweeps rpcs and must not serialize the monitor
        if newly_hung and self.on_hung is not None:
            for info in newly_hung:
                try:
                    self.on_hung(info)
                except Exception as e:  # noqa: BLE001 — observability
                    count_swallowed(
                        "numPostmortemErrors", "spark_rapids_tpu.cluster",
                        "hung-task postmortem hook failed (%r)", e)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
                # fold the current per-worker lag into the heartbeatLag
                # high-water every sweep — an outage must register even
                # if nobody calls progress() while it lasts
                self.lag_s()
            except Exception:  # noqa: BLE001 — the monitor must survive
                log.debug("heartbeat poll sweep failed", exc_info=True)

    # -- surfaces ------------------------------------------------------------

    def lag_s(self) -> Dict[str, float]:
        """Seconds since each worker was last heard from (workers never
        heard from count from monitor start)."""
        now = time.monotonic()
        with self._lock:
            out = {w.executor_id:
                   now - self.last_ok_mono.get(w.executor_id,
                                               self.started_mono)
                   for w in self.cluster.workers}
            if out:
                self.max_lag_s = max(self.max_lag_s, max(out.values()))
        return out

    def probes(self) -> Dict[str, list]:
        with self._lock:
            return {ex: list(dq) for ex, dq in self.clock_probes.items()}

    def peak_memory(self) -> dict:
        """Cluster peak memory from heartbeat pool stats: per-executor
        restart-aware high-waters (max over every epoch of that executor
        id) plus the cluster sum per tier.  Monotonic: values never
        decrease over the monitor's lifetime."""
        with self._lock:
            per_worker = {ex: dict(p) for ex, p in self._peak_seen.items()}
        return {
            "per_worker": per_worker,
            **{f: sum(p.get(f, 0) for p in per_worker.values())
               for f in ("device_peak", "host_peak", "disk_peak")},
        }

    def progress(self) -> dict:
        lag = self.lag_s()
        with self._lock:
            active = [dict(t, executor=ex)
                      for ex, hb in self.latest.items()
                      for t in (hb.get("active_tasks") or [])]
            totals = dict(self.totals)
            out = {
                **totals,
                "workers": len(self.cluster.workers),
                "active_tasks": active,
                "heartbeat_lag_s": max(lag.values()) if lag else 0.0,
                "missed_heartbeats": self.missed_heartbeats,
                "hung_tasks": self.hung_tasks,
                # single monotonic figure for "is the query advancing?":
                # every component is a cluster-lifetime high-water total
                # of WORK (heartbeats deliberately excluded — a fully
                # hung cluster keeps answering polls, and liveness is
                # already surfaced as heartbeat_lag_s)
                "score": (totals["tasks_completed"]
                          + totals["rows_written"]
                          + totals["wire_bytes"]),
            }
        # cluster peak memory (restart-aware max roll-up of each worker's
        # pool_stats high-waters; peak_memory() takes the lock itself)
        out["peak_memory"] = self.peak_memory()
        return out

    def metrics(self) -> dict:
        """The lint-checked metric names this monitor owns
        (docs/monitoring.md): folded into observability rollups."""
        from .metrics import names as MN
        return {MN.HEARTBEAT_LAG: self.max_lag_s,
                MN.NUM_HUNG_TASKS: self.hung_tasks,
                MN.NUM_MISSED_HEARTBEATS: self.missed_heartbeats}

    def stop(self) -> None:
        self._stop.set()
        # let an in-flight poll finish (bounded by its rpc timeout) so it
        # cannot re-create clients after the close/clear below
        self._thread.join(timeout=5.0)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for _addr, client in clients:
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass  # tpulint: disable=TPU006 driver shutdown close of a possibly-dead control client; nothing actionable remains

# the control RPC flattens worker-side exceptions to strings; FetchFailed's
# repr deliberately carries this machine-parseable peer marker so the
# driver can identify WHICH peer served garbage even through two layers of
# wrapping (mem/integrity.FetchFailed.__repr__)
_FETCH_FAILED_RE = re.compile(r"FetchFailed\(peer='([^']+)'")


def _fetch_failed_peer(err: BaseException) -> Optional[str]:
    """Executor id of the peer a (possibly rpc-flattened) FetchFailed
    blames, scanning the exception chain; None when no FetchFailed is
    involved."""
    seen = set()
    e: Optional[BaseException] = err
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        peer = getattr(e, "peer", None)
        if peer is not None and type(e).__name__ == "FetchFailed":
            return str(peer)
        m = _FETCH_FAILED_RE.search(str(e))
        if m:
            return m.group(1)
        e = e.__cause__ or e.__context__
    return None


class WorkerProc:
    """One spawned executor worker and its control-plane client."""

    def __init__(self, executor_id: str, conf_env: str, cpu: bool,
                 ready_timeout: float):
        env = dict(os.environ)
        env["SPARK_RAPIDS_TPU_CONF"] = conf_env
        if cpu:
            env["SPARK_RAPIDS_TPU_WORKER_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        self.executor_id = executor_id
        self.cpu = cpu
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.shuffle.worker",
             "--executor-id", executor_id],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        self.address: Optional[tuple] = None
        self.http_port: Optional[int] = None
        # reader thread: readline() itself can block forever on a silently
        # hung worker (e.g. TPU backend bring-up stuck on the tunnel
        # lease), so the deadline must bound the WAIT, not line arrivals
        lines: List[str] = []
        cond = threading.Condition()

        def _pump():
            for ln in self.proc.stdout:
                with cond:
                    lines.append(ln)
                    cond.notify()
            with cond:
                lines.append("")  # EOF marker
                cond.notify()

        threading.Thread(target=_pump, daemon=True).start()
        deadline = time.time() + ready_timeout
        while self.address is None:
            with cond:
                while not lines:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"worker {executor_id} never became ready")
                    cond.wait(min(remaining, 5))
                line = lines.pop(0)
            if line == "":
                raise RuntimeError(
                    f"worker {executor_id} exited before announcing "
                    f"(rc={self.proc.poll()})")
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # library banner noise is normal; a FLOOD of it means the
                # worker is dying before it ever announces — keep each
                # skipped line visible at debug and counted
                count_swallowed("numWorkerStdoutNoise",
                                "spark_rapids_tpu.cluster",
                                "worker %s stdout noise before ready: %r",
                                executor_id, line[:200])
                continue
            if rec.get("ready"):
                self.address = (rec["host"], rec["port"])
                # telemetry endpoint, when the worker serves one
                # (metrics/http.py): /metrics, /healthz, /debug
                self.http_port = rec.get("http_port")
        self.client = None  # set by ProcCluster (needs its transport)

    def rpc(self, method: str, **kw):
        return self.client.rpc(method, **kw)

    def stop(self, grace_s: float = 10.0) -> None:
        try:
            self.rpc("shutdown")
        except Exception:  # noqa: BLE001 — already dead is fine
            pass  # tpulint: disable=TPU006 shutdown RPC to a worker that may already have exited; both outcomes are the goal state
        try:
            self.proc.stdin.close()  # workers also exit on stdin EOF
        except OSError:
            pass  # tpulint: disable=TPU006 stdin already closed means the EOF signal was already delivered
        deadline = time.time() + grace_s
        while self.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if self.proc.poll() is None:
            if self.cpu:
                self.proc.kill()
            # a device-attached worker is NEVER signalled: SIGKILLing a
            # TPU-attached process poisons the machine-wide tunnel lease
            # for 30+ minutes (bench.py's child-deadline design exists
            # for the same reason) — it exits on its own via the
            # shutdown event / stdin watcher


class ProcCluster:
    """N executor worker PROCESSES + a driver-side transport for control.

    Usage:
        cluster = ProcCluster(2, conf)
        table = cluster.run_map_reduce(map_plans, key_names, n_parts,
                                       reduce_plan)
        cluster.shutdown()
    """

    def __init__(self, n_workers: int, conf: Optional[dict] = None,
                 cpu: bool = True, ready_timeout: float = 120.0,
                 max_task_retries: int = 1, session=None):
        from .shuffle.net import SocketTransport
        self.conf = dict(conf or {})
        self._conf_env = json.dumps(self.conf)
        self._cpu = cpu
        self._ready_timeout = ready_timeout
        self.max_task_retries = max_task_retries
        self.workers: List[WorkerProc] = []
        try:
            for i in range(n_workers):
                self.workers.append(WorkerProc(f"exec-{i}", self._conf_env,
                                               cpu, ready_timeout))
        except Exception:
            self.shutdown()
            raise
        # driver-side transport: client factory only (no server)
        self._transport = SocketTransport()
        from . import config as C
        from .config import TpuConf
        tconf = TpuConf(self.conf)
        self._transport.configure(tconf)
        self._sid = 0
        self._lock = threading.Lock()
        self.task_retries = 0   # observability: recoveries this cluster
        self.lost_map_outputs = 0  # FetchFailed-driven recompute count
        # bumped on every worker replacement: statistics consumers
        # (exec/exchange._ShuffleHandle) treat a bump as "a map stage
        # died" and re-aggregate instead of re-planning on dead stats
        self.map_epoch = 0
        self._publish_peers()
        # distributed tracing + live heartbeats (docs/monitoring.md):
        # accumulated worker journal drains, straggler conf, and the
        # heartbeat monitor on its dedicated connections
        self.trace_enabled = bool(tconf.get(C.TRACE_ENABLED))
        self.straggler_factor = float(tconf.get(C.TRACE_STRAGGLER_FACTOR))
        # task deadlines / bounded retry / speculation (docs/tuning-guide
        # .md, Fault tolerance, speculation, and chaos testing)
        self._task_timeout_ms = int(tconf.get(C.TASK_TIMEOUT))
        self._hung_timeout_ms = int(tconf.get(C.TRACE_HUNG_TASK_TIMEOUT))
        self._task_backoff_s = int(tconf.get(C.TASK_RETRY_BACKOFF)) / 1e3
        self._task_backoff_cap_s = int(tconf.get(C.TASK_MAX_BACKOFF)) / 1e3
        self.speculation_enabled = bool(
            tconf.get(C.TASK_SPECULATION_ENABLED))
        self.max_worker_replacements = int(
            tconf.get(C.TASK_MAX_WORKER_REPLACEMENTS))
        self._replacements_used = 0  # reset per query (run_map_reduce)
        # deterministic jitter for the inter-wave backoff (never wall
        # clock: chaos rounds must replay identically under one seed)
        self._backoff_rng = random.Random("task-retry-backoff")
        self.speculative_tasks = 0
        self.speculation_wins = 0
        self.evicted_workers = 0
        self.abandoned_tasks = 0
        self.worker_shrinks = 0
        # accumulated shard drains, keyed (executor_id, shard pid) so a
        # replaced worker's restarted journal never aliases its
        # predecessor's span ids (drain_journals)
        self._drained: Dict[tuple, dict] = {}
        self._query_counter = 0
        # session attachment: session.progress() delegates here, and the
        # post-mortem triggers below reach the session's manager through
        # a weakref (the cluster must never keep a dead session alive)
        self._session_ref = None
        if session is not None:
            session._proc_cluster = self
            import weakref
            self._session_ref = weakref.ref(session)
        self.monitor: Optional[HeartbeatMonitor] = None
        interval_ms = int(tconf.get(C.TRACE_HEARTBEAT_INTERVAL))
        if self.trace_enabled and interval_ms > 0:
            self.monitor = HeartbeatMonitor(
                self, interval_ms / 1e3,
                int(tconf.get(C.TRACE_HUNG_TASK_TIMEOUT)) / 1e3)
            # hung-task watchdog -> post-mortem bundle: fired OFF the
            # monitor lock (see _ingest) and dumped asynchronously so a
            # multi-second rpc sweep never stalls the heartbeat loop
            self.monitor.on_hung = self._on_hung_task

    def _on_hung_task(self, info: dict) -> None:
        self._postmortem_trigger(
            "hung-task",
            error=RuntimeError(
                "hung-task watchdog: %s task %r active for %.1fs"
                % (info.get("executor"), info.get("name"),
                   info.get("elapsed_s", 0.0))),
            asynchronous=True)

    def _postmortem_trigger(self, reason: str, error=None,
                            asynchronous: bool = False) -> None:
        s = self._session_ref() if self._session_ref is not None else None
        pm = getattr(s, "_postmortem", None) if s is not None else None
        if pm is not None:
            pm.trigger(reason, error=error, asynchronous=asynchronous)

    def _publish_peers(self) -> None:
        # replace=True prunes peers that are GONE (a shrunk worker slot):
        # survivors must stop dialing the dead address on remote fetches
        peers = {w.executor_id: list(w.address) for w in self.workers}
        self._transport.set_peers(peers, replace=True)
        for w in self.workers:
            if w.client is None:
                w.client = self._transport.make_client(w.executor_id)
            try:
                w.rpc("set_peers", peers=peers, replace=True)
            except Exception as e:  # noqa: BLE001 — a peer that is ALSO
                # dead (multi-worker loss) gets replaced by its own
                # recovery iteration, which re-publishes to everyone;
                # failing the whole recovery on ITS broken socket would
                # burn the retry budget before the second replacement
                # happens.  But never SILENTLY: a survivor that missed a
                # replacement's address dials a dead port on its next
                # remote fetch, and without this log + counter that
                # failure mode is indistinguishable from a network fault.
                self._transport.count("peer_publish_failures")
                log.warning("peer-map publish to %s failed (it may still "
                            "hold stale addresses): %r", w.executor_id, e)

    def _replace_worker(self, i: int) -> "WorkerProc":
        """Executor-loss recovery (the Spark task-retry / lineage analogue:
        the logical map fragment IS the lineage, recomputed on a fresh
        worker).  Spawns a replacement under the SAME executor id, rewires
        every peer map, and returns it."""
        old = self.workers[i]
        try:
            old.stop(grace_s=1.0)
        except Exception:  # noqa: BLE001 — it is already gone
            pass  # tpulint: disable=TPU006 stopping the worker being REPLACED for unresponsiveness; its death is the point
        fresh = WorkerProc(old.executor_id, self._conf_env, self._cpu,
                           self._ready_timeout)
        self.workers[i] = fresh
        # the dead worker's client holds a broken socket; drop it and
        # re-point the peer map at the replacement BEFORE dialing
        self._transport.drop_client(old.executor_id)
        self._transport.set_peers(
            {fresh.executor_id: list(fresh.address)})
        fresh.client = self._transport.make_client(fresh.executor_id)
        self._publish_peers()
        self.task_retries += 1
        self.map_epoch += 1  # its old map outputs died with the process
        return fresh

    def _shrink_worker(self, i: int, cause: str) -> "WorkerProc":
        """Graceful degradation: remove a worker SLOT instead of failing
        the query — the replacement budget is exhausted or the spawn
        itself failed.  Task assignments re-balance onto the survivors
        (task i runs on workers[i % len(workers)]); the caller recomputes
        any map fragments the dead slot homed via on_replace.  Returns
        the adoptive survivor for the slot's tasks."""
        w = self.workers[i]
        if len(self.workers) <= 1:
            raise RuntimeError(
                f"cluster cannot shrink below one worker: last worker "
                f"{w.executor_id} lost ({cause}) and no replacement "
                f"could be spawned")
        try:
            w.stop(grace_s=0.5)
        except Exception:  # noqa: BLE001 — it is already gone
            pass  # tpulint: disable=TPU006 stopping the worker being shrunk away; its loss is already the subject
        del self.workers[i]
        self._transport.drop_client(w.executor_id)
        self._transport.count("worker_shrinks")
        self._count("worker_shrinks")
        self.map_epoch += 1  # its map outputs died with the slot
        self._publish_peers()  # prunes the dead address everywhere
        journal_event("spec", "clusterShrunk", executor=w.executor_id,
                      cause=cause, workers=len(self.workers))
        log.warning(
            "graceful degradation: worker %s shrunk away (%s); cluster "
            "re-balanced onto %d surviving worker(s)", w.executor_id,
            cause, len(self.workers))
        return self.workers[i % len(self.workers)]

    def _replace_or_shrink(self, worker: "WorkerProc",
                           cause: str) -> "WorkerProc":
        """Replace a lost/evicted worker, degrading to a cluster shrink
        when the per-query replacement budget is exhausted or the spawn
        fails.  Returns the worker now responsible for the slot (the
        replacement, or the adoptive survivor)."""
        i = next((k for k, w in enumerate(self.workers) if w is worker),
                 None)
        if i is None:
            # already replaced/shrunk (e.g. two tasks blamed one peer in
            # one wave): hand back the current holder of the executor id
            return next((w for w in self.workers
                         if w.executor_id == worker.executor_id),
                        self.workers[0])
        if self.max_worker_replacements < 0 \
                or self._replacements_used < self.max_worker_replacements:
            self._replacements_used += 1
            try:
                return self._replace_worker(i)
            except Exception as e:  # noqa: BLE001 — degrade, not fail
                log.error("replacement spawn for %s failed (%r); "
                          "degrading to a cluster shrink",
                          worker.executor_id, e)
                return self._shrink_worker(i, f"spawn_failed:{cause}")
        log.warning("worker replacement budget exhausted (%d used); "
                    "degrading to a cluster shrink",
                    self._replacements_used)
        return self._shrink_worker(i, f"budget_exhausted:{cause}")

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._sid += 1
            return self._sid

    # -- task scheduling: deadlines, retry with backoff, speculation ---------

    def _count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def _task_deadline_s(self) -> Optional[float]:
        """Per-attempt task rpc deadline: task.timeoutMs, derived as
        2 x trace.hungTaskTimeoutMs when unset (the watchdog WARNS at the
        hung bound; the scheduler ACTS at twice it, so a task flagged
        hung gets one watchdog interval of grace — and a test tuning the
        watchdog alone does not change scheduling).  None = unbounded."""
        if self._task_timeout_ms > 0:
            return self._task_timeout_ms / 1e3
        if self._hung_timeout_ms > 0:
            return 2 * self._hung_timeout_ms / 1e3
        return None

    def _task_rpc(self, worker: "WorkerProc", method: str, **kw):
        """Task rpc on a DEDICATED connection: a task that outlives its
        deadline (or a speculation loser grinding on) must never hold the
        worker's shared control client hostage — cleanup rpcs and later
        waves dial fresh."""
        from .shuffle.net import SocketClient
        client = SocketClient(self._transport, tuple(worker.address))
        try:
            return client.rpc(method, **kw)
        finally:
            client.close()

    def _probe_worker(self, worker: "WorkerProc") -> bool:
        """Health-probe a worker whose task crossed its deadline, over
        the heartbeat monitor's dedicated connection when the monitor is
        running (the probe must never queue behind the wedged task rpc),
        falling back to a FRESH dial when that fails — a stale monitor
        socket must not misclassify a live worker as dead (the hung-vs-
        dead attribution feeds numEvictedWorkers and the journal).
        True = the process answers (wedged-but-alive); False = dead."""
        try:
            if self.monitor is not None:
                client = self.monitor._client_for(worker)
                if client is not None:
                    client.rpc("heartbeat", _rpc_timeout=2.0)
                    return True
        except Exception:  # noqa: BLE001 — stale socket, not a verdict
            pass  # tpulint: disable=TPU006 a broken monitor client is inconclusive; the fresh-dial probe below delivers the verdict
        try:
            from .shuffle.net import SocketClient
            probe = SocketClient(self._transport, tuple(worker.address),
                                 inject_faults=False, connect_timeout=2.0)
            try:
                probe.rpc("ping", _rpc_timeout=2.0)
                return True
            finally:
                probe.close()
        except Exception:  # noqa: BLE001 — the probe's answer IS the info
            return False

    def _speculation_candidates_locked(self, tasks: Dict[int, dict],
                                       durations: List[float]):
        """Straggler detection over the running wave (caller holds the
        wave condition): tasks past stragglerFactor x the stage's median
        successful-attempt duration (or past the hung-task bound) with no
        copy yet.  Returns [(task, target worker, attempt id)] with the
        target chosen least-loaded among healthy workers."""
        if not self.speculation_enabled:
            return []
        med = sorted(durations)[len(durations) // 2] \
            if len(durations) >= 2 else None
        hung_s = self._hung_timeout_ms / 1e3 \
            if self._hung_timeout_ms > 0 else None
        if med is None and hung_s is None:
            return []
        now = time.monotonic()
        load: Dict[str, int] = {}
        for t in tasks.values():
            for a in t["attempts"]:
                if not a["done"]:
                    ex = a["worker"].executor_id
                    load[ex] = load.get(ex, 0) + 1
        out = []
        for i, t in sorted(tasks.items()):
            if t["resolved"] or len(t["attempts"]) != 1:
                continue  # already raced, or already settled
            a = t["attempts"][0]
            if a["done"]:
                continue
            elapsed = now - a["start"]
            # the 250ms floor keeps speculation out of millisecond-task
            # noise: a 60ms transient stall on a 20ms-median stage is not
            # a straggler worth a copy (and possibly an eviction)
            straggling = (med is not None and elapsed >= 0.25
                          and elapsed > self.straggler_factor * med)
            hung = hung_s is not None and elapsed > hung_s
            if not (straggling or hung):
                continue
            healthy = [w for w in self.workers
                       if w is not a["worker"] and w.proc.poll() is None]
            if not healthy:
                continue
            target = min(healthy,
                         key=lambda w: load.get(w.executor_id, 0))
            load[target.executor_id] = \
                load.get(target.executor_id, 0) + 1
            out.append((i, target, len(t["attempts"]) + 1))
        return out

    def _run_task_round(self, stage: str, indices, attempt, store,
                        durations: List[float], on_loser,
                        on_replace=None) -> Dict[int, tuple]:
        """One wave: launch every pending task on its assigned worker,
        speculate on stragglers, resolve first-result-wins, clean up
        losers.  Returns {task: (error, worker, all_failed_attempts)}
        for unresolved tasks."""
        cond = threading.Condition()
        tasks: Dict[int, dict] = {
            i: {"resolved": False, "stored": False, "winner": None,
                "attempts": []}
            for i in indices}

        def launch(i: int, worker: "WorkerProc", attempt_id: int) -> None:
            rec = {"id": attempt_id, "worker": worker, "done": False,
                   "ok": False, "out": None, "start": time.monotonic(),
                   "thread": None}

            def run():
                try:
                    res = attempt(i, worker=worker, attempt_id=attempt_id)
                    ok = True
                except Exception as e:  # noqa: BLE001 — classified below
                    res, ok = e, False
                dur = time.monotonic() - rec["start"]
                if not ok and isinstance(res, TimeoutError):
                    # the deadline cut this attempt off: abandoned, the
                    # wave moves on (worker health handled in recovery)
                    self._count("abandoned_tasks")
                    journal_event("spec", "taskAbandoned", stage=stage,
                                  task=i, attempt=attempt_id,
                                  executor=worker.executor_id,
                                  elapsed_s=round(dur, 3))
                first = False
                with cond:
                    rec["done"], rec["ok"], rec["out"] = True, ok, res
                    t = tasks[i]
                    if ok and not t["resolved"]:
                        t["resolved"], t["winner"] = True, rec
                        durations.append(dur)
                        first = True
                    cond.notify_all()
                if first:
                    store(i, res, worker=worker)
                    if attempt_id > 1:
                        self._count("speculation_wins")
                        journal_event("spec", "speculationWin",
                                      stage=stage, task=i,
                                      attempt=attempt_id,
                                      executor=worker.executor_id)
                    # `stored` gates the settle loop: the round must not
                    # return while the winner's result is still being
                    # written (results[i] would read None — silent row
                    # loss in the reduce concat)
                    with cond:
                        tasks[i]["stored"] = True
                        cond.notify_all()

            th = threading.Thread(target=run, daemon=True,  # tpulint: disable=TPU009 attempt threads journal spec recovery events on the DRIVING query's behalf by design (worker-side they land on the process shard; driver-side on the submitting query's journal)
                                  name=f"task-{stage}-{i}-a{attempt_id}")
            rec["thread"] = th
            with cond:
                tasks[i]["attempts"].append(rec)
            th.start()

        for i in indices:
            launch(i, self._task_worker(i), 1)

        while True:
            with cond:
                settled = all(
                    t["stored"] if t["resolved"]
                    else (t["attempts"] and all(a["done"]
                                                for a in t["attempts"]))
                    for t in tasks.values())
                to_spec = [] if settled else \
                    self._speculation_candidates_locked(tasks, durations)
                if settled:
                    break
                if not to_spec:
                    cond.wait(0.05)
            for i, target, attempt_id in to_spec:
                self._count("speculative_tasks")
                self._transport.count("task_retries_speculation")
                journal_event("spec", "speculativeLaunch", stage=stage,
                              task=i, attempt=attempt_id,
                              executor=target.executor_id)
                log.warning("%s task %d flagged as a straggler; "
                            "launching speculative copy on %s (attempt "
                            "%d)", stage, i, target.executor_id,
                            attempt_id)
                launch(i, target, attempt_id)

        # first result won; cancel/ignore the losers.  Side-effectful
        # stages (on_loser set: the map stage) must ERASE the losing
        # attempt's registrations before the reduce side can read a mix
        # of attempts — result-only stages just ignore late results.
        #
        # Cleanup is SURGICAL FIRST: the worker's per-fragment lock
        # serializes remove_map_range behind any still-running attempt
        # of that fragment, so a merely-late loser is waited out (within
        # the cleanup rpc's deadline) and cleaned without killing its
        # worker; only a cleanup that FAILS (worker wedged past the
        # bound, or dead) escalates to eviction inside on_loser
        # (process death is total cleanup).
        for i, t in sorted(tasks.items()):
            if t["winner"] is None or on_loser is None:
                continue
            for a in t["attempts"]:
                if a is t["winner"]:
                    continue
                a["thread"].join(2.0)  # grace: most losers settle fast
                w = a["worker"]
                if any(x is w for x in self.workers):
                    on_loser(i, w)

        errs: Dict[int, tuple] = {}
        for i, t in sorted(tasks.items()):
            if t["resolved"]:
                continue
            fails = [a for a in t["attempts"] if not a["ok"]]
            # prefer the error that names a blamable peer (FetchFailed);
            # EVERY failed attempt rides along so recovery can handle
            # the other attempts' workers too (a task whose original AND
            # speculative copy both wedged must evict both)
            pick = next((a for a in fails
                         if _fetch_failed_peer(a["out"]) is not None),
                        fails[0])
            errs[i] = (pick["out"], pick["worker"],
                       [(a["out"], a["worker"]) for a in fails])
        return errs

    def _task_worker(self, i: int) -> "WorkerProc":
        """Worker assigned to task i: 1:1 while the cluster is at full
        strength, re-balanced modulo the survivors after a shrink."""
        return self.workers[i % len(self.workers)]

    def _recover_task_failure(self, stage: str, i: int, err, worker,
                              handled: set, on_replace) -> str:
        """Classify one failed task and run its recovery.  Returns the
        retry CAUSE ('dead' | 'timeout' | 'fetch_failed' | 'other') for
        the per-cause transport counters."""
        def lost(w, label):
            if w.executor_id in handled:
                return
            handled.add(w.executor_id)
            if not any(x is w for x in self.workers):
                # already replaced/shrunk this wave (loser-cleanup
                # escalation, or two attempts naming one worker): its
                # fragments were recomputed then — replacing the
                # innocent fresh process again would be pure churn
                return
            new = self._replace_or_shrink(w, label)
            if on_replace is not None:
                on_replace(w.executor_id, new)

        if worker is not None and worker.proc.poll() is not None:
            lost(worker, "dead")
            return "dead"
        if isinstance(err, TimeoutError):
            # the attempt crossed its deadline: probe the worker over the
            # monitor's dedicated connection — a wedged-but-alive worker
            # is evicted exactly like a dead one (replace + lineage
            # recompute); a dead one just failed to be noticed yet
            present = worker is not None \
                and any(x is worker for x in self.workers)
            alive = present and self._probe_worker(worker)
            if alive and worker.executor_id not in handled:
                self._count("evicted_workers")
                journal_event("spec", "workerEvicted",
                              executor=worker.executor_id, stage=stage,
                              task=i, cause="hung")
                log.warning("%s task %d: worker %s wedged past the task "
                            "deadline (alive on probe); evicting it",
                            stage, i, worker.executor_id)
            if worker is not None:
                lost(worker, "hung" if alive else "dead")
            return "timeout"
        # typed FetchFailed escalation: the error names the peer whose
        # map output is lost (corrupt/gone), which may be a DIFFERENT
        # worker than the one whose task failed — and one whose process
        # is perfectly alive, just serving garbage.  Replace the blamed
        # peer and recompute ITS map fragments; the failing task re-runs
        # in the next wave.
        peer = _fetch_failed_peer(err)
        if peer is not None:
            if peer not in handled:
                self.lost_map_outputs += 1
                log.warning(
                    "%s task %d lost map output at %s; replacing it and "
                    "recomputing the fragment", stage, i, peer)
                pw = next((w for w in self.workers
                           if w.executor_id == peer), None)
                if pw is not None:
                    lost(pw, "fetch_failed")
                else:
                    # blamed peer already shrunk away: its fragments
                    # still need a new home for the retry to fetch from
                    handled.add(peer)
                    if on_replace is not None:
                        on_replace(peer, self._task_worker(i))
            return "fetch_failed"
        return "other"

    def _run_tasks_with_retry(self, stage: str, attempt, store,
                              on_replace=None, on_loser=None,
                              n_tasks: Optional[int] = None) -> None:
        """Run every task in parallel waves with per-attempt DEADLINES,
        speculative re-execution of stragglers, and bounded PER-TASK
        retry with jittered exponential backoff between waves.

        Contract with the callers (run_map_reduce builds these):
          attempt(i, worker=, attempt_id=) — run task i on `worker`;
          store(i, out, worker=)           — first (winning) result only;
          on_replace(executor_id, worker)  — map outputs homed on
              `executor_id` are gone; recompute them on `worker` (the
              logical plan is the lineage);
          on_loser(i, worker)              — a losing speculative copy of
              task i may have registered side effects on `worker`; erase
              them (attempt-id-guarded map-output registration).

        Recovery per failed task, classified and counted per cause
        (task_retries_* transport counters): a DEAD worker is replaced
        under the same executor id; an attempt past its deadline
        (task.timeoutMs, derived from trace.hungTaskTimeoutMs) is
        ABANDONED, its worker health-probed, and a wedged-but-alive
        worker EVICTED exactly like a dead one; a typed FetchFailed
        blames the peer whose map output is unservable and that peer is
        replaced even if alive.  When the per-query replacement budget
        (task.maxWorkerReplacements) is exhausted — or a spawn fails —
        the slot is SHRUNK and tasks re-balance onto the survivors
        instead of failing the query.  Failed waves back off
        (task.retryBackoffMs doubling to task.maxBackoffMs, jittered)
        instead of hammering a recovering peer."""
        n_tasks = len(self.workers) if n_tasks is None else n_tasks
        budget = {i: self.max_task_retries for i in range(n_tasks)}
        durations: List[float] = []
        pending = sorted(range(n_tasks))
        round_no = 0
        while pending:
            errs = self._run_task_round(stage, pending, attempt, store,
                                        durations, on_loser,
                                        on_replace=on_replace)
            if not errs:
                return
            round_no += 1
            for i in sorted(errs):
                if budget[i] <= 0:
                    exhausted = RuntimeError(
                        f"{stage} task {i} failed after "
                        f"{self.max_task_retries} retries")
                    exhausted.__cause__ = errs[i][0]
                    # first-failure diagnostics BEFORE the raise unwinds
                    # the wave: the dying stage's journals/rings are
                    # still warm, and the query-failure trigger upstream
                    # would only see the driver side of the story
                    self._postmortem_trigger("retry-exhausted",
                                             error=exhausted)
                    raise exhausted
                budget[i] -= 1
            handled: set = set()
            for i in sorted(errs):
                err, worker, all_fails = errs[i]
                cause = self._recover_task_failure(stage, i, err, worker,
                                                   handled, on_replace)
                self._transport.count(f"task_retries_{cause}")
                # the OTHER failed attempts' workers get the same
                # dead/wedged recovery (dedup'd through `handled`), but
                # the task's retry is counted once, under the primary
                # error's cause
                for e2, w2 in all_fails:
                    if w2 is worker:
                        continue
                    self._recover_task_failure(stage, i, e2, w2,
                                               handled, on_replace)
            if on_loser is not None:
                # side-effectful stage: erase every failed attempt's
                # possible partial registrations on SURVIVING workers
                # before the retry wave — the re-run may land on a
                # different worker (replacement, shrink re-balance), and
                # its own attempt-id guard only cleans the worker it
                # runs on.  The fragment lock serializes this behind a
                # still-writing server task; failures escalate to
                # eviction inside on_loser.
                for i in sorted(errs):
                    for _e2, w2 in errs[i][2]:
                        if any(x is w2 for x in self.workers):
                            on_loser(i, w2)
            pending = sorted(errs)
            if self._task_backoff_s > 0:
                raw = min(self._task_backoff_cap_s,
                          self._task_backoff_s * (2 ** (round_no - 1)))
                time.sleep(raw * (0.5 + self._backoff_rng.random() / 2))

    def run_map_reduce(self, map_plans: Sequence, key_names: List[str],
                       n_parts: int, reduce_plan,
                       trace_query: Optional[str] = None):
        """One full distributed stage:
          map_plans[i] — logical fragment worker i executes (its input
                         slice), hash-partitioned on key_names;
          reduce_plan  — logical fragment with a LogicalPlaceholder where
                         the fetched partition rows attach.
        Returns the concatenated arrow table of every partition's reduce
        output, plus map statuses.

        `trace_query` names the query in the distributed trace (defaults
        to a driver-unique id): every task rpc carries a {query, stage}
        trace context, so the merged timeline groups the map and reduce
        stages of ONE query across workers (metrics/timeline.py)."""
        import pyarrow as pa

        from .shuffle.catalog import MAP_ID_STRIDE
        n_tasks = len(map_plans)
        assert n_tasks == len(self.workers), \
            "one map fragment per worker"
        sid = self.new_shuffle_id()
        with self._lock:
            self._replacements_used = 0  # replacement budget is per query
        if trace_query is None:
            with self._lock:
                self._query_counter += 1
                trace_query = f"mr-{os.getpid()}-{self._query_counter}"
        map_trace = {"query": trace_query, "stage": f"s{sid}.map"}
        reduce_trace = {"query": trace_query, "stage": f"s{sid}.reduce"}
        map_stats: List[dict] = [None] * n_tasks
        # which executor each map FRAGMENT's outputs live on (a fragment
        # follows its winning attempt: speculation, shrink re-balancing
        # and lineage recomputes can all move it off its home slot)
        frag_home: Dict[int, str] = {}
        deadline_s = self._task_deadline_s()

        def _attempt_map(i: int, worker=None, attempt_id: int = 1) -> dict:
            w = worker if worker is not None else self._task_worker(i)
            return self._task_rpc(
                w, "run_map", sid=sid,
                plan_blob=pickle.dumps(map_plans[i]),
                key_names=list(key_names), n_parts=n_parts,
                trace=map_trace, map_id_base=i * MAP_ID_STRIDE,
                attempt=attempt_id, _rpc_timeout=deadline_s)

        def _store_map(i: int, out: dict, worker=None) -> None:
            map_stats[i] = out
            if worker is not None:
                frag_home[i] = worker.executor_id

        def _recompute_fragments(executor_id: str, worker) -> None:
            # map outputs homed on `executor_id` died with it (process
            # loss, eviction, or shrink): the map fragments (the logical
            # lineage) recompute on `worker` — during the map stage this
            # covers fragments a lost worker had already WON (its own
            # pending task just re-runs in the wave); during the reduce
            # stage it runs before failed reduce tasks retry their
            # fetches
            for i in sorted(frag_home):
                if frag_home[i] != executor_id:
                    continue
                map_stats[i] = _attempt_map(i, worker=worker)
                frag_home[i] = worker.executor_id

        def _cleanup_map_loser(i: int, worker) -> None:
            # a losing speculative map copy registered fragment i's
            # blocks on a worker that also (rightly) holds other state:
            # drop exactly that fragment's range.  If the surgical
            # cleanup fails the bit-for-bit invariant is at stake —
            # escalate to eviction (process death is total cleanup).
            # The wait bound is the TASK deadline (the fragment lock
            # serializes behind a still-running loser, and a loser that
            # legitimately runs long on a heavy stage must not get its
            # healthy worker killed over a hardcoded 30s).
            try:
                self._task_rpc(worker, "remove_map_range", sid=sid,
                               lo=i * MAP_ID_STRIDE,
                               hi=(i + 1) * MAP_ID_STRIDE,
                               _rpc_timeout=deadline_s or 30.0)
            except Exception as e:  # noqa: BLE001 — escalates, never silent
                log.warning("speculation-loser cleanup of task %d at %s "
                            "failed (%r); evicting the worker", i,
                            worker.executor_id, e)
                if any(x is worker for x in self.workers):
                    self._count("evicted_workers")
                    journal_event("spec", "workerEvicted",  # tpulint: disable=TPU011 reached through the on_loser callback parameter of _run_tasks_with_retry (closure indirection the call graph cannot resolve)
                                  executor=worker.executor_id,
                                  stage="map", task=i,
                                  cause="loser_cleanup_failed")
                    new = self._replace_or_shrink(worker,
                                                  "loser_cleanup_failed")
                    _recompute_fragments(worker.executor_id, new)

        self._run_tasks_with_retry("map", _attempt_map, _store_map,
                                   on_replace=_recompute_fragments,
                                   on_loser=_cleanup_map_loser,
                                   n_tasks=n_tasks)

        reduce_blob = pickle.dumps(reduce_plan)
        results: List[Optional[bytes]] = [None] * n_tasks

        def _attempt_reduce(i: int, worker=None,
                            attempt_id: int = 1) -> bytes:
            w = worker if worker is not None else self._task_worker(i)
            # partition ownership is keyed by TASK index (fixed at stage
            # entry), not worker count — a mid-stage shrink re-balances
            # workers without re-slicing the partition space
            parts = [p for p in range(n_parts) if p % n_tasks == i]
            return self._task_rpc(w, "run_reduce", sid=sid,
                                  partitions=parts, plan_blob=reduce_blob,
                                  trace=reduce_trace, attempt=attempt_id,
                                  _rpc_timeout=deadline_s)

        def _store_reduce(i: int, out, worker=None) -> None:
            results[i] = out

        self._run_tasks_with_retry(
            "reduce", _attempt_reduce, _store_reduce,
            # a replaced worker lost its map outputs with the process;
            # the map fragments (the lineage) recompute them first
            on_replace=_recompute_fragments, n_tasks=n_tasks)
        for w in self.workers:
            try:
                w.rpc("remove_shuffle", sid=sid)
            except Exception:  # noqa: BLE001 — cleanup best-effort
                pass  # tpulint: disable=TPU006 remove_shuffle on a worker that may have died; the shuffle dies with it either way

        tables = []
        for blob in results:
            if blob is None:
                continue
            with pa.ipc.open_stream(blob) as r:
                tables.append(r.read_all())
        if not tables:
            return pa.table({}), map_stats
        return pa.concat_tables(tables), map_stats

    def transport_counters(self) -> Dict[str, dict]:
        """Per-worker wire counters (bytes_sent/received, metadata round
        trips) — observability + test assertions that bytes really crossed
        process boundaries.  The extra 'driver' entry carries the
        DRIVER-side transport's counters: per-cause task retries
        (task_retries_dead/timeout/fetch_failed/speculation/other),
        worker_shrinks, peer_publish_failures."""
        out = {w.executor_id: w.rpc("transport_counters")
               for w in self.workers}
        out["driver"] = dict(self._transport.counters)
        return out

    def pool_stats(self) -> Dict[str, dict]:
        """Per-worker runtime pool/retry/spill stats over the control RPC
        (the cluster half of docs/monitoring.md's aggregation story)."""
        return {w.executor_id: w.rpc("pool_stats") for w in self.workers}

    def map_output_stats(self, sid: int, num_partitions: int):
        """Cluster-wide MapOutputStatistics for one shuffle, aggregated
        over the control RPC (rpc_map_output_stats, alongside
        rpc_pool_stats) — what adaptive re-planning reads after a
        distributed map stage."""
        from .adaptive.stats import merge_cluster_stats
        return merge_cluster_stats(
            sid, num_partitions,
            (w.rpc("map_output_stats", sid=sid) for w in self.workers))

    def observability_snapshot(self) -> Dict[str, dict]:
        """{executor_id: {"transport": ..., "pool": ...}} — one RPC sweep,
        also reachable via metrics.export.cluster_snapshot(cluster)."""
        from .metrics.export import cluster_snapshot
        return cluster_snapshot(self)

    # -- distributed tracing / live progress ---------------------------------

    def progress(self) -> dict:
        """Live, monotonically advancing progress snapshot (heartbeat
        totals + recovery counters).  The `score` field never decreases
        while work is happening — the serving tier's admission signal and
        what `session.progress()` surfaces."""
        if self.monitor is not None:
            out = self.monitor.progress()
        else:
            out = {"heartbeats": 0, "tasks_completed": 0,
                   "tasks_failed": 0, "rows_written": 0, "wire_bytes": 0,
                   "workers": len(self.workers), "active_tasks": [],
                   "heartbeat_lag_s": 0.0, "missed_heartbeats": 0,
                   "hung_tasks": 0, "score": 0,
                   "peak_memory": {"per_worker": {}, "device_peak": 0,
                                   "host_peak": 0, "disk_peak": 0}}
        out["task_retries"] = self.task_retries
        out["lost_map_outputs"] = self.lost_map_outputs
        with self._lock:
            out["speculative_tasks"] = self.speculative_tasks
            out["speculation_wins"] = self.speculation_wins
            out["evicted_workers"] = self.evicted_workers
            out["abandoned_tasks"] = self.abandoned_tasks
            out["worker_shrinks"] = self.worker_shrinks
        return out

    def recovery_metrics(self) -> dict:
        """The lint-checked metric names the task-recovery tier owns
        (docs/monitoring.md): folded into timeline_report()['metrics']
        and session_observability."""
        from .metrics import names as MN
        with self._lock:
            return {MN.NUM_SPECULATIVE_TASKS: self.speculative_tasks,
                    MN.NUM_SPECULATION_WINS: self.speculation_wins,
                    MN.NUM_EVICTED_WORKERS: self.evicted_workers,
                    MN.NUM_ABANDONED_TASKS: self.abandoned_tasks}

    def drain_journals(self) -> Dict[tuple, dict]:
        """Pull every worker's undrained trace-shard events
        (rpc_drain_journal) and fold them into the cluster-lifetime
        accumulation — repeated drains compose, a dead worker keeps its
        previously drained history.

        Accumulation is keyed per shard EPOCH (executor id + the anchor's
        pid): a replaced worker restarts its journal, so its span ids —
        and its wall-clock anchor — collide with the dead process's.
        Folding both under one label would re-pair old B records with new
        E records and mis-aim flow links; instead the replacement gets a
        suffixed timeline label (`exec-1#r2`) and its own anchor."""
        for w in self.workers:
            try:
                rec = w.rpc("drain_journal")
            except Exception as e:  # noqa: BLE001 — a dead worker keeps
                log.debug("journal drain of %s failed: %r",  # its history
                          w.executor_id, e)
                continue
            if not rec:
                continue
            ex = rec.get("executor_id", w.executor_id)
            pid = (rec.get("anchor") or {}).get("pid")
            key = (ex, pid)
            if key not in self._drained:
                n_epochs = sum(1 for (e2, _p) in self._drained
                               if e2 == ex)
                label = ex if n_epochs == 0 else f"{ex}#r{n_epochs + 1}"
                self._drained[key] = {"label": label, "anchor": None,
                                      "events": [], "dropped": 0}
            acc = self._drained[key]
            if rec.get("anchor"):
                acc["anchor"] = rec["anchor"]
            acc["events"].extend(rec.get("events") or [])
            # the shard's dropped counter is cumulative over ITS lifetime
            acc["dropped"] = int(rec.get("dropped") or 0)
        return self._drained

    def merged_timeline(self, extra_shards: Optional[List[dict]] = None):
        """Drain every worker shard and merge into ONE wall-clock-aligned
        Timeline, clock-corrected from the heartbeat monitor's probe
        samples.  `extra_shards` adds driver-side journals (e.g. the
        session's last query journal events under a 'driver' label)."""
        from .metrics.timeline import merge_shards
        self.drain_journals()
        shards = [dict(rec) for rec in self._drained.values()]
        shards.extend(extra_shards or [])
        probes = self.monitor.probes() if self.monitor is not None else None
        if probes:
            # probe samples are keyed by executor id; restarted shard
            # epochs carry suffixed labels (exec-1#r2) — hand each epoch
            # its executor's samples under its timeline label
            probes = dict(probes, **{
                rec["label"]: probes[ex]
                for (ex, _pid), rec in self._drained.items()
                if ex in probes})
        return merge_shards(shards, probes)

    def timeline_report(self) -> dict:
        """The merged timeline's analysis dict (critical path, per-task
        overlap, stragglers, flow links) at the configured straggler
        factor, plus the monitor's heartbeat metrics."""
        rep = self.merged_timeline().report(self.straggler_factor)
        if self.monitor is not None:
            rep["metrics"].update(self.monitor.metrics())
        rep["metrics"].update(self.recovery_metrics())
        return rep

    def shutdown(self) -> None:
        if getattr(self, "monitor", None) is not None:
            self.monitor.stop()
        for w in self.workers:
            w.stop()
        t = getattr(self, "_transport", None)
        if t is not None:
            t.shutdown()
