"""Multi-process cluster driver: spawn executor workers, ship plan
fragments, run distributed map/shuffle/reduce over the socket wire.

This is the PROCESS-level deployment of the shuffle stack — the analogue
of a Spark cluster running the reference's UCX shuffle
(shuffle-plugin/.../RapidsShuffleInternalManager.scala + UCX transport):
`ProcCluster` spawns N worker processes (shuffle/worker.py), each with its
own runtime + ShuffleEnv + SocketTransport server; the driver distributes
the peer address map (the management handshake), sends map fragments to
every worker, assigns reduce partitions round-robin, and concatenates the
arrow IPC results.  Shuffle bytes cross real process boundaries over TCP;
on a TPU pod the same wire is the DCN path between hosts while ICI
collectives handle the in-mesh exchange (shuffle/ici.py).

In-process `plugin.TpuCluster` remains the single-interpreter deployment
for tests and one-host runs; `ProcCluster` is its multi-process twin.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import re
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from .config import TpuConf

log = logging.getLogger("spark_rapids_tpu.cluster")

# the control RPC flattens worker-side exceptions to strings; FetchFailed's
# repr deliberately carries this machine-parseable peer marker so the
# driver can identify WHICH peer served garbage even through two layers of
# wrapping (mem/integrity.FetchFailed.__repr__)
_FETCH_FAILED_RE = re.compile(r"FetchFailed\(peer='([^']+)'")


def _fetch_failed_peer(err: BaseException) -> Optional[str]:
    """Executor id of the peer a (possibly rpc-flattened) FetchFailed
    blames, scanning the exception chain; None when no FetchFailed is
    involved."""
    seen = set()
    e: Optional[BaseException] = err
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        peer = getattr(e, "peer", None)
        if peer is not None and type(e).__name__ == "FetchFailed":
            return str(peer)
        m = _FETCH_FAILED_RE.search(str(e))
        if m:
            return m.group(1)
        e = e.__cause__ or e.__context__
    return None


class WorkerProc:
    """One spawned executor worker and its control-plane client."""

    def __init__(self, executor_id: str, conf_env: str, cpu: bool,
                 ready_timeout: float):
        env = dict(os.environ)
        env["SPARK_RAPIDS_TPU_CONF"] = conf_env
        if cpu:
            env["SPARK_RAPIDS_TPU_WORKER_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        self.executor_id = executor_id
        self.cpu = cpu
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.shuffle.worker",
             "--executor-id", executor_id],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        self.address: Optional[tuple] = None
        # reader thread: readline() itself can block forever on a silently
        # hung worker (e.g. TPU backend bring-up stuck on the tunnel
        # lease), so the deadline must bound the WAIT, not line arrivals
        lines: List[str] = []
        cond = threading.Condition()

        def _pump():
            for ln in self.proc.stdout:
                with cond:
                    lines.append(ln)
                    cond.notify()
            with cond:
                lines.append("")  # EOF marker
                cond.notify()

        threading.Thread(target=_pump, daemon=True).start()
        deadline = time.time() + ready_timeout
        while self.address is None:
            with cond:
                while not lines:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"worker {executor_id} never became ready")
                    cond.wait(min(remaining, 5))
                line = lines.pop(0)
            if line == "":
                raise RuntimeError(
                    f"worker {executor_id} exited before announcing "
                    f"(rc={self.proc.poll()})")
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # library banner noise
            if rec.get("ready"):
                self.address = (rec["host"], rec["port"])
        self.client = None  # set by ProcCluster (needs its transport)

    def rpc(self, method: str, **kw):
        return self.client.rpc(method, **kw)

    def stop(self, grace_s: float = 10.0) -> None:
        try:
            self.rpc("shutdown")
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        try:
            self.proc.stdin.close()  # workers also exit on stdin EOF
        except OSError:
            pass
        deadline = time.time() + grace_s
        while self.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if self.proc.poll() is None:
            if self.cpu:
                self.proc.kill()
            # a device-attached worker is NEVER signalled: SIGKILLing a
            # TPU-attached process poisons the machine-wide tunnel lease
            # for 30+ minutes (bench.py's child-deadline design exists
            # for the same reason) — it exits on its own via the
            # shutdown event / stdin watcher


class ProcCluster:
    """N executor worker PROCESSES + a driver-side transport for control.

    Usage:
        cluster = ProcCluster(2, conf)
        table = cluster.run_map_reduce(map_plans, key_names, n_parts,
                                       reduce_plan)
        cluster.shutdown()
    """

    def __init__(self, n_workers: int, conf: Optional[dict] = None,
                 cpu: bool = True, ready_timeout: float = 120.0,
                 max_task_retries: int = 1):
        from .shuffle.net import SocketTransport
        self.conf = dict(conf or {})
        self._conf_env = json.dumps(self.conf)
        self._cpu = cpu
        self._ready_timeout = ready_timeout
        self.max_task_retries = max_task_retries
        self.workers: List[WorkerProc] = []
        try:
            for i in range(n_workers):
                self.workers.append(WorkerProc(f"exec-{i}", self._conf_env,
                                               cpu, ready_timeout))
        except Exception:
            self.shutdown()
            raise
        # driver-side transport: client factory only (no server)
        self._transport = SocketTransport()
        from .config import TpuConf
        self._transport.configure(TpuConf(self.conf))
        self._sid = 0
        self._lock = threading.Lock()
        self.task_retries = 0   # observability: recoveries this cluster
        self.lost_map_outputs = 0  # FetchFailed-driven recompute count
        # bumped on every worker replacement: statistics consumers
        # (exec/exchange._ShuffleHandle) treat a bump as "a map stage
        # died" and re-aggregate instead of re-planning on dead stats
        self.map_epoch = 0
        self._publish_peers()

    def _publish_peers(self) -> None:
        peers = {w.executor_id: list(w.address) for w in self.workers}
        self._transport.set_peers(peers)
        for w in self.workers:
            if w.client is None:
                w.client = self._transport.make_client(w.executor_id)
            try:
                w.rpc("set_peers", peers=peers)
            except Exception as e:  # noqa: BLE001 — a peer that is ALSO
                # dead (multi-worker loss) gets replaced by its own
                # recovery iteration, which re-publishes to everyone;
                # failing the whole recovery on ITS broken socket would
                # burn the retry budget before the second replacement
                # happens.  But never SILENTLY: a survivor that missed a
                # replacement's address dials a dead port on its next
                # remote fetch, and without this log + counter that
                # failure mode is indistinguishable from a network fault.
                self._transport.count("peer_publish_failures")
                log.warning("peer-map publish to %s failed (it may still "
                            "hold stale addresses): %r", w.executor_id, e)

    def _replace_worker(self, i: int) -> "WorkerProc":
        """Executor-loss recovery (the Spark task-retry / lineage analogue:
        the logical map fragment IS the lineage, recomputed on a fresh
        worker).  Spawns a replacement under the SAME executor id, rewires
        every peer map, and returns it."""
        old = self.workers[i]
        try:
            old.stop(grace_s=1.0)
        except Exception:  # noqa: BLE001 — it is already gone
            pass
        fresh = WorkerProc(old.executor_id, self._conf_env, self._cpu,
                           self._ready_timeout)
        self.workers[i] = fresh
        # the dead worker's client holds a broken socket; drop it and
        # re-point the peer map at the replacement BEFORE dialing
        self._transport.drop_client(old.executor_id)
        self._transport.set_peers(
            {fresh.executor_id: list(fresh.address)})
        fresh.client = self._transport.make_client(fresh.executor_id)
        self._publish_peers()
        self.task_retries += 1
        self.map_epoch += 1  # its old map outputs died with the process
        return fresh

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._sid += 1
            return self._sid

    def _run_tasks_with_retry(self, stage: str, attempt, store,
                              on_replace=None) -> None:
        """Run task i on worker i for every worker, in parallel; on
        failure, recover and retry up to `max_task_retries` times.

        Recovery (Spark's task-retry + executor-loss handling, absorbed
        into one mechanism): a DEAD worker is replaced by a fresh process
        under the same executor id (peers rewired) and `on_replace(i)`
        regenerates whatever worker-local state the stage depends on (the
        reduce stage re-runs the lost map fragment — the logical plan is
        the lineage); a worker that is alive but errored (e.g. its fetch
        raced a peer's death) just re-runs its task after replacements
        settle.

        FetchFailed handling (data-integrity escalation): a reduce task
        that raises FetchFailed names the PEER whose map output is
        unservable — dead socket, vanished buffer, or persistently
        corrupt data.  That peer is replaced EVEN IF ITS PROCESS IS
        STILL ALIVE (a live executor serving garbage is as lost as a
        dead one) and its map fragment is recomputed from the lineage
        before the failed reduce task retries."""

        def wave(indices):
            errs = {}

            def one(i):
                try:
                    store(i, attempt(i))
                except Exception as e:  # noqa: BLE001 — retried/re-raised
                    errs[i] = e
            threads = [threading.Thread(target=one, args=(i,))
                       for i in indices]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return errs

        errs = wave(range(len(self.workers)))
        tries = 0
        while errs and tries < self.max_task_retries:
            tries += 1
            replaced = set()
            for i in sorted(errs):
                if self.workers[i].proc.poll() is not None:
                    if i not in replaced:
                        self._replace_worker(i)
                        replaced.add(i)
                        if on_replace is not None:
                            on_replace(i)
                    continue
                # typed FetchFailed escalation: the error names the peer
                # whose map output is lost (corrupt/gone), which may be a
                # DIFFERENT worker than the one whose task failed — and
                # one whose process is perfectly alive, just serving
                # garbage.  Replace the blamed peer and recompute ITS map
                # fragment; the failing task re-runs in the next wave.
                peer = _fetch_failed_peer(errs[i])
                if peer is not None:
                    j = next((k for k, w in enumerate(self.workers)
                              if w.executor_id == peer), None)
                    if j is not None and j not in replaced:
                        self.lost_map_outputs += 1
                        log.warning(
                            "%s task %d lost map output at %s; replacing "
                            "it and recomputing the fragment", stage, i,
                            peer)
                        self._replace_worker(j)
                        replaced.add(j)
                        if on_replace is not None:
                            on_replace(j)
            errs = wave(sorted(errs))
        if errs:
            i, e = next(iter(sorted(errs.items())))
            raise RuntimeError(
                f"{stage} task {i} failed after "
                f"{self.max_task_retries} retries") from e

    def run_map_reduce(self, map_plans: Sequence, key_names: List[str],
                       n_parts: int, reduce_plan):
        """One full distributed stage:
          map_plans[i] — logical fragment worker i executes (its input
                         slice), hash-partitioned on key_names;
          reduce_plan  — logical fragment with a LogicalPlaceholder where
                         the fetched partition rows attach.
        Returns the concatenated arrow table of every partition's reduce
        output, plus map statuses."""
        import pyarrow as pa
        assert len(map_plans) == len(self.workers), \
            "one map fragment per worker"
        sid = self.new_shuffle_id()
        map_stats: List[dict] = [None] * len(self.workers)

        def _attempt_map(i: int) -> dict:
            return self.workers[i].rpc(
                "run_map", sid=sid,
                plan_blob=pickle.dumps(map_plans[i]),
                key_names=list(key_names), n_parts=n_parts)

        self._run_tasks_with_retry(
            "map", _attempt_map,
            lambda i, out: map_stats.__setitem__(i, out))

        reduce_blob = pickle.dumps(reduce_plan)
        results: List[Optional[bytes]] = [None] * len(self.workers)

        def _attempt_reduce(i: int) -> bytes:
            parts = [p for p in range(n_parts)
                     if p % len(self.workers) == i]
            return self.workers[i].rpc("run_reduce", sid=sid,
                                       partitions=parts,
                                       plan_blob=reduce_blob)

        self._run_tasks_with_retry(
            "reduce", _attempt_reduce,
            lambda i, out: results.__setitem__(i, out),
            # a replaced worker lost its map outputs with the process;
            # the map fragment (the lineage) recomputes them first
            on_replace=lambda i: map_stats.__setitem__(i, _attempt_map(i)))
        for w in self.workers:
            try:
                w.rpc("remove_shuffle", sid=sid)
            except Exception:  # noqa: BLE001 — cleanup best-effort
                pass

        tables = []
        for blob in results:
            if blob is None:
                continue
            with pa.ipc.open_stream(blob) as r:
                tables.append(r.read_all())
        if not tables:
            return pa.table({}), map_stats
        return pa.concat_tables(tables), map_stats

    def transport_counters(self) -> Dict[str, dict]:
        """Per-worker wire counters (bytes_sent/received, metadata round
        trips) — observability + test assertions that bytes really crossed
        process boundaries."""
        return {w.executor_id: w.rpc("transport_counters")
                for w in self.workers}

    def pool_stats(self) -> Dict[str, dict]:
        """Per-worker runtime pool/retry/spill stats over the control RPC
        (the cluster half of docs/monitoring.md's aggregation story)."""
        return {w.executor_id: w.rpc("pool_stats") for w in self.workers}

    def map_output_stats(self, sid: int, num_partitions: int):
        """Cluster-wide MapOutputStatistics for one shuffle, aggregated
        over the control RPC (rpc_map_output_stats, alongside
        rpc_pool_stats) — what adaptive re-planning reads after a
        distributed map stage."""
        from .adaptive.stats import merge_cluster_stats
        return merge_cluster_stats(
            sid, num_partitions,
            (w.rpc("map_output_stats", sid=sid) for w in self.workers))

    def observability_snapshot(self) -> Dict[str, dict]:
        """{executor_id: {"transport": ..., "pool": ...}} — one RPC sweep,
        also reachable via metrics.export.cluster_snapshot(cluster)."""
        from .metrics.export import cluster_snapshot
        return cluster_snapshot(self)

    def shutdown(self) -> None:
        for w in self.workers:
            w.stop()
        t = getattr(self, "_transport", None)
        if t is not None:
            t.shutdown()
