"""Multi-process cluster driver: spawn executor workers, ship plan
fragments, run distributed map/shuffle/reduce over the socket wire.

This is the PROCESS-level deployment of the shuffle stack — the analogue
of a Spark cluster running the reference's UCX shuffle
(shuffle-plugin/.../RapidsShuffleInternalManager.scala + UCX transport):
`ProcCluster` spawns N worker processes (shuffle/worker.py), each with its
own runtime + ShuffleEnv + SocketTransport server; the driver distributes
the peer address map (the management handshake), sends map fragments to
every worker, assigns reduce partitions round-robin, and concatenates the
arrow IPC results.  Shuffle bytes cross real process boundaries over TCP;
on a TPU pod the same wire is the DCN path between hosts while ICI
collectives handle the in-mesh exchange (shuffle/ici.py).

In-process `plugin.TpuCluster` remains the single-interpreter deployment
for tests and one-host runs; `ProcCluster` is its multi-process twin.
"""
from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from .config import TpuConf


class WorkerProc:
    """One spawned executor worker and its control-plane client."""

    def __init__(self, executor_id: str, conf_env: str, cpu: bool,
                 ready_timeout: float):
        env = dict(os.environ)
        env["SPARK_RAPIDS_TPU_CONF"] = conf_env
        if cpu:
            env["SPARK_RAPIDS_TPU_WORKER_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        self.executor_id = executor_id
        self.cpu = cpu
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.shuffle.worker",
             "--executor-id", executor_id],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        self.address: Optional[tuple] = None
        # reader thread: readline() itself can block forever on a silently
        # hung worker (e.g. TPU backend bring-up stuck on the tunnel
        # lease), so the deadline must bound the WAIT, not line arrivals
        lines: List[str] = []
        cond = threading.Condition()

        def _pump():
            for ln in self.proc.stdout:
                with cond:
                    lines.append(ln)
                    cond.notify()
            with cond:
                lines.append("")  # EOF marker
                cond.notify()

        threading.Thread(target=_pump, daemon=True).start()
        deadline = time.time() + ready_timeout
        while self.address is None:
            with cond:
                while not lines:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"worker {executor_id} never became ready")
                    cond.wait(min(remaining, 5))
                line = lines.pop(0)
            if line == "":
                raise RuntimeError(
                    f"worker {executor_id} exited before announcing "
                    f"(rc={self.proc.poll()})")
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # library banner noise
            if rec.get("ready"):
                self.address = (rec["host"], rec["port"])
        self.client = None  # set by ProcCluster (needs its transport)

    def rpc(self, method: str, **kw):
        return self.client.rpc(method, **kw)

    def stop(self, grace_s: float = 10.0) -> None:
        try:
            self.rpc("shutdown")
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        try:
            self.proc.stdin.close()  # workers also exit on stdin EOF
        except OSError:
            pass
        deadline = time.time() + grace_s
        while self.proc.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if self.proc.poll() is None:
            if self.cpu:
                self.proc.kill()
            # a device-attached worker is NEVER signalled: SIGKILLing a
            # TPU-attached process poisons the machine-wide tunnel lease
            # for 30+ minutes (bench.py's child-deadline design exists
            # for the same reason) — it exits on its own via the
            # shutdown event / stdin watcher


class ProcCluster:
    """N executor worker PROCESSES + a driver-side transport for control.

    Usage:
        cluster = ProcCluster(2, conf)
        table = cluster.run_map_reduce(map_plans, key_names, n_parts,
                                       reduce_plan)
        cluster.shutdown()
    """

    def __init__(self, n_workers: int, conf: Optional[dict] = None,
                 cpu: bool = True, ready_timeout: float = 120.0):
        from .shuffle.net import SocketTransport
        self.conf = dict(conf or {})
        conf_env = json.dumps(self.conf)
        self.workers: List[WorkerProc] = []
        try:
            for i in range(n_workers):
                self.workers.append(WorkerProc(f"exec-{i}", conf_env, cpu,
                                               ready_timeout))
        except Exception:
            self.shutdown()
            raise
        # driver-side transport: client factory only (no server)
        self._transport = SocketTransport()
        peers = {w.executor_id: list(w.address) for w in self.workers}
        self._transport.set_peers(peers)
        for w in self.workers:
            w.client = self._transport.make_client(w.executor_id)
            w.rpc("set_peers", peers=peers)
        self._sid = 0
        self._lock = threading.Lock()

    def new_shuffle_id(self) -> int:
        with self._lock:
            self._sid += 1
            return self._sid

    def run_map_reduce(self, map_plans: Sequence, key_names: List[str],
                       n_parts: int, reduce_plan):
        """One full distributed stage:
          map_plans[i] — logical fragment worker i executes (its input
                         slice), hash-partitioned on key_names;
          reduce_plan  — logical fragment with a LogicalPlaceholder where
                         the fetched partition rows attach.
        Returns the concatenated arrow table of every partition's reduce
        output, plus map statuses."""
        import pyarrow as pa
        assert len(map_plans) == len(self.workers), \
            "one map fragment per worker"
        sid = self.new_shuffle_id()
        map_stats: List[dict] = [None] * len(self.workers)
        errors: List[Exception] = []

        def run_map(i: int, w: WorkerProc):
            try:
                map_stats[i] = w.rpc(
                    "run_map", sid=sid,
                    plan_blob=pickle.dumps(map_plans[i]),
                    key_names=list(key_names), n_parts=n_parts)
            except Exception as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [threading.Thread(target=run_map, args=(i, w))
                   for i, w in enumerate(self.workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

        reduce_blob = pickle.dumps(reduce_plan)
        results: List[Optional[bytes]] = [None] * len(self.workers)

        def run_reduce(i: int, w: WorkerProc):
            parts = [p for p in range(n_parts)
                     if p % len(self.workers) == i]
            try:
                results[i] = w.rpc("run_reduce", sid=sid,
                                   partitions=parts,
                                   plan_blob=reduce_blob)
            except Exception as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        threads = [threading.Thread(target=run_reduce, args=(i, w))
                   for i, w in enumerate(self.workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for w in self.workers:
            try:
                w.rpc("remove_shuffle", sid=sid)
            except Exception:  # noqa: BLE001 — cleanup best-effort
                pass
        if errors:
            raise errors[0]

        tables = []
        for blob in results:
            if blob is None:
                continue
            with pa.ipc.open_stream(blob) as r:
                tables.append(r.read_all())
        if not tables:
            return pa.table({}), map_stats
        return pa.concat_tables(tables), map_stats

    def transport_counters(self) -> Dict[str, dict]:
        """Per-worker wire counters (bytes_sent/received, metadata round
        trips) — observability + test assertions that bytes really crossed
        process boundaries."""
        return {w.executor_id: w.rpc("transport_counters")
                for w in self.workers}

    def shutdown(self) -> None:
        for w in self.workers:
            w.stop()
        t = getattr(self, "_transport", None)
        if t is not None:
            t.shutdown()
