from .column import Column, bucket_strlen
from .batch import ColumnarBatch, bucket_rows, concat_batches

__all__ = ["Column", "ColumnarBatch", "bucket_rows", "bucket_strlen",
           "concat_batches"]
