"""Columnar batches: struct-of-arrays with a STATIC bucketed capacity.

The TPU analogue of Spark's ColumnarBatch over GpuColumnVector
(reference: sql-plugin/src/main/java/.../GpuColumnVector.java batch<->Table
conversions).  Design differences, deliberately TPU-first:

  * capacity is rounded up to power-of-two buckets so every (plan, bucket)
    pair compiles exactly once under jit (XLA static shapes);
  * the live row set is a boolean `sel` mask instead of a compacted length —
    filters just AND into the mask and defer compaction to batch boundaries
    (coalesce/shuffle/materialize), where one gather pays for many operators;
  * the whole batch is a pytree, so operator pipelines take and return batches
    inside a single traced function.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (DataType, Schema, StructField, from_arrow, to_arrow,
                     StringType)
from .column import Column, bucket_strlen


def bucket_rows(n: int, minimum: int = 1024) -> int:
    """Round row count up to a power-of-two capacity bucket."""
    b = minimum
    while b < n:
        b <<= 1
    return b


@jax.tree_util.register_pytree_node_class
class ColumnarBatch:
    """columns + selection mask. `schema` and `capacity` are static."""

    # __weakref__: the donation-safety registry (mem/donation.py) pins
    # multi-owner batches in a WeakSet so pins die with the batch
    __slots__ = ("columns", "sel", "schema", "known_rows", "__weakref__")

    def __init__(self, columns: Sequence[Column], sel, schema: Schema):
        self.columns = tuple(columns)
        self.sel = sel
        self.schema = schema
        # host-known live-row count, when the producer already holds it
        # (scan chunk metadata, a join's fetched total): lets downstream
        # adaptive decisions (maybe_shrink) skip a device sync.  NOT part
        # of the pytree (values in the treedef would retrace per count);
        # any structural transform drops it back to None.
        self.known_rows = None

    def tree_flatten(self):
        return (self.columns, self.sel), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, sel = children
        return cls(columns, sel, schema)

    # ---- static metadata ---------------------------------------------------

    @property
    def capacity(self) -> int:
        return int(self.sel.shape[0])

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, i_or_name) -> Column:
        if isinstance(i_or_name, str):
            return self.columns[self.schema.index_of(i_or_name)]
        return self.columns[i_or_name]

    # ---- row-count (traced) ------------------------------------------------

    def num_rows(self):
        """Traced scalar count of live rows."""
        return jnp.sum(self.sel.astype(jnp.int32))

    def num_rows_host(self) -> int:
        if self.known_rows is not None:
            return self.known_rows
        return int(self.num_rows())

    def device_size_bytes(self) -> int:
        """Static upper bound on HBM footprint."""
        total = self.sel.size * 1
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.valid.size
            if c.lengths is not None:
                total += c.lengths.size * 4
        return total

    # ---- structural transforms (jit-safe) ----------------------------------

    def with_sel(self, sel) -> "ColumnarBatch":
        return ColumnarBatch(self.columns, sel, self.schema)

    def filter(self, keep) -> "ColumnarBatch":
        """AND a predicate into the selection mask — no data movement."""
        return self.with_sel(jnp.logical_and(self.sel, keep))

    def take(self, indices, sel=None) -> "ColumnarBatch":
        cols = [c.take(indices) for c in self.columns]
        if sel is None:
            sel = jnp.take(self.sel, indices, mode="clip")
        return ColumnarBatch(cols, sel, self.schema)

    def shrink_to(self, new_cap: int) -> "ColumnarBatch":
        """Live rows gathered (stably) into a SMALLER-capacity batch.

        The sort/aggregate kernels cost O(capacity log capacity) no
        matter how few rows are live — a selective filter or a grouped
        aggregate leaves a handful of live rows in an input-capacity
        batch, and sorting 8M dead rows to order 6 live ones dominated
        TPC-H q1 (measured ~7s of its 19s).  One cumsum + scatter + per-
        column gather; caller guarantees new_cap >= num_rows."""
        pos = jnp.cumsum(self.sel.astype(jnp.int32)) - 1
        iota = jnp.arange(self.capacity, dtype=jnp.int32)
        idx = jnp.zeros(new_cap, jnp.int32).at[
            jnp.where(self.sel, pos, new_cap)].set(iota, mode="drop")
        cols = [c.take(idx) for c in self.columns]
        sel2 = jnp.arange(new_cap, dtype=jnp.int32) < self.num_rows()
        return ColumnarBatch(cols, sel2, self.schema)

    def maybe_shrink(self, n_live: int) -> "ColumnarBatch":
        """shrink_to a bucket when mostly dead (>=8x oversized, which
        with bucket_rows' 1024 floor means capacity >= 8192); host caller
        passes the synced live count."""
        new_cap = bucket_rows(max(n_live, 1))
        if self.capacity >= 8 * new_cap:
            return self.shrink_to(new_cap)
        return self

    def compact(self) -> "ColumnarBatch":
        """Gather live rows to the front (stable).  Capacity unchanged.

        The permutation is a 1-bit packed-key sort (utils/packed_sort):
        jnp.argsort is a VARIADIC sort HLO (operand + iota) that costs
        ~6x a single-operand sort on the CPU/TPU sort path, and compact
        runs per batch in every concat/coalesce."""
        from ..utils import packed_sort as PS
        cap = self.capacity
        iota = jnp.arange(cap, dtype=jnp.int32)
        if PS.packed_enabled() and cap & (cap - 1) == 0:
            order = PS.packed_argsort([((~self.sel).astype(jnp.uint64), 1)],
                                      cap)
        else:
            # stable: live rows keep relative order, dead rows at the back
            order = jnp.argsort(jnp.where(self.sel, iota, cap + iota))
        n = self.num_rows()
        new_sel = iota < n
        return self.take(order, sel=new_sel)

    def select_columns(self, indices: Sequence[int],
                       schema: Optional[Schema] = None) -> "ColumnarBatch":
        cols = [self.columns[i] for i in indices]
        if schema is None:
            schema = Schema([self.schema[i] for i in indices])
        return ColumnarBatch(cols, self.sel, schema)

    # ---- host interop ------------------------------------------------------

    @staticmethod
    def from_pydict(data: dict, schema: Schema,
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity if capacity is not None else bucket_rows(max(n, 1))
        cols = []
        for f in schema:
            vals = data[f.name]
            if f.dtype.is_string:
                cols.append(Column.from_strings(vals, capacity=cap))
            else:
                valid = np.array([v is not None for v in vals], dtype=np.bool_)
                clean = np.array([0 if v is None else v for v in vals])
                cols.append(Column.from_numpy(clean, valid, f.dtype,
                                              capacity=cap))
        sel = jnp.arange(cap, dtype=jnp.int32) < n
        return ColumnarBatch(cols, sel, schema)

    @staticmethod
    def from_arrow(table, capacity: Optional[int] = None) -> "ColumnarBatch":
        """Build a device batch from a pyarrow Table (H2D transfer point)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        n = table.num_rows
        cap = capacity if capacity is not None else bucket_rows(max(n, 1))
        fields = []
        cols = []
        for name, col in zip(table.column_names, table.columns):
            at = col.type
            dt = from_arrow(at)
            fields.append(StructField(name, dt))
            arr = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
            if pa.types.is_dictionary(arr.type):
                arr = arr.dictionary_decode()
            if pa.types.is_decimal(arr.type):
                arr = pc.cast(arr, pa.float64())
            if dt.is_string:
                cols.append(Column.from_strings(arr.to_pylist(), capacity=cap))
                continue
            if pa.types.is_date32(arr.type):
                arr = arr.view(pa.int32())
            elif pa.types.is_timestamp(arr.type):
                arr = pc.cast(arr, pa.timestamp("us", tz="UTC")).view(pa.int64())
            elif pa.types.is_boolean(arr.type):
                arr = pc.cast(arr, pa.uint8())
            valid_np = np.ones(n, dtype=np.bool_)
            if arr.null_count:
                valid_np = np.asarray(arr.is_valid())
                arr = arr.fill_null(0)
            vals = arr.to_numpy(zero_copy_only=False)
            if dt.np_dtype == np.bool_:
                vals = vals.astype(np.bool_)
            cols.append(Column.from_numpy(vals, valid_np, dt, capacity=cap))
        sel = jnp.arange(cap, dtype=jnp.int32) < n
        return ColumnarBatch(cols, sel, Schema(fields))

    def _live_rows(self):
        """Selector of live rows for the D2H tail.

        Returns (rows, n) where rows is an int prefix length, a numpy
        index array, or a DEVICE int32 index array (bucket-padded).  The
        device form triggers a per-column device gather in _host_rows so
        only ~n rows ever cross to the host: a static-shape aggregate or
        sort emits its handful of result rows in an input-capacity batch,
        and materializing 8M-row buffers to read 6 rows dominated collect
        (measured 17.8s of TPC-H q1's 18.2s steady state).  Indices pad
        to a power-of-two bucket so gather compiles stay bounded."""
        sel_np = np.asarray(self.sel)
        n = int(sel_np.sum())
        dense = bool(sel_np[:n].all())
        if self.capacity >= 8 * bucket_rows(n):
            import jax.numpy as jnp
            idx = (np.arange(n, dtype=np.int32) if dense
                   else np.flatnonzero(sel_np).astype(np.int32))
            padded = np.zeros(bucket_rows(max(n, 1)), np.int32)
            padded[:n] = idx
            return jnp.asarray(padded), n
        if dense:
            return n, n
        return np.flatnonzero(sel_np), n

    def to_arrow(self):
        """D2H: convert live rows to a pyarrow Table (vectorized — one
        buffer-level conversion per column, no per-row Python loop)."""
        import pyarrow as pa
        rows, n = self._live_rows()
        arrays = [c.to_arrow(rows, to_arrow(f.dtype), n=n)
                  for f, c in zip(self.schema, self.columns)]
        return pa.table(arrays, names=self.schema.names)

    def to_pylist(self) -> List[tuple]:
        rows, n = self._live_rows()
        cols = [c.to_pylist(rows, n=n) for c in self.columns]
        return list(zip(*cols)) if cols else [()] * n

    def __repr__(self):  # pragma: no cover
        return (f"ColumnarBatch(cap={self.capacity}, "
                f"schema={self.schema!r})")


def _normalize_devices(batches: Sequence[ColumnarBatch]
                       ) -> Sequence[ColumnarBatch]:
    """Move single-device batches committed to DIFFERENT devices onto
    one device before eager concatenation: the mesh shuffle tier serves
    reduce partition p as device p's shard of the exchanged chunks
    (shuffle/mesh_exchange.py), so a coalesced read or a chunk staging
    that concatenates across partitions mixes committed devices — which
    eager dynamic_update_slice rejects.  device_put is jax's TRANSFER
    path (D2D over ICI on a real mesh; bit-exact, unlike cross-shard
    eager compute).  Mesh-SHARDED (multi-device) inputs are left
    untouched — re-placing a global array would gather it."""
    devs = []
    for b in batches:
        d = getattr(b.sel, "devices", None)
        devs.append(d() if callable(d) else None)
    if any(d is None or len(d) != 1 for d in devs):
        return batches  # tracers / host arrays / sharded globals
    if len(set().union(*devs)) <= 1:
        return batches  # already co-located (the common case)
    target = next(iter(devs[0]))
    return [b if devs[i] == {target} else jax.device_put(b, target)
            for i, b in enumerate(batches)]


def concat_batches(batches: Sequence[ColumnarBatch],
                   capacity: Optional[int] = None) -> ColumnarBatch:
    """Concatenate batches (the coalesce primitive; reference:
    GpuCoalesceBatches.scala concatenates via cudf Table.concatenate).

    Host-driven: capacities are static per input, result capacity is the
    bucket of the sum of capacities (or caller-provided)."""
    assert batches, "concat of nothing"
    schema = batches[0].schema
    batches = _normalize_devices(batches)
    compacted = [b.compact() for b in batches]
    counts = [b.num_rows_host() for b in compacted]
    total = sum(counts)
    cap = capacity if capacity is not None else bucket_rows(max(total, 1))
    out_cols = []
    for ci, f in enumerate(schema):
        parts = [b.columns[ci] for b in compacted]
        if f.dtype.is_string:
            ml = max(p.max_len for p in parts)
            parts = [p.pad_strings_to(ml) for p in parts]
            data = jnp.zeros((cap, ml), dtype=jnp.uint8)
            lengths = jnp.zeros(cap, dtype=jnp.int32)
            valid = jnp.zeros(cap, dtype=jnp.bool_)
            off = 0
            for p, cnt in zip(parts, counts):
                data = jax.lax.dynamic_update_slice(data, p.data[:cnt],
                                                    (off, 0))
                lengths = jax.lax.dynamic_update_slice(lengths,
                                                       p.lengths[:cnt], (off,))
                valid = jax.lax.dynamic_update_slice(valid, p.valid[:cnt],
                                                     (off,))
                off += cnt
            out_cols.append(Column(data, valid, f.dtype, lengths))
        else:
            data = jnp.zeros(cap, dtype=f.dtype.jnp_dtype)
            valid = jnp.zeros(cap, dtype=jnp.bool_)
            off = 0
            for p, cnt in zip(parts, counts):
                data = jax.lax.dynamic_update_slice(data, p.data[:cnt], (off,))
                valid = jax.lax.dynamic_update_slice(valid, p.valid[:cnt],
                                                     (off,))
                off += cnt
            out_cols.append(Column(data, valid, f.dtype))
    sel = jnp.arange(cap, dtype=jnp.int32) < total
    return ColumnarBatch(out_cols, sel, schema)
