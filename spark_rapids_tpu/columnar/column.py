"""Device column vectors.

The TPU analogue of the reference's GpuColumnVector
(reference: sql-plugin/src/main/java/.../GpuColumnVector.java) — but instead of
wrapping a cuDF buffer, a column IS a small pytree of jnp arrays so whole
operator pipelines can be traced into one XLA program:

  * data  : jnp array [capacity]           (numeric/bool/date/timestamp)
            or uint8 [capacity, max_len]   (strings, padded UTF-8 bytes)
  * valid : bool [capacity]                (null bitmap; True = non-null)
  * lengths : int32 [capacity]             (strings only)

`capacity` is a STATIC bucketed size (see batch.py); the actual row count of a
batch is tracked by the batch's row mask.  Null slots hold zeros so reductions
can mask without NaN poisoning.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BooleanType, DataType, DoubleType, StringType)


@jax.tree_util.register_pytree_node_class
class Column:
    """One device column. Registered as a pytree: `data`/`valid`/`lengths`
    are traced leaves, `dtype` is static."""

    __slots__ = ("data", "valid", "lengths", "dtype")

    def __init__(self, data, valid, dtype: DataType, lengths=None):
        self.data = data
        self.valid = valid
        self.dtype = dtype
        self.lengths = lengths

    def tree_flatten(self):
        if self.dtype.is_string:
            return (self.data, self.valid, self.lengths), self.dtype
        return (self.data, self.valid), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        if dtype.is_string:
            data, valid, lengths = children
            return cls(data, valid, dtype, lengths)
        data, valid = children
        return cls(data, valid, dtype)

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        assert self.dtype.is_string
        return self.data.shape[1]

    # ---- constructors ------------------------------------------------------

    @staticmethod
    def from_numpy(values: np.ndarray, valid: Optional[np.ndarray],
                   dtype: DataType, capacity: Optional[int] = None) -> "Column":
        """Build a (host-side) column from numpy, padding to `capacity`."""
        n = len(values)
        cap = capacity if capacity is not None else n
        assert cap >= n, (cap, n)
        if valid is None:
            valid = np.ones(n, dtype=np.bool_)
        vfull = np.zeros(cap, dtype=np.bool_)
        vfull[:n] = valid
        if dtype.is_string:
            raise ValueError("use Column.from_strings for string data")
        dfull = np.zeros(cap, dtype=dtype.np_dtype)
        arr = np.asarray(values, dtype=dtype.np_dtype)
        # zero out nulls so masked reductions are safe
        arr = np.where(valid, arr, np.zeros((), dtype=dtype.np_dtype))
        dfull[:n] = arr
        return Column(jnp.asarray(dfull), jnp.asarray(vfull), dtype)

    @staticmethod
    def from_strings(values, capacity: Optional[int] = None,
                     max_len: Optional[int] = None) -> "Column":
        """values: sequence of str | None."""
        n = len(values)
        cap = capacity if capacity is not None else n
        enc = [v.encode("utf-8") if v is not None else b"" for v in values]
        need = max((len(b) for b in enc), default=0)
        ml = max_len if max_len is not None else bucket_strlen(need)
        assert ml >= need, (ml, need)
        data = np.zeros((cap, ml), dtype=np.uint8)
        lengths = np.zeros(cap, dtype=np.int32)
        valid = np.zeros(cap, dtype=np.bool_)
        for i, (v, b) in enumerate(zip(values, enc)):
            if v is None:
                continue
            valid[i] = True
            lengths[i] = len(b)
            if b:
                data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        return Column(jnp.asarray(data), jnp.asarray(valid), StringType,
                      jnp.asarray(lengths))

    @staticmethod
    def all_null(dtype: DataType, capacity: int, max_len: int = 8) -> "Column":
        valid = jnp.zeros(capacity, dtype=jnp.bool_)
        if dtype.is_string:
            return Column(jnp.zeros((capacity, max_len), dtype=jnp.uint8),
                          valid, dtype, jnp.zeros(capacity, dtype=jnp.int32))
        return Column(jnp.zeros(capacity, dtype=dtype.jnp_dtype), valid, dtype)

    # ---- host materialization ---------------------------------------------

    def _host_rows(self, rows, n=None):
        """D2H the column, restricted to live rows.

        `rows` is an int n (prefix-dense: take [:n]), an np.ndarray of row
        indices (sparse selection), or a DEVICE index array (bucket-padded
        int32, see ColumnarBatch._live_rows): then the gather runs on
        device and only the compacted rows are materialized."""
        if not isinstance(rows, (int, np.ndarray)):
            import jax.numpy as jnp

            def pick(buf):
                return np.asarray(jnp.take(buf, rows, axis=0))[:n]
        else:
            def pick(buf):
                a = np.asarray(buf)
                return a[:rows] if isinstance(rows, int) else a[rows]
        valid = pick(self.valid)
        data = pick(self.data)
        lens = pick(self.lengths) if self.dtype.is_string else None
        return data, valid, lens

    def to_pylist(self, rows, n=None):
        """Materialize live rows as Python values (None=null).

        `rows`: int prefix length or index array (see _host_rows).
        Vectorized: one D2H per buffer, C-speed ndarray.tolist(), and a None
        splice only when nulls exist (no per-row .item() calls)."""
        data, valid, lens = self._host_rows(rows, n)
        n = len(valid)
        all_valid = bool(valid.all()) if n else True
        if self.dtype.is_string:
            lens = np.where(valid, lens, 0)
            ml = data.shape[1] if data.ndim == 2 else 0
            keep = np.arange(ml, dtype=np.int32)[None, :] < lens[:, None]
            flat = data[keep].tobytes()
            ends = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lens, out=ends[1:])
            out = [flat[ends[i]:ends[i + 1]].decode("utf-8", "replace")
                   for i in range(n)]
        else:
            out = data.tolist()
        if all_valid:
            return out
        return [v if ok else None for v, ok in zip(out, valid)]

    def to_arrow(self, rows, arrow_type=None, n=None):
        """Materialize live rows as a pyarrow Array.

        `rows`: int prefix length or index array (see _host_rows).
        Zero-copy-ish: numerics go numpy -> pa.array with a null mask;
        strings are rebuilt as a varbinary (offsets + flattened bytes)
        Arrow buffer triple — no per-row Python objects (reference contrast:
        GpuColumnarToRowExec copies D2H then iterates rows; here collect()
        and the writers consume whole Arrow columns)."""
        import pyarrow as pa
        from ..types import to_arrow as _to_arrow_type
        at = arrow_type if arrow_type is not None else _to_arrow_type(self.dtype)
        data, valid, lens = self._host_rows(rows, n)
        n = len(valid)
        if n == 0:
            return pa.nulls(0, type=at)
        valid = np.ascontiguousarray(valid)
        all_valid = bool(valid.all())
        if self.dtype.is_string:
            lens = np.where(valid, lens, 0).astype(np.int64)
            ml = data.shape[1] if data.ndim == 2 else 0
            keep = np.arange(ml, dtype=np.int32)[None, :] < lens[:, None]
            flat = np.ascontiguousarray(data[keep])
            offsets = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lens, out=offsets[1:])  # int64: no silent wrap at 2GiB
            validity = None if all_valid else pa.array(valid).buffers()[1]
            if offsets[-1] <= np.iinfo(np.int32).max:
                return pa.Array.from_buffers(
                    pa.utf8(), n,
                    [validity,
                     pa.py_buffer(offsets.astype(np.int32).tobytes()),
                     pa.py_buffer(flat.tobytes())])
            # >2GiB of string payload in one column: 64-bit offsets
            return pa.Array.from_buffers(
                pa.large_utf8(), n,
                [validity, pa.py_buffer(offsets.tobytes()),
                 pa.py_buffer(flat.tobytes())])
        vals = np.ascontiguousarray(data)
        mask = None if all_valid else ~valid
        return pa.array(vals, type=at, mask=mask)

    # ---- structural ops (all static-shape, jit-safe) -----------------------

    def take(self, indices) -> "Column":
        """Gather rows; indices out of range produce garbage rows the caller
        must mask."""
        if self.dtype.is_string:
            return Column(jnp.take(self.data, indices, axis=0,
                                   mode="clip"),
                          jnp.take(self.valid, indices, mode="clip"),
                          self.dtype,
                          jnp.take(self.lengths, indices, mode="clip"))
        return Column(jnp.take(self.data, indices, mode="clip"),
                      jnp.take(self.valid, indices, mode="clip"),
                      self.dtype)

    def with_valid(self, valid) -> "Column":
        return Column(self.data, valid, self.dtype, self.lengths)

    def mask_invalid(self) -> "Column":
        """Zero data in null slots (keeps reductions clean after ops that may
        have written garbage there)."""
        if self.dtype.is_string:
            lens = jnp.where(self.valid, self.lengths, 0)
            data = jnp.where(self.valid[:, None], self.data, 0)
            return Column(data, self.valid, self.dtype, lens)
        zero = jnp.zeros((), dtype=self.data.dtype)
        return Column(jnp.where(self.valid, self.data, zero), self.valid,
                      self.dtype)

    def pad_strings_to(self, max_len: int) -> "Column":
        assert self.dtype.is_string
        cur = self.max_len
        if cur == max_len:
            return self
        if cur < max_len:
            pad = jnp.zeros((self.capacity, max_len - cur), dtype=jnp.uint8)
            return Column(jnp.concatenate([self.data, pad], axis=1),
                          self.valid, self.dtype, self.lengths)
        raise ValueError(f"cannot shrink string column {cur} -> {max_len}")

    def __repr__(self):  # pragma: no cover
        return f"Column({self.dtype.name}, cap={self.capacity})"


def bucket_strlen(n: int, minimum: int = 8) -> int:
    """Round a string max-length up to a power-of-two bucket (static shapes)."""
    b = minimum
    while b < n:
        b <<= 1
    return b
