"""Contiguous-buffer batches: a whole ColumnarBatch as ONE device buffer.

Reference analogue: GpuColumnVectorFromBuffer / ContiguousTable
(sql-plugin/src/main/java/.../GpuColumnVectorFromBuffer.java:1-95,
rapids/MetaUtils.scala:41-137) — cuDF carves every column out of one device
allocation so a shuffle partition or spill unit is one transferable buffer.

The TPU version packs on device with a single compiled kernel: every leaf is
bit-reinterpreted to bytes and concatenated into one uint8 array.  What that
buys here is TRANSFER granularity, not allocator control (XLA owns device
memory): device->host moves one array instead of 3-4 leaves per column,
which matters when the host link is high-latency (tunneled dev TPUs) and for
the shuffle transport's bounce-buffer staging.

float64 on the axon TPU backend has no byte bitcast (it is an emulated
f32-pair); those leaves pack as the (hi, lo) f32 pair's bytes and unpack by
summation — exactly reversible for every value the device represents, the
same envelope as ops/hashing.f64_bits.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..types import Schema
from ..utils.kernel_cache import cached_kernel
from .batch import ColumnarBatch
from .column import Column


@dataclass
class LeafSlot:
    """Where one leaf lives inside the flat buffer."""
    offset: int
    nbytes: int
    shape: Tuple[int, ...]
    dtype: str          # logical jnp dtype of the leaf
    f64_pair: bool      # packed as (hi, lo) float32 pair


@dataclass
class ContiguousMeta:
    schema: Schema
    capacity: int
    slots: List[LeafSlot]           # per-column leaves, then sel last
    leaves_per_col: List[int]
    total_bytes: int


class ContiguousBatch:
    """One uint8 device buffer + reconstruction metadata."""

    __slots__ = ("buffer", "meta")

    def __init__(self, buffer, meta: ContiguousMeta):
        self.buffer = buffer
        self.meta = meta

    @property
    def nbytes(self) -> int:
        return self.meta.total_bytes


def _leaves_of(batch: ColumnarBatch):
    out = []
    per_col = []
    for c in batch.columns:
        ls = [c.data, c.valid] + ([c.lengths] if c.lengths is not None
                                  else [])
        out.extend(ls)
        per_col.append(len(ls))
    out.append(batch.sel)
    return out, per_col


def _to_bytes(x):
    """Device bit-reinterpret of one leaf to flat uint8; returns
    (byte_array, f64_pair_flag)."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint8).reshape(-1), False
    if x.dtype == jnp.float64 and jax.default_backend() != "cpu":
        hi = x.astype(jnp.float32)
        lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
        pair = jnp.stack([hi, lo], axis=-1)
        return jax.lax.bitcast_convert_type(pair, jnp.uint8).reshape(-1), \
            True
    return jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(-1), False


def _layout(batch: ColumnarBatch):
    """Static layout (shapes/dtypes only — no device work)."""
    leaves, per_col = _leaves_of(batch)
    slots: List[LeafSlot] = []
    off = 0
    for x in leaves:
        if x.dtype == jnp.bool_:
            nb = int(np.prod(x.shape, dtype=np.int64))
            pair = False
        elif x.dtype == jnp.float64 and jax.default_backend() != "cpu":
            nb = int(np.prod(x.shape, dtype=np.int64)) * 8
            pair = True
        else:
            nb = int(np.prod(x.shape, dtype=np.int64)) * x.dtype.itemsize
            pair = False
        slots.append(LeafSlot(off, nb, tuple(x.shape), str(x.dtype), pair))
        off += nb
    return leaves, per_col, slots, off


def _layout_key(batch: ColumnarBatch) -> tuple:
    leaves, _ = _leaves_of(batch)
    return tuple((str(x.dtype), tuple(x.shape)) for x in leaves)


def pack_batch(batch: ColumnarBatch) -> ContiguousBatch:
    """batch -> one uint8 device buffer (a single compiled concat per
    layout)."""
    leaves, per_col, slots, total = _layout(batch)

    def build():
        def k(ls):
            return jnp.concatenate([_to_bytes(x)[0] for x in ls])
        return k

    fn = cached_kernel(("contig_pack", _layout_key(batch)), build)
    buf = fn(leaves)
    meta = ContiguousMeta(batch.schema, batch.capacity, slots, per_col,
                          total)
    return ContiguousBatch(buf, meta)


def _from_bytes(raw, slot: LeafSlot):
    dt = np.dtype(slot.dtype)
    if dt == np.bool_:
        return raw.reshape(slot.shape).astype(jnp.bool_)
    if slot.f64_pair:
        pair = jax.lax.bitcast_convert_type(
            raw.reshape(slot.shape + (2, 4)), jnp.float32)
        hi = pair[..., 0].astype(jnp.float64)
        lo = pair[..., 1].astype(jnp.float64)
        return hi + lo
    if dt.itemsize == 1:
        return raw.reshape(slot.shape).astype(dt)
    return jax.lax.bitcast_convert_type(
        raw.reshape(slot.shape + (dt.itemsize,)), dt)


def unpack_batch(cb: ContiguousBatch) -> ColumnarBatch:
    """One uint8 device buffer -> batch (single compiled slice kernel)."""
    meta = cb.meta

    def build():
        def k(buf):
            outs = []
            for slot in meta.slots:
                raw = jax.lax.slice(buf, (slot.offset,),
                                    (slot.offset + slot.nbytes,))
                outs.append(_from_bytes(raw, slot))
            return outs
        return k

    key = ("contig_unpack",
           tuple((s.offset, s.nbytes, s.shape, s.dtype, s.f64_pair)
                 for s in meta.slots))
    leaves = cached_kernel(key, build)(cb.buffer)
    cols = []
    i = 0
    for f, n_leaves in zip(meta.schema, meta.leaves_per_col):
        ls = leaves[i:i + n_leaves]
        i += n_leaves
        cols.append(Column(ls[0], ls[1], f.dtype,
                           ls[2] if n_leaves == 3 else None))
    sel = leaves[i]
    return ColumnarBatch(cols, sel, meta.schema)


def contiguous_to_host(batch: ColumnarBatch):
    """D2H as ONE transfer: pack on device, pull the single buffer, slice
    host leaves out as numpy views (zero-copy reinterpret)."""
    cb = pack_batch(batch)
    raw = np.asarray(jax.device_get(cb.buffer))
    leaves = []
    for slot, dt_str in [(s, s.dtype) for s in cb.meta.slots]:
        piece = raw[slot.offset:slot.offset + slot.nbytes]
        if slot.f64_pair:
            pair = piece.view(np.float32).reshape(slot.shape + (2,))
            leaves.append(pair[..., 0].astype(np.float64)
                          + pair[..., 1].astype(np.float64))
        elif dt_str == "bool":
            leaves.append(piece.view(np.uint8).astype(np.bool_)
                          .reshape(slot.shape))
        else:
            leaves.append(piece.view(np.dtype(dt_str)).reshape(slot.shape))
    return leaves, cb.meta
