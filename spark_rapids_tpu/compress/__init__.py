"""Shuffle & spill buffer compression: chunked codec subsystem.

Layering: this package is pure (numpy + pyarrow + stdlib) so both
`shuffle/` (wire compression) and `mem/` (spill compression) can import
it without cycles — the same reason `mem/integrity.py` lives below the
shuffle stack.
"""
from .codec import (ArrowCodec, Codec, CodecError, CopyCodec,
                    available_codecs, codec_names, is_codec_available,
                    resolve_codec)
from .framed import (FLAG_RAW, CompressionPolicy, compression_from_conf,
                     frame_chunk_flags, frame_compress, frame_decompress,
                     frame_uncompressed_size)
from .serving import CompressedServe, CompressedServeCache

__all__ = [
    "ArrowCodec", "Codec", "CodecError", "CopyCodec", "available_codecs",
    "codec_names", "is_codec_available", "resolve_codec", "FLAG_RAW",
    "CompressionPolicy", "compression_from_conf", "frame_chunk_flags",
    "frame_compress", "frame_decompress", "frame_uncompressed_size",
    "CompressedServe", "CompressedServeCache",
]
