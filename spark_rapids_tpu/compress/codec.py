"""Codec SPI for shuffle/spill buffer compression.

TPU-native analogue of the reference's TableCompressionCodec SPI
(sql-plugin/.../rapids/TableCompressionCodec.scala — pluggable
lz4/zstd/copy codecs selected by `spark.rapids.shuffle.compression.codec`;
GpuCompressedColumnVector carries the codec id in the table meta).  The
reference compresses on-GPU with nvcomp; there is no TPU-side nvcomp, so
the honest placement is the HOST boundary every shuffle/spill byte
already crosses (batch_to_host / the bounce-buffer staging), using
pyarrow's C++ codecs — the same GIL-releasing entry points the parquet
reader already trusts (io/parquet_device.py _decompress), so chunk
(de)compression parallelizes on a thread pool.

A `Codec` is a one-shot block transform; the chunked *framed* container
that makes large leaves parallel and streamable lives in framed.py.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

log = logging.getLogger("spark_rapids_tpu.compress")


class CodecError(RuntimeError):
    """A codec failed to round-trip bytes it was handed.  When the input
    already passed checksum verification this means a codec/version bug,
    not data corruption; when verification is disabled it is the typed
    surface corrupt compressed bytes raise through."""


class Codec:
    """One-shot block codec (TableCompressionCodec analogue)."""

    name: str = "?"

    def compress(self, data) -> bytes:
        raise NotImplementedError

    def decompress(self, data, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class CopyCodec(Codec):
    """The `none` codec: a passthrough copy, so every conf/negotiation
    path has a real object to talk to (reference: CopyCompressionCodec)."""

    name = "none"

    def compress(self, data) -> bytes:
        return bytes(data)

    def decompress(self, data, uncompressed_size: int) -> bytes:
        out = bytes(data)
        if len(out) != uncompressed_size:
            raise CodecError(
                f"copy codec size mismatch: {len(out)} != "
                f"{uncompressed_size}")
        return out


class ArrowCodec(Codec):
    """lz4/zstd/snappy through pyarrow's C++ codecs.  The codec calls
    release the GIL (proven by the parquet reader's decompression pool),
    which is what lets framed.py overlap chunk compression with socket
    send/recv on a side thread pool."""

    def __init__(self, name: str, arrow_name: Optional[str] = None,
                 level: Optional[int] = None):
        import pyarrow as pa
        self.name = name
        self._codec = pa.Codec(arrow_name or name, compression_level=level)

    def compress(self, data) -> bytes:
        return self._codec.compress(data, asbytes=True)

    def decompress(self, data, uncompressed_size: int) -> bytes:
        try:
            return self._codec.decompress(
                data, decompressed_size=uncompressed_size, asbytes=True)
        except Exception as e:  # noqa: BLE001 — arrow raises several types
            raise CodecError(
                f"{self.name} decompress of {len(data)}B -> "
                f"{uncompressed_size}B failed: {e!r}") from e


# ---- registry ---------------------------------------------------------------

# conf/wire name -> factory; instances are cached (codecs are stateless)
_FACTORIES = {
    "none": CopyCodec,
    "copy": CopyCodec,  # the reference's name for the passthrough codec
    "lz4": lambda: ArrowCodec("lz4"),
    "zstd": lambda: ArrowCodec("zstd"),
    "snappy": lambda: ArrowCodec("snappy"),
}
_INSTANCES: Dict[str, Codec] = {}
# codec instances own worker-pool state (framed.py side pools): a racy
# first-touch from two scheduler threads must not build two of them
_INSTANCES_LOCK = threading.Lock()


def codec_names() -> List[str]:
    return sorted(set(_FACTORIES) - {"copy"})


def is_codec_available(name: str) -> bool:
    """Can this process actually construct the named codec?  (The image
    may lack a compression library; negotiation must know, not assume.)"""
    try:
        resolve_codec(name)
        return True
    except (ValueError, ImportError, OSError):
        return False
    except Exception:  # noqa: BLE001 — an unbuildable codec is unavailable
        return False


def available_codecs() -> List[str]:
    """The codec names this host can serve/decode — recorded in bench
    artifacts and answered during peer negotiation."""
    return [n for n in codec_names() if is_codec_available(n)]


def resolve_codec(name: str) -> Codec:
    """Named codec instance.  Unknown names raise ValueError so a typo'd
    conf fails loudly (mirrors integrity.resolve_hasher)."""
    key = (name or "none").strip().lower()
    if key in ("", "off"):
        key = "none"
    codec = _INSTANCES.get(key)
    if codec is None:
        factory = _FACTORIES.get(key)
        if factory is None:
            raise ValueError(
                f"unknown compression codec {name!r} "
                f"({'|'.join(codec_names())})")
        with _INSTANCES_LOCK:
            codec = _INSTANCES.get(key)
            if codec is None:
                codec = _INSTANCES[key] = factory()
    return codec
