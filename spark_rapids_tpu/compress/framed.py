"""Chunked framed container for compressed buffer leaves.

One leaf's framed form:

    header     <I n_chunks> <I chunk_size> <Q uncomp_len>
    directory  n_chunks x (<I comp_len> <B flags>)
    payload    compressed (or raw-escaped) chunks back to back

Chunk i covers uncompressed bytes [i*chunk_size, min((i+1)*chunk_size,
uncomp_len)).  Fixed chunking is what buys three properties the one-shot
codec call cannot give:

  * chunks (de)compress in PARALLEL on a side thread pool (pyarrow's
    codecs release the GIL), overlapped with socket send/recv exactly
    like the wire checksum's AsyncLeafVerifier;
  * an incompressible chunk is stored RAW with a directory flag
    (FLAG_RAW), so adversarial/random data costs one memcpy instead of
    inflating (the reference's codec escape hatch);
  * a leaf below `minSizeBytes` skips codec calls entirely (every chunk
    raw) while staying in the ONE uniform container every reader
    understands.

The framed bytes are what the wire/disk checksums cover: digests are
established over the COMPRESSED form at the compression boundary, so the
integrity ladder verifies frames before they ever reach a decompressor.
"""
from __future__ import annotations

import struct
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .codec import Codec, CodecError, resolve_codec

_FRAME_HDR = struct.Struct("<IIQ")   # n_chunks, chunk_size, uncomp_len
_CHUNK_HDR = struct.Struct("<IB")    # comp_len, flags
FLAG_RAW = 1

FRAME_HEADER_BYTES = _FRAME_HDR.size
CHUNK_HEADER_BYTES = _CHUNK_HDR.size

# ---- shared codec thread pool ----------------------------------------------
# One pool per process (like io/parquet_device._decomp_pool): the codec
# calls release the GIL, so pool workers genuinely run beside the socket
# recv loop / the spill writer.
_POOL = None
_POOL_LOCK = threading.Lock()


def codec_pool():
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                import os
                from concurrent.futures import ThreadPoolExecutor
                _POOL = ThreadPoolExecutor(
                    max_workers=max(2, min(8, os.cpu_count() or 1)),
                    thread_name_prefix="srtpu-codec")
    return _POOL


def _as_flat_u8(data) -> np.ndarray:
    # codec framing runs on host-staged leaves: the bytes already left the
    # device at the spill/serve boundary (mem/buffer.py), so this asarray
    # is a view/copy of host memory, never a device pull
    a = np.asarray(data)  # tpulint: disable=TPU001 host-staged leaf bytes at the codec boundary, not a device pull
    return np.ascontiguousarray(a).view(np.uint8).reshape(-1)


def frame_compress(codec: Codec, data, chunk_size: int,
                   min_size: int = 0, parallel: bool = True) -> np.ndarray:
    """Compress one leaf into its framed form (flat uint8 array).

    `min_size`: leaves smaller than this skip the codec entirely (all
    chunks raw) — the conf'd CPU-cost floor.  Incompressible chunks
    (compressed >= raw) take the per-chunk raw escape independently."""
    u8 = _as_flat_u8(data)
    total = u8.nbytes
    chunk_size = max(1, int(chunk_size))
    n_chunks = -(-total // chunk_size) if total else 0
    skip = codec.name == "none" or total < min_size

    def one(i: int) -> Tuple[bytes, int]:
        lo = i * chunk_size
        chunk = u8[lo:min(lo + chunk_size, total)]
        if not skip:
            comp = codec.compress(chunk)
            if len(comp) < chunk.nbytes:
                return comp, 0
        return chunk.tobytes(), FLAG_RAW

    if n_chunks > 1 and parallel and not skip:
        blobs = list(codec_pool().map(one, range(n_chunks)))
    else:
        blobs = [one(i) for i in range(n_chunks)]

    out_len = (FRAME_HEADER_BYTES + n_chunks * CHUNK_HEADER_BYTES
               + sum(len(b) for b, _ in blobs))
    out = np.empty(out_len, dtype=np.uint8)
    view = memoryview(out)
    _FRAME_HDR.pack_into(view, 0, n_chunks, chunk_size, total)
    off = FRAME_HEADER_BYTES
    for blob, flags in blobs:
        _CHUNK_HDR.pack_into(view, off, len(blob), flags)
        off += CHUNK_HEADER_BYTES
    for blob, _flags in blobs:
        view[off:off + len(blob)] = blob
        off += len(blob)
    return out


def frame_uncompressed_size(framed) -> int:
    """Uncompressed length recorded in a frame header (no payload walk)."""
    u8 = _as_flat_u8(framed)
    _n, _c, total = _FRAME_HDR.unpack_from(memoryview(u8), 0)
    return int(total)


def frame_chunk_flags(framed) -> List[int]:
    """Per-chunk flag bytes from a frame's directory (tests assert the
    raw-escape and min-size-skip paths actually took the raw flag)."""
    u8 = _as_flat_u8(framed)
    view = memoryview(u8)
    n_chunks, _chunk, _total = _FRAME_HDR.unpack_from(view, 0)
    flags = []
    off = FRAME_HEADER_BYTES
    for _ in range(n_chunks):
        _len, f = _CHUNK_HDR.unpack_from(view, off)
        flags.append(int(f))
        off += CHUNK_HEADER_BYTES
    return flags


def frame_decompress(codec: Codec, framed,
                     parallel: bool = True) -> np.ndarray:
    """Inverse of frame_compress: framed bytes -> flat uint8 leaf.

    Callers on the verified paths only reach here AFTER the frame's
    checksum passed; a malformed frame therefore raises the typed
    CodecError (codec/version bug — or corruption the caller chose not
    to checksum)."""
    u8 = _as_flat_u8(framed)
    view = memoryview(u8)
    if u8.nbytes < FRAME_HEADER_BYTES:
        raise CodecError(f"framed leaf too short ({u8.nbytes}B)")
    n_chunks, chunk_size, total = _FRAME_HDR.unpack_from(view, 0)
    directory = []
    off = FRAME_HEADER_BYTES
    payload_off = FRAME_HEADER_BYTES + n_chunks * CHUNK_HEADER_BYTES
    if payload_off > u8.nbytes:
        raise CodecError("framed leaf directory overruns the buffer")
    pos = payload_off
    for i in range(n_chunks):
        comp_len, flags = _CHUNK_HDR.unpack_from(view, off)
        off += CHUNK_HEADER_BYTES
        directory.append((pos, comp_len, flags))
        pos += comp_len
    if pos != u8.nbytes:
        raise CodecError(f"framed leaf payload mismatch: directory says "
                         f"{pos}B, buffer holds {u8.nbytes}B")
    out = np.empty(total, dtype=np.uint8)

    def one(i: int) -> None:
        src, comp_len, flags = directory[i]
        lo = i * chunk_size
        want = min(chunk_size, total - lo)
        blob = view[src:src + comp_len]
        if flags & FLAG_RAW:
            if comp_len != want:
                raise CodecError(
                    f"raw chunk {i} length {comp_len} != {want}")
            out[lo:lo + want] = np.frombuffer(blob, dtype=np.uint8)
            return
        raw = codec.decompress(blob, want)
        if len(raw) != want:
            raise CodecError(
                f"chunk {i} decompressed to {len(raw)}B, expected {want}B")
        out[lo:lo + want] = np.frombuffer(raw, dtype=np.uint8)

    if n_chunks > 1 and parallel:
        # materialize to surface the first worker exception
        list(codec_pool().map(one, range(n_chunks)))
    else:
        for i in range(n_chunks):
            one(i)
    return out


# ---- policy (the resolved conf one subsystem carries around) ----------------

class CompressionPolicy:
    """Resolved compression configuration, mirroring ChecksumPolicy: the
    effective codec + chunking parameters, shared by the shuffle env, the
    transports, and the spill stores.  `metrics` (runtime-level Metrics)
    times compression/decompression when attached; byte counters are the
    call sites' duty because shuffle and spill account separately."""

    __slots__ = ("codec", "chunk_size", "min_size", "metrics")

    def __init__(self, codec: str = "none", chunk_size: int = 1 << 20,
                 min_size: int = 1 << 10, metrics=None):
        try:
            self.codec = resolve_codec(codec)
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — known name, lib missing
            import logging
            logging.getLogger("spark_rapids_tpu.compress").warning(
                "compression codec %r unavailable (%r); falling back to "
                "none", codec, e)
            self.codec = resolve_codec("none")
        self.chunk_size = max(1, int(chunk_size))
        self.min_size = max(0, int(min_size))
        self.metrics = metrics

    @property
    def enabled(self) -> bool:
        return self.codec.name != "none"

    @property
    def codec_name(self) -> str:
        return self.codec.name

    def compress_one(self, data) -> np.ndarray:
        return frame_compress(self.codec, data, self.chunk_size,
                              self.min_size)

    def compress_leaves(self, leaves: Sequence[np.ndarray]
                        ) -> List[np.ndarray]:
        if self.metrics is not None:
            from ..metrics import names as MN
            with self.metrics.timer(MN.COMPRESSION_TIME):
                return [self.compress_one(a) for a in leaves]
        return [self.compress_one(a) for a in leaves]

    def decompress_one(self, framed, codec: Optional[Codec] = None
                       ) -> np.ndarray:
        return frame_decompress(codec or self.codec, framed)

    def decompress_leaves(self, framed_leaves: Sequence[np.ndarray],
                          codec: Optional[Codec] = None
                          ) -> List[np.ndarray]:
        if self.metrics is not None:
            from ..metrics import names as MN
            with self.metrics.timer(MN.DECOMPRESSION_TIME):
                return [self.decompress_one(f, codec)
                        for f in framed_leaves]
        return [self.decompress_one(f, codec) for f in framed_leaves]

    def record_ratio(self, raw_bytes: int, comp_bytes: int) -> None:
        """Surface the best observed raw:compressed ratio as the
        compressionRatio gauge (set_max semantics: gauges here are
        high-water marks, like peakDevMemory)."""
        if self.metrics is not None and comp_bytes > 0:
            from ..metrics import names as MN
            self.metrics.set_max(MN.COMPRESSION_RATIO,
                                 raw_bytes / comp_bytes)


def compression_from_conf(conf, metrics=None, codec_entry=None
                          ) -> CompressionPolicy:
    """Build a CompressionPolicy from a TpuConf.  `codec_entry` selects
    the flavor: SHUFFLE_COMPRESSION_CODEC (default) or
    SPILL_COMPRESSION_CODEC — the two tiers are conf'd independently but
    share chunking parameters."""
    from .. import config as C
    codec_entry = codec_entry or C.SHUFFLE_COMPRESSION_CODEC
    return CompressionPolicy(
        str(conf.get(codec_entry)),
        int(conf.get(C.SHUFFLE_COMPRESSION_CHUNK_SIZE)),
        int(conf.get(C.SHUFFLE_COMPRESSION_MIN_SIZE)),
        metrics=metrics)
