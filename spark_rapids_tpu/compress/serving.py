"""Serve-side compressed-leaf cache for the shuffle transports.

The wire protocol streams a buffer's leaves in bounce-buffer-sized
chunks; with compression negotiated, those chunks come out of the leaf's
FRAMED COMPRESSED form instead of the raw bytes.  Compressing per bounce
chunk would re-run the codec for every 1MB slice of every retry, so the
server compresses each (buffer, codec) ONCE and serves every chunk/shm
fill/refetch from the cached frames — the analogue of the reference's
BufferSendState staging compressed tables through send bounce buffers.

Checksums over the COMPRESSED frames are established here, at the
compression boundary, and travel in the layout response: the reader
verifies frames before its decompressor ever sees them, extending the
PR-4 integrity ladder rather than bypassing it.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from .codec import is_codec_available, resolve_codec
from .framed import CompressionPolicy


@dataclass
class CompressedServe:
    """One buffer's leaves, framed with one codec, ready to stream."""
    codec: str
    leaves: List[np.ndarray]          # framed compressed forms, flat u8
    sizes: List[int]                  # per-leaf framed nbytes
    checksums: Optional[Tuple[int, ...]]  # digests over the FRAMES
    algorithm: Optional[str]
    raw_bytes: int
    comp_bytes: int

    def descriptor(self) -> dict:
        """The layout-response record the reader negotiates on."""
        return {"codec": self.codec, "sizes": list(self.sizes),
                "checksums": (list(self.checksums)
                              if self.checksums is not None else None),
                "algorithm": self.algorithm}


class CompressedServeCache:
    """Bounded (buffer_id, codec) -> CompressedServe cache, mirroring the
    raw serving cache in shuffle/manager.ShuffleServer."""

    def __init__(self, policy: CompressionPolicy, integrity=None,
                 capacity: int = 16):
        from collections import OrderedDict
        self.policy = policy
        self.integrity = integrity    # ChecksumPolicy or None
        # LRU, not FIFO: the serve loop calls get() once per BOUNCE
        # CHUNK of a stream, so the entry a stream is mid-way through
        # must be the last thing evicted — FIFO under > capacity
        # concurrent streams would recompress the whole buffer per chunk
        self.capacity = capacity
        self._cache: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def peek(self, buffer_id: int,
             codec_name: Optional[str]) -> Optional[CompressedServe]:
        """Cached entry or None — never compresses (metadata responses
        report framed sizes only where a serve already built them)."""
        with self._lock:
            return self._cache.get((buffer_id, codec_name))

    def get(self, buffer_id: int, codec_name: str,
            leaves: List[np.ndarray]) -> Optional[CompressedServe]:
        """Framed form of `leaves` under the REQUESTED codec, or None
        when this process cannot encode it (the caller answers raw and
        counts the fallback — the typed negotiation miss)."""
        key = (buffer_id, codec_name)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                return hit
        if codec_name == "none" or not is_codec_available(codec_name):
            return None
        codec = resolve_codec(codec_name)
        raw_bytes = int(sum(a.nbytes for a in leaves))
        if self.policy.metrics is not None:
            from ..metrics import names as MN
            with self.policy.metrics.timer(MN.COMPRESSION_TIME):
                frames = [_frame(self.policy, codec, a) for a in leaves]
        else:
            frames = [_frame(self.policy, codec, a) for a in leaves]
        sums = None
        algo = None
        if self.integrity is not None and self.integrity.enabled:
            sums = tuple(int(s)
                         for s in self.integrity.checksum_leaves(frames))
            algo = self.integrity.algorithm
        entry = CompressedServe(
            codec=codec_name, leaves=frames,
            sizes=[f.nbytes for f in frames], checksums=sums,
            algorithm=algo, raw_bytes=raw_bytes,
            comp_bytes=int(sum(f.nbytes for f in frames)))
        self.policy.record_ratio(entry.raw_bytes, entry.comp_bytes)
        if self.policy.metrics is not None:
            from ..metrics import names as MN
            self.policy.metrics.add(MN.COMPRESSED_SHUFFLE_BYTES_WRITTEN,
                                    entry.comp_bytes)
        from ..metrics.journal import journal_event
        journal_event("compress", "serveCompress", buffer=buffer_id,
                      codec=codec_name, raw_bytes=entry.raw_bytes,
                      comp_bytes=entry.comp_bytes,
                      ratio=round(entry.raw_bytes
                                  / max(1, entry.comp_bytes), 3))
        with self._lock:
            while len(self._cache) >= self.capacity:
                self._cache.popitem(last=False)  # least recently served
            self._cache[key] = entry
        return entry

    def drop(self, buffer_id: int) -> None:
        with self._lock:
            for key in [k for k in self._cache if k[0] == buffer_id]:
                self._cache.pop(key, None)

    def invalidate(self, buffer_ids) -> None:
        ids = set(buffer_ids)
        with self._lock:
            for key in [k for k in self._cache if k[0] in ids]:
                self._cache.pop(key, None)


def _frame(policy: CompressionPolicy, codec, a: np.ndarray) -> np.ndarray:
    from .framed import frame_compress
    return frame_compress(codec, a, policy.chunk_size, policy.min_size)
