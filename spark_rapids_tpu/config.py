"""Typed configuration registry.

Mirrors the reference's conf system (reference: sql-plugin/.../rapids/
RapidsConf.scala:96-220 for the builder machinery, :221-590 for the key list,
:600-689 for doc generation): every entry has a key, a typed default, a doc
string, and an `internal` flag; docs/configs.md is *generated* from this
registry; every operator/expression additionally gets an auto-derived
kill-switch key (see plan/overrides.py).

Key namespace keeps the reference's `spark.rapids.` prefix so users of the
reference find the same knobs, with `tpu` substituted where the reference says
`gpu`.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: "Dict[str, ConfEntry]" = {}


def _to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes"):
        return True
    if s in ("false", "0", "no"):
        return False
    raise ValueError(f"not a boolean: {v!r}")


_BYTE_SUFFIXES = {
    "b": 1, "k": 1 << 10, "kb": 1 << 10, "m": 1 << 20, "mb": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "t": 1 << 40, "tb": 1 << 40,
}


def to_bytes(v) -> int:
    """Parse '2g', '512m', '1024' -> bytes (reference: byte converters in
    TypedConfBuilder, RapidsConf.scala:141-150)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*", str(v))
    if not m:
        raise ValueError(f"not a byte size: {v!r}")
    num, suf = float(m.group(1)), m.group(2).lower()
    if suf == "":
        return int(num)
    if suf not in _BYTE_SUFFIXES:
        raise ValueError(f"unknown byte suffix {suf!r} in {v!r}")
    return int(num * _BYTE_SUFFIXES[suf])


class ConfEntry:
    def __init__(self, key: str, default: Any, doc: str,
                 converter: Callable[[Any], Any],
                 internal: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.converter = converter
        self.internal = internal
        if key in _REGISTRY:
            raise ValueError(f"duplicate conf key {key}")
        _REGISTRY[key] = self

    def get(self, conf: "TpuConf"):
        raw = conf._settings.get(self.key)
        if raw is None:
            return self.default
        return self.converter(raw)


def _conf(key, default, doc, converter, internal=False) -> ConfEntry:
    return ConfEntry(key, default, doc, converter, internal)


# --- core enables -----------------------------------------------------------
SQL_ENABLED = _conf("spark.rapids.sql.enabled", True,
                    "Enable (true) or disable (false) TPU acceleration of SQL "
                    "plans.", _to_bool)
TEST_CONF = _conf("spark.rapids.sql.test.enabled", False,
                  "Intended for internal testing only: fail if an operation "
                  "falls back to CPU instead of running on the TPU.", _to_bool,
                  internal=True)
TEST_ALLOWED_NONTPU = _conf(
    "spark.rapids.sql.test.allowedNonTpu", "",
    "Comma separated exec class names allowed to stay on CPU in test mode.",
    str, internal=True)
INCOMPATIBLE_OPS = _conf(
    "spark.rapids.sql.incompatibleOps.enabled", False,
    "Enable operations that produce results that differ from Spark in corner "
    "cases (e.g. float aggregation ordering).", _to_bool)
EXPLAIN = _conf(
    "spark.rapids.sql.explain", "NONE",
    "Explain why parts of a query were or were not placed on the TPU. "
    "NONE|ALL|NOT_ON_TPU; METRICS additionally prints the executed plan "
    "tree with each node's accumulated metrics after every query "
    "(EXPLAIN-with-metrics, docs/monitoring.md).", str)
HAS_NANS = _conf(
    "spark.rapids.sql.hasNans", True,
    "Assume floating point data may contain NaNs (affects eligibility of some "
    "ops, matching the reference's hasNans gate).", _to_bool)
VARIABLE_FLOAT_AGG = _conf(
    "spark.rapids.sql.variableFloatAgg.enabled", False,
    "Allow float/double aggregations whose result may differ in last-bit "
    "rounding from CPU due to reduction order.", _to_bool)
ENABLE_CAST_STRING_TO_FLOAT = _conf(
    "spark.rapids.sql.castStringToFloat.enabled", False,
    "Enable string->float casts on device; off by default because corner-case "
    "formats differ from the CPU.", _to_bool)
ENABLE_CAST_FLOAT_TO_STRING = _conf(
    "spark.rapids.sql.castFloatToString.enabled", False,
    "Enable float->string casts on device; formatting differs in corner cases.",
    _to_bool)
ENABLE_CAST_STRING_TO_TIMESTAMP = _conf(
    "spark.rapids.sql.castStringToTimestamp.enabled", False,
    "Enable string->timestamp casts on device.", _to_bool)
IMPROVED_FLOAT_OPS = _conf(
    "spark.rapids.sql.improvedFloatOps.enabled", False,
    "Use device float ops that are faster but not bit-identical to the JVM.",
    _to_bool)

# --- batching ---------------------------------------------------------------
BATCH_SIZE_BYTES = _conf(
    "spark.rapids.sql.batchSizeBytes", 2 << 30,
    "Target size in bytes for TPU columnar batches; operators coalesce "
    "smaller batches up to this goal (reference default 2GiB).", to_bytes)
MAX_READER_BATCH_SIZE_ROWS = _conf(
    "spark.rapids.sql.reader.batchSizeRows", 2 ** 31 - 1,
    "Soft cap on rows per batch produced by file readers.", int)
MAX_READER_BATCH_SIZE_BYTES = _conf(
    "spark.rapids.sql.reader.batchSizeBytes", 2 << 30,
    "Soft cap on bytes per batch produced by file readers.", to_bytes)
MIN_BUCKET_ROWS = _conf(
    "spark.rapids.sql.tpu.minBucketRows", 1024,
    "Smallest row-capacity bucket; batch capacities are rounded up to "
    "power-of-two buckets so XLA recompiles are bounded (TPU-specific: XLA "
    "traces once per static shape).", int)

# --- memory -----------------------------------------------------------------
TPU_ALLOC_FRACTION = _conf(
    "spark.rapids.memory.tpu.allocFraction", 0.9,
    "Fraction of usable HBM to reserve for the columnar batch pool.", float)
TPU_POOL_SIZE = _conf(
    "spark.rapids.memory.tpu.poolSizeBytes", 0,
    "Absolute accounted HBM pool budget in bytes; overrides allocFraction "
    "when > 0.  The knob memory-budget sweeps (bench.py pressure stage) "
    "and the serving tier's per-query budgets are expressed in — an exact "
    "byte budget is reproducible across hosts where a fraction of "
    "detected HBM is not.", to_bytes)
HOST_SPILL_STORAGE_SIZE = _conf(
    "spark.rapids.memory.host.spillStorageSize", 1 << 30,
    "Bytes of host memory to use for spilled device buffers before spilling "
    "to disk.", to_bytes)
TPU_OOM_SPILL_ENABLED = _conf(
    "spark.rapids.memory.tpu.oomSpill.enabled", True,
    "Synchronously spill device buffers when an HBM allocation fails.",
    _to_bool)
TPU_DEBUG = _conf(
    "spark.rapids.memory.tpu.debug", "NONE",
    "Log device allocations/frees: NONE|STDOUT|STDERR.", str)
CONCURRENT_TPU_TASKS = _conf(
    "spark.rapids.sql.concurrentTpuTasks", 1,
    "Number of tasks that may use the TPU concurrently (device semaphore).",
    int)
PINNED_POOL_SIZE = _conf(
    "spark.rapids.memory.pinnedPool.size", 0,
    "Size of the pinned host staging pool used for H2D/D2H transfer.",
    to_bytes)
SPILL_CHECKSUM_ENABLED = _conf(
    "spark.rapids.memory.spill.checksum.enabled", True,
    "Checksum device buffers as they spill to the host tier and verify "
    "on every subsequent movement (host->disk write, disk read, "
    "host/disk->device unspill), so a flipped bit in spilled bytes "
    "surfaces as a typed CorruptBuffer instead of silently wrong query "
    "results.  Uses spark.rapids.shuffle.checksum.algorithm.", _to_bool)
OOM_RETRY_MAX = _conf(
    "spark.rapids.memory.tpu.retry.maxRetries", 2,
    "Same-size retries of an operator allocation attempt after an OOM "
    "(each retry runs behind the synchronous spill cascade) before the "
    "input is split (reference: withRetry over RmmSpark retry OOMs).", int)
OOM_RETRY_SPLIT_DEPTH = _conf(
    "spark.rapids.memory.tpu.retry.maxSplitDepth", 4,
    "Maximum halving depth of split-and-retry: an input batch may be "
    "split into at most 2^depth pieces before the block gives up "
    "(RetryExhausted -> CPU fallback or query failure).", int)
OOM_RETRY_CHECKPOINT = _conf(
    "spark.rapids.memory.tpu.retry.checkpointInputs.enabled", True,
    "Register retryable-block input batches as spillable buffers so the "
    "OOM spill cascade can evict them between attempts (they are pinned "
    "only while an attempt runs).", _to_bool)
OOM_CPU_FALLBACK = _conf(
    "spark.rapids.sql.tpu.cpuFallbackOnOom.enabled", True,
    "When a device operator exhausts its OOM retries and split depth, "
    "re-execute it through its CPU implementation instead of failing the "
    "query; the downgrade is recorded in the operator's numCpuFallbacks "
    "metric.", _to_bool)
MEMORY_SCAN_CACHE_ENABLED = _conf(
    "spark.rapids.sql.tpu.memoryScanCache.enabled", True,
    "Keep device batches decoded from immutable in-memory tables "
    "HBM-resident across queries so repeated scans skip the host->device "
    "transfer (TPU-native storage-layer cache; Spark analogue df.cache()).",
    _to_bool)
MEMORY_SCAN_CACHE_SIZE = _conf(
    "spark.rapids.sql.tpu.memoryScanCache.maxSize", 4 << 30,
    "LRU byte bound on HBM held by the in-memory scan cache.", to_bytes)
WHOLE_STAGE_ENABLED = _conf(
    "spark.rapids.sql.tpu.wholeStage.enabled", True,
    "Compile scan->rowLocal->aggregate stages over equal-capacity batches "
    "into ONE device program (batches stacked on a leading dim, per-batch "
    "work vmapped, partials merged in-program) — the TPU analogue of "
    "whole-stage codegen; one dispatch instead of O(batches), which is "
    "what high host-link latency punishes.", _to_bool)
SCAN_PREFETCH_DEPTH = _conf(
    "spark.rapids.sql.tpu.scan.prefetchDepth", 1,
    "Chunks of device file-scan decode kept ready ahead of the consumer "
    "by a background thread (the reference's MULTITHREADED reader mode): "
    "chunk N+1's host control plane overlaps chunk N's H2D transfer. "
    "0 disables.", int)
COMPILATION_CACHE_DIR = _conf(
    "spark.rapids.sql.tpu.compilationCache.dir",
    "/tmp/spark_rapids_tpu_xla_cache",
    "Persistent XLA compilation cache directory shared across processes; "
    "a fresh session replays compiled programs from disk instead of "
    "paying tens of seconds per query shape (the reference has zero "
    "query-time compile cost; this is the TPU equivalent).  Empty string "
    "disables.", str)
FUSION_ENABLED = _conf(
    "spark.rapids.sql.tpu.fusion.enabled", True,
    "Whole-stage fusion kill switch: after planning, maximal chains of "
    "row-local device operators (project/filter/expand over scan-decode "
    "output) compile into ONE jitted XLA stage per batch shape "
    "(TpuWholeStageExec), the hash-partition bucketing of a shuffle "
    "exchange fuses into its child stage's program, and grouped "
    "aggregation absorbs the chain into its whole-stage program.  A "
    "stage materializes exactly one ColumnarBatch at its fusion boundary "
    "(exchange, join build, sort, full aggregation) instead of one per "
    "operator; OOM retry runs at stage granularity (split-retry the "
    "stage input, then operator-at-a-time, then per-operator CPU "
    "fallback).  false disables the ENTIRE compiled-stage family — "
    "per-operator dispatch with the legacy FusedPipelineExec chain "
    "fusion only, aggregate whole-stage absorption off too (toggle that "
    "alone via wholeStage.enabled while fusion stays on).", _to_bool)
DONATION_ENABLED = _conf(
    "spark.rapids.sql.tpu.donation.enabled", True,
    "Buffer donation through compiled stage programs: when the fusion "
    "pass proves a stage is the LAST consumer of its input batches "
    "(source is scan decode / host->device adoption / an upstream whole "
    "stage) and the batch gained no second owner at runtime (spillable "
    "registration, scan cache, retry checkpoint — mem/donation.py pins "
    "those), the stage executable compiles with donate_argnums on the "
    "batch-column leaves so XLA reuses input HBM for the outputs instead "
    "of allocating a fresh copy per column per batch.  Results are "
    "byte-identical either way; false restores the copying behavior "
    "(numDonatedBuffers counts what warm runs saved).", _to_bool)
SORT_PACKED_ENABLED = _conf(
    "spark.rapids.sql.tpu.sort.packed.enabled", True,
    "One-shot packed-key sort: fuse the order-preserving integer sort "
    "keys (exec/sort.py encodings) into as few 64-bit words as their "
    "static bit widths allow, embed the row id in the low bits, and "
    "order rows with SINGLE-operand jax.lax.sort passes (one pass when "
    "key+rowid bits fit 64, else a stable LSD radix over 64-bit chunks) "
    "instead of the N-pass variadic lexsort.  Grouped aggregation's "
    "(h1, h2) hash sort takes the same path.  The permutation is "
    "bit-identical to lexsort (ties break by row id = stable); columns "
    "whose keys are not order-preserving integers on this backend "
    "(float sort keys on the emulated-f64 TPU backend) fall back to "
    "lexsort.  false restores lexsort everywhere.", _to_bool)
FUSION_MAX_OPS = _conf(
    "spark.rapids.sql.tpu.fusion.maxOpsPerStage", 16,
    "Upper bound on row-local operators fused into one whole-stage "
    "program; longer chains split into consecutive stages (bounds the "
    "size/compile time of any single XLA program).", int)
AGG_MERGE_FAN_IN = _conf(
    "spark.rapids.sql.tpu.agg.mergeFanIn", 8,
    "Number of per-batch partial aggregate states buffered before one "
    "K-way concat+merge; larger values amortize merge-kernel dispatches "
    "and host syncs across more input batches.", int)
AGG_BUCKET_GROUPS = _conf(
    "spark.rapids.sql.tpu.agg.bucketGroups", True,
    "Low-cardinality grouped-aggregate fast path: rows scatter into "
    "hash buckets and per-bucket states replace the per-batch sort when "
    "every bucket holds one distinct key (checked exactly per batch; "
    "dirty batches fall back to the sort path).  Applies to "
    "sum/count/avg and non-string min/max without distinct.", _to_bool)

CLUSTER_EXECUTORS = _conf(
    "spark.rapids.sql.tpu.cluster.executors", 1,
    "Host-mode executor count: each executor owns a runtime + shuffle env "
    "on a shared transport wire; shuffle map tasks write to their "
    "executor's catalog and reduce tasks fetch remote blocks through the "
    "client/server path (plugin.py TpuCluster; reference: one plugin "
    "executor per Spark executor).", int)

# --- multi-chip / shuffle planning ------------------------------------------
MESH_DEVICES = _conf(
    "spark.rapids.sql.tpu.mesh.devices", 0,
    "Devices in the SPMD execution mesh.  >1 routes aggregate/join/sort "
    "subtrees through the distributed all-to-all operators "
    "(exec/distributed.py); 0/1 keeps single-chip execution.  Must be a "
    "power of two and <= the local device count (falls back to single-chip "
    "when fewer devices exist).", int)
PALLAS_ENABLED = _conf(
    "spark.rapids.sql.tpu.pallas.enabled", False,
    "Use hand-written pallas kernels where available (currently the "
    "prefix-sum inside segmented aggregation: one sequential-grid VMEM "
    "pass with an SMEM carry instead of XLA's log-depth scan).  Any "
    "pallas failure (unsupported dtype on the chip, CPU backend) falls "
    "back to the XLA lowering per call.", _to_bool)
MESH_COORDINATOR = _conf(
    "spark.rapids.sql.tpu.mesh.coordinator", "",
    "host:port of the jax.distributed coordinator for MULTI-HOST meshes "
    "(empty = single host).  When set, session startup joins the "
    "coordination service so jax.devices() enumerates every host's chips "
    "and the SPMD mesh spans the pod; collectives ride ICI within a slice "
    "and DCN across slices.  Process count/id come from the companion "
    "confs or JAX_NUM_PROCESSES/JAX_PROCESS_ID.", str)
MESH_NUM_PROCESSES = _conf(
    "spark.rapids.sql.tpu.mesh.numProcesses", 0,
    "Total processes in the multi-host mesh (0 = let jax infer from the "
    "TPU runtime, which works on Cloud TPU pods).", int)
MESH_PROCESS_ID = _conf(
    "spark.rapids.sql.tpu.mesh.processId", 0,
    "This process's id in [0, numProcesses) for multi-host bring-up.", int)
MESH_USE_ALLGATHER = _conf(
    "spark.rapids.sql.tpu.mesh.useAllGather", False,
    "Use the sel-mask all-gather exchange instead of the compact quota "
    "all-to-all in distributed operators (zero overflow risk, O(n) cost; "
    "debugging/safety knob).", _to_bool)
ICI_SHUFFLE_ENABLED = _conf(
    "spark.rapids.sql.tpu.shuffle.ici.enabled", True,
    "Lower generic shuffle exchanges (TpuShuffleExchangeExec) into jitted "
    "ICI collectives when the exchange's producer and consumer partitions "
    "are co-resident on one device mesh (mesh.devices > 1, single "
    "process, hash/round_robin/single partitioning): the fused chain, "
    "partition-id compute and the all-to-all compile into ONE program and "
    "the data never leaves HBM.  Off (or off-mesh: a cluster, a range "
    "exchange, too few devices) the exchange takes the host socket tier "
    "byte-identically to the pre-mesh behavior; RetryExhausted inside the "
    "collective also de-lowers to the socket tier (counted in the "
    "transport's socket_fallbacks).", _to_bool)
MESH_INPUT_CHUNK_ROWS = _conf(
    "spark.rapids.sql.tpu.mesh.inputChunkRows", 1 << 20,
    "Row budget per SPMD input chunk.  Distributed aggregate/join STREAM "
    "their input through the mesh in chunks of at most this many rows "
    "(partial-agg then device-resident state merge; per-chunk probe "
    "against a resident build side), so an input larger than HBM never "
    "materializes as one host-side concat.", int)
SHUFFLE_PARTITIONS = _conf(
    "spark.rapids.sql.tpu.shuffle.partitions", 8,
    "Partition count for planner-inserted shuffle exchanges around "
    "shuffled hash joins (spark.sql.shuffle.partitions analogue; the "
    "single-build-batch bound then holds per partition, not per input).",
    int)
PARTITIONED_JOIN_ENABLED = _conf(
    "spark.rapids.sql.tpu.join.partitioned.enabled", True,
    "Insert hash-partition exchanges around non-broadcast equi-joins so "
    "the build side is bounded per partition (EnsureRequirements "
    "analogue; reference GpuShuffledHashJoinExec).", _to_bool)
PARTITIONED_JOIN_THRESHOLD = _conf(
    "spark.rapids.sql.tpu.join.partitioned.threshold", 64 << 20,
    "Estimated build-side bytes above which a non-broadcast join is "
    "planned with partition exchanges; below it the whole build side is "
    "one batch.  Unknown sizes partition.", to_bytes)

# --- formats ----------------------------------------------------------------
CSV_ENABLED = _conf("spark.rapids.sql.format.csv.enabled", True,
                    "Enable CSV read acceleration.", _to_bool)
CSV_READ_ENABLED = _conf("spark.rapids.sql.format.csv.read.enabled", True,
                         "Enable CSV reads.", _to_bool)
PARQUET_ENABLED = _conf("spark.rapids.sql.format.parquet.enabled", True,
                        "Enable Parquet acceleration.", _to_bool)
PARQUET_READ_ENABLED = _conf("spark.rapids.sql.format.parquet.read.enabled",
                             True, "Enable Parquet reads.", _to_bool)
PARQUET_WRITE_ENABLED = _conf("spark.rapids.sql.format.parquet.write.enabled",
                              True, "Enable Parquet writes.", _to_bool)
ORC_ENABLED = _conf("spark.rapids.sql.format.orc.enabled", True,
                    "Enable ORC acceleration.", _to_bool)
ORC_READ_ENABLED = _conf("spark.rapids.sql.format.orc.read.enabled", True,
                         "Enable ORC reads.", _to_bool)
ORC_WRITE_ENABLED = _conf("spark.rapids.sql.format.orc.write.enabled", True,
                          "Enable ORC writes.", _to_bool)
PARQUET_DEVICE_DECODE = _conf(
    "spark.rapids.sql.format.parquet.deviceDecode.enabled", True,
    "Decode parquet PLAIN/dictionary pages of flat numeric/bool columns "
    "on the device (host keeps only page headers, run structure, and "
    "definition levels); columns outside scope fall back to the host "
    "arrow reader per column.", _to_bool)
ORC_DEVICE_ENCODE = _conf(
    "spark.rapids.sql.format.orc.deviceEncode.enabled", True,
    "Encode ORC writes on the device: null compaction, contiguous string "
    "byte packing + lengths, and min/max/count statistics run as device "
    "kernels and the compacted stream payload is the only D2H transfer; "
    "the host writes RLE runs and the protobuf stripe footer / metadata "
    "/ footer / postscript (io/orc_device_write.py).  Timestamp columns "
    "and partitioned writes fall back to the host arrow encoder.",
    _to_bool)
PARQUET_DEVICE_ENCODE = _conf(
    "spark.rapids.sql.format.parquet.deviceEncode.enabled", True,
    "Encode parquet writes on the device: null compaction, string "
    "[len][bytes] stream packing, and column statistics run as device "
    "kernels and the encoded page payload is the only D2H transfer; the "
    "host writes definition-level runs, page headers, and the thrift "
    "footer.  Partitioned writes fall back to the host arrow encoder.",
    _to_bool)
ORC_DEVICE_DECODE = _conf(
    "spark.rapids.sql.format.orc.deviceDecode.enabled", True,
    "Decode the core ORC primitives on the device: floats/doubles (IEEE "
    "payload), tinyint/ints/dates (byte-RLE / RLEv2 DIRECT "
    "bit-extraction), strings "
    "(DIRECT_V2 and DICTIONARY_V2 blob gathers), booleans, and "
    "timestamps.  The host keeps the protobuf control plane, zlib "
    "inflation, byte-RLE bitmaps, and RLEv2 run headers.  "
    "Char/varchar/decimal/binary, non-GMT writer timezones, and nested "
    "types fall back to the host stripe reader column-granularly.",
    _to_bool)
CSV_DEVICE_DECODE = _conf(
    "spark.rapids.sql.format.csv.deviceDecode.enabled", True,
    "Tokenize and parse CSV on the device: the host computes only the "
    "delimiter index structure (one vectorized scan), the device gathers "
    "per-column byte matrices from the raw file buffer and runs the "
    "string->value parse kernels; quoted files tokenize through the "
    "native C scanner.  CR line endings and jagged rows fall back to "
    "the host arrow reader.", _to_bool)
PARQUET_DEBUG_DUMP_PREFIX = _conf(
    "spark.rapids.sql.parquet.debug.dumpPrefix", "",
    "If set, dump the clipped host parquet buffer to this path prefix for "
    "offline repro.", str)

# --- shuffle ----------------------------------------------------------------
SHUFFLE_TRANSPORT_CLASS = _conf(
    "spark.rapids.shuffle.transport.class",
    "spark_rapids_tpu.shuffle.ici.IciShuffleTransport",
    "Implementation of the device shuffle transport "
    "(ICI all-to-all on-slice; loopback transport for tests).", str)
SHUFFLE_MAX_RECV_INFLIGHT = _conf(
    "spark.rapids.shuffle.maxReceiveInflightBytes", 1 << 30,
    "Cap on bytes of shuffle data in flight to a receiving task.", to_bytes)
SHUFFLE_ASYNC_FETCH = _conf(
    "spark.rapids.shuffle.asyncFetch.enabled", True,
    "Pipeline the shuffle read: a producer thread fetches partition k+1 "
    "while partition k is being consumed, bounded by "
    "maxReceiveInflightBytes of un-consumed batches.", _to_bool)
SHUFFLE_DEVICE_RESIDENT = _conf(
    "spark.rapids.shuffle.deviceResident.enabled", True,
    "Keep shuffle partitions resident in HBM (spillable) instead of "
    "serializing to host between stages.", _to_bool)
SHUFFLE_RETRY_ATTEMPTS = _conf(
    "spark.rapids.shuffle.retry.maxAttempts", 4,
    "Attempts per shuffle socket operation (connect, metadata, fetch) "
    "before the error propagates; attempts after the first back off "
    "exponentially with jitter.", int)
SHUFFLE_RETRY_BACKOFF_BASE = _conf(
    "spark.rapids.shuffle.retry.backoffBaseMs", 50,
    "Base backoff in milliseconds between shuffle retries; attempt k "
    "waits ~base*2^k (jittered, capped by backoffCapMs).", int)
SHUFFLE_RETRY_BACKOFF_CAP = _conf(
    "spark.rapids.shuffle.retry.backoffCapMs", 2000,
    "Upper bound in milliseconds on the shuffle retry backoff.", int)
SHUFFLE_CONNECT_TIMEOUT = _conf(
    "spark.rapids.shuffle.connectTimeoutMs", 30000,
    "Per-attempt TCP connect timeout for shuffle clients, in "
    "milliseconds.", int)
SHUFFLE_IO_TIMEOUT = _conf(
    "spark.rapids.shuffle.ioTimeoutMs", 60000,
    "Per-socket-operation I/O deadline for shuffle DATA-plane requests "
    "(metadata, layout, fetch), in milliseconds; a dead peer surfaces as "
    "a timeout within this bound instead of hanging.  0 disables.  "
    "Control-plane RPCs (task dispatch) are exempt: they legitimately "
    "block on first-query compilation at the peer.", int)
SHUFFLE_TXN_TIMEOUT = _conf(
    "spark.rapids.shuffle.transactionTimeoutMs", 600000,
    "Overall deadline for one shuffle fetch transaction (layout + every "
    "data frame + END) in milliseconds; past it the transaction is "
    "CANCELLED and the error propagates without further retries.  "
    "0 disables.", int)
SHUFFLE_CHECKSUM_ENABLED = _conf(
    "spark.rapids.shuffle.checksum.enabled", True,
    "Checksum every shuffle buffer leaf at its first device->host "
    "materialization and verify before fetched bytes become a columnar "
    "batch (streamed, shared-memory and loopback fetch paths).  On "
    "mismatch the reader refetches up to maxRefetchAttempts and runs a "
    "writer-side diagnosis to classify the corruption site "
    "(SPARK-35275/36206 analogue; docs/tuning-guide.md, Data integrity).",
    _to_bool)
SHUFFLE_CHECKSUM_ALGO = _conf(
    "spark.rapids.shuffle.checksum.algorithm", "crc32c",
    "Checksum algorithm for shuffle and spill integrity: crc32c "
    "(hardware CRC32C when the google_crc32c C library is importable, "
    "~10 GB/s; falls back to xxhash then zlib crc32), xxhash (xxh3_64), "
    "crc32, adler32, or none.", str)
SHUFFLE_CHECKSUM_VERIFY_LOCAL = _conf(
    "spark.rapids.shuffle.checksum.verifyOnLocalRead", False,
    "Also verify checksums when a reduce task reads blocks from its OWN "
    "executor's catalog (host-serialized baseline buffers and "
    "host/disk-tier spilled buffers).  Off by default: local reads never "
    "cross a wire, so this only guards against host-memory rot at extra "
    "read cost.", _to_bool)
SHUFFLE_COMPRESSION_CODEC = _conf(
    "spark.rapids.shuffle.compression.codec", "none",
    "Codec for shuffle buffers crossing the wire or served from spill "
    "tiers: lz4, zstd, snappy, or none (reference: "
    "spark.rapids.shuffle.compression.codec / TableCompressionCodec).  "
    "Leaves are compressed into a chunked framed format so chunks "
    "(de)compress in parallel on a side thread pool overlapped with "
    "socket send/recv; incompressible chunks are stored raw.  The codec "
    "is negotiated per fetch: a peer that cannot encode the requested "
    "codec answers raw (counted in numCompressionFallbacks).  Checksums "
    "cover the compressed frames, so corrupt bytes are detected before "
    "they reach a decompressor.  `none` keeps today's raw wire path.",
    str)
SHUFFLE_COMPRESSION_CHUNK_SIZE = _conf(
    "spark.rapids.shuffle.compression.chunkSizeBytes", 1 << 20,
    "Chunk size of the framed compression container (shuffle AND spill "
    "tiers).  Smaller chunks parallelize better across the codec thread "
    "pool and bound the raw-escape granularity; larger chunks compress "
    "slightly better.", to_bytes)
SHUFFLE_COMPRESSION_MIN_SIZE = _conf(
    "spark.rapids.shuffle.compression.minSizeBytes", 1 << 10,
    "Leaves smaller than this skip the codec entirely (framed with raw "
    "chunks): below it the per-call codec overhead outweighs any wire/"
    "disk savings.", to_bytes)
SPILL_COMPRESSION_CODEC = _conf(
    "spark.rapids.memory.spill.compression.codec", "none",
    "Codec for host->disk spill files: lz4, zstd, snappy, or none.  "
    "Conf'd independently of the shuffle wire codec; shares "
    "spark.rapids.shuffle.compression.{chunkSizeBytes,minSizeBytes}.  "
    "Spill-time checksums are recorded over BOTH forms: the compressed "
    "disk image is verified before decompression at disk-read/unspill, "
    "and the decompressed leaves are verified against the original "
    "spill digests after.", str)
SHUFFLE_BOUNCE_POOL_SIZE = _conf(
    "spark.rapids.shuffle.bounce.poolSizeBytes", 8 << 20,
    "Size of the pre-allocated host bounce-buffer staging pool every "
    "shuffle transport sub-allocates transfer slices from "
    "(BounceBufferManager analogue).  "
    "spark.rapids.memory.pinnedPool.size, when set, overrides this.",
    to_bytes)
SHUFFLE_BOUNCE_CHUNK_SIZE = _conf(
    "spark.rapids.shuffle.bounce.chunkSizeBytes", 1 << 20,
    "Size of one bounce-buffer transfer slice: shuffle data frames "
    "cross the wire in chunks of at most this many bytes.", to_bytes)
SHUFFLE_MAX_REFETCH = _conf(
    "spark.rapids.shuffle.maxRefetchAttempts", 2,
    "Refetch attempts for a shuffle buffer whose checksum verification "
    "failed at the reader (transient wire/reader corruption).  Exhausting "
    "them — or a writer-side diagnosis (the peer's stored data no longer "
    "matches its recorded checksum) — escalates to FetchFailed, marking "
    "the map output lost so the map fragment is recomputed.", int)

# --- joins ------------------------------------------------------------------
def _to_bytes_or_disabled(v) -> int:
    """Byte size, or any negative value meaning 'disabled' (Spark allows
    autoBroadcastJoinThreshold=-1; other byte confs stay strictly
    non-negative via to_bytes)."""
    try:
        n = int(str(v).strip())
        if n < 0:
            return n
    except ValueError:
        pass  # tpulint: disable=TPU006 parse fallthrough: not a bare int, try the byte-suffix grammar next
    return to_bytes(v)


AUTO_BROADCAST_JOIN_THRESHOLD = _conf(
    "spark.sql.autoBroadcastJoinThreshold", 10 << 20,
    "Maximum estimated size in bytes of a join build side that will be "
    "broadcast to every consumer instead of shuffled (Spark's conf key; "
    "-1 disables broadcast joins).", _to_bytes_or_disabled)

# --- adaptive query execution -----------------------------------------------
ADAPTIVE_ENABLED = _conf(
    "spark.rapids.sql.tpu.adaptive.enabled", True,
    "Re-plan queries at shuffle-stage boundaries from OBSERVED map-output "
    "sizes (Spark 3 AQE analogue; reference: GpuShuffleExchangeExec + "
    "GpuCustomShuffleReaderExec).  Map stages are materialized first, then "
    "the reduce side is instantiated with coalesced small partitions, "
    "split skewed partitions, and possibly a different join strategy "
    "(adaptive/).", _to_bool)
ADAPTIVE_ADVISORY_PARTITION_SIZE = _conf(
    "spark.rapids.sql.tpu.adaptive.advisoryPartitionSizeBytes", 64 << 20,
    "Target size of a shuffle partition after adaptive re-planning: "
    "contiguous partitions smaller than this are merged by the coalesce "
    "rule, and skewed partitions are split into slices of roughly this "
    "size (spark.sql.adaptive.advisoryPartitionSizeInBytes analogue).",
    to_bytes)
ADAPTIVE_COALESCE_ENABLED = _conf(
    "spark.rapids.sql.tpu.adaptive.coalescePartitions.enabled", True,
    "Enable the AQE rule that merges contiguous small reduce partitions "
    "up to advisoryPartitionSizeBytes (served by "
    "TpuCoalescedShuffleReaderExec).", _to_bool)
ADAPTIVE_SKEW_ENABLED = _conf(
    "spark.rapids.sql.tpu.adaptive.skewJoin.enabled", True,
    "Enable the AQE skew-join rule: a stream-side partition larger than "
    "skewedPartitionFactor x the median partition size is split into "
    "map-range slices, each joined against a replicated copy of the "
    "build-side partition.", _to_bool)
ADAPTIVE_SKEW_FACTOR = _conf(
    "spark.rapids.sql.tpu.adaptive.skewJoin.skewedPartitionFactor", 5.0,
    "A partition is skew-split when its observed bytes exceed this factor "
    "times the median non-empty partition size (and the size floor "
    "skewedPartitionThresholdInBytes).", float)
ADAPTIVE_SKEW_THRESHOLD = _conf(
    "spark.rapids.sql.tpu.adaptive.skewJoin.skewedPartitionThresholdInBytes",
    256 << 20,
    "Size floor below which a partition is never considered skewed, "
    "whatever the factor test says.", to_bytes)
ADAPTIVE_JOIN_STRATEGY_ENABLED = _conf(
    "spark.rapids.sql.tpu.adaptive.joinStrategy.enabled", True,
    "Enable AQE join-strategy switching: a partitioned join whose "
    "observed build side fits under spark.sql.autoBroadcastJoinThreshold "
    "is promoted to a single-build (broadcast-style) join, and a planned "
    "broadcast whose observed build side exceeds the threshold is demoted "
    "to a partitioned join.", _to_bool)

# --- fault injection (test-only) --------------------------------------------
TEST_INJECT_OOM = _conf(
    "spark.rapids.tpu.test.injectOom", "",
    "Deterministic OOM injection spec over the process-wide reserve() "
    "counter: '3' fails reserve #3 once, '3x2' fails #3 and #4, "
    "'split@5' raises SplitAndRetryOOM at #5, 'p=0.05' fails with that "
    "probability (seeded by injectSeed).  Testing only.", str,
    internal=True)
TEST_INJECT_NET = _conf(
    "spark.rapids.tpu.test.injectNetFault", "",
    "Deterministic network-fault injection spec over the client-side "
    "shuffle socket-op counter (same grammar as injectOom, minus "
    "split@).  An @-prefixed item selects a per-SITE ordinal instead "
    "('rpc:run_reduce@1' fails the 1st run_reduce control rpc; sites "
    "are the on_net_op labels: metadata, layout, fetch, fetch_shm, "
    "done, diag, rpc:<method>) — the cluster-rpc fault sweep's "
    "addressing mode.  Testing only.", str, internal=True)
TEST_INJECT_CORRUPTION = _conf(
    "spark.rapids.tpu.test.injectCorruption", "",
    "Deterministic single-bit corruption injection over the transfer/"
    "spill paths.  Items are site-scoped ordinals: 'wire@3' flips a bit "
    "in the 3rd chunk staged for a socket send, 'shm@1' in the 1st "
    "shared-memory leaf fill, 'loopback@2' in the 2nd loopback bounce "
    "chunk, 'spill@1' in the 1st device->host spilled leaf, 'disk@1' in "
    "the 1st host->disk image, 'writer@1x9' in the writer's served "
    "leaves (persistent: window of 9).  A bare ordinal ('5') counts "
    "across all sites; 'p=0.01' corrupts probabilistically (seeded by "
    "injectSeed).  Testing only.", str, internal=True)
TEST_INJECT_DELAY = _conf(
    "spark.rapids.tpu.test.injectDelay", "",
    "Deterministic slowdown injection for straggler/watchdog testing.  "
    "Comma-separated items 'site:ms' or 'scope/site:ms': the injector "
    "sleeps that many milliseconds at every matching delay point "
    "(worker task sites are 'map' and 'reduce').  A scope prefix "
    "restricts the item to the process whose injector scope matches "
    "(executor workers set their executor id as the scope), so "
    "'exec-1/reduce:1500' slows ONLY exec-1's reduce tasks.  "
    "Testing only.", str, internal=True)
TEST_INJECT_CRASH = _conf(
    "spark.rapids.tpu.test.injectCrash", "",
    "Deterministic worker-crash injection for chaos testing: the worker "
    "process calls os._exit mid-task at the selected crash point.  Items "
    "are site-scoped ordinals over the per-process crash-point counter "
    "('map@2' = this process's 2nd map task, 'reduce@1'), bare ordinals "
    "count across all sites, 'p=0.02' crashes probabilistically (seeded "
    "by injectSeed), and a 'scope/' prefix restricts the item to the "
    "process whose injector scope matches ('exec-1/map@1' kills only "
    "exec-1, on its 1st map task) — the same scoping injectDelay uses.  "
    "Testing only.", str, internal=True)
TEST_INJECT_SEED = _conf(
    "spark.rapids.tpu.test.injectSeed", 0,
    "Seed for the probabilistic fault-injection mode.", int,
    internal=True)

# --- observability -----------------------------------------------------------
def _to_metrics_level(v) -> str:
    s = str(v).strip().upper()
    if s not in ("ESSENTIAL", "MODERATE", "DEBUG"):
        raise ValueError(
            f"not a metrics level: {v!r} (ESSENTIAL|MODERATE|DEBUG)")
    return s


METRICS_LEVEL = _conf(
    "spark.rapids.sql.tpu.metrics.level", "MODERATE",
    "How many operator metrics to record (reference: "
    "spark.rapids.sql.metrics.level).  ESSENTIAL keeps only free host-side "
    "counters; MODERATE (default) adds timers and lazily folded device row "
    "counts; DEBUG adds per-batch device-sync metrics (exact row counts, "
    "peakDevMemory) with measurable overhead.  See docs/monitoring.md.",
    _to_metrics_level)
METRICS_JOURNAL_DIR = _conf(
    "spark.rapids.sql.tpu.metrics.journal.dir", "",
    "Directory for per-query structured event journals (JSON-lines spans: "
    "query/operator/retry/spill/fetch events with monotonic timestamps and "
    "parent links; one query-<id>.jsonl per query).  Empty disables the "
    "file journal; at metrics.level=DEBUG an in-memory journal is kept "
    "regardless and is reachable via session.last_execution.journal.  "
    "Executor worker processes additionally write one shard-<executor>"
    ".jsonl trace shard each (docs/monitoring.md, Distributed tracing).",
    str)

# --- roofline-attribution profiler (metrics/roofline.py) ---------------------
ROOFLINE_ENABLED = _conf(
    "spark.rapids.sql.tpu.roofline.enabled", True,
    "Roofline ledger annotations in EXPLAIN METRICS: each plan node's "
    "line gains its bottleneck resource (hbm / h2d / d2h / wire / flops "
    "/ host), achieved rate, and utilization vs the resource's peak, "
    "joined from the operators' cost declarations and measured span "
    "durations.  The underlying cost COUNTERS (hbmBytesRead/Written, "
    "h2dBytes, d2hBytes, wireBytes, estFlops) are ordinary MODERATE "
    "metrics gated by metrics.level, not by this flag.  See "
    "docs/monitoring.md, 'Reading the roofline ledger'.", _to_bool)
ROOFLINE_COST_ENABLED = _conf(
    "spark.rapids.sql.tpu.roofline.costAccounting.enabled", True,
    "Per-operator roofline cost declarations (hbmBytesRead/Written, "
    "h2dBytes, d2hBytes, wireBytes, estFlops — free host-side metadata "
    "increments).  Off disables the declarations entirely (every ledger "
    "node reads host-bound), which is the A/B the bench profile stage "
    "and tests/test_roofline.py measure profiler overhead with.  "
    "Latched per query like the packed-sort flag: the declarations are "
    "observability-only, so a concurrent query with a different setting "
    "at worst records (or skips) its own declarations.", _to_bool)
ROOFLINE_PEAK_HBM = _conf(
    "spark.rapids.sql.tpu.roofline.peakHbmGBs", 0.0,
    "HBM bandwidth roofline in GB/s used as the ledger's denominator "
    "for the 'hbm' resource.  0 (default) picks the platform nominal "
    "(v5e-class 819 GB/s on TPU, a conservative 20 GB/s on the CPU "
    "backend).  Set it to a measured STREAM-like figure for honest "
    "utilization percentages on your hardware.", float)
ROOFLINE_PEAK_LINK = _conf(
    "spark.rapids.sql.tpu.roofline.peakLinkGBs", 0.0,
    "Host<->device link roofline in GB/s ('h2d'/'d2h' resources).  "
    "0 picks the platform nominal; on a tunneled dev chip the REAL link "
    "is ~0.026 GB/s — setting this to the measured transfer_microbench "
    "number makes host-detour nodes light up honestly.", float)
ROOFLINE_PEAK_WIRE = _conf(
    "spark.rapids.sql.tpu.roofline.peakWireGBs", 0.0,
    "Socket shuffle-wire roofline in GB/s ('wire' resource).  0 picks "
    "1 GB/s (the measured BENCH_WIRE loopback figure); set to your NIC "
    "line rate on a real cluster.", float)
ROOFLINE_PEAK_GFLOPS = _conf(
    "spark.rapids.sql.tpu.roofline.peakGflops", 0.0,
    "Compute roofline in GFLOP/s ('flops' resource).  0 picks the "
    "platform nominal (98 TFLOP/s f32-class on TPU, 50 GFLOP/s on the "
    "CPU backend).", float)
ROOFLINE_PEAK_ICI = _conf(
    "spark.rapids.sql.tpu.roofline.peakIciGBs", 0.0,
    "Inter-chip-interconnect roofline in GB/s ('ici' resource): the "
    "denominator for bytes moved by mesh-lowered exchange collectives "
    "(iciBytesMoved).  0 picks the platform nominal (v5e-class ~100 GB/s "
    "per-chip on TPU; memcpy-class 20 GB/s on the virtual-device CPU "
    "backend, where the 'collective' is a compiled copy).", float)

# --- distributed tracing (metrics/timeline.py + shuffle wire trace) ----------
TRACE_ENABLED = _conf(
    "spark.rapids.sql.tpu.trace.enabled", True,
    "Cluster-wide distributed tracing: every executor worker keeps a "
    "process-lifetime journal shard (task/operator/fetch/serve spans with "
    "a wall-clock anchor record), shuffle wire requests carry a "
    "(query, stage, span, executor) trace context so a reducer's fetch "
    "span flow-links to the mapper's serve span, and the driver can drain "
    "+ merge every shard into ONE query timeline "
    "(python -m spark_rapids_tpu.metrics --timeline; "
    "cluster.merged_timeline()).  Off disables shard journaling, wire "
    "trace stamping and the heartbeat monitor.", _to_bool)
TRACE_STRAGGLER_FACTOR = _conf(
    "spark.rapids.sql.tpu.trace.stragglerFactor", 3.0,
    "A task whose duration exceeds this factor times the median duration "
    "of its stage's tasks is flagged as a straggler by the merged-"
    "timeline analysis (numStragglers; --timeline report).", float)
TRACE_HEARTBEAT_INTERVAL = _conf(
    "spark.rapids.sql.tpu.trace.heartbeatIntervalMs", 1000,
    "Interval between live progress heartbeats pulled from every worker "
    "over a DEDICATED control connection (counters, pool stats, active-"
    "task snapshots -> session.progress() / cluster.progress()).  "
    "0 disables the heartbeat monitor.", int)
TRACE_HUNG_TASK_TIMEOUT = _conf(
    "spark.rapids.sql.tpu.trace.hungTaskTimeoutMs", 600000,
    "A task still active past this bound in a worker's heartbeat "
    "snapshots is logged by the driver's hung-task watchdog and counted "
    "(numHungTasks).  0 disables the watchdog.", int)
TRACE_SHARD_MAX_EVENTS = _conf(
    "spark.rapids.sql.tpu.trace.shard.maxEvents", 65536,
    "Bound on undrained in-memory trace-shard events per worker; overflow "
    "evicts the oldest events and is counted in the drain response "
    "(a driver that never drains must not leak worker memory).", int,
    internal=True)

# --- live telemetry plane (metrics/ring.py + bundle.py + http.py) ------------
TELEMETRY_ENABLED = _conf(
    "spark.rapids.sql.tpu.telemetry.enabled", True,
    "Always-on flight recorder: every process (driver and each executor "
    "worker) keeps a bounded in-memory ring of its last journal records "
    "plus a background gauge-sampler thread snapshotting pool / "
    "transport / scheduler gauges into fixed-interval time series.  The "
    "ring and sampler feed the /metrics endpoint, the Chrome-trace "
    "counter lanes, and post-mortem bundles; their measured overhead is "
    "gated at <=2% wall time by scripts/obs_overhead.py (BENCH_OBS.json). "
    " Off disables the ring tap, the sampler thread and the per-process "
    "HTTP endpoints.", _to_bool)
TELEMETRY_RING_MAX_EVENTS = _conf(
    "spark.rapids.sql.tpu.telemetry.ring.maxEvents", 2048,
    "Capacity of the per-process flight-recorder ring: the last N "
    "journal records are mirrored in memory (oldest evicted first, "
    "evictions counted) and land in post-mortem bundles as "
    "ring-<process>.jsonl.  Sized so a bundle holds the final seconds "
    "of every process at negligible resident cost.", int)
TELEMETRY_SAMPLE_INTERVAL = _conf(
    "spark.rapids.sql.tpu.telemetry.sampleIntervalMs", 250,
    "Interval between gauge-sampler snapshots (pool bytes in use, "
    "in-flight tasks, spill bytes, scheduler queue depths).  Each "
    "snapshot appends one point per series to the in-memory time series "
    "served by /metrics and, when a trace shard is open, one "
    "gaugeSample journal instant that becomes a Chrome-trace counter "
    "lane.  0 disables the sampler thread (the ring tap stays on).",
    int)
TELEMETRY_SAMPLE_MAX = _conf(
    "spark.rapids.sql.tpu.telemetry.sample.maxSamples", 2400,
    "Bound on retained points per sampled gauge series; overflow evicts "
    "the oldest points (10 minutes of history at the default 250ms "
    "interval).", int, internal=True)
TELEMETRY_HTTP_ENABLED = _conf(
    "spark.rapids.sql.tpu.telemetry.http.enabled", True,
    "Per-process loopback HTTP endpoint serving /metrics (Prometheus "
    "text of the sampler's current series, parse_prometheus-clean), "
    "/healthz (liveness verdict) and /debug/observability "
    "(session_observability + progress as JSON).  Workers announce "
    "their port in the ready line; the driver's is in "
    "session_observability['telemetry']['http_address'].", _to_bool)
TELEMETRY_HTTP_PORT = _conf(
    "spark.rapids.sql.tpu.telemetry.http.port", 0,
    "Port for the driver telemetry HTTP endpoint (workers always bind "
    "an ephemeral loopback port and announce it).  0 (default) binds an "
    "ephemeral port.", int)
TELEMETRY_POSTMORTEM_DIR = _conf(
    "spark.rapids.sql.tpu.telemetry.postmortem.dir", "",
    "Directory for automatic post-mortem diagnostic bundles.  When set, "
    "a bundle (config, EXPLAIN with roofline, merged timeline, "
    "memledger replay, SLO state, per-process ring dumps) is dumped on "
    "query failure, hung-task watchdog fire, retry-budget exhaustion, "
    "and SIGUSR1; render one with "
    "`python -m spark_rapids_tpu.metrics postmortem <bundle>`.  "
    "Empty (default) disables automatic dumps — "
    "session.dump_diagnostics() stays available either way.", str)
TELEMETRY_POSTMORTEM_MIN_INTERVAL = _conf(
    "spark.rapids.sql.tpu.telemetry.postmortem.minIntervalMs", 30000,
    "Rate limit between automatic post-mortem dumps: a trigger firing "
    "within this window of the previous dump is counted "
    "(numPostmortemSuppressed) instead of dumped, so a failure storm "
    "cannot fill the disk.", int, internal=True)

# --- distributed task scheduling: deadlines, backoff, speculation ------------
TASK_TIMEOUT = _conf(
    "spark.rapids.sql.tpu.task.timeoutMs", 0,
    "Per-attempt deadline for a distributed task rpc (run_map/run_reduce "
    "on a ProcCluster worker), in milliseconds.  A task past its deadline "
    "is ABANDONED (counted in numAbandonedTasks), its worker is "
    "health-probed over the heartbeat monitor's dedicated connection, and "
    "a wedged-but-alive worker is evicted exactly like a dead one "
    "(replaced, its map fragments recomputed from the lineage).  "
    "0 (default) derives the deadline from "
    "spark.rapids.sql.tpu.trace.hungTaskTimeoutMs; set both to 0 to run "
    "task waves unbounded (the pre-deadline behavior).", int)
TASK_RETRY_BACKOFF = _conf(
    "spark.rapids.sql.tpu.task.retryBackoffMs", 200,
    "Base backoff in milliseconds between distributed task retry waves; "
    "wave k waits ~base*2^k with deterministic jitter, capped by "
    "task.maxBackoffMs — a failed wave backs off instead of hammering a "
    "recovering peer.  0 disables the inter-wave backoff.", int)
TASK_MAX_BACKOFF = _conf(
    "spark.rapids.sql.tpu.task.maxBackoffMs", 10000,
    "Upper bound in milliseconds on the distributed task retry backoff.",
    int)
TASK_SPECULATION_ENABLED = _conf(
    "spark.rapids.sql.tpu.task.speculation.enabled", True,
    "Speculatively re-execute straggling distributed tasks: when a task "
    "runs longer than spark.rapids.sql.tpu.trace.stragglerFactor x the "
    "median task duration of its stage (or past the hung-task watchdog "
    "bound), a second copy launches on the least-loaded healthy worker "
    "under a distinct attempt id.  First result wins; the loser is "
    "cancelled/ignored and map-output registration is attempt-id-guarded "
    "so the reduce side never reads a mix of attempts "
    "(numSpeculativeTasks / numSpeculationWins).", _to_bool)
TASK_MAX_WORKER_REPLACEMENTS = _conf(
    "spark.rapids.sql.tpu.task.maxWorkerReplacements", 8,
    "Worker replacements allowed per query (run_map_reduce call) before "
    "the cluster degrades gracefully: when the budget is exhausted — or "
    "a replacement spawn itself fails — the dead worker's slot is "
    "SHRUNK away and its task assignments re-balance onto the surviving "
    "workers instead of failing the query (worker_shrinks counter, "
    "journal kind 'spec').  Negative means unlimited.", int)

# --- memory ledger (mem/ledger.py + metrics/memledger.py) --------------------
MEM_LEDGER_ENABLED = _conf(
    "spark.rapids.sql.tpu.memory.ledger.enabled", True,
    "Memory-pressure ledger: journal every allocation-boundary event of "
    "the spill framework (alloc/free/spill/unspill/oomSpill, journal kind "
    "'mem') stamped with the active trace context and causally linked — "
    "an oomSpill record names the triggering reservation site and the "
    "exact victim buffer ids, so spill cascades are traversable chains.  "
    "Events land in the active query journal / worker trace shard; "
    "`python -m spark_rapids_tpu.metrics --memory <journal-dir>` "
    "reconstructs peak attribution, spill churn, victim quality and a "
    "headroom estimate offline.  At metrics.level=DEBUG every reserve() "
    "is additionally journaled; below DEBUG only pressured reservations "
    "are (docs/tuning-guide.md, Memory observability).", _to_bool)
MEM_LEDGER_SAMPLE_MS = _conf(
    "spark.rapids.sql.tpu.memory.ledger.sampleIntervalMs", 100,
    "Minimum milliseconds between sampled memory-pressure records "
    "(ledger 'pressure' instants carrying per-tier used bytes + the pool "
    "limit — the per-worker memory lane of the Chrome trace / merged "
    "timeline).  OOM events always force a sample.  0 samples on every "
    "ledger event.", int)

# --- data-movement policy engine (policy/) -----------------------------------
POLICY_ENABLED = _conf(
    "spark.rapids.sql.tpu.policy.enabled", True,
    "Master switch for the data-movement policy engine (policy/): "
    "next-use spill victim selection, proactive unspill of soon-needed "
    "buffers, reduce-driven shuffle flow control, and roofline-driven "
    "codec re-selection.  The engine only CONSUMES signals the ledgers "
    "already produce (memory ledger re-touch history, shuffle read "
    "order, roofline wire peak) and journals every decision under kind "
    "'policy'.  false is the kill switch: victim order, fetch admission "
    "and wire codec revert byte-identically to the pre-policy engine "
    "(docs/tuning-guide.md, Data-movement policy).", _to_bool)
POLICY_RETOUCH_WEIGHT = _conf(
    "spark.rapids.sql.tpu.policy.victim.retouchWeight", 4.0,
    "Score bonus protecting a spill victim per prior spill of the same "
    "buffer (capped at 4 round trips).  The memory ledger's re-touch "
    "history is the churn signal: a buffer that already paid a "
    "spill+unspill round trip is this much LESS likely to be evicted "
    "again than a never-spilled peer.  0 disables re-touch protection; "
    "victims then rank purely on shuffle-partition liveness.", float)
POLICY_EARLY_RELEASE = _conf(
    "spark.rapids.sql.tpu.policy.earlyRelease.enabled", True,
    "Free a shuffle partition's map-side device buffers as soon as the "
    "declared read plan has consumed it for the LAST time (single-"
    "consumer local exchanges only — never with a cluster attached, "
    "where a peer or a speculative re-read may still fetch the block).  "
    "A fully-consumed partition has next-use = never: releasing it "
    "outright returns its bytes to the pool with no spill write, where "
    "the baseline would re-spill it under pressure and count churn.  "
    "Skew slices and coalesced specs that read a partition more than "
    "once are planned for — the release fires only after the final "
    "planned consumption.", _to_bool)
POLICY_UNSPILL_INTERVAL = _conf(
    "spark.rapids.sql.tpu.policy.unspill.intervalMs", 20,
    "Wake interval of the proactive-unspill policy thread.  Each tick "
    "re-materializes up to a few spilled buffers with the nearest "
    "declared next use, charged to the owning query's ledger scope "
    "(and its serve.queryBudgetBytes, so a prefetch can never cause "
    "another query's OOM).  0 disables the thread; victim scoring and "
    "flow control stay active.", int)
POLICY_UNSPILL_HEADROOM = _conf(
    "spark.rapids.sql.tpu.policy.unspill.headroomFraction", 0.5,
    "Pool fraction that must remain free AFTER a proactive unspill for "
    "it to be admitted — the prefetch is opportunistic and must never "
    "push the device pool toward an eviction it would not otherwise "
    "have performed.  Unspills additionally require the pool to be "
    "spill-quiescent since the policy's previous tick.", float)
POLICY_FLOW_MIN_WINDOW = _conf(
    "spark.rapids.sql.tpu.policy.flow.minWindowBytes", 4 << 20,
    "Floor of the reduce-driven flow-control window.  The window is "
    "max(this, observed reduce consumption rate x flow.horizonMs): a "
    "stalled consumer shrinks admission to this floor (progress is "
    "always possible; one batch of any size still admits alone), a fast "
    "consumer widens it up to the transport's static "
    "maxReceiveInflightBytes bound.", to_bytes)
POLICY_FLOW_HORIZON = _conf(
    "spark.rapids.sql.tpu.policy.flow.horizonMs", 200,
    "Flow-control horizon: the in-flight-bytes window targets this many "
    "milliseconds of the reduce side's observed consumption rate, so a "
    "producer holds at most ~horizon's worth of un-consumed bytes in "
    "flight instead of ballooning host memory behind a slow consumer.",
    int)
POLICY_FLOW_MAX_STALL = _conf(
    "spark.rapids.sql.tpu.policy.flow.maxServeStallMs", 50,
    "Upper bound on one map-side serve stall when in-flight served "
    "bytes exceed the flow-control window; past it the serve proceeds "
    "anyway (soft backpressure — bounded stalls cannot deadlock the "
    "exchange; counted in numBackpressureStalls).", int, internal=True)
POLICY_CODEC = _conf(
    "spark.rapids.sql.tpu.policy.codec.candidate", "lz4",
    "Codec the policy engine advises for fetches of an exchange proven "
    "wire-bound at runtime (read throughput at or above "
    "codec.wireBoundFraction of the roofline wire peak at "
    "codec.minExchangeBytes volume).  Rides the shuffle compression "
    "negotiation end to end — the server may still answer raw when the "
    "codec is unavailable there.  'none' disables re-selection; a "
    "session with spark.rapids.shuffle.compression.codec explicitly "
    "enabled is never second-guessed.", str)
POLICY_CODEC_MIN_BYTES = _conf(
    "spark.rapids.sql.tpu.policy.codec.minExchangeBytes", 32 << 20,
    "Minimum wire bytes an exchange's read phase must have moved before "
    "its throughput evidence can trigger codec re-selection — tiny "
    "exchanges prove nothing and never pay codec CPU.", to_bytes)
POLICY_CODEC_WIRE_BOUND = _conf(
    "spark.rapids.sql.tpu.policy.codec.wireBoundFraction", 0.5,
    "Fraction of the roofline wire peak (metrics/roofline.py "
    "platform_peaks, overridable via ROOFLINE_PEAK_* confs) an "
    "exchange's observed read throughput must reach to be judged "
    "wire-bound for codec re-selection.", float)

# --- serving tier (serve/: scheduler, admission, plan cache) -----------------
SERVE_MAX_CONCURRENT = _conf(
    "spark.rapids.sql.tpu.serve.maxConcurrentQueries", 4,
    "Worker threads the session's QueryScheduler runs — the upper bound "
    "on queries EXECUTING at once (TpuSession.submit).  Device occupancy "
    "within an executing query is still bounded by "
    "spark.rapids.sql.concurrentTpuTasks (the device semaphore); this "
    "knob bounds how many queries overlap their host-side phases "
    "(planning, scan decode, D2H) around it.", int)
SERVE_QUEUE_CAPACITY = _conf(
    "spark.rapids.sql.tpu.serve.queue.capacity", 256,
    "Submitted-but-not-yet-admitted queries the scheduler will hold; a "
    "submit() past this bound raises AdmissionRejected (counted in "
    "numAdmissionRejections) instead of buffering without bound — "
    "backpressure belongs at admission, not in the spill tier.", int)
SERVE_ADMISSION_FRACTION = _conf(
    "spark.rapids.sql.tpu.serve.admission.memoryFraction", 1.5,
    "Fair-share admission bound: the sum of in-flight queries' declared/"
    "estimated memory needs is kept under this fraction of the accounted "
    "HBM pool (poolSizeBytes / allocFraction x detected HBM).  >1 "
    "oversubscribes deliberately — estimates are peak, not resident, and "
    "the spill tier absorbs overlap; <1 keeps headroom for unestimated "
    "allocations.  A query whose need alone exceeds the bound is still "
    "admitted when nothing else is in flight (progress over strictness).",
    float)
SERVE_DEFAULT_NEED = _conf(
    "spark.rapids.sql.tpu.serve.defaultMemoryNeedBytes", 256 << 20,
    "Memory need assumed for a submitted query when the caller declared "
    "none and the planner's size estimate is unavailable (memory scans "
    "of unknown size, exotic plans).", to_bytes)
SERVE_QUERY_BUDGET = _conf(
    "spark.rapids.sql.tpu.serve.queryBudgetBytes", 0,
    "Per-query device-bytes budget enforced at reserve() time for "
    "queries run through the scheduler: a query over its budget spills "
    "its OWN buffers (never its neighbors'), then raises RetryOOM into "
    "its own spill-retry/split/CPU-fallback ladder (numBudgetOoms).  "
    "0 disables; size it ~poolSizeBytes / maxConcurrentQueries so "
    "concurrent peaks cannot force cross-query eviction "
    "(docs/tuning-guide.md, Concurrent serving).", to_bytes)
SERVE_PLAN_CACHE_ENABLED = _conf(
    "spark.rapids.sql.tpu.serve.planCache.enabled", True,
    "Parameterized plan cache for scheduler-submitted queries "
    "(serve/plan_cache.py): literals in row-local positions are lifted "
    "into parameters, the normalized plan keys the cache, and parameter "
    "values enter compiled whole-stage programs as runtime arguments — "
    "so the 2nd..Nth literal-variant submission skips trace AND compile "
    "(planCacheHits).  Blocking collect() paths are unaffected.",
    _to_bool)
SERVE_PLAN_CACHE_SIZE = _conf(
    "spark.rapids.sql.tpu.serve.planCache.maxEntries", 128,
    "LRU bound on distinct normalized plans the plan cache tracks.", int)
SERVE_LIFECYCLE_ENABLED = _conf(
    "spark.rapids.sql.tpu.serve.lifecycle.enabled", True,
    "Query lifecycle layer for scheduler-submitted queries "
    "(serve/lifecycle.py): cooperative cancellation "
    "(QueryFuture.cancel()), per-query deadlines (submit deadline_ms=, "
    "with admission-time shedding) and SLO-aware preemption all ride a "
    "per-query token checked at reserve()/retry/stage/exchange "
    "boundaries.  Kill switch: false installs no token at all, making "
    "every checkpoint a no-op byte-identical to the pre-lifecycle "
    "paths — cancel() then returns False and deadlines are ignored.",
    _to_bool)
SERVE_PREEMPTION_ENABLED = _conf(
    "spark.rapids.sql.tpu.serve.preemption.enabled", False,
    "SLO-aware preemption: when a higher-priority query arrives while a "
    "lower-priority one holds the admission share/device gate, the "
    "scheduler asks the victim to suspend at its next stage boundary — "
    "its device buffers park as spillable state charged to its own "
    "budget, its semaphore slots and admission share release — and "
    "resume FIFO-within-priority once no higher-priority work remains, "
    "bit-for-bit with the unpreempted run (numPreemptions, "
    "numPreemptionResumes, SLO phase 'preempt').  Off by default: "
    "preemption trades victim latency for latency-class p99, a policy "
    "choice the operator should opt into (docs/tuning-guide.md, Query "
    "lifecycle).  Requires serve.lifecycle.enabled.", _to_bool)
SERVE_PREEMPTION_RESUME_TIMEOUT = _conf(
    "spark.rapids.sql.tpu.serve.preemption.resumeTimeoutSeconds", 600.0,
    "Hard bound on how long a preempted query stays suspended waiting "
    "for the scheduler's resume grant; past it the victim force-resumes "
    "(re-taking its admission share even over budget) so a scheduler "
    "fault can never hang a suspended query forever.", float)
SERVE_DEADLINE_SHED_FACTOR = _conf(
    "spark.rapids.sql.tpu.serve.deadline.shedSafetyFactor", 1.0,
    "Admission-time shedding margin: a query is shed (numDeadlineSheds, "
    "typed QueryDeadlineExceeded on its future) when its remaining "
    "deadline is under this factor x the scheduler's EWMA of observed "
    "plan+compile seconds — rejecting a doomed query at admission is "
    "cheaper than admitting it to time out mid-compile.  0 disables "
    "estimate-based shedding (already-expired deadlines still shed).",
    float)

# --- streaming micro-batch engine (streaming/) ------------------------------
STREAM_MAX_BATCH_ROWS = _conf(
    "spark.rapids.sql.tpu.streaming.maxBatchRows", 65536,
    "Upper bound on rows one streaming epoch reads from an append-only "
    "source (streaming/source.py epoch planner).  Keeping it CONSTANT "
    "for a query's lifetime keeps micro-batch capacities in one bucket, "
    "so warm epochs replay compiled stages instead of re-tracing "
    "(docs/tuning-guide.md, Streaming micro-batch execution).", int)
STREAM_MAX_FILES_PER_EPOCH = _conf(
    "spark.rapids.sql.tpu.streaming.maxFilesPerEpoch", 1,
    "Upper bound on newly-arrived files one epoch of a directory-tail "
    "streaming source decodes through the io/ device readers.", int)
STREAM_CHECKPOINT_KEEP = _conf(
    "spark.rapids.sql.tpu.streaming.checkpoint.keepEpochs", 2,
    "Committed epoch snapshots retained in a streaming checkpoint "
    "directory; older epoch dirs are pruned after each atomic commit "
    "(the commit marker always lands last, so a kill mid-commit "
    "resumes from the previous epoch bit-for-bit).", int)
STREAM_EPOCH_DEADLINE_MS = _conf(
    "spark.rapids.sql.tpu.streaming.epochDeadlineMs", 0.0,
    "Default per-epoch deadline for streaming queries: each epoch is a "
    "scheduler query carrying a lifecycle token, so past the deadline "
    "it stops at its next checkpoint with QueryDeadlineExceeded and "
    "owner-confined cleanup — the stream's device-resident state is "
    "untouched and the next trigger retries the epoch.  0 disables.",
    float)

# --- export -----------------------------------------------------------------
EXPORT_COLUMNAR_RDD = _conf(
    "spark.rapids.sql.exportColumnarRdd", False,
    "Allow exporting device columnar data for ML integration "
    "(ColumnarRdd equivalent).", _to_bool)


class TpuConf:
    """A view over string settings, like RapidsConf over SparkConf."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None,
                 use_env: bool = True):
        self._settings: Dict[str, Any] = {}
        if use_env:
            for k, v in os.environ.items():
                if k.startswith("SPARK_RAPIDS_"):
                    key = k.lower().replace("_", ".").replace(
                        "spark.rapids.", "spark.rapids.", 1)
                    self._settings[key] = v
        if settings:
            self._settings.update(settings)

    def get(self, entry_or_key):
        if isinstance(entry_or_key, ConfEntry):
            return entry_or_key.get(self)
        entry = _REGISTRY.get(entry_or_key)
        if entry is not None:
            return entry.get(self)
        return self._settings.get(entry_or_key)

    def set(self, key: str, value) -> "TpuConf":
        self._settings[key] = value
        return self

    def is_op_enabled(self, conf_key: str, default: bool = True) -> bool:
        raw = self._settings.get(conf_key)
        if raw is None:
            return default
        return _to_bool(raw)

    # convenience properties (subset; prefer .get(ENTRY))
    @property
    def sql_enabled(self):
        return self.get(SQL_ENABLED)

    @property
    def is_test_enabled(self):
        return self.get(TEST_CONF)

    @property
    def explain(self):
        return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_bytes(self):
        return self.get(BATCH_SIZE_BYTES)


def registered_entries() -> List[ConfEntry]:
    return sorted(_REGISTRY.values(), key=lambda e: e.key)


def help_doc(include_internal: bool = False) -> str:
    """Generate docs/configs.md, like RapidsConf.help (RapidsConf.scala:600-689)."""
    lines = [
        "# TPU Accelerator for Apache Spark — Configuration",
        "",
        "The following configs are generated from the registry in "
        "`spark_rapids_tpu/config.py`; do not edit by hand.",
        "",
        "Name | Description | Default Value",
        "-----|-------------|--------------",
    ]
    for e in registered_entries():
        if e.internal and not include_internal:
            continue
        lines.append(f"{e.key}|{e.doc}|{e.default}")
    lines += [
        "",
        "## Fine-tuning: per-operator enables",
        "",
        "Every accelerated expression, exec, scan and partitioning also gets an "
        "auto-derived boolean config `spark.rapids.sql.<kind>.<Name>` that can "
        "force it back to the CPU (see `spark_rapids_tpu/plan/overrides.py`).",
        "",
    ]
    return "\n".join(lines)


def write_config_docs(path: str = None) -> str:
    """Emit docs/configs.md from the registry (the reference generates its
    configs.md from RapidsConf.main the same way, RapidsConf.scala:689)."""
    import os
    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "configs.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    text = help_doc()
    with open(path, "w") as f:
        f.write(text)
    return path


if __name__ == "__main__":  # python -m spark_rapids_tpu.config
    print(write_config_docs())
