"""Exclusive-mode TPU discovery (resource scheduler integration).

Reference analogue: ExclusiveModeGpuDiscoveryPlugin
(sql-plugin/.../ExclusiveModeGpuDiscoveryPlugin.scala + the
getGpusResource.sh discovery script): Spark's resource scheduler invokes a
discovery hook per worker that claims an unused accelerator and emits a
ResourceInformation JSON ({"name": ..., "addresses": [...]}).

TPU differences, deliberate:
  * exclusivity is enforced by the PLATFORM, not by this plugin — a TPU
    chip is attached to exactly one process tree (and the axon dev tunnel
    adds a machine-wide lease on top), so the reference's CUDA
    try-acquire-retry dance is unnecessary; the claim happens implicitly
    at backend initialization;
  * addresses are jax device ids on the local host; a multi-host slice
    exposes only this host's devices, matching Spark's per-worker
    discovery contract.

`main()` prints the ResourceInformation JSON, so this module doubles as
the discovery *script*:  `python -m spark_rapids_tpu.discovery`.
"""
from __future__ import annotations

import json
from typing import List, Optional


RESOURCE_NAME = "tpu"


def discover_addresses(platform: Optional[str] = None) -> List[str]:
    """Local accelerator device ids, claiming the backend (exclusive mode).

    `platform` pins the jax backend to probe (None = whatever the
    environment resolves; tests pass "cpu" to stay off the machine-wide
    TPU lease)."""
    import jax
    devices = jax.devices(platform) if platform else jax.devices()
    return [str(d.id) for d in devices]


def resource_information(platform: Optional[str] = None) -> dict:
    """Spark ResourceInformation shape (name + addresses)."""
    return {"name": RESOURCE_NAME,
            "addresses": discover_addresses(platform)}


def main() -> None:  # pragma: no cover - exercised via the function API
    print(json.dumps(resource_information()))


if __name__ == "__main__":  # pragma: no cover
    main()
