"""TpuSession + DataFrame: the user-facing entry points.

Standalone equivalent of the reference's plugin bootstrap + Spark session
surface (reference: com/nvidia/spark/SQLPlugin.scala, rapids/Plugin.scala):
a session owns the conf and the device runtime; DataFrames build logical
plans; collect() runs the overrides pass (tag -> explain -> convert ->
transitions) and executes the physical plan.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

from . import config as C
from .config import TpuConf
from .exec.base import CpuExec, ExecContext, ExecNode, TpuExec
from .exec import basic as B
from .plan import logical as L
from .plan.logical import ColumnExpr, SortOrder, col, functions, lit
from .plan.overrides import PlanMeta, plan_schema
from .plan.physical import convert
from .plan import transitions as T
from .types import Schema, StructField, from_arrow


# one shared owner of the jax persistent-cache config dance: engine,
# bench.py children and the executor worker bootstrap all call this, so
# the cache knobs cannot drift between entry points
from .utils.compile_cache import enable_compilation_cache  # noqa: E402


def _enable_compilation_cache(path: str) -> None:
    """Back-compat alias (platform-gated: TPU-backed processes only;
    see utils/compile_cache.py for the rationale)."""
    enable_compilation_cache(path, force=False)


class TpuSession:
    def __init__(self, conf: Optional[Dict] = None):
        self.conf = TpuConf(conf)
        self._runtime = None
        # observability surface (docs/monitoring.md): the last query's
        # QueryExecution (explain_with_metrics / prometheus / journal) and
        # session-cumulative counters for bench/export rollups
        self.last_execution = None
        self.query_metrics_total: Dict[str, float] = {}
        self.queries_executed = 0
        # live-progress surface (docs/monitoring.md): a ProcCluster
        # constructed with session= attaches itself here and progress()
        # delegates to its heartbeat monitor
        self._proc_cluster = None
        self._progress_high_water = 0
        # serving tier (serve/scheduler.py): built lazily by submit();
        # the locks make the lazy singletons and the session-cumulative
        # counters safe under the scheduler's concurrent query threads
        self._scheduler = None
        self._serve_lock = threading.Lock()
        self._finish_lock = threading.Lock()
        self._lazy_lock = threading.RLock()  # runtime/cluster first touch
        _enable_compilation_cache(self.conf.get(C.COMPILATION_CACHE_DIR))
        # post-mortem plane (metrics/bundle.py, docs/monitoring.md):
        # armed only on the DRIVER (executor workers set ring.PROCESS_ROLE
        # before building their session) and only when a bundle dir is
        # configured.  _last_qe feeds the explain section of dumps whose
        # trigger has no QueryExecution in hand (SIGUSR1, watchdog).
        self._last_qe = None
        self._postmortem = None
        try:
            from .metrics import bundle as _bundle, ring as _ring
            pm_dir = str(self.conf.get(C.TELEMETRY_POSTMORTEM_DIR) or "")
            if pm_dir and _ring.PROCESS_ROLE[0] == "driver":
                self._postmortem = _bundle.PostmortemManager(
                    self, pm_dir,
                    int(self.conf.get(C.TELEMETRY_POSTMORTEM_MIN_INTERVAL)))
                _bundle.install_sigusr1(self._postmortem)
        except Exception:  # noqa: BLE001 — arming is observability-only
            from .metrics.registry import count_swallowed
            count_swallowed("numPostmortemErrors", "spark_rapids_tpu",
                            "postmortem arming failed at session init")
        # flight recorder + gauge sampler + /metrics endpoint: the
        # per-process telemetry singleton (metrics/ring.py).  The LATEST
        # session rebinds the driver gauge source and the endpoint
        # payloads (weakref — telemetry must never keep a session alive)
        try:
            from .metrics import ring as _ring
            t = _ring.init_telemetry(self.conf,
                                     role=_ring.PROCESS_ROLE[0])
            if t is not None and _ring.PROCESS_ROLE[0] == "driver":
                self._wire_driver_telemetry(t)
        except Exception:  # noqa: BLE001 — telemetry must never block
            from .metrics.registry import count_swallowed
            count_swallowed("numTelemetrySampleErrors", "spark_rapids_tpu",
                            "driver telemetry wiring failed")

    def _wire_driver_telemetry(self, t) -> None:
        """Bind this session to the process telemetry: the driver gauge
        source (pool / scheduler / spill figures the sampler snapshots)
        and — once per process — the loopback HTTP endpoint."""
        t.session_ref = weakref.ref(self)

        def driver_gauges() -> Dict[str, float]:
            s = t.session_ref()
            if s is None:
                return {}
            out: Dict[str, float] = {}
            rt = s._runtime  # never force a runtime build from a sampler
            if rt is not None:
                stats = rt.pool_stats()
                out.update({k: float(v) for k, v in stats.items()
                            if isinstance(v, (int, float))})
                out["spill_bytes"] = float(stats.get("host_used", 0)
                                           + stats.get("disk_used", 0))
            sched = s._scheduler
            out["in_flight_tasks"] = 0.0
            out["queued_queries"] = 0.0
            if sched is not None:
                out.update(sched.telemetry_gauges())
            return out

        def policy_gauges() -> Dict[str, float]:
            s = t.session_ref()
            rt = s._runtime if s is not None else None
            pol = getattr(rt, "policy", None) if rt is not None else None
            return pol.gauges() if pol is not None else {}

        t.sampler.add_source("driver", driver_gauges)
        t.sampler.add_source("policy", policy_gauges)
        t.sampler.start()
        if t.http is None \
                and bool(self.conf.get(C.TELEMETRY_HTTP_ENABLED)):
            from .metrics.export import session_observability
            from .metrics.http import serve_telemetry

            def observability() -> Dict:
                s = t.session_ref()
                if s is None:
                    return {}
                return {"session_observability": session_observability(s),
                        "progress": s.progress()}

            def healthz():
                s = t.session_ref()
                payload = {"ok": s is not None, "role": "driver",
                           "pid": os.getpid()}
                pc = getattr(s, "_proc_cluster", None) if s else None
                if pc is not None and pc.monitor is not None:
                    lag = pc.monitor.lag_s()
                    payload["heartbeat_lag_s"] = \
                        max(lag.values()) if lag else 0.0
                    payload["hung_tasks"] = pc.monitor.hung_tasks
                    payload["workers"] = len(pc.workers)
                return (200 if payload["ok"] else 503), payload

            serve_telemetry(t, {"executor": "driver"}, healthz=healthz,
                            observability=observability,
                            port=int(self.conf.get(C.TELEMETRY_HTTP_PORT)))

    def dump_diagnostics(self, out_dir: Optional[str] = None,
                         reason: str = "manual") -> str:
        """Write a post-mortem diagnostic bundle NOW (config, EXPLAIN
        with roofline, merged timeline, memledger replay, SLO state,
        per-process flight-recorder rings) and return its directory.
        Render it with `python -m spark_rapids_tpu.metrics postmortem
        <bundle>` (docs/monitoring.md, Post-mortem bundles)."""
        from .metrics import bundle as _bundle
        if out_dir is None:
            base = str(self.conf.get(C.TELEMETRY_POSTMORTEM_DIR) or "") \
                or "."
            out_dir = os.path.join(
                base, f"postmortem-{reason}-{os.getpid()}-"
                      f"{time.time_ns() // 1_000_000}")
        return _bundle.dump_diagnostics(out_dir, session=self,
                                        reason=reason)

    def _begin_execution(self, physical: ExecNode, runtime=None):
        """Open the per-query observability scope (metrics levels, event
        journal, operator spans) around an about-to-run physical tree."""
        from .metrics.query import QueryExecution
        return QueryExecution(self.conf, physical,
                              runtime=runtime or self._runtime)

    def _finish_execution(self, qe, error=None) -> None:
        # runs in every execution finally-block: a failure in the
        # observability path (journal write on a full disk, metric fold on
        # an exhausted device) must neither fail a successful query nor
        # mask the real error — and the journal must come off the active
        # stack regardless (QueryExecution.finish guarantees that part)
        try:
            qe.finish(error)
            with self._finish_lock:
                # concurrent serving: N query threads finish at once;
                # the read-modify-write counter folds must not race
                self.last_execution = qe
                self._last_qe = qe
                self.queries_executed += 1
                for k, v in qe.aggregate().items():
                    self.query_metrics_total[k] = \
                        self.query_metrics_total.get(k, 0) + v
            if self.conf.explain == "METRICS" and error is None:
                print(qe.explain_with_metrics(), file=sys.stderr)
            if error is not None and self._postmortem is not None:
                # first-failure diagnostics: the bundle is written while
                # the dying query's journal/metrics are still warm
                self._postmortem.trigger("query-failure", qe=qe,
                                         error=error)
        except Exception:  # pragma: no cover - reporting is best-effort
            import logging
            logging.getLogger("spark_rapids_tpu.metrics").warning(
                "observability finish failed", exc_info=True)

    # -- data sources -------------------------------------------------------
    def from_arrow(self, table) -> "DataFrame":
        fields = [StructField(n, from_arrow(t))
                  for n, t in zip(table.column_names, table.schema.types)]
        return DataFrame(self, L.LogicalScan(table, Schema(fields), "memory"))

    def from_pydict(self, data: Dict, schema: Optional[Schema] = None
                    ) -> "DataFrame":
        import pyarrow as pa
        if schema is None:
            table = pa.table(data)
        else:
            from .types import to_arrow
            table = pa.table(
                {k: pa.array(v, type=to_arrow(schema.field(k).dtype))
                 for k, v in data.items()})
        return self.from_arrow(table)

    def from_pandas(self, df) -> "DataFrame":
        import pyarrow as pa
        return self.from_arrow(pa.Table.from_pandas(df, preserve_index=False))

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    # -- runtime ------------------------------------------------------------
    @property
    def runtime(self):
        if self._runtime is None:
            with self._lazy_lock:
                if self._runtime is not None:
                    return self._runtime
                self._build_runtime()
        return self._runtime

    def _build_runtime(self) -> None:
        from .mem.runtime import TpuRuntime
        limit = None
        if int(self.conf.get(C.CLUSTER_EXECUTORS)) > 1:
            # cluster mode: the N executor pools already claim half of
            # the session budget (plugin.TpuCluster); the driving
            # session's compute pool takes the other half so combined
            # accounting reflects ONE physical device, not two.
            # configured_pool_bytes honors an explicit poolSizeBytes
            # before falling back to allocFraction of detected HBM.
            from .mem.runtime import configured_pool_bytes
            limit = configured_pool_bytes(self.conf) // 2
        self._runtime = TpuRuntime(self.conf, pool_limit_bytes=limit)

    @property
    def cluster(self):
        """Multi-executor host-mode cluster, or None (plugin.TpuCluster;
        enabled by spark.rapids.sql.tpu.cluster.executors > 1)."""
        if getattr(self, "_cluster", None) is None:
            with self._lazy_lock:
                if getattr(self, "_cluster", None) is None:
                    if int(self.conf.get(C.CLUSTER_EXECUTORS)) > 1:
                        from .plugin import TpuCluster
                        self._cluster = TpuCluster(self.conf)
                    else:
                        self._cluster = False  # resolved: disabled
        return self._cluster or None

    def set(self, key: str, value) -> "TpuSession":
        self.conf.set(key, value)
        return self

    def progress(self) -> Dict:
        """Live progress snapshot, advancing monotonically while work
        happens.  With an attached ProcCluster (`ProcCluster(...,
        session=session)`) this is the heartbeat monitor's cluster
        rollup; for a local session it tracks executed queries, the
        in-flight query's journal growth, and cumulative output rows.
        `score` is the single never-decreasing figure."""
        pc = self._proc_cluster
        if pc is not None:
            return pc.progress()
        from .metrics.journal import active_journal
        j = active_journal()
        events = j.event_count() if j is not None else 0
        rows = int(self.query_metrics_total.get("numOutputRows", 0))
        raw = self.queries_executed + events + rows
        # high-water: per-query journal ids restart, so the raw sum may
        # dip between queries — the surfaced score never does.  The
        # max() makes concurrent racing writes (watchdog/postmortem
        # threads snapshotting progress) order-independent: the water
        # mark only rises, so the lock would buy nothing.
        self._progress_high_water = max(self._progress_high_water, raw)  # tpulint: disable=TPU009 monotonic max is race-tolerant by construction
        out = {"queries": self.queries_executed,
               "journal_events": events, "rows": rows,
               "active_query": j is not None,
               "score": self._progress_high_water}
        if self._runtime is not None:
            # local-session twin of the cluster roll-up: the runtime's
            # store high-waters (pool_stats device_peak/host_peak/
            # disk_peak are store-tracked and monotonic until reset)
            ps = self._runtime.pool_stats()
            out["peak_memory"] = {
                f: int(ps.get(f, 0))
                for f in ("device_peak", "host_peak", "disk_peak")}
        return out

    # -- serving tier (serve/) ----------------------------------------------
    @property
    def scheduler(self):
        """The session's QueryScheduler, built on first submit() from the
        spark.rapids.sql.tpu.serve.* confs; None before that."""
        return self._scheduler

    def submit(self, df, priority: int = 0,
               memory_need: Optional[int] = None,
               deadline_ms: Optional[float] = None):
        """Submit a DataFrame (or logical plan) for concurrent execution;
        returns a serve.QueryFuture immediately.  Queries flow through
        the priority queue, fair-share admission control, the
        parameterized plan cache and a per-query memory budget
        (docs/tuning-guide.md, Concurrent serving and plan caching);
        the blocking collect() paths are unchanged.  `deadline_ms`
        bounds the query end to end: past it the query fails with a
        typed QueryDeadlineExceeded at its next lifecycle checkpoint —
        or is shed at admission when the remaining deadline cannot cover
        the estimated plan+compile cost (docs/tuning-guide.md, Query
        lifecycle)."""
        if self._scheduler is None:
            with self._serve_lock:
                if self._scheduler is None:
                    from .serve.scheduler import QueryScheduler
                    self._scheduler = QueryScheduler(self)
        return self._scheduler.submit(df, priority=priority,
                                      memory_need=memory_need,
                                      deadline_ms=deadline_ms)

    def shutdown_serving(self, wait: bool = True) -> None:
        """Stop the scheduler's workers (idempotent).  In-flight queries
        finish; queued-but-never-admitted futures resolve with a
        RuntimeError so nothing blocks forever in result()."""
        with self._serve_lock:
            sched = self._scheduler
        if sched is not None:
            sched.shutdown(wait=wait)

    # -- execution core ------------------------------------------------------

    def _collect_physical(self, physical, out_schema, *, budget_bytes=0,
                          sched_attrs=None, future=None):
        """Execute an already-planned physical tree to ONE pyarrow Table —
        the shared body of DataFrame.to_arrow and the serving tier's
        worker threads.  Installs the per-query observability scope, the
        memory-ledger query scope (buffer ownership + optional budget)
        and the device semaphore (wait time attributed to THIS query's
        root-node metrics)."""
        import pyarrow as pa
        runtime = self.runtime
        on_device = isinstance(physical, TpuExec)
        # adaptive execution wraps at EXECUTE time (never in
        # physical_plan()): map stages materialize first and the reduce
        # side re-plans from observed sizes (adaptive/executor.py)
        from .adaptive.executor import maybe_wrap_adaptive
        physical = maybe_wrap_adaptive(physical, self.conf)
        if on_device:
            physical = B.DeviceToHostExec(physical)
        qe = self._begin_execution(physical, runtime)
        if future is not None:
            future.query_id = qe.query_id
        if sched_attrs and qe.journal is not None:
            # the scheduling decision, journaled into THIS query's
            # journal under its own trace context (kind `sched`)
            qe.journal.instant("sched", "admitted", **sched_attrs)
        ctx = ExecContext(self.conf, runtime=runtime,
                          cluster=self.cluster, journal=qe.journal,
                          query_execution=qe)
        # lifecycle token of a scheduler-run query (serve/lifecycle.py):
        # installed on the ledger query scope so every tier's checkpoint
        # reaches it thread-locally; None for blocking collect() paths
        # and when the serve.lifecycle.enabled kill switch is off
        lifecycle = getattr(future, "lifecycle", None) \
            if future is not None else None
        if lifecycle is not None:
            lifecycle.journal = qe.journal
        error = None
        qscope = None
        try:
            with runtime.ledger.query_scope(f"q{qe.query_id}",
                                            budget_bytes,
                                            lifecycle=lifecycle) as qscope:
                if on_device:
                    # device semaphore: this "task" holds a device slot
                    # for the duration of its device work (reference:
                    # GpuSemaphore.acquireIfNecessary, released on task
                    # completion).  Blocked-wait time lands on the
                    # query's own root-node metrics, not the runtime
                    # globals (per-query attribution under concurrency).
                    with runtime.semaphore.held(metrics=physical.metrics):
                        tables = list(physical.execute_cpu(ctx))
                else:
                    tables = list(physical.execute_cpu(ctx))
        except BaseException as e:
            error = e
            raise
        finally:
            # task-completion cleanup, success or failure: releases
            # resources operators registered (e.g. shuffle partitions
            # orphaned by a mid-write error)
            ctx.run_cleanups()
            if error is not None:
                # owner-confined cleanup for lifecycle kills: after the
                # shuffle cleanups above, free whatever buffers still
                # carry this query's owner stamp across device/host/disk
                # — a cancelled or past-deadline query must not leak
                # pool bytes (received shuffle buffers, parked
                # checkpoints, partial writes the cleanups missed)
                from .serve.lifecycle import (QueryCancelled,
                                              QueryDeadlineExceeded)
                if isinstance(error, (QueryCancelled,
                                      QueryDeadlineExceeded)):
                    freed = runtime.release_owner(f"q{qe.query_id}")
                    if qe.journal is not None:
                        qe.journal.instant(
                            "lifecycle", "ownerCleanup",
                            q=f"q{qe.query_id}", freed_bytes=freed,
                            reason=type(error).__name__)
            self._finish_execution(qe, error)
            if future is not None:
                # phase breakdown for the serving SLO histograms
                # (metrics/slo.py): the scheduler observes these into
                # the per-priority compile/execute/spill distributions
                try:
                    from .metrics import names as MN
                    agg = qe.aggregate()
                    # stageCompileTime is NODE-recorded, so the
                    # aggregate is per-query even under concurrency;
                    # spill time comes from THIS query's scope (the
                    # runtime spillTime metric is shared — a delta
                    # window would absorb concurrent neighbors' spills)
                    future.compile_seconds = float(
                        agg.get(MN.STAGE_COMPILE_TIME, 0.0))
                    future.spill_seconds = float(
                        qscope.spill_seconds if qscope is not None
                        else 0.0)
                    future.exec_seconds = float(qe.duration or 0.0)
                except Exception:  # noqa: BLE001 — reporting only
                    pass  # tpulint: disable=TPU006 phase metrics are best-effort; the future's result/error is already set by the caller
        if not tables:
            from .types import to_arrow
            return pa.table({f.name: pa.array([], type=to_arrow(f.dtype))
                             for f in out_schema})
        return pa.concat_tables(tables)

    # -- planning -----------------------------------------------------------
    def plan(self, logical: L.LogicalPlan) -> ExecNode:
        from .plan.pushdown import optimize_scans
        logical = optimize_scans(logical, self.conf)
        meta = PlanMeta(logical, self.conf)
        meta.tag_tree()
        explain_mode = self.conf.explain
        if explain_mode in ("ALL", "NOT_ON_TPU", "NOT_ON_GPU"):
            text = meta.explain(verbose=explain_mode == "ALL")
            if explain_mode == "ALL" or "!" in text:
                print(text, file=sys.stderr)
        physical = convert(meta)
        return T.finalize(physical, self.conf)

    def explain_str(self, logical: L.LogicalPlan) -> str:
        meta = PlanMeta(logical, self.conf)
        meta.tag_tree()
        return meta.explain()


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self.session = session
        self._options: Dict = {}

    def option(self, k, v) -> "DataFrameReader":
        self._options[k] = v
        return self

    def options(self, **kw) -> "DataFrameReader":
        self._options.update(kw)
        return self

    def parquet(self, *paths: str) -> "DataFrame":
        from .io.scan import scan_info
        files, schema, opts = scan_info(paths, "parquet", self._options)
        return DataFrame(self.session,
                         L.LogicalScan(files, schema, "parquet", opts))

    def csv(self, *paths: str, schema: Optional[Schema] = None,
            header: bool = False) -> "DataFrame":
        from .io.scan import scan_info
        opts = dict(self._options)
        opts.setdefault("header", header)
        files, schema, opts = scan_info(paths, "csv", opts, schema)
        return DataFrame(self.session,
                         L.LogicalScan(files, schema, "csv", opts))

    def orc(self, *paths: str) -> "DataFrame":
        from .io.scan import scan_info
        files, schema, opts = scan_info(paths, "orc", self._options)
        return DataFrame(self.session,
                         L.LogicalScan(files, schema, "orc", opts))


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan

    # -- transformations ----------------------------------------------------
    def _wrap_cols(self, cols):
        out = []
        for c in cols:
            if isinstance(c, str):
                out.append(col(c))
            elif isinstance(c, ColumnExpr):
                out.append(c)
            else:
                out.append(lit(c))
        return out

    def __getitem__(self, name: str) -> ColumnExpr:
        if name not in self.schema.names:
            raise KeyError(name)
        return col(name)

    def _project(self, exprs) -> "DataFrame":
        """Build a projection, splitting out window expressions (including
        ones nested inside arithmetic, like sum(v).over(w) + 1) into
        LogicalWindow nodes beneath the project (Spark's
        ExtractWindowExpressions analyzer rule, in spirit)."""
        win: list = []

        def extract(e):
            if not isinstance(e, ColumnExpr):
                return e
            if e.op == "WindowExpr":
                if e._alias is None:
                    e = e.alias(f"_w{len(win)}")
                win.append(e)
                return col(e.output_name)

            def walk(a):
                if isinstance(a, ColumnExpr):
                    return extract(a)
                if isinstance(a, (list, tuple)):
                    return type(a)(walk(x) for x in a)
                return a
            new_args = tuple(walk(a) for a in e.args)
            return ColumnExpr(e.op, new_args, alias=e._alias)

        # generators (explode/posexplode) first: they change the row count
        gens = [e for e in exprs if e.op in ("Explode", "PosExplode")]
        if len(gens) > 1:
            raise ValueError("only one generator (explode/posexplode) is "
                             "allowed per select, like Spark")
        if gens:
            g = gens[0]
            pos = g.op == "PosExplode"
            names = (["pos"] if pos else []) + [g._alias or "col"]
            base = DataFrame(self.session,
                             L.LogicalGenerate(g, names, self.plan))
            out = []
            for e in exprs:
                if e is g:
                    out.extend(col(n) for n in names)
                else:
                    out.append(e)
            return base._project(out)

        rewritten = [extract(e) for e in exprs]
        if not win:
            return DataFrame(self.session,
                             L.LogicalProject(exprs, self.plan))
        groups: dict = {}
        for e in win:
            spec = e.args[1]
            groups.setdefault(spec._group_key(), (spec, []))[1].append(e)
        child = self.plan
        for _k, (spec, es) in groups.items():
            child = L.LogicalWindow(es, spec.parts, spec.orders, child)
        return DataFrame(self.session, L.LogicalProject(rewritten, child))

    def select(self, *cols) -> "DataFrame":
        return self._project(self._wrap_cols(cols))

    def with_column(self, name: str, expr: ColumnExpr) -> "DataFrame":
        exprs = [col(n) for n in self.schema.names if n != name]
        exprs.append(expr.alias(name))
        return self._project(exprs)

    withColumn = with_column

    def filter(self, condition: ColumnExpr) -> "DataFrame":
        return DataFrame(self.session,
                         L.LogicalFilter(condition, self.plan))

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        return GroupedData(self, self._wrap_cols(cols))

    groupBy = group_by

    def rollup(self, *cols) -> "GroupedData":
        """GROUP BY ROLLUP: grouping sets {(k1..kn), (k1..kn-1), ..., ()}
        planned as an Expand fan-out + one hash aggregate keyed on
        (keys..., grouping id), Spark's physical shape (reference:
        GpuExpandExec, rapids/GpuExpandExec.scala)."""
        return GroupedData(self, self._wrap_cols(cols), rollup=True)

    def cube(self, *cols) -> "GroupedData":
        """GROUP BY CUBE: every subset of the keys as a grouping set (the
        same Expand + grouping-id plan as rollup, 2^n projections)."""
        return GroupedData(self, self._wrap_cols(cols), rollup=True,
                           cube=True)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = how.replace("outer", "").rstrip("_") or how
        how = {"leftsemi": "left_semi", "leftanti": "left_anti",
               "left_semi": "left_semi", "left_anti": "left_anti",
               "inner": "inner", "left": "left", "cross": "cross",
               "full": "full", "right": "right"}.get(how, how)
        if isinstance(on, (list, tuple)) and on \
                and all(isinstance(x, str) for x in on):
            return DataFrame(self.session, L.LogicalJoin(
                self.plan, other.plan, how, using=list(on)))
        if isinstance(on, str):
            return DataFrame(self.session, L.LogicalJoin(
                self.plan, other.plan, how, using=[on]))
        return DataFrame(self.session, L.LogicalJoin(
            self.plan, other.plan, how, condition=on))

    def order_by(self, *orders) -> "DataFrame":
        os = []
        for o in orders:
            if isinstance(o, SortOrder):
                os.append(o)
            elif isinstance(o, str):
                os.append(SortOrder(col(o)))
            else:
                os.append(SortOrder(o))
        return DataFrame(self.session, L.LogicalSort(os, self.plan))

    orderBy = sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, L.LogicalLimit(n, self.plan))

    def hint(self, name: str, *args) -> "DataFrame":
        """Spark-style plan hints; \"broadcast\" marks this side for a
        broadcast hash join."""
        hints = set(getattr(self.plan, "_hints", ())) | {name.lower()}
        self.plan._hints = hints
        return self

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session,
                         L.LogicalUnion([self.plan, other.plan]))

    unionAll = union

    def distinct(self) -> "DataFrame":
        return DataFrame(self.session, L.LogicalDistinct(self.plan))

    def repartition(self, n: int, *cols) -> "DataFrame":
        keys = self._wrap_cols(cols)
        mode = "hash" if keys else "round_robin"
        return DataFrame(self.session, L.LogicalRepartition(
            n, keys, self.plan, mode))

    def repartition_by_range(self, n: int, *orders) -> "DataFrame":
        keys, asc, nf = [], [], []
        for o in orders:
            if isinstance(o, str):
                o = SortOrder(col(o))
            elif not isinstance(o, SortOrder):
                o = SortOrder(o)
            keys.append(o.child)
            asc.append(o.ascending)
            nf.append(o.effective_nulls_first)
        return DataFrame(self.session, L.LogicalRepartition(
            n, keys, self.plan, "range", asc, nf))

    repartitionByRange = repartition_by_range

    # -- actions ------------------------------------------------------------
    @property
    def schema(self) -> Schema:
        return plan_schema(self.plan, self.session.conf)

    def explain(self) -> str:
        return self.session.explain_str(self.plan)

    def physical_plan(self) -> ExecNode:
        return self.session.plan(self.plan)

    def to_arrow(self):
        physical = self.session.plan(self.plan)
        return self.session._collect_physical(physical, self.schema)

    def collect(self) -> List[tuple]:
        table = self.to_arrow()
        return [tuple(r.values()) for r in table.to_pylist()]

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def count(self) -> int:
        return self.to_arrow().num_rows

    def show(self, n: int = 20):
        print(self.limit(n).to_arrow().to_pandas())

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    # ML integration: ColumnarRdd equivalent (reference: ColumnarRdd.scala)
    def to_device_batches(self):
        """Export device ColumnarBatches for ML handoff (requires
        spark.rapids.sql.exportColumnarRdd=true, like the reference)."""
        if not self.session.conf.get(C.EXPORT_COLUMNAR_RDD):
            raise RuntimeError(
                f"set {C.EXPORT_COLUMNAR_RDD.key}=true to export device "
                "columnar data")
        physical = self.session.plan(self.plan)
        runtime = self.session.runtime
        from .adaptive.executor import maybe_wrap_adaptive
        physical = maybe_wrap_adaptive(physical, self.session.conf)
        qe = self.session._begin_execution(physical, runtime)
        ctx = ExecContext(self.session.conf, runtime=runtime,
                          cluster=self.session.cluster, journal=qe.journal,
                          query_execution=qe)
        error = None
        try:
            if isinstance(physical, TpuExec):
                runtime.semaphore.acquire_if_necessary()
                try:
                    yield from physical.execute(ctx)
                finally:
                    runtime.semaphore.task_done()
            else:
                for table in physical.execute_cpu(ctx):
                    from .columnar import ColumnarBatch
                    yield ColumnarBatch.from_arrow(table)
        except BaseException as e:
            error = e
            raise
        finally:
            ctx.run_cleanups()
            self.session._finish_execution(qe, error)


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[ColumnExpr],
                 rollup: bool = False, cube: bool = False):
        self.df = df
        self.keys = keys
        self.rollup = rollup
        self.cube = cube

    def agg(self, *aggs) -> "DataFrame":
        """Aggregate; compound expressions over aggregates (e.g.
        sum(a)/sum(b)) are split into leaf aggregates + a result projection,
        the way Spark's analyzer plans them (and the reference's
        resultProjection phase executes them, aggregate.scala:403-510)."""
        from .ops.aggregates import AGG_FUNCS
        leaf_aggs: List[ColumnExpr] = []
        projections: List[ColumnExpr] = []
        compound = False

        def walk(e):
            if not isinstance(e, ColumnExpr):
                return e
            if e.op in AGG_FUNCS:
                name = f"_agg{len(leaf_aggs)}"
                leaf_aggs.append(e.alias(name))
                return col(name)

            def sub(a):
                if isinstance(a, ColumnExpr):
                    return walk(a)
                if isinstance(a, (list, tuple)):
                    return type(a)(sub(x) for x in a)
                return a
            return ColumnExpr(e.op, tuple(sub(a) for a in e.args),
                              alias=e._alias)

        for e in aggs:
            if isinstance(e, ColumnExpr) and e.op in AGG_FUNCS:
                leaf_aggs.append(e)
                projections.append(col(e.output_name))
            else:
                before = len(leaf_aggs)
                rewritten = walk(e)
                if len(leaf_aggs) == before:
                    raise ValueError(
                        f"aggregate expression {e!r} contains no aggregate "
                        "function")
                compound = True
                projections.append(rewritten.alias(e.output_name))

        child_plan = self.df.plan
        group_keys = list(self.keys)
        if self.rollup:
            child_plan, group_keys = self._expand_rollup(child_plan)
        agg_plan = L.LogicalAggregate(group_keys, leaf_aggs, child_plan)
        key_cols = [col(k.output_name) for k in self.keys]
        if not compound and not self.rollup:
            return DataFrame(self.df.session, agg_plan)
        if not compound:
            projections = [col(a.output_name) for a in leaf_aggs]
        # rollup drops the internal grouping-id column here
        return DataFrame(self.df.session, L.LogicalProject(
            key_cols + projections, agg_plan))

    def _expand_rollup(self, child_plan):
        """Expand fan-out for ROLLUP grouping sets: one projection per set.
        Every ORIGINAL column passes through unchanged (aggregates over a
        grouping-key column must still see real values in subtotal rows —
        Spark's Expand nulls only duplicated grouping COPIES), plus one
        nullable copy per key for grouping and a grouping-id column so a
        rolled-up null never merges with a data null."""
        schema = self.df.schema
        key_names = [k.output_name for k in self.keys]
        for k, name in zip(self.keys, key_names):
            if k.op != "col" or name not in schema.names:
                raise ValueError(
                    "rollup keys must be existing columns; project "
                    f"{name!r} first")
        gid = "_grouping_id"
        n = len(self.keys)
        if self.cube:
            # every subset; grouping id = bitmask of PRUNED keys (Spark's
            # grouping_id bit convention)
            sets = [[name for b, name in enumerate(key_names)
                     if not (mask >> (n - 1 - b)) & 1]
                    for mask in range(1 << n)]
            gids = list(range(1 << n))
        else:
            sets = [key_names[:g] for g in range(n, -1, -1)]
            gids = [(1 << (n - g)) - 1 for g in range(n, -1, -1)]
        projections = []
        for kept, g_val in zip(sets, gids):
            proj = [col(f.name) for f in schema]
            for name in key_names:
                f = schema.field(name)
                copy = (col(name) if name in kept
                        else lit(None).cast(f.dtype))
                proj.append(copy.alias(f"_gkey_{name}"))
            proj.append(lit(g_val).alias(gid))
            projections.append(proj)
        expand = L.LogicalExpand(projections, child_plan)
        group_keys = [col(f"_gkey_{name}").alias(name)
                      for name in key_names] + [col(gid)]
        return expand, group_keys

    def count(self) -> "DataFrame":
        return self.agg(functions.count(lit(1)).alias("count"))


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self.df = df
        self._options: Dict = {}
        self._partition_by: List[str] = []

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def parquet(self, path: str):
        self._write(path, "parquet")

    def csv(self, path: str):
        self._write(path, "csv")

    def orc(self, path: str):
        self._write(path, "orc")

    def _write(self, path: str, fmt: str):
        plan = L.LogicalWrite(path, fmt, self.df.plan, self._options,
                              self._partition_by)
        physical = self.df.session.plan(plan)
        runtime = self.df.session.runtime
        from .adaptive.executor import maybe_wrap_adaptive
        physical = maybe_wrap_adaptive(physical, self.df.session.conf)
        qe = self.df.session._begin_execution(physical, runtime)
        ctx = ExecContext(self.df.session.conf, runtime=runtime,
                          cluster=self.df.session.cluster,
                          journal=qe.journal, query_execution=qe)
        error = None
        try:
            if isinstance(physical, TpuExec):
                with runtime.semaphore.held():
                    for _ in physical.execute(ctx):
                        pass
            else:
                for _ in physical.execute_cpu(ctx):
                    pass
        except BaseException as e:
            error = e
            raise
        finally:
            ctx.run_cleanups()
            self.df.session._finish_execution(qe, error)
