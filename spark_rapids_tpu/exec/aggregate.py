"""TPU hash aggregate.

Reference behavior: rapids/aggregate.scala — streaming per-partition loop
(per batch: update-aggregate; across batches: concat running state and
merge-aggregate; finally: finalize projection), Partial/Final phases bound
separately (setupReferences :585).

TPU-first implementation: no hash table.  Scatter is slow on TPU, so
grouping is SORT-based with static shapes:

  1. hash keys twice (64-bit each), stable-sort rows by (h1, h2) — dead
     rows get max hash and fall to the back;
  2. group boundary = hash changed OR any key column differs from the
     previous sorted row (hash collisions cannot create wrong groups unless
     BOTH 64-bit hashes collide AND rows interleave);
  3. group id = prefix-sum of boundaries; segment reductions with
     indices_are_sorted=True (XLA lowers these without scatter);
  4. output keys gathered from each group's first row; output capacity =
     input capacity, live rows = number of groups.

Multi-batch streams fold through the same kernel: the running state batch is
concatenated with each new partial result and re-grouped (merge aggregates),
exactly the reference's concatenateBatches + merge pass.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, ColumnarBatch, concat_batches
from ..ops import expressions as E
from ..ops.aggregates import AggregateExpression
from ..ops.hashing import hash_columns_double
from ..types import (DoubleType, LongType, Schema, StructField)
from ..utils.tracing import named_range
from .base import (ExecContext, ExecNode, TpuExec, record_cost,
                   record_output_batch)
from ..metrics import names as MN

_I64_MAX = np.int64(2**63 - 1)
_I64_MIN = np.int64(-(2**63))

# kernel keys whose bucket fast-path probe came back dirty (cardinality
# above the bucket count): skip the probe for them from then on
_BUCKET_DIRTY_KEYS: set = set()


def _flatten_stacked(partials: ColumnarBatch, state_schema) -> ColumnarBatch:
    """vmapped per-batch partial states [k, pcap, ...] -> one [k*pcap]
    merge input (shared by the sort and bucket whole-stage programs)."""
    cols = []
    for c in partials.columns:
        data = c.data.reshape((-1,) + c.data.shape[2:])
        valid = c.valid.reshape(-1)
        lengths = c.lengths.reshape(-1) if c.lengths is not None else None
        cols.append(Column(data, valid, c.dtype, lengths))
    return ColumnarBatch(cols, partials.sel.reshape(-1), state_schema)


def _type_max(dt):
    """Identity element for Min over dtype dt (largest value)."""
    j = dt.jnp_dtype
    if dt.is_floating:
        return jnp.asarray(jnp.inf, j)
    return jnp.asarray(jnp.iinfo(j).max if dt.name != "boolean" else True,
                       j)


def _type_min(dt):
    """Identity element for Max over dtype dt (smallest value)."""
    j = dt.jnp_dtype
    if dt.is_floating:
        return jnp.asarray(-jnp.inf, j)
    return jnp.asarray(jnp.iinfo(j).min if dt.name != "boolean" else False,
                       j)


def _key_equal_at(c: Column, idx):
    """Row i's key value-equals the key at row idx[i] (Spark grouping
    equality: nulls equal, NaN equal, -0.0 == 0.0 — the same contract as
    _col_differs_from_prev, against an arbitrary gathered row)."""
    from ..ops.hashing import _normalize_bits
    vg = jnp.take(c.valid, idx)
    both_null = (~c.valid) & (~vg)
    valid_mismatch = c.valid != vg
    if c.dtype.is_string:
        dg = jnp.take(c.data, idx, axis=0)
        lg = jnp.take(c.lengths, idx)
        dd = jnp.all(c.data == dg, axis=1) & (c.lengths == lg)
    else:
        bits = _normalize_bits(c)
        dd = bits == jnp.take(bits, idx)
    return jnp.where(both_null, True,
                     jnp.where(valid_mismatch, False,
                               jnp.where(c.valid, dd, True)))


def group_rows(key_cols: Sequence[Column], live, value_cols=None):
    """-> (order, gid_sorted, boundary_sorted, num_groups).

    order: stable permutation putting equal keys adjacent, dead rows last.
    gid_sorted[i]: group id of sorted position i (garbage for dead rows).
    `value_cols`: optional minor sort keys — equal values land adjacent
    WITHIN each group (the distinct-aggregate dedup needs this)."""
    from ..utils import packed_sort as PS
    cap = live.shape[0]
    packed = PS.packed_enabled() and cap & (cap - 1) == 0
    if not key_cols and not value_cols:
        # one group — but the contract (dead rows LAST) must still hold:
        # merge states interleave live/dead rows, and the searchsorted
        # segmented reducers require gid sorted after the dead->cap-1 remap
        if packed:
            # single-operand packed sort (lexsort is variadic even for
            # one key); identical stable permutation
            order = PS.packed_argsort([((~live).astype(jnp.uint64), 1)],
                                      cap)
        else:
            order = jnp.lexsort(((~live).astype(jnp.int8),)) \
                .astype(jnp.int32)
        gid = jnp.zeros(cap, dtype=jnp.int32)
        live_s = jnp.take(live, order)
        boundary = jnp.zeros(cap, dtype=jnp.bool_).at[0].set(live_s[0])
        return order, gid, boundary, jnp.minimum(jnp.sum(live), 1)
    h1, h2 = hash_columns_double(key_cols, live) if key_cols else (
        jnp.zeros(cap, jnp.uint64), jnp.zeros(cap, jnp.uint64))
    # stable sort: primary h1, secondary h2, tertiary original index —
    # packed path runs it as an LSD radix of single-operand sorts (the
    # variadic lexsort costs ~6x per pass on the CPU sort HLO; identical
    # permutation either way)
    if value_cols:
        vh1, vh2 = hash_columns_double(value_cols, live)
        if packed:
            order = PS.packed_argsort(
                [(h1, 64), (h2, 64), (vh1, 64), (vh2, 64)], cap)
        else:
            order = jnp.lexsort((vh2, vh1, h2, h1)).astype(jnp.int32)
    elif packed:
        order = PS.packed_argsort([(h1, 64), (h2, 64)], cap)
    else:
        order = jnp.lexsort((h2, h1)).astype(jnp.int32)
    if not key_cols:
        live_s = jnp.take(live, order)
        gid = jnp.zeros(cap, dtype=jnp.int32)
        boundary = jnp.zeros(cap, dtype=jnp.bool_).at[0].set(live_s[0])
        return order, gid, boundary, jnp.minimum(jnp.sum(live), 1)
    live_s = jnp.take(live, order)
    h1s = jnp.take(h1, order)
    h2s = jnp.take(h2, order)
    differs = (h1s != _shift1(h1s)) | (h2s != _shift1(h2s))
    for c in key_cols:
        cs = c.take(order)
        differs = differs | _col_differs_from_prev(cs)
    boundary = live_s & differs
    boundary = boundary.at[0].set(live_s[0])
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_groups = jnp.sum(boundary.astype(jnp.int32))
    return order, gid, boundary, num_groups


def _shift1(x):
    """x shifted down by one position (x[i-1]); position 0 gets x[0]."""
    return jnp.roll(x, 1)


def _col_differs_from_prev(c: Column):
    """Row i differs from row i-1 (null-aware, Spark key equality: nulls
    equal, NaN equal, -0.0 == 0.0 — the hash normalizes floats, and direct
    bit compare after the same normalization keeps it consistent)."""
    from ..ops.hashing import _normalize_bits
    vprev = _shift1(c.valid)
    both_null = (~c.valid) & (~vprev)
    valid_mismatch = c.valid != vprev
    if c.dtype.is_string:
        data_diff = jnp.any(c.data != _shift1_rows(c.data), axis=1) \
            | (c.lengths != _shift1(c.lengths))
    else:
        bits = _normalize_bits(c)
        data_diff = bits != _shift1(bits)
    return jnp.where(both_null, False,
                     jnp.where(valid_mismatch, True,
                               jnp.where(c.valid, data_diff, False)))


def _shift1_rows(m):
    return jnp.roll(m, 1, axis=0)


# --------------------------------------------------------------------------
# segment reducers (sorted ids, masked)
# --------------------------------------------------------------------------
#
# INTEGER sums/counts exploit sortedness: prefix-sum + two searchsorted
# gathers instead of XLA scatter-add (scatter serializes on the TPU;
# cumsum/compare/gather are native VPU shapes).  Exact even under int64
# overflow — modular addition is associative, so a prefix DIFFERENCE wraps
# to the same value the per-segment wrap produces.  FLOATS keep the
# scatter: a segment sum as a difference of two running prefixes loses the
# segment entirely once the running total dwarfs it (1e300-scale values in
# a batch would absorb 1e5-scale segment sums to 0.0) — not an "order
# variance" the variableFloatAgg conf covers, but catastrophic
# cancellation.  min/max have no invertible prefix form and keep
# segment_min/max.

_PALLAS_CUMSUM = [False]  # flipped by the conf via set_pallas_cumsum
# test hook: route the fused segmented kernel through pallas INTERPRET
# mode on the CPU backend so the full dispatcher (not just the kernel)
# is exercised by tests/test_pallas.py
_PALLAS_SEG_INTERPRET = [False]


def set_pallas_cumsum(enabled: bool) -> None:
    _PALLAS_CUMSUM[0] = bool(enabled)  # tpulint: disable=TPU009 per-session conf latch: atomic boolean store, same-value writers under one session conf


def _masked_cumsum(v):
    # pallas path: real TPU only (CPU lacks non-interpret pallas) and
    # 32-bit dtypes only (64-bit is emulated on current chips and does not
    # lower); everything else takes XLA's cumsum
    if _PALLAS_CUMSUM[0] and v.dtype.itemsize < 8 \
            and jax.default_backend() == "tpu":
        from ..ops.pallas_kernels import cumsum_1d
        try:
            return cumsum_1d(v)
        except Exception as e:  # noqa: BLE001 — any pallas failure falls back
            # a silent fallback here means "pallas on" quietly runs the
            # XLA lowering forever; count it so perf triage can see it
            from ..metrics.registry import count_swallowed
            count_swallowed("numPallasFallbacks", "spark_rapids_tpu.pallas",
                            "pallas cumsum_1d failed (%r); using XLA "
                            "cumsum", e)
    return jnp.cumsum(v)


def _pallas_seg_mode():
    """Which fused-kernel mode the dispatcher may use: 'tpu' (compiled,
    BACKEND-gated — BENCH_PALLAS showed the pallas formulation slower
    than XLA on the CPU backend, so the flag alone is not enough),
    'interpret' (test hook), or None (XLA per-request reducers)."""
    if _PALLAS_SEG_INTERPRET[0]:
        return "interpret"
    if _PALLAS_CUMSUM[0] and jax.default_backend() == "tpu":
        return "tpu"
    return None


def _seg_multi(reqs, gid, cap):
    """All requested segmented reductions over sorted `gid` in as few
    HBM passes as the backend allows.

    `reqs`: list of (op, vals, contribute, fill[, is_count]) with op in
    'sum'|'min'|'max' — contribute masks rows out (sum: add 0; min/max:
    compare fill), exactly the legacy _seg_sum/_seg_min/_seg_max
    contracts.  Returns one [cap] array per request.

    Fused path (TPU backend + pallas.enabled, or the interpret test
    hook): ONE pallas pass (ops/pallas_kernels.seg_agg_1d) computes the
    running segmented aggregate of every request at once, and a SHARED
    searchsorted pair gathers each segment's last-row value — instead of
    one scatter/prefix pass per aggregate.  64-bit requests stay on the
    XLA reducers on real chips (emulated dtypes do not lower), except
    counts (`is_count`: 0/1 values) which run in int32 and widen after.
    XLA path: the prior per-request formulations verbatim — integer sums
    via prefix-diff, float sums via scatter segment_sum (a restart-free
    prefix would cancel catastrophically), min/max via segment_min/max —
    sharing one searchsorted pair across every request."""
    n = gid.shape[0]
    results = [None] * len(reqs)
    mode = _pallas_seg_mode()
    # shared segment bounds (one searchsorted pair for ALL requests; the
    # legacy path recomputed them per _seg_sum call)
    seg = jnp.arange(cap, dtype=gid.dtype)
    start = jnp.searchsorted(gid, seg, side="left")
    end = jnp.searchsorted(gid, seg, side="right")
    end_ix = jnp.clip(end - 1, 0, n - 1)
    nonempty = end > start

    fused: list = []  # (req index, kernel value array, out cast dtype)
    if mode is not None:
        for i, req in enumerate(reqs):
            op, vals, contribute, fill = req[0], req[1], req[2], req[3]
            is_count = bool(req[4]) if len(req) > 4 else False
            dt = vals.dtype
            if mode == "tpu" and dt.itemsize >= 8:
                if not (is_count and op == "sum"):
                    continue  # emulated 64-bit: XLA reducer below
                vals, dt = vals.astype(jnp.int32), jnp.dtype(jnp.int32)
            if op == "sum":
                v = jnp.where(contribute, vals, jnp.zeros((), dt))
            else:
                v = jnp.where(contribute, vals, fill)
            fused.append((i, v, reqs[i][1].dtype))
    if fused:
        from ..ops.pallas_kernels import seg_agg_1d
        try:
            running = seg_agg_1d(gid, [v for _, v, _ in fused],
                                 [reqs[i][0] for i, _, _ in fused],
                                 interpret=(mode == "interpret"))
        except Exception as e:  # noqa: BLE001 — any pallas failure falls back
            from ..metrics.registry import count_swallowed
            count_swallowed("numPallasFallbacks", "spark_rapids_tpu.pallas",
                            "pallas seg_agg_1d failed (%r); using XLA "
                            "reducers", e)
            running = None
        if running is not None:
            for (i, _v, out_dt), run in zip(fused, running):
                op, fill = reqs[i][0], reqs[i][3]
                ident = (jnp.zeros((), run.dtype) if op == "sum"
                         else jnp.asarray(fill).astype(run.dtype))
                out = jnp.where(nonempty, run[end_ix], ident)
                results[i] = out.astype(out_dt)
    for i, req in enumerate(reqs):
        if results[i] is not None:
            continue
        op, vals, contribute, fill = req[0], req[1], req[2], req[3]
        if op == "sum":
            v = jnp.where(contribute, vals, jnp.zeros((), vals.dtype))
            if jnp.issubdtype(vals.dtype, jnp.floating):
                results[i] = jax.ops.segment_sum(
                    v, gid, num_segments=cap, indices_are_sorted=True)
                continue
            c = _masked_cumsum(v)
            zero = jnp.zeros((), c.dtype)
            total = jnp.where(end > 0, c[end_ix], zero)
            prev = jnp.where(start > 0, c[jnp.clip(start - 1, 0, n - 1)],
                             zero)
            results[i] = jnp.where(nonempty, total - prev,
                                   zero).astype(vals.dtype)
        else:
            v = jnp.where(contribute, vals, fill)
            reducer = (jax.ops.segment_min if op == "min"
                       else jax.ops.segment_max)
            results[i] = reducer(v, gid, num_segments=cap,
                                 indices_are_sorted=True)
    return results


def _seg_sum(vals, gid, contribute, cap):
    return _seg_multi([("sum", vals, contribute, 0)], gid, cap)[0]


def _seg_min(vals, gid, contribute, cap, fill):
    return _seg_multi([("min", vals, contribute, fill)], gid, cap)[0]


def _seg_max(vals, gid, contribute, cap, fill):
    return _seg_multi([("max", vals, contribute, fill)], gid, cap)[0]


class _AggState:
    """Internal state layout per aggregate: list of (field_suffix, dtype)."""

    @staticmethod
    def fields(agg: AggregateExpression):
        f = agg.func
        if f == "Count":
            return [("count", LongType)]
        if f == "Average":
            return [("sum", DoubleType), ("count", LongType)]
        if f == "Sum":
            return [("sum", agg.dtype)]
        if f in ("Min", "Max"):
            return [(f.lower(), agg.child.dtype)]
        if f in ("First", "Last"):
            return [("val", agg.child.dtype), ("pos", LongType)]
        raise NotImplementedError(f)


def _update_one(agg: AggregateExpression, col, gid, live_s, cap,
                dedup=None):
    """Compute state columns for one aggregate from sorted input values.

    `dedup`: for distinct aggregates, the is-first-occurrence-of-(group,
    value) mask over sorted rows — duplicate values contribute nothing."""
    f = agg.func
    if f == "Count":
        if col is None:  # count(*)
            contribute = live_s
        else:
            contribute = live_s & col.valid
        if agg.distinct and dedup is not None:
            contribute = contribute & dedup
        cnt = _seg_multi([("sum", contribute.astype(jnp.int64), live_s,
                           0, True)], gid, cap)[0]
        return [Column(cnt, jnp.ones(cap, jnp.bool_), LongType)]
    valid = col.valid
    contribute = live_s & valid
    if f in ("Sum", "Average") and agg.distinct and dedup is not None:
        contribute = contribute & dedup
    if f in ("Sum", "Average"):
        out_t = DoubleType if f == "Average" else agg.dtype
        v = col.data.astype(out_t.jnp_dtype)
        # one fused segmented pass for the value sum AND its count
        s, nvalid = _seg_multi(
            [("sum", v, contribute, 0),
             ("sum", contribute.astype(jnp.int64), live_s, 0, True)],
            gid, cap)
        sum_col = Column(s, nvalid > 0, out_t).mask_invalid()
        if f == "Sum":
            return [sum_col]
        return [sum_col, Column(nvalid, jnp.ones(cap, jnp.bool_), LongType)]
    if f in ("Min", "Max"):
        # distinct is a no-op for min/max
        if agg.child.dtype.is_string:
            return [_minmax_string(f, col, gid, contribute, cap)]
        return [_minmax(f, agg.child.dtype, col.data, gid, contribute, cap)]
    raise NotImplementedError(f)


def _string_order_keys(col: Column):
    """Order-preserving int64 keys for a string column, most significant
    first: big-endian uint64 words over the padded byte matrix (UTF-8 byte
    order == code-point order) + length tiebreak, sign-bias mapped so int64
    compare equals unsigned compare."""
    cap, L = col.data.shape
    assert L % 8 == 0, L  # bucket_strlen yields power-of-two >= 8
    w = col.data.reshape(cap, L // 8, 8).astype(jnp.uint64)
    shifts = jnp.arange(56, -8, -8, dtype=jnp.uint64)
    words = jnp.sum(w << shifts, axis=2, dtype=jnp.uint64)
    bias = jnp.uint64(1 << 63)
    keys = [(words[:, j] ^ bias).astype(jnp.int64) for j in range(L // 8)]
    keys.append(col.lengths.astype(jnp.int64))
    return keys


def _minmax_string(f, scol: Column, gid, contribute, cap):
    """Per-group lexicographic min/max of a string column: iterated
    segmented reductions narrow the candidate set one 8-byte word at a
    time, then the winning row's bytes are gathered (the byte-matrix
    segment reduction the round-1 verdict flagged as pending)."""
    keys = _string_order_keys(scol)
    nvalid = _seg_sum(contribute.astype(jnp.int64), gid,
                      jnp.ones_like(contribute), cap)
    cand = contribute
    gidc = jnp.clip(gid, 0, cap - 1)
    for k in keys:
        if f == "Min":
            best = _seg_min(k, gid, cand, cap, jnp.int64(_I64_MAX))
        else:
            best = _seg_max(k, gid, cand, cap, jnp.int64(_I64_MIN))
        cand = cand & (k == jnp.take(best, gidc))
    rowpos = jnp.arange(cap, dtype=jnp.int64)
    win = _seg_min(jnp.where(cand, rowpos, _I64_MAX), gid,
                   jnp.ones_like(cand), cap, jnp.int64(_I64_MAX))
    widx = jnp.clip(win, 0, cap - 1).astype(jnp.int32)
    out = scol.take(widx)
    return out.with_valid(nvalid > 0).mask_invalid()


def _minmax(f, dtype, vals, gid, contribute, cap):
    ones = jnp.ones_like(contribute)
    if dtype.is_floating:
        v = vals.astype(jnp.float64)
        isnan = jnp.isnan(v)
        # every reduction this aggregate needs, one fused segmented pass
        if f == "Min":
            has_nan_i, nvalid, n_non_nan, r = _seg_multi(
                [("max", (contribute & isnan).astype(jnp.int32), ones,
                  jnp.int32(0)),
                 ("sum", contribute.astype(jnp.int64), ones, 0, True),
                 ("sum", (contribute & ~isnan).astype(jnp.int32), ones,
                  0, True),
                 ("min", jnp.where(isnan, jnp.inf, v), contribute,
                  jnp.float64(np.inf))], gid, cap)
            # NaN only wins min when the group has NO non-NaN values
            # (min(+inf, NaN) is +inf: NaN is greatest)
            only_nan = (has_nan_i > 0) & (n_non_nan == 0)
            r = jnp.where(only_nan, jnp.nan, r)
        else:
            has_nan_i, nvalid, r = _seg_multi(
                [("max", (contribute & isnan).astype(jnp.int32), ones,
                  jnp.int32(0)),
                 ("sum", contribute.astype(jnp.int64), ones, 0, True),
                 ("max", jnp.where(isnan, -jnp.inf, v), contribute,
                  jnp.float64(-np.inf))], gid, cap)
            r = jnp.where(has_nan_i > 0, jnp.nan, r)  # NaN is greatest
        out = r.astype(dtype.jnp_dtype)
        return Column(out, nvalid > 0, dtype).mask_invalid()
    v = vals.astype(jnp.int64)
    if f == "Min":
        nvalid, r = _seg_multi(
            [("sum", contribute.astype(jnp.int64), ones, 0, True),
             ("min", v, contribute, jnp.int64(_I64_MAX))], gid, cap)
    else:
        nvalid, r = _seg_multi(
            [("sum", contribute.astype(jnp.int64), ones, 0, True),
             ("max", v, contribute, jnp.int64(_I64_MIN))], gid, cap)
    return Column(r.astype(dtype.jnp_dtype), nvalid > 0, dtype) \
        .mask_invalid()


class TpuHashAggregateExec(TpuExec):
    coalesce_after = True

    def __init__(self, grouping: Sequence[E.Expression],
                 group_names: Sequence[str],
                 aggregates: Sequence[AggregateExpression], child: ExecNode):
        super().__init__(child)
        self.grouping = list(grouping)
        self.group_names = list(group_names)
        self.aggregates = list(aggregates)
        fields = [StructField(n, g.dtype)
                  for n, g in zip(group_names, grouping)]
        fields += [StructField(a.output_name or a.func.lower(), a.dtype)
                   for a in self.aggregates]
        self._schema = Schema(fields)
        self._state_schema = self._make_state_schema()
        if self._distinct_child() is not None:
            # distinct dedup happens inside one update kernel call: partial
            # states are NOT mergeable across batches (the same value may
            # appear in several), so the child must coalesce to one batch
            # (the reference falls back to CPU for these shapes instead;
            # aggregate.scala GpuHashAggregateMeta.tagPlanForGpu)
            self.child_coalesce_goal = "single"

    def _cost_weight(self) -> int:
        """Per-row op-count estimate for the roofline cost declaration
        (metrics/roofline.py): the grouped update sorts by key then runs
        one segmented pass per aggregate — coarse, like every estFlops
        figure outside the HLO-analyzed whole-stage programs."""
        return max(1, len(self.grouping) + len(self.aggregates)) * 4

    def _distinct_child(self):
        """The single distinct-aggregate child expression, or None.
        The planner rejects plans with more than one distinct child."""
        for a in self.aggregates:
            if a.distinct and a.func in ("Sum", "Count", "Average") \
                    and a.child is not None:
                return a.child
        return None

    @property
    def schema(self):
        return self._schema

    def describe(self):
        gs = ", ".join(map(repr, self.grouping))
        ags = ", ".join(map(repr, self.aggregates))
        return f"TpuHashAggregateExec[keys=[{gs}] aggs=[{ags}]]"

    def _make_state_schema(self) -> Schema:
        fields = [StructField(f"_k{i}", g.dtype)
                  for i, g in enumerate(self.grouping)]
        for ai, a in enumerate(self.aggregates):
            for suffix, dt in _AggState.fields(a):
                fields.append(StructField(f"_a{ai}_{suffix}", dt))
        return Schema(fields)

    # ---- per-batch kernels (jitted) ---------------------------------------

    def _update_kernel(self, batch: ColumnarBatch) -> ColumnarBatch:
        """input batch -> state batch (update aggregation)."""
        cap = batch.capacity
        keys = [g.eval(batch) for g in self.grouping]
        live = batch.sel
        dchild = self._distinct_child()
        if dchild is not None:
            # sort equal (group, value) pairs adjacent; first occurrence of
            # each pair is the only row a distinct aggregate counts
            dval = dchild.eval(batch)
            order, gid, boundary, ngroups = group_rows(keys, live, [dval])
            dval_s = dval.take(order)
            dedup = boundary | _col_differs_from_prev(dval_s)
            dedup = dedup.at[0].set(True)
        else:
            order, gid, boundary, ngroups = group_rows(keys, live)
            dedup = None
        live_s = jnp.take(live, order)
        gid = jnp.where(live_s, gid, cap - 1)

        state_cols: List[Column] = []
        # group keys: first row of each group (the boundary rows, compacted)
        first_pos = _seg_min(jnp.arange(cap, dtype=jnp.int64), gid,
                             live_s, cap, jnp.int64(_I64_MAX))
        first_idx = jnp.take(order,
                             jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32))
        for k in keys:
            state_cols.append(k.take(first_idx))
        for a in self.aggregates:
            col = a.child.eval(batch) if a.child is not None else None
            scol = col.take(order) if col is not None else None
            f = a.func
            if f in ("First", "Last"):
                # first/last over live rows INCLUDING null values (Spark
                # ignoreNulls=false default).  Position = rank among LIVE
                # rows in original order (the driver advances the offset by
                # live-row count, so raw indices of non-compacted batches
                # would break cross-batch ordering) + partition row offset.
                rank_orig = jnp.cumsum(live.astype(jnp.int64)) - 1
                pos = jnp.take(rank_orig, order)
                if f == "First":
                    best = _seg_min(pos, gid, live_s, cap,
                                    jnp.int64(_I64_MAX))
                else:
                    best = _seg_max(pos, gid, live_s, cap, jnp.int64(-1))
                # original index of the winning row: sorted position whose
                # pos equals the group's best
                is_best = live_s & (pos == jnp.take(best,
                                                    jnp.clip(gid, 0,
                                                             cap - 1)))
                rowpos = jnp.arange(cap, dtype=jnp.int64)
                win_sorted = _seg_min(jnp.where(is_best, rowpos, _I64_MAX),
                                      gid, live_s, cap, jnp.int64(_I64_MAX))
                widx = jnp.take(
                    order, jnp.clip(win_sorted, 0, cap - 1).astype(jnp.int32))
                state_cols.append(col.take(widx))
                gpos = best + E.current_row_offset()
                state_cols.append(Column(gpos, jnp.ones(cap, jnp.bool_),
                                         LongType))
            else:
                state_cols.extend(_update_one(a, scol, gid, live_s, cap,
                                              dedup=dedup))
        sel = jnp.arange(cap, dtype=jnp.int32) < ngroups
        # zero out dead state rows
        state_cols = [c.with_valid(c.valid & sel).mask_invalid()
                      if not c.dtype.is_string else c for c in state_cols]
        return ColumnarBatch(state_cols, sel, self._state_schema)

    # ---- low-cardinality bucket fast path ---------------------------------

    _BUCKETS = 1024

    def _bucketable(self) -> bool:
        """Aggregate set eligible for the bucket fast path: mergeable
        scatter-computable states (sum/count/avg, non-string min/max),
        no distinct dedup, no arrival-order state."""
        if not self.grouping:
            return False
        for a in self.aggregates:
            if a.distinct or a.func in ("First", "Last"):
                return False
            if a.func in ("Min", "Max") and a.child.dtype.is_string:
                return False
            if a.func not in ("Count", "Sum", "Average", "Min", "Max"):
                return False
        return True

    def _bucket_update_kernel(self, batch: ColumnarBatch):
        """-> (clean: bool[], state batch at capacity _BUCKETS).

        The sort-free grouped update: rows scatter into h1-hash buckets;
        `clean` is an EXACT per-batch check that every live row's key
        VALUE-equals its bucket representative's (so each occupied bucket
        holds one distinct group, with Spark key semantics: nulls equal,
        NaN equal, -0.0 == 0.0).  When clean, per-bucket segment
        reductions are the partial state — same schema as the sort path,
        so the merge/finalize kernels take either.  More distinct groups
        than buckets forces a collision, so high-cardinality batches
        fail the check and take the sort path; no cardinality estimate
        is needed.  XLA lowers the segment ops to scatter-adds; on TPU
        the alternative one-hot-matmul formulation rides the MXU, but
        scatter keeps the state layout identical across backends."""
        B = self._BUCKETS
        keys = [g.eval(batch) for g in self.grouping]
        live = batch.sel
        cap = batch.capacity
        h1, _h2 = hash_columns_double(keys, live)
        ids = (h1 & jnp.uint64(B - 1)).astype(jnp.int32)
        sid = jnp.where(live, ids, B)  # B = trash bucket for dead rows
        iota = jnp.arange(cap, dtype=jnp.int32)
        rep = jnp.zeros(B, jnp.int32).at[sid].set(iota, mode="drop")
        occ = jnp.zeros(B, jnp.bool_).at[sid].set(True, mode="drop")
        rep_of_row = jnp.take(rep, ids)
        eq = jnp.ones(cap, jnp.bool_)
        for k in keys:
            eq &= _key_equal_at(k, rep_of_row)
        clean = jnp.all(jnp.where(live, eq, True))

        def seg(vals, mask, reducer, fill):
            full = jnp.where(mask, vals, fill)
            return reducer(full, sid, num_segments=B + 1)[:B]

        state_cols: List[Column] = []
        for k in keys:
            kk = k.take(rep)
            state_cols.append(kk)
        for a in self.aggregates:
            col = a.child.eval(batch) if a.child is not None else None
            f = a.func
            if f == "Count":
                contribute = live if col is None else live & col.valid
                cnt = seg(contribute.astype(jnp.int64), live,
                          jax.ops.segment_sum, jnp.int64(0))
                state_cols.append(Column(cnt, jnp.ones(B, jnp.bool_),
                                         LongType))
                continue
            contribute = live & col.valid
            nvalid = seg(contribute.astype(jnp.int64), live,
                         jax.ops.segment_sum, jnp.int64(0))
            if f in ("Sum", "Average"):
                out_t = DoubleType if f == "Average" else a.dtype
                v = col.data.astype(out_t.jnp_dtype)
                s = seg(v, contribute, jax.ops.segment_sum,
                        jnp.zeros((), out_t.jnp_dtype))
                state_cols.append(Column(s, nvalid > 0, out_t)
                                  .mask_invalid())
                if f == "Average":
                    state_cols.append(Column(nvalid,
                                             jnp.ones(B, jnp.bool_),
                                             LongType))
            else:  # Min / Max (numeric)
                dt = a.child.dtype
                v = col.data
                if dt.is_floating:
                    # Spark float ordering: NaN greatest, -0.0 == 0.0
                    # (the sort path's [nan_flag, value] key, as direct
                    # reductions: no f64 bitcasts — unimplemented on the
                    # emulated-f64 TPU backend)
                    isnan = jnp.isnan(v)
                    v = jnp.where(v == 0.0, jnp.zeros((), v.dtype), v)
                    nn_mask = contribute & ~isnan
                    n_nonnan = seg(nn_mask.astype(jnp.int64), live,
                                   jax.ops.segment_sum, jnp.int64(0))
                    if f == "Min":
                        m = seg(v, nn_mask, jax.ops.segment_min,
                                _type_max(dt))
                        # all-NaN group: min is NaN
                        m = jnp.where((nvalid > 0) & (n_nonnan == 0),
                                      jnp.asarray(jnp.nan, v.dtype), m)
                    else:
                        m = seg(v, nn_mask, jax.ops.segment_max,
                                _type_min(dt))
                        # any NaN in group: max is NaN (NaN greatest)
                        m = jnp.where(nvalid > n_nonnan,
                                      jnp.asarray(jnp.nan, v.dtype), m)
                else:
                    if f == "Min":
                        m = seg(v, contribute, jax.ops.segment_min,
                                _type_max(dt))
                    else:
                        m = seg(v, contribute, jax.ops.segment_max,
                                _type_min(dt))
                state_cols.append(Column(m, nvalid > 0, dt)
                                  .mask_invalid())
        sel = occ
        state_cols = [c.with_valid(c.valid & sel).mask_invalid()
                      if not c.dtype.is_string else c for c in state_cols]
        return clean, ColumnarBatch(state_cols, sel, self._state_schema)

    def _merge_kernel(self, state: ColumnarBatch) -> ColumnarBatch:
        """state batch (concat of partials) -> merged state batch."""
        cap = state.capacity
        nkeys = len(self.grouping)
        keys = list(state.columns[:nkeys])
        live = state.sel
        order, gid, boundary, ngroups = group_rows(keys, live)
        live_s = jnp.take(live, order)
        gid = jnp.where(live_s, gid, cap - 1)
        out_cols: List[Column] = []
        first_pos = _seg_min(jnp.arange(cap, dtype=jnp.int64), gid,
                             live_s, cap, jnp.int64(_I64_MAX))
        first_idx = jnp.take(order,
                             jnp.clip(first_pos, 0, cap - 1).astype(jnp.int32))
        for k in keys:
            out_cols.append(k.take(first_idx))
        ci = nkeys
        for a in self.aggregates:
            f = a.func
            nfields = len(_AggState.fields(a))
            cols = state.columns[ci:ci + nfields]
            ci += nfields
            if f == "Count":
                scol = cols[0].take(order)
                s = _seg_sum(scol.data, gid, live_s & scol.valid, cap)
                out_cols.append(Column(s, jnp.ones(cap, jnp.bool_),
                                       LongType))
            elif f == "Sum":
                scol = cols[0].take(order)
                contribute = live_s & scol.valid
                s, nvalid = _seg_multi(
                    [("sum", scol.data, contribute, 0),
                     ("sum", contribute.astype(jnp.int64), live_s, 0,
                      True)], gid, cap)
                out_cols.append(Column(s, nvalid > 0, cols[0].dtype)
                                .mask_invalid())
            elif f == "Average":
                scol = cols[0].take(order)
                ccol = cols[1].take(order)
                contribute = live_s & scol.valid
                # ccol holds per-partial COUNTS (not 0/1 flags): their
                # sum is unbounded, so no int32 is_count narrowing
                s, n = _seg_multi(
                    [("sum", scol.data, contribute, 0),
                     ("sum", ccol.data, live_s & ccol.valid, 0)],
                    gid, cap)
                out_cols.append(Column(s, n > 0, DoubleType).mask_invalid())
                out_cols.append(Column(n, jnp.ones(cap, jnp.bool_),
                                       LongType))
            elif f in ("Min", "Max"):
                scol = cols[0].take(order)
                contribute = live_s & scol.valid
                if scol.dtype.is_string:
                    out_cols.append(_minmax_string(f, scol, gid, contribute,
                                                   cap))
                else:
                    out_cols.append(_minmax(f, scol.dtype, scol.data, gid,
                                            contribute, cap))
            elif f in ("First", "Last"):
                vcol = cols[0].take(order)
                pcol = cols[1].take(order)
                if f == "First":
                    best = _seg_min(pcol.data, gid, live_s, cap,
                                    jnp.int64(_I64_MAX))
                else:
                    best = _seg_max(pcol.data, gid, live_s, cap,
                                    jnp.int64(-1))
                is_best = live_s & (pcol.data == jnp.take(best, gid))
                # position of the winning row in sorted order
                rowpos = jnp.arange(cap, dtype=jnp.int64)
                win = _seg_min(jnp.where(is_best, rowpos, _I64_MAX), gid,
                               live_s, cap, jnp.int64(_I64_MAX))
                widx = jnp.clip(win, 0, cap - 1).astype(jnp.int32)
                out_cols.append(vcol.take(widx))
                out_cols.append(Column(best, jnp.ones(cap, jnp.bool_),
                                       LongType))
            else:
                raise NotImplementedError(f)
        sel = jnp.arange(cap, dtype=jnp.int32) < ngroups
        out_cols = [c.with_valid(c.valid & sel).mask_invalid()
                    if not c.dtype.is_string else c for c in out_cols]
        return ColumnarBatch(out_cols, sel, self._state_schema)

    def _finalize_kernel(self, state: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.grouping)
        out_cols = list(state.columns[:nkeys])
        ci = nkeys
        for a in self.aggregates:
            nfields = len(_AggState.fields(a))
            cols = state.columns[ci:ci + nfields]
            ci += nfields
            if a.func == "Average":
                s, n = cols[0], cols[1]
                nz = n.data > 0
                avg = s.data / jnp.where(nz, n.data, 1).astype(jnp.float64)
                out_cols.append(Column(avg, s.valid & nz, DoubleType)
                                .mask_invalid())
            elif a.func in ("First", "Last"):
                out_cols.append(cols[0])
            else:
                c = cols[0]
                if c.dtype is not a.dtype and not c.dtype.is_string:
                    c = Column(c.data.astype(a.dtype.jnp_dtype), c.valid,
                               a.dtype)
                out_cols.append(c)
        return ColumnarBatch(out_cols, state.sel, self._schema)

    # ---- ungrouped fast path ----------------------------------------------

    def _global_kernel(self, batch: ColumnarBatch) -> ColumnarBatch:
        """No grouping keys: masked whole-batch reductions to a 1-row state."""
        live = batch.sel
        cap = 8  # tiny static output
        cols: List[Column] = []
        dchild = self._distinct_child()
        first_occ = None
        if dchild is not None:
            # value-sorted first-occurrence mask over the whole batch
            dval = dchild.eval(batch)
            dorder, _g, _b, _n = group_rows([], live, value_cols=[dval])
            dval_s = dval.take(dorder)
            occ_sorted = _col_differs_from_prev(dval_s).at[0].set(True)
            first_occ = jnp.zeros(batch.capacity, jnp.bool_
                                  ).at[dorder].set(occ_sorted)
        for a in self.aggregates:
            col = a.child.eval(batch) if a.child is not None else None
            f = a.func
            distinct = (a.distinct and first_occ is not None
                        and f in ("Sum", "Count", "Average"))
            if f == "Count":
                contribute = live if col is None else live & col.valid
                if distinct:
                    contribute = contribute & first_occ
                v = jnp.sum(contribute.astype(jnp.int64))
                cols.append(_scalar_col(v, True, LongType, cap))
                continue
            contribute = live & col.valid
            if distinct:
                contribute = contribute & first_occ
            nvalid = jnp.sum(contribute.astype(jnp.int64))
            if f in ("Min", "Max") and col.dtype.is_string:
                keys = _string_order_keys(col)
                cand = contribute
                for k in keys:
                    if f == "Min":
                        best = jnp.min(jnp.where(cand, k, _I64_MAX))
                    else:
                        best = jnp.max(jnp.where(cand, k, _I64_MIN))
                    cand = cand & (k == best)
                rowpos = jnp.arange(batch.capacity, dtype=jnp.int64)
                win = jnp.min(jnp.where(cand, rowpos, _I64_MAX))
                widx = jnp.clip(win, 0, batch.capacity - 1).astype(jnp.int32)
                taken = col.take(jnp.full((cap,), widx, dtype=jnp.int32))
                row0 = jnp.arange(cap, dtype=jnp.int32) < 1
                cols.append(taken.with_valid(row0 & (nvalid > 0))
                            .mask_invalid())
                continue
            if f in ("Sum", "Average"):
                out_t = DoubleType if f == "Average" else a.dtype
                v = jnp.sum(jnp.where(contribute,
                                      col.data.astype(out_t.jnp_dtype),
                                      jnp.zeros((), out_t.jnp_dtype)))
                cols.append(_scalar_col(v, nvalid > 0, out_t, cap))
                if f == "Average":
                    cols.append(_scalar_col(nvalid, True, LongType, cap))
            elif f in ("Min", "Max"):
                mm = _minmax(f, col.dtype, col.data,
                             jnp.zeros(batch.capacity, jnp.int32),
                             contribute, 1)
                cols.append(_scalar_col(mm.data[0], mm.valid[0], col.dtype,
                                        cap))
            elif f in ("First", "Last"):
                pos = jnp.arange(batch.capacity, dtype=jnp.int64)
                if f == "First":
                    raw = jnp.min(jnp.where(live, pos, _I64_MAX))
                else:
                    raw = jnp.max(jnp.where(live, pos, -1))
                idx = jnp.clip(raw, 0, batch.capacity - 1).astype(jnp.int32)
                # rank among live rows, for cross-batch ordering
                rank = jnp.cumsum(live.astype(jnp.int64)) - 1
                best = rank[idx]
                # strings need a take-based path (no scalar buffer dtype)
                taken = col.take(jnp.full((cap,), idx, dtype=jnp.int32))
                row0 = jnp.arange(cap, dtype=jnp.int32) < 1
                cols.append(taken.with_valid(taken.valid & row0))
                cols.append(_scalar_col(best + E.current_row_offset(), True,
                                        LongType, cap))
            else:
                raise NotImplementedError(f)
        sel = jnp.arange(cap, dtype=jnp.int32) < 1
        return ColumnarBatch(cols, sel, self._state_schema)

    # ---- driver -----------------------------------------------------------

    def _needs_offset(self) -> bool:
        if any(a.func in ("First", "Last") for a in self.aggregates):
            return True
        exprs = list(self.grouping)
        exprs += [a.child for a in self.aggregates if a.child is not None]
        return any(E.tree_needs_row_offset(e) for e in exprs)

    def kernel_key(self) -> tuple:
        from ..utils.kernel_cache import expr_key, schema_key
        from ..utils import packed_sort as _PS
        return ("TpuHashAggregateExec",
                # the pallas/packed flags change the traced program (the
                # packed kill switch must also bust cached kernels —
                # "false restores lexsort" is a per-process contract)
                ("pallas" if _PALLAS_CUMSUM[0] else "xla"),
                (_pallas_seg_mode() or "none"),
                ("packed" if _PS.packed_enabled() else "lex"),
                tuple(expr_key(g) for g in self.grouping),
                tuple(self.group_names),
                tuple(expr_key(a) for a in self.aggregates),
                tuple(a.output_name for a in self.aggregates),
                schema_key(self._schema))

    # ---- whole-stage path --------------------------------------------------

    def _try_whole_stage(self, ctx: ExecContext):
        """Scan -> row-local -> aggregate as ONE compiled dispatch (the TPU
        analogue of Spark's whole-stage codegen): equal-capacity input
        batches stack on a leading axis, the per-batch pre+update work is
        vmapped, partials merge and finalize inside the same program.  On a
        high-latency host link (tunneled dev TPU) this collapses
        O(batches) kernel dispatches + host syncs into one.

        Returns the result batch, or None when the stage shape doesn't
        qualify (caller falls back to the streaming loop)."""
        from .. import config as C
        from ..utils.kernel_cache import cached_kernel
        from .basic import RowLocalExec
        # FUSION_ENABLED is the master whole-stage kill switch (plan/
        # fusion.py); WHOLE_STAGE_ENABLED remains the aggregate-specific
        # knob for this absorption path
        if not ctx.conf.get(C.WHOLE_STAGE_ENABLED) \
                or not ctx.conf.get(C.FUSION_ENABLED) \
                or self._needs_offset():
            return None, None
        child = self.children[0]
        if isinstance(child, RowLocalExec):
            if child._needs_row_offset() or child._needs_input_file():
                # the fused stage threads a per-batch row offset
                # (monotonically_increasing_id / rand); vmapping it with
                # offset 0 would silently repeat per-batch streams.
                # input_file_name() likewise bakes a per-FILE constant that
                # one vmapped program cannot vary across batches
                return None, None
            pre_builder = child.batch_fn
            pre_params = child.stage_params()
            if pre_params:
                # plan-cache parameters in the absorbed chain: value-free
                # pre-key + the bound values as a leading traced argument
                # of the whole-stage program, so literal-variant
                # re-submissions replay this compiled program
                from ..utils.kernel_cache import param_free_keys
                with param_free_keys():
                    pre_key = child.kernel_key()
                pre_key += ("params", E.parameter_signature(pre_params))
            else:
                pre_key = child.kernel_key()
            source = child.children[0]
        else:
            pre_builder = None
            pre_params = []
            pre_key = ()
            source = child
        # drain INCREMENTALLY: eligibility (leaf shapes, byte budget) is
        # checked per batch so an over-budget input bails to the streaming
        # loop with the tail still unconsumed — the probe must never pin a
        # bigger working set than whole-stage itself would use
        src_iter = iter(source.execute(ctx))
        batches: list = []
        shape0 = None
        cap = 0
        byte_budget = ctx.conf.get(C.BATCH_SIZE_BYTES) // 2
        total_bytes = 0
        from ..serve.lifecycle import ctx_checkpoint
        for b in src_iter:
            # stage-boundary lifecycle checkpoint: the probe drain is
            # the last per-batch loop before the fused agg becomes ONE
            # device dispatch, so this is the agg's cancel/suspend point
            ctx_checkpoint(ctx, allow_suspend=True)
            shapes = [tuple(x.shape) for x in
                      jax.tree_util.tree_flatten(b)[0]]
            total_bytes += b.device_size_bytes()
            if shape0 is None:
                cap = b.capacity
                shape0 = shapes
            batches.append(b)
            if shapes != shape0 \
                    or b.schema.names != batches[0].schema.names \
                    or total_bytes > byte_budget:
                return None, (source, batches, src_iter)
        if not batches:
            return None, (source, batches, src_iter)
        k = len(batches)
        grouped = bool(self.grouping)
        update = self._update_kernel if grouped else self._global_kernel
        merge = self._merge_kernel
        finalize = self._finalize_kernel
        state_schema = self._state_schema

        flat0, treedef = jax.tree_util.tree_flatten(batches[0])
        flats = [jax.tree_util.tree_flatten(b)[0] for b in batches]
        nleaf = len(flat0)

        def _unrolled(leaves, one):
            # per-batch UNROLLED inside the compiled program: each batch's
            # pre+update chain fuses with its own input params, and only
            # the small per-batch STATES stack for the merge.  (Earlier
            # versions stacked the full inputs — first eagerly, then
            # in-jit — which materialized a whole-input concatenate before
            # any real work; for a 192MB q6 scan that copy was ~0.5s.)
            partial_list = []
            for j in range(k):
                b = jax.tree_util.tree_unflatten(
                    treedef, leaves[j * nleaf:(j + 1) * nleaf])
                partial_list.append(one(b))
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *partial_list)

        param_slots = [p.slot for p in pre_params]
        pvals = E.parameter_values(pre_params) if pre_params else None

        def _with_params(whole):
            """Parameter-threaded twin: the bound values lead the leaf
            arguments and install as the active binding while the program
            traces (see exec/basic.bound_param_builder)."""
            if not pre_params:
                return whole

            def whole_p(pv, *leaves):
                with E.bound_params(dict(zip(param_slots, pv))):
                    return whole(*leaves)
            return whole_p

        def build():
            def whole(*leaves):
                pre = pre_builder() if pre_builder is not None else None

                def one(b):
                    if pre is not None:
                        b = pre(b)
                    return update(b)
                partials = _unrolled(leaves, one)   # leaves [k, pcap, ...]
                both = _flatten_stacked(partials, state_schema)
                return finalize(merge(both))
            return _with_params(whole)

        def build_bucket():
            bupdate = self._bucket_update_kernel

            def whole_bucket(*leaves):
                pre = pre_builder() if pre_builder is not None else None

                def one(b):
                    if pre is not None:
                        b = pre(b)
                    return bupdate(b)
                outs = _unrolled(leaves, one)
                cleans, partials = outs
                both = _flatten_stacked(partials, state_schema)
                return jnp.all(cleans), finalize(merge(both))
            return _with_params(whole_bucket)

        # treedef in the key: the per-batch structure is baked into the
        # compiled closure (tree_unflatten over bare leaves), so two
        # stages with equal agg shape but different batch layouts must
        # not share a cache entry
        key = (("whole_stage", k, cap, pre_key, str(treedef))
               + self.kernel_key())
        all_leaves = [leaf for f in flats for leaf in f]
        # roofline: the absorbed whole-stage program reads every drained
        # source leaf out of HBM once (metadata sizes, never a sync)
        record_cost(self.metrics,
                    hbm_read=sum(
                        getattr(x, "size", 0)
                        * getattr(getattr(x, "dtype", None), "itemsize", 1)
                        for x in all_leaves),
                    flops=sum(b.capacity for b in batches)
                    * self._cost_weight())
        # buffer donation for the FINAL whole-stage program (never the
        # bucket probe — a dirty probe re-dispatches the same leaves):
        # the drained source batches are dead after this one dispatch
        # when the fusion-pass whitelist admits the source and no batch
        # is pinned; leaf ids must also be globally unique (a buffer
        # appearing twice cannot be donated once and read once)
        donate_leaf_argnums: tuple = ()
        from .. import config as _C
        if bool(ctx.conf.get(_C.DONATION_ENABLED)):
            from ..mem import donation as _donation
            from ..plan.fusion import source_donatable
            if source_donatable(source) \
                    and all(_donation.donatable(b) for b in batches):
                ids = [id(x) for x in all_leaves]
                if len(set(ids)) == len(ids):
                    base = 1 if pre_params else 0
                    donate_leaf_argnums = tuple(
                        base + i for i in range(len(all_leaves)))
        if grouped and self._bucketable() \
                and ctx.conf.get(C.AGG_BUCKET_GROUPS) \
                and key not in _BUCKET_DIRTY_KEYS:
            # sort-free program first: per-batch bucket states + an exact
            # all-clean check; only the k*_BUCKETS-row merge sorts.  A
            # dirty batch (high cardinality / bucket collision) falls
            # through to the sort-based program below and latches the
            # key dirty so later executions skip the probe.
            fnb = cached_kernel(key + ("bucket",), build_bucket)
            with self.metrics.timer(MN.COMPUTE_AGG_TIME), \
                    named_range("agg_whole_stage_bucket"):
                from ..utils.kernel_cache import record_dispatch
                record_dispatch()
                all_clean, out = (fnb(pvals, *all_leaves) if pre_params
                                  else fnb(*all_leaves))
            if bool(all_clean):
                self.metrics.add(MN.NUM_FUSED_STAGES, 1)
                record_output_batch(self.metrics, out, ctx.runtime)
                return out, None
            _BUCKET_DIRTY_KEYS.add(key)
        fn = cached_kernel(key, build,
                           **({"donate_argnums": donate_leaf_argnums}
                              if donate_leaf_argnums else {}))
        with self.metrics.timer(MN.COMPUTE_AGG_TIME), \
                named_range("agg_whole_stage"):
            from ..utils.kernel_cache import record_dispatch
            record_dispatch()
            if donate_leaf_argnums:
                from ..mem import donation as _donation
                _donation.record_donated_dispatch(
                    len(donate_leaf_argnums), self.metrics)
            out = fn(pvals, *all_leaves) if pre_params else fn(*all_leaves)
        self.metrics.add(MN.NUM_FUSED_STAGES, 1)
        record_output_batch(self.metrics, out, ctx.runtime)
        return out, None

    def _cpu_twin(self):
        """CPU re-execution plan for OOM fallback (exec/retryable.py):
        the CPU aggregate over the device child bridged through D2H."""
        from .basic import DeviceToHostExec
        from .cpu_relational import CpuHashAggregateExec
        return CpuHashAggregateExec(self.grouping, self.group_names,
                                    self.aggregates,
                                    DeviceToHostExec(self.children[0]))

    def execute(self, ctx: ExecContext):
        from .retryable import execute_with_cpu_fallback
        yield from execute_with_cpu_fallback(
            self, ctx, self._execute_device(ctx), self._cpu_twin)

    def _execute_device(self, ctx: ExecContext):
        from ..utils.kernel_cache import cached_kernel
        from .. import config as C
        set_pallas_cumsum(ctx.conf.get(C.PALLAS_ENABLED))
        whole, materialized = self._try_whole_stage(ctx)
        if whole is not None:
            yield whole
            return
        grouped = bool(self.grouping)
        base_update = (self._update_kernel if grouped
                       else self._global_kernel)
        needs_off = self._needs_offset()
        key = self.kernel_key()
        if needs_off:
            update = cached_kernel(
                key + ("update_off",),
                lambda: lambda b, off: E.eval_with_row_offset(
                    base_update, b, off))
        else:
            update = cached_kernel(key + ("update",), lambda: base_update)
        merge = cached_kernel(key + ("merge",),
                              lambda: self._merge_kernel)
        finalize = cached_kernel(key + ("finalize",),
                                 lambda: self._finalize_kernel)
        # Deferred merging: buffer per-batch partials and merge FAN_IN at a
        # time, so the expensive sort-based merge kernel (and the host
        # row-count syncs inside concat_batches) run once per FAN_IN input
        # batches instead of once per batch.  Merge aggregates are
        # associative, and order-sensitive ones (First/Last) carry explicit
        # row-offset tiebreak columns in the partial state, so K-way
        # concat-then-merge equals the pairwise fold.
        from ..config import AGG_MERGE_FAN_IN
        fan_in = max(2, ctx.conf.get(AGG_MERGE_FAN_IN))

        from .retryable import run_retryable

        def fold(state, pending):
            parts = ([state] if state is not None else []) + pending
            if len(parts) == 1:
                return parts[0]

            def attempt_merge(_):
                # merge allocates the K-way concat: reserve it so the
                # spill cascade (and the fault injector) see the boundary
                merge_bytes = sum(p.device_size_bytes() for p in parts)
                if ctx.runtime is not None:
                    ctx.runtime.reserve(merge_bytes, site="agg.merge")
                record_cost(self.metrics, hbm_read=merge_bytes,
                            flops=sum(p.capacity for p in parts)
                            * self._cost_weight())
                with self.metrics.timer(MN.CONCAT_TIME):
                    both = concat_batches(parts)
                with self.metrics.timer(MN.MERGE_AGG_TIME), \
                        self.metrics.timer(MN.SEG_AGG_TIME), \
                        named_range("agg_merge"):
                    return merge(both)
            # retry-only: partial states are merge inputs, not splittable
            # row ranges (splitting them would change nothing — the merge
            # concat is the allocation)
            return run_retryable(ctx, self.metrics, "aggMerge",
                                 attempt_merge, [None])[0]

        # if the whole-stage probe already drained the source, stream the
        # materialized batches through the child's per-batch kernel instead
        # of re-executing the scan (it would double I/O and decode work)
        if materialized is not None:
            import itertools
            from .basic import RowLocalExec
            src_exec, src_batches, src_rest = materialized
            upstream = itertools.chain(src_batches, src_rest)
            child = self.children[0]
            if isinstance(child, RowLocalExec) \
                    and src_exec is child.children[0]:
                # parameter-threaded like RowLocalExec.execute's plain
                # path, so the replay shares the same compiled kernel
                child_fn = child.parameterized_kernel()
                input_iter = (child_fn(b) for b in upstream)
            else:
                input_iter = upstream
        else:
            input_iter = self.children[0].execute(ctx)

        bucket_fn = None
        if self._bucketable() and not needs_off \
                and ctx.conf.get(C.AGG_BUCKET_GROUPS) \
                and key not in _BUCKET_DIRTY_KEYS:
            # needs_off excluded: the bucket kernel evaluates expressions
            # outside eval_with_row_offset, so a row-offset expression
            # would silently restart at 0 every batch
            bucket_fn = cached_kernel(key + ("bucket",),
                                      lambda: self._bucket_update_kernel)
        state = None
        pending: list = []
        hot = {"bucket_fn": bucket_fn, "offset": 0}
        from ..mem.retry import split_batch_rows
        # distinct dedup happens inside ONE update call (partial states
        # are not mergeable across batches) — a row-range split would
        # double-count values straddling the halves, so distinct shapes
        # are retry-only (exhaustion -> CPU fallback)
        update_split = (None if self._distinct_child() is not None
                        else split_batch_rows)

        def attempt_update(b):
            """Retryable per-batch update: reserve the partial-state
            footprint, then run the bucket probe / sort-based update.  A
            split input re-enters here piece by piece IN ORDER, so the
            row offset (First/Last tiebreaks) advances exactly as the
            unsplit batch would have."""
            if ctx.runtime is not None:
                ctx.runtime.reserve(b.device_size_bytes(),
                                    site="agg.update")
            # roofline: the update kernel reads the batch and does
            # ~sort + one segmented pass per aggregate (exec/base)
            record_cost(self.metrics, hbm_read=b.device_size_bytes(),
                        flops=(b.known_rows if b.known_rows is not None
                               else b.capacity) * self._cost_weight())
            partial = None
            with self.metrics.timer(MN.SEG_AGG_TIME):
                bfn = hot["bucket_fn"]
                if bfn is not None:
                    clean, bstate = bfn(b)
                    if bool(clean):  # host sync: pick the sort-free state
                        partial = bstate
                    else:
                        # dirty latch: a high-cardinality shape stays
                        # dirty — stop probing it (this query AND this
                        # kernel key process-wide)
                        hot["bucket_fn"] = None
                        _BUCKET_DIRTY_KEYS.add(key)
                if partial is None:
                    partial = update(b, jnp.int64(hot["offset"])) \
                        if needs_off else update(b)
            if needs_off:
                hot["offset"] += b.num_rows_host()
            return partial

        from ..serve.lifecycle import ctx_checkpoint
        for batch in input_iter:
            # stage-boundary lifecycle checkpoint (serve/lifecycle.py):
            # between per-batch updates no reservation is mid-flight —
            # partial states are spillable like any owned buffers, so a
            # preemption suspend here parks and resumes bit-for-bit
            ctx_checkpoint(ctx, allow_suspend=True)
            # the update kernel sorts at batch CAPACITY: a selective
            # upstream filter leaves mostly-dead batches, so shrink first
            # (capacity check is static: dense small batches skip the
            # num_rows_host device sync entirely)
            if batch.capacity >= 8192:
                batch = batch.maybe_shrink(batch.num_rows_host())
            with self.metrics.timer(MN.COMPUTE_AGG_TIME), \
                    named_range("agg_update"):
                partials = run_retryable(ctx, self.metrics, "aggUpdate",
                                         attempt_update, [batch],
                                         split=update_split)
            pending.extend(partials)
            if len(pending) >= fan_in:
                state = fold(state, pending)
                pending = []
        if pending:
            state = fold(state, pending)
        if state is None:
            if grouped:
                return
            # global agg over empty input still yields one row: run the
            # kernel on an all-dead batch of the child schema
            child_schema = self.children[0].schema
            data = {f.name: [] for f in child_schema}
            dead = ColumnarBatch.from_pydict(data, child_schema)
            state = update(dead, jnp.int64(0)) if needs_off else update(dead)
        out = finalize(state)
        record_output_batch(self.metrics, out, ctx.runtime)
        yield out


def _scalar_col(value, valid, dtype, cap):
    data = jnp.zeros(cap, dtype=dtype.jnp_dtype).at[0].set(
        value.astype(dtype.jnp_dtype) if hasattr(value, "astype") else value)
    v = jnp.zeros(cap, dtype=jnp.bool_).at[0].set(valid)
    return Column(data, v, dtype).mask_invalid()
