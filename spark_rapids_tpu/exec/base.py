"""Physical execution operators.

Reference: sql-plugin/.../rapids/GpuExec.scala — every device operator is a
`TpuExec` producing an iterator of ColumnarBatch with standard metrics
(numOutputRows/numOutputBatches/totalTime).  The CPU fallback side
(`CpuExec`) runs on pyarrow Tables, playing the role CPU Spark plays for the
reference: anything the planner can't put on the device still executes, and
the pair gives the CPU-vs-TPU comparison oracle the test suite uses.
"""
from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Sequence

from ..columnar import ColumnarBatch
from ..config import TpuConf
from ..types import Schema


class Metrics:
    """SQLMetric equivalent (reference: GpuExec.scala:24-41)."""

    def __init__(self):
        self._values: Dict[str, float] = {}
        self._lazy: Dict[str, list] = {}

    def add(self, name: str, v: float):
        self._values[name] = self._values.get(name, 0) + v

    def add_lazy(self, name: str, traced_scalar):
        """Accumulate a DEVICE scalar without syncing: row counts inside
        streaming hot loops are data-dependent, and an int() per batch is
        a device round trip (a tunnel RTT on chip).  Deferred scalars
        resolve in one sweep when the metrics are read."""
        self._lazy.setdefault(name, []).append(traced_scalar)

    @property
    def values(self) -> Dict[str, float]:
        """Metric dict with every deferred device scalar folded in (the
        fold syncs; readers are reporting paths, never hot loops)."""
        for name, pend in self._lazy.items():
            if pend:
                self.add(name, float(sum(int(x) for x in pend)))
                pend.clear()
        return self._values

    def timer(self, name: str):
        return _Timer(self, name)

    def __repr__(self):
        return repr(self.values)


class _Timer:
    def __init__(self, m: Metrics, name: str):
        self.m, self.name = m, name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        self.m.add(self.name, time.perf_counter() - self.t0)


class ExecContext:
    """Per-query execution context: conf, partition id, runtime services."""

    def __init__(self, conf: Optional[TpuConf] = None, partition_id: int = 0,
                 num_partitions: int = 1, runtime=None, cluster=None):
        self.conf = conf or TpuConf()
        self.partition_id = partition_id
        self.num_partitions = num_partitions
        self.runtime = runtime  # mem.runtime.TpuRuntime when active
        self.cluster = cluster  # plugin.TpuCluster in multi-executor mode
        # task-scoped cleanup callbacks (reference: task-completion
        # listeners releasing GPU resources, GpuSemaphore.scala:27-161 /
        # RapidsBufferCatalog task cleanup).  Operators register IDEMPOTENT
        # callbacks for resources that would otherwise orphan when a query
        # dies mid-flight; the engine runs them on task end, normal or not.
        self.cleanups: list = []

    def add_cleanup(self, cb) -> None:
        self.cleanups.append(cb)

    def run_cleanups(self) -> None:
        """Run registered callbacks newest-first; a failing callback does
        not prevent the rest from running."""
        while self.cleanups:
            cb = self.cleanups.pop()
            try:
                cb()
            except Exception:  # pragma: no cover - best-effort teardown
                pass

    def with_partition(self, pid: int, nparts: int) -> "ExecContext":
        ctx = ExecContext(self.conf, pid, nparts, self.runtime,
                          self.cluster)
        ctx.cleanups = self.cleanups  # share the task scope
        return ctx


class ExecNode:
    """Base physical operator."""

    def __init__(self, *children: "ExecNode"):
        self.children: List[ExecNode] = list(children)
        self.metrics = Metrics()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    # columnar device path
    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(f"{self.name} has no device execution")

    # host path (pyarrow Tables)
    def execute_cpu(self, ctx: ExecContext):
        raise NotImplementedError(f"{self.name} has no CPU execution")

    def tree_string(self, indent: int = 0) -> str:
        lines = [" " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 2))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name


class TpuExec(ExecNode):
    """Device columnar operator (GpuExec equivalent)."""

    # hint to the transition pass (reference: CoalesceGoal lattice)
    coalesce_after: bool = False
    # None | "single" | int target bytes — requirement on children batches
    child_coalesce_goal = None

    @property
    def is_device(self) -> bool:
        return True


class CpuExec(ExecNode):
    """Host operator running on pyarrow Tables."""

    @property
    def is_device(self) -> bool:
        return False
