"""Physical execution operators.

Reference: sql-plugin/.../rapids/GpuExec.scala — every device operator is a
`TpuExec` producing an iterator of ColumnarBatch with standard metrics
(numOutputRows/numOutputBatches/totalTime).  The CPU fallback side
(`CpuExec`) runs on pyarrow Tables, playing the role CPU Spark plays for the
reference: anything the planner can't put on the device still executes, and
the pair gives the CPU-vs-TPU comparison oracle the test suite uses.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from ..columnar import ColumnarBatch
from ..config import TpuConf
from ..metrics import names as MN
# Metrics moved to the observability package (level gating + batched lazy
# fold + journal integration); re-exported here because mem/runtime.py and
# half the test suite import it from exec.base
from ..metrics.registry import Metrics  # noqa: F401
from ..metrics.roofline import cost_accounting_enabled
from ..types import Schema


def record_output_batch(metrics: Metrics, batch, runtime=None) -> None:
    """Standard per-output-batch bookkeeping for device operators.

    * always: numOutputBatches, and numOutputRows whenever the count is
      host-known (both ESSENTIAL: free host-side increments);
    * DEBUG: exact numOutputRows resolved EAGERLY (one device sync per
      batch, counted against metrics.registry.DEVICE_SYNCS) plus a
      peakDevMemory sample of the accounting pool;
    * MODERATE: data-dependent numOutputRows accumulated as a LAZY device
      scalar (one device reduction per batch, folded into a single host
      transfer when the metrics are read — never a per-batch sync);
    * ESSENTIAL: data-dependent row counting skipped entirely (the count
      of a filtered batch would cost device work)."""
    metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
    # roofline cost declaration (metrics/roofline.py): every produced
    # batch is HBM the operator wrote.  device_size_bytes is a static
    # METADATA bound (shapes x dtype widths, never a sync), and the
    # metric is MODERATE-gated inside add(), so ESSENTIAL pays nothing.
    if metrics.level >= MN.MODERATE and cost_accounting_enabled():
        metrics.add(MN.HBM_BYTES_WRITTEN, batch.device_size_bytes())
    if batch.known_rows is not None:  # host-known: free at every level
        metrics.add(MN.NUM_OUTPUT_ROWS, batch.known_rows)
        if metrics.debug_active and runtime is not None:
            metrics.set_max(MN.PEAK_DEV_MEMORY,
                            runtime.device_store.current_size)
    elif metrics.debug_active:
        metrics.add_sync(MN.NUM_OUTPUT_ROWS, batch.num_rows_host)
        if runtime is not None:
            metrics.set_max(MN.PEAK_DEV_MEMORY,
                            runtime.device_store.current_size)
    elif metrics.level >= MN.MODERATE:
        metrics.add_lazy(MN.NUM_OUTPUT_ROWS, batch.num_rows())


def record_cost(metrics: Metrics, hbm_read: int = 0, hbm_written: int = 0,
                h2d: int = 0, d2h: int = 0, wire: int = 0, ici: int = 0,
                flops: float = 0) -> None:
    """Roofline cost declaration for one dispatch (metrics/roofline.py):
    bytes the operator moved per resource (HBM, host<->device link,
    socket wire) plus an estimated op count.  All values must be host-
    known metadata (batch capacities x dtype widths, expression-tree op
    counts, wire byte totals) — never a device sync.  The ledger joins
    these against measured span durations to name each plan node's
    bottleneck resource."""
    if metrics.level < MN.MODERATE or not cost_accounting_enabled():
        return
    if hbm_read:
        metrics.add(MN.HBM_BYTES_READ, hbm_read)
    if hbm_written:
        metrics.add(MN.HBM_BYTES_WRITTEN, hbm_written)
    if h2d:
        metrics.add(MN.H2D_BYTES, h2d)
    if d2h:
        metrics.add(MN.D2H_BYTES, d2h)
    if wire:
        metrics.add(MN.WIRE_BYTES, wire)
    if ici:
        metrics.add(MN.ICI_BYTES_MOVED, ici)
    if flops:
        metrics.add(MN.EST_FLOPS, flops)


class ExecContext:
    """Per-query execution context: conf, partition id, runtime services."""

    def __init__(self, conf: Optional[TpuConf] = None, partition_id: int = 0,
                 num_partitions: int = 1, runtime=None, cluster=None,
                 journal=None, query_execution=None):
        self.conf = conf or TpuConf()
        # latch the packed-sort kill switch for every device path this
        # query touches (sort, grouping, compact, join build, partition
        # split) — the flag only selects between two formulations that
        # produce IDENTICAL permutations, so a concurrent query with a
        # different conf can at worst run the other (equally correct)
        # kernel, mirroring the pallas flag's semantics
        from .. import config as _C
        from ..utils import packed_sort as _PS
        _PS.set_packed_enabled(self.conf.get(_C.SORT_PACKED_ENABLED))
        # roofline cost-accounting latch: same semantics as the packed
        # flag — observability-only, so cross-query interleaving is safe
        from ..metrics.roofline import set_cost_accounting
        set_cost_accounting(self.conf.get(_C.ROOFLINE_COST_ENABLED))
        self.partition_id = partition_id
        self.num_partitions = num_partitions
        self.runtime = runtime  # mem.runtime.TpuRuntime when active
        self.cluster = cluster  # plugin.TpuCluster in multi-executor mode
        self.journal = journal  # metrics.journal.EventJournal per query
        # metrics.query.QueryExecution of the running query: adaptive
        # re-planning registers rewritten plan nodes through it so
        # EXPLAIN METRICS shows the final stage plan
        self.query_execution = query_execution
        # task-scoped cleanup callbacks (reference: task-completion
        # listeners releasing GPU resources, GpuSemaphore.scala:27-161 /
        # RapidsBufferCatalog task cleanup).  Operators register IDEMPOTENT
        # callbacks for resources that would otherwise orphan when a query
        # dies mid-flight; the engine runs them on task end, normal or not.
        self.cleanups: list = []

    def add_cleanup(self, cb) -> None:
        self.cleanups.append(cb)

    def run_cleanups(self) -> None:
        """Run registered callbacks newest-first; a failing callback does
        not prevent the rest from running."""
        while self.cleanups:
            cb = self.cleanups.pop()
            try:
                cb()
            except Exception as e:  # noqa: BLE001 — the rest must still run
                # a dropped cleanup is a potential buffer/file-handle
                # leak; keep teardown going but leave a trace + count
                from ..metrics.registry import count_swallowed
                count_swallowed("numCleanupErrors", "spark_rapids_tpu.exec",
                                "execution cleanup callback %r failed: %r",
                                cb, e, warn=True)

    def with_partition(self, pid: int, nparts: int) -> "ExecContext":
        ctx = ExecContext(self.conf, pid, nparts, self.runtime,
                          self.cluster, self.journal,
                          self.query_execution)
        ctx.cleanups = self.cleanups  # share the task scope
        return ctx


class ExecNode:
    """Base physical operator."""

    def __init__(self, *children: "ExecNode"):
        self.children: List[ExecNode] = list(children)
        self.metrics = Metrics()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    # columnar device path
    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        raise NotImplementedError(f"{self.name} has no device execution")

    # host path (pyarrow Tables)
    def execute_cpu(self, ctx: ExecContext):
        raise NotImplementedError(f"{self.name} has no CPU execution")

    def tree_string(self, indent: int = 0) -> str:
        lines = [" " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 2))
        return "\n".join(lines)

    def describe(self) -> str:
        return self.name


class TpuExec(ExecNode):
    """Device columnar operator (GpuExec equivalent)."""

    # hint to the transition pass (reference: CoalesceGoal lattice)
    coalesce_after: bool = False
    # None | "single" | int target bytes — requirement on children batches
    child_coalesce_goal = None

    @property
    def is_device(self) -> bool:
        return True


class CpuExec(ExecNode):
    """Host operator running on pyarrow Tables."""

    @property
    def is_device(self) -> bool:
        return False
