"""Basic physical operators: scan (memory), project, filter, coalesce,
limit, union, expand, and the host<->device transitions.

Reference: rapids/basicPhysicalOperators.scala (project/filter/union),
GpuCoalesceBatches.scala, limit.scala, GpuExpandExec.scala,
GpuRowToColumnarExec/GpuColumnarToRowExec (transitions).

TPU-first difference from the reference: project/filter don't move data at
all — filter ANDs into the batch's selection mask and the transition pass
fuses maximal chains of row-local operators into ONE jitted per-batch
function (FusedPipelineExec), so XLA emits a single fused program where cuDF
would launch one kernel per operator.
"""
from __future__ import annotations

import functools
from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import ColumnarBatch, Column, bucket_rows, concat_batches
from ..config import MAX_READER_BATCH_SIZE_ROWS
from ..ops import expressions as E
from ..metrics import names as MN
from ..ops.cpu_eval import (cpu_cols_to_table, cpu_eval, table_to_cpu_cols)
from ..types import BooleanType, Schema, StructField
from ..utils.tracing import named_range
from .base import (CpuExec, ExecContext, ExecNode, TpuExec,
                   record_cost, record_output_batch)


def _pred_keep(col: Column):
    """null predicate result filters the row out (SQL WHERE semantics)."""
    return jnp.logical_and(col.valid, col.data)


def bound_param_builder(builder, slots):
    """Wrap a batch_fn builder so the traced function takes the plan-cache
    parameter values as ONE extra runtime argument (a tuple of device
    scalars) and installs them as the active binding while the chain
    traces — Parameter.eval then broadcasts tracers instead of baking
    constants, so one compiled program serves every literal variant
    (serve/plan_cache.py)."""
    def build():
        inner = builder()

        def fn(batch, pvals):
            with E.bound_params(dict(zip(slots, pvals))):
                return inner(batch)
        return fn
    return build


class TpuScanMemoryExec(TpuExec):
    """In-memory arrow table scan -> device batches (the H2D edge)."""

    def __init__(self, table, schema: Schema, conf=None):
        super().__init__()
        # cache identity must be the ORIGINAL table: select() creates a new
        # pyarrow object every planning pass, so keying on it would miss
        # (and leak an entry) on every column-pruned query
        self._cache_table = table
        if list(table.column_names) != schema.names:
            table = table.select(schema.names)  # pushdown pruned the scan
        self.table = table
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..config import (MEMORY_SCAN_CACHE_ENABLED,
                              MEMORY_SCAN_CACHE_SIZE)
        from ..utils.scan_cache import MEMORY_SCAN_CACHE
        E.clear_input_file()  # in-memory rows have no file provenance
        rows = self.table.num_rows
        limit = min(ctx.conf.get(MAX_READER_BATCH_SIZE_ROWS), 1 << 20)
        use_cache = ctx.conf.get(MEMORY_SCAN_CACHE_ENABLED)
        max_cache = ctx.conf.get(MEMORY_SCAN_CACHE_SIZE)
        names = tuple(self._schema.names)
        if use_cache:
            cached = MEMORY_SCAN_CACHE.get(self._cache_table, names, limit)
            if cached is not None:
                for batch, nrows in cached:
                    self.metrics.add(MN.NUM_OUTPUT_ROWS, nrows)
                    self.metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
                    yield batch
                return
        produced = []
        produced_bytes = 0
        off = 0
        while off < rows or (rows == 0 and off == 0):
            chunk = self.table.slice(off, limit)
            with self.metrics.timer(MN.SCAN_TIME):
                batch = ColumnarBatch.from_arrow(chunk)
            self.metrics.add(MN.NUM_OUTPUT_ROWS, chunk.num_rows)
            self.metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
            # cost declaration: the H2D edge — the adopted batch crossed
            # the host->device link and landed in HBM
            record_cost(self.metrics, h2d=batch.device_size_bytes(),
                        hbm_written=batch.device_size_bytes())
            if use_cache:
                # pinned BEFORE the first consumer sees it: a cached
                # batch is re-served to later queries, so a downstream
                # whole-stage program must never donate its buffers
                from ..mem.donation import pin
                pin(batch)
                produced.append((batch, chunk.num_rows))
                produced_bytes += batch.device_size_bytes()
                if produced_bytes > max_cache:
                    # table can never fit: stop pinning batches so the scan
                    # streams with bounded live memory again
                    use_cache = False
                    produced = []
            yield batch
            off += limit
            if rows == 0:
                break
        if use_cache:
            MEMORY_SCAN_CACHE.put(self._cache_table, names, limit, produced,
                                  max_cache, produced_bytes)

    def describe(self):
        return f"TpuScanMemoryExec[rows={self.table.num_rows}]"


class RowLocalExec(TpuExec):
    """A device op whose per-batch work is a pure batch->batch function —
    the fusion unit for FusedPipelineExec."""

    # per-row op-count estimate of expressions(), cached lazily (roofline
    # cost declaration; None until the first batch)
    _flops_per_row = None

    def batch_fn(self):
        raise NotImplementedError

    def _record_batch_cost(self, batch: ColumnarBatch) -> None:
        """Roofline cost declaration for one dispatched input batch:
        the kernel reads the whole input footprint from HBM and runs
        ~flops-per-row x rows ops (metrics/roofline.py; the output
        write side is record_output_batch's)."""
        from ..metrics.roofline import cost_accounting_enabled
        if self.metrics.level < MN.MODERATE \
                or not cost_accounting_enabled():
            return
        if self._flops_per_row is None:
            from ..metrics.roofline import estimate_expr_flops
            self._flops_per_row = max(1, estimate_expr_flops(
                self.expressions()))
        rows = batch.known_rows if batch.known_rows is not None \
            else batch.capacity
        record_cost(self.metrics, hbm_read=batch.device_size_bytes(),
                    flops=self._flops_per_row * rows)

    def expressions(self) -> List[E.Expression]:
        return []

    def kernel_key(self) -> tuple:
        """Structural cache key; must fully determine batch_fn's closure."""
        from ..utils.kernel_cache import expr_key
        return (type(self).__name__,
                tuple(expr_key(e) for e in self.expressions()))

    def _needs_row_offset(self) -> bool:
        return any(E.tree_needs_row_offset(e) for e in self.expressions())

    def _needs_input_file(self) -> bool:
        return any(E.tree_needs_input_file(e) for e in self.expressions())

    def stage_params(self) -> list:
        """Plan-cache Parameters in this operator's expressions, slot
        order (serve/plan_cache.py lifts literals into these)."""
        return E.collect_parameters(self.expressions())

    def parameterized_kernel(self, extra_key: tuple = (),
                             donate: bool = False):
        """The cached jitted per-batch kernel as a batch->batch callable,
        with plan-cache parameters threaded as runtime arguments when
        present.  With parameters the cache key is VALUE-FREE (slot +
        dtype) and the current bound values ride into every dispatch, so
        a literal-variant re-submission reuses the compiled program; with
        no parameters this is exactly `cached_kernel(kernel_key(),
        batch_fn)`.

        `donate=True` builds the variant that donates the input batch's
        buffers to XLA (deleted after the call!) — callers must hold the
        last-consumer proof (mem/donation.py) per dispatch and fall back
        to the non-donated kernel otherwise; cached_kernel keys the two
        variants apart."""
        from ..utils.kernel_cache import cached_kernel, param_free_keys
        jit_kw = {"donate_argnums": (0,)} if donate else {}
        params = self.stage_params()
        if not params:
            return cached_kernel(self.kernel_key() + tuple(extra_key),
                                 self.batch_fn, **jit_kw)
        with param_free_keys():
            key = self.kernel_key()
        key += tuple(extra_key) + (
            "params", E.parameter_signature(params))
        slots = [p.slot for p in params]
        pvals = E.parameter_values(params)
        inner = cached_kernel(key, bound_param_builder(self.batch_fn,
                                                       slots), **jit_kw)

        def call(batch, _inner=inner, _pvals=pvals):
            return _inner(batch, _pvals)
        return call

    def cpu_twin(self, child: ExecNode) -> ExecNode:
        """CPU twin of THIS operator over `child` — the per-operator
        fallback unit the whole-stage retry ladder degrades to
        (exec/whole_stage.py)."""
        raise NotImplementedError(self.name)

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..utils.kernel_cache import cached_kernel, record_dispatch
        key = self.kernel_key()
        needs_file = self._needs_input_file()
        if self._needs_row_offset():
            # stateful exprs (mono id / rand): thread the partition row
            # offset through as a traced argument; costs one host sync per
            # batch, paid only when such an expression is present.
            # input_file_name() may appear in the SAME projection — the
            # per-batch file key composes with the offset threading.
            offset = 0
            for batch in self.children[0].execute(ctx):
                fkey = key + ("row_offset",)
                if needs_file:
                    fkey += (E.current_input_file(),)
                fn = cached_kernel(
                    fkey,
                    lambda: functools.partial(E.eval_with_row_offset,
                                              self.batch_fn()))
                self._record_batch_cost(batch)
                with self.metrics.timer(MN.TOTAL_TIME), \
                        named_range(self.name):
                    record_dispatch()
                    out = fn(batch, jnp.int64(offset))
                offset += batch.num_rows_host()
                record_output_batch(self.metrics, out, ctx.runtime)
                yield out
            return
        if needs_file:
            # input_file_name()/block exprs bake the scan's current file
            # into the program as a constant; key the cache on it so each
            # file gets its own compiled constant (files are few, so the
            # recompile count is bounded — reference GpuInputFileBlock
            # reads the holder per task the same way)
            for batch in self.children[0].execute(ctx):
                fn = cached_kernel(key + (E.current_input_file(),),
                                   self.batch_fn)
                self._record_batch_cost(batch)
                with self.metrics.timer(MN.TOTAL_TIME), \
                        named_range(self.name):
                    record_dispatch()
                    out = fn(batch)
                record_output_batch(self.metrics, out, ctx.runtime)
                yield out
            return
        # plain path: parameter-threaded when the plan cache lifted
        # literals here (the row_offset / input_file paths above keep
        # value-inclusive keys — their per-batch key composition already
        # recompiles per constant, so baked Parameter values stay correct)
        fn = self.parameterized_kernel()
        for batch in self.children[0].execute(ctx):
            self._record_batch_cost(batch)
            with self.metrics.timer(MN.TOTAL_TIME), named_range(self.name):
                record_dispatch()
                out = fn(batch)
            record_output_batch(self.metrics, out, ctx.runtime)
            yield out


class TpuProjectExec(RowLocalExec):
    def __init__(self, exprs: Sequence[E.Expression], names: Sequence[str],
                 child: ExecNode):
        super().__init__(child)
        self.exprs = list(exprs)
        self._schema = Schema([StructField(n, e.dtype)
                               for n, e in zip(names, exprs)])

    @property
    def schema(self):
        return self._schema

    def batch_fn(self):
        exprs, schema = self.exprs, self._schema

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            cols = [e.eval(batch) for e in exprs]
            return ColumnarBatch(cols, batch.sel, schema)
        return fn

    def expressions(self):
        return list(self.exprs)

    def kernel_key(self):
        from ..utils.kernel_cache import schema_key
        return super().kernel_key() + (schema_key(self._schema),)

    def cpu_twin(self, child):
        return CpuProjectExec(self.exprs, self._schema.names, child)

    def describe(self):
        return f"TpuProjectExec[{', '.join(map(repr, self.exprs))}]"


class TpuFilterExec(RowLocalExec):
    def __init__(self, condition: E.Expression, child: ExecNode):
        super().__init__(child)
        self.condition = condition

    @property
    def schema(self):
        return self.children[0].schema

    def batch_fn(self):
        cond = self.condition

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            keep = _pred_keep(cond.eval(batch))
            return batch.filter(keep)
        return fn

    def expressions(self):
        return [self.condition]

    def cpu_twin(self, child):
        return CpuFilterExec(self.condition, child)

    def describe(self):
        return f"TpuFilterExec[{self.condition!r}]"


class FusedPipelineExec(RowLocalExec):
    """Maximal chain of row-local ops compiled as ONE jitted function.
    Created by the transition pass; this is where XLA fusion pays."""

    def __init__(self, stages: List[RowLocalExec], child: ExecNode):
        super().__init__(child)
        self.stages = stages

    @property
    def schema(self):
        return self.stages[-1].schema

    def batch_fn(self):
        fns = [s.batch_fn() for s in self.stages]

        def fn(batch):
            for f in fns:
                batch = f(batch)
            return batch
        return fn

    def expressions(self):
        out = []
        for s in self.stages:
            out.extend(s.expressions())
        return out

    def kernel_key(self):
        return ("FusedPipelineExec",
                tuple(s.kernel_key() for s in self.stages))

    def cpu_twin(self, child):
        for s in self.stages:
            child = s.cpu_twin(child)
        return child

    def describe(self):
        inner = " -> ".join(s.name for s in self.stages)
        return f"FusedPipelineExec[{inner}]"


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate small batches up to a goal (reference:
    GpuCoalesceBatches.scala; goals RequireSingleBatch / TargetSize)."""

    def __init__(self, child: ExecNode, goal="target", target_bytes=None):
        super().__init__(child)
        self.goal = goal
        self.target_bytes = target_bytes

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        target = self.target_bytes or ctx.conf.batch_size_bytes
        pending: List[ColumnarBatch] = []
        pending_bytes = 0
        for batch in self.children[0].execute(ctx):
            sz = batch.device_size_bytes()
            if self.goal != "single" and pending \
                    and pending_bytes + sz > target:
                yield self._flush(pending)
                pending, pending_bytes = [], 0
            pending.append(batch)
            pending_bytes += sz
        if pending:
            yield self._flush(pending)

    def _flush(self, pending):
        # cost declaration: a concat/compact reads every pending batch
        # out of HBM (the write side is record_output_batch's)
        record_cost(self.metrics,
                    hbm_read=sum(b.device_size_bytes() for b in pending))
        with self.metrics.timer(MN.CONCAT_TIME):
            if len(pending) == 1:
                out = pending[0].compact()
            else:
                out = concat_batches(pending)
        record_output_batch(self.metrics, out)
        return out

    def describe(self):
        return f"TpuCoalesceBatchesExec[{self.goal}]"


class TpuUnionExec(TpuExec):
    def __init__(self, children: Sequence[ExecNode]):
        super().__init__(*children)

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        for child in self.children:
            yield from child.execute(ctx)


class TpuLocalLimitExec(TpuExec):
    """Slice batches to the first n live rows (per partition)."""

    def __init__(self, n: int, child: ExecNode):
        super().__init__(child)
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        remaining = self.n
        for batch in self.children[0].execute(ctx):
            if remaining <= 0:
                return
            batch = batch.compact()
            count = batch.num_rows_host()
            if count > remaining:
                sel = jnp.arange(batch.capacity, dtype=jnp.int32) < remaining
                batch = batch.with_sel(sel)
                count = remaining
            remaining -= count
            self.metrics.add(MN.NUM_OUTPUT_ROWS, count)  # host-known: free
            self.metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
            yield batch

    def describe(self):
        return f"TpuLocalLimitExec[{self.n}]"


class TpuGlobalLimitExec(TpuLocalLimitExec):
    """Same slice on the single merged stream (single partition upstream)."""

    def describe(self):
        return f"TpuGlobalLimitExec[{self.n}]"


class TpuExpandExec(RowLocalExec):
    """Projection-list fan-out (ROLLUP/CUBE).  Reference: GpuExpandExec.

    TPU shape discipline: output capacity = capacity * n_projections
    (static), built by interleaved concat, no scatter."""

    def __init__(self, projections: List[List[E.Expression]],
                 names: Sequence[str], child: ExecNode):
        super().__init__(child)
        self.projections = projections
        self._schema = Schema([StructField(n, e.dtype)
                               for n, e in zip(names, projections[0])])

    @property
    def schema(self):
        return self._schema

    def batch_fn(self):
        projections, schema = self.projections, self._schema

        def fn(batch: ColumnarBatch) -> ColumnarBatch:
            parts = []
            for proj in projections:
                cols = [e.eval(batch) for e in proj]
                parts.append(ColumnarBatch(cols, batch.sel, schema))
            ncols = []
            for ci in range(len(schema)):
                f = schema[ci]
                cs = [p.columns[ci] for p in parts]
                if f.dtype.is_string:
                    ml = max(c.max_len for c in cs)
                    cs = [c.pad_strings_to(ml) for c in cs]
                    ncols.append(Column(
                        jnp.concatenate([c.data for c in cs], axis=0),
                        jnp.concatenate([c.valid for c in cs]),
                        f.dtype,
                        jnp.concatenate([c.lengths for c in cs])))
                else:
                    ncols.append(Column(
                        jnp.concatenate([c.data for c in cs]),
                        jnp.concatenate([c.valid for c in cs]), f.dtype))
            sel = jnp.concatenate([batch.sel] * len(projections))
            return ColumnarBatch(ncols, sel, schema)
        return fn

    def expressions(self):
        return [e for proj in self.projections for e in proj]

    def kernel_key(self):
        from ..utils.kernel_cache import schema_key
        return super().kernel_key() + (
            tuple(len(p) for p in self.projections),
            schema_key(self._schema))

    def cpu_twin(self, child):
        return CpuExpandExec(self.projections, self._schema.names, child)

    def describe(self):
        return f"TpuExpandExec[{len(self.projections)} projections]"


# --------------------------------------------------------------------------
# transitions (reference: GpuRowToColumnarExec / GpuColumnarToRowExec /
# HostColumnarToGpu — ours are arrow<->device batch edges)
# --------------------------------------------------------------------------

class HostToDeviceExec(TpuExec):
    """Adopt host arrow tables from a CPU subtree into device batches."""

    def __init__(self, child: ExecNode):
        super().__init__(child)

    @property
    def schema(self):
        return self.children[0].schema

    def execute(self, ctx):
        for table in self.children[0].execute_cpu(ctx):
            with self.metrics.timer(MN.H2D_TIME):
                batch = ColumnarBatch.from_arrow(table)
            self.metrics.add(MN.NUM_OUTPUT_ROWS, table.num_rows)
            self.metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
            record_cost(self.metrics, h2d=batch.device_size_bytes(),
                        hbm_written=batch.device_size_bytes())
            yield batch


class DeviceToHostExec(CpuExec):
    """Materialize device batches to host arrow tables."""

    def __init__(self, child: ExecNode):
        super().__init__(child)

    @property
    def schema(self):
        return self.children[0].schema

    def execute_cpu(self, ctx):
        for batch in self.children[0].execute(ctx):
            # cost declaration: the D2H edge reads the batch out of HBM
            # and moves it over the link to the host
            record_cost(self.metrics, d2h=batch.device_size_bytes(),
                        hbm_read=batch.device_size_bytes())
            with self.metrics.timer(MN.D2H_TIME):
                table = batch.to_arrow()
            self.metrics.add(MN.NUM_OUTPUT_ROWS, table.num_rows)
            self.metrics.add(MN.NUM_OUTPUT_BATCHES, 1)
            yield table


# --------------------------------------------------------------------------
# CPU fallback operators (the "CPU Spark" side of the oracle)
# --------------------------------------------------------------------------

class CpuScanMemoryExec(CpuExec):
    def __init__(self, table, schema: Schema):
        super().__init__()
        if list(table.column_names) != schema.names:
            table = table.select(schema.names)  # pushdown pruned the scan
        self.table = table
        self._schema = schema

    @property
    def schema(self):
        return self._schema

    def execute_cpu(self, ctx):
        yield self.table


class CpuProjectExec(CpuExec):
    def __init__(self, exprs, names, child):
        super().__init__(child)
        self.exprs = list(exprs)
        self._schema = Schema([StructField(n, e.dtype)
                               for n, e in zip(names, exprs)])

    @property
    def schema(self):
        return self._schema

    def execute_cpu(self, ctx):
        for table in self.children[0].execute_cpu(ctx):
            cols = table_to_cpu_cols(table)
            n = table.num_rows
            out = [cpu_eval(e, cols, n) for e in self.exprs]
            yield cpu_cols_to_table(out, self._schema)

    def describe(self):
        return f"CpuProjectExec[{', '.join(map(repr, self.exprs))}]"


class CpuFilterExec(CpuExec):
    def __init__(self, condition, child):
        super().__init__(child)
        self.condition = condition

    @property
    def schema(self):
        return self.children[0].schema

    def execute_cpu(self, ctx):
        for table in self.children[0].execute_cpu(ctx):
            cols = table_to_cpu_cols(table)
            n = table.num_rows
            v, m = cpu_eval(self.condition, cols, n)
            keep = m & v.astype(bool)
            yield table.filter(keep)

    def describe(self):
        return f"CpuFilterExec[{self.condition!r}]"


class CpuUnionExec(CpuExec):
    def __init__(self, children):
        super().__init__(*children)

    @property
    def schema(self):
        return self.children[0].schema

    def execute_cpu(self, ctx):
        for child in self.children:
            yield from child.execute_cpu(ctx)


class CpuLimitExec(CpuExec):
    def __init__(self, n, child):
        super().__init__(child)
        self.n = n

    @property
    def schema(self):
        return self.children[0].schema

    def execute_cpu(self, ctx):
        remaining = self.n
        for table in self.children[0].execute_cpu(ctx):
            if remaining <= 0:
                return
            if table.num_rows > remaining:
                table = table.slice(0, remaining)
            remaining -= table.num_rows
            yield table


class CpuExpandExec(CpuExec):
    def __init__(self, projections, names, child):
        super().__init__(child)
        self.projections = projections
        self._schema = Schema([StructField(n, e.dtype)
                               for n, e in zip(names, projections[0])])

    @property
    def schema(self):
        return self._schema

    def execute_cpu(self, ctx):
        import pyarrow as pa
        for table in self.children[0].execute_cpu(ctx):
            cols = table_to_cpu_cols(table)
            n = table.num_rows
            for proj in self.projections:
                out = [cpu_eval(e, cols, n) for e in proj]
                yield cpu_cols_to_table(out, self._schema)
