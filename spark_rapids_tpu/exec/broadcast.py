"""Broadcast exchange + broadcast hash join.

TPU-native analogue of GpuBroadcastExchangeExec / GpuBroadcastHashJoinExec
(org/.../execution/GpuBroadcastExchangeExec.scala:47-391 — the child is
collected ONCE as serialized host buffers and lazily re-uploaded per
executor; GpuBroadcastHashJoinExec.scala:115-151 — each task reconstitutes
the device build table from the broadcast).  Here: the child is drained
once, concatenated, pulled to host leaves (the serialized form), and every
consumer re-uploads lazily — one H2D per process, cached, registered as a
spillable buffer so broadcast data participates in memory pressure
handling.
"""
from __future__ import annotations

import threading
from typing import Iterator, Optional

from ..columnar import ColumnarBatch, concat_batches
from ..mem.buffer import SpillPriorities, batch_to_host, host_to_batch
from .base import CpuExec, ExecContext, ExecNode, TpuExec, record_cost
from .join import TpuHashJoinExec
from ..metrics import names as MN


class TpuBroadcastExchangeExec(TpuExec):
    """Collect once to host; serve a device batch to every consumer."""

    def __init__(self, child: ExecNode):
        super().__init__(child)
        self._host_form = None       # (leaves, meta) — the broadcast value
        self._buffer_id: Optional[int] = None
        self._lock = threading.Lock()

    @property
    def schema(self):
        return self.children[0].schema

    def describe(self):
        return "TpuBroadcastExchangeExec"

    def _collect(self, ctx: ExecContext):
        """The async driver job of the reference (collect + serialize),
        run once (GpuBroadcastExchangeExec.scala:215-391)."""
        with self.metrics.timer(MN.COLLECT_TIME):
            batches = list(self.children[0].execute(ctx))
        with self.metrics.timer(MN.BUILD_TIME):
            if batches:
                batch = batches[0] if len(batches) == 1 \
                    else concat_batches(batches)
            else:
                from .join import _empty_batch
                batch = _empty_batch(self.schema)
            leaves, meta = batch_to_host(batch)
        self.metrics.add(MN.DATA_SIZE, meta.size_bytes)
        # roofline: the broadcast payload left the device (d2h) and is
        # re-published to every executor over the wire
        record_cost(self.metrics, d2h=meta.size_bytes,
                    wire=meta.size_bytes)
        return leaves, meta

    def materialize_host(self, ctx: ExecContext):
        """Collect the child ONCE and return the host form (leaves, meta)
        — the adaptive demotion check reads `meta.size_bytes` here BEFORE
        the join instantiates, and a kept broadcast reuses the same
        cached collect through `broadcast_batch`."""
        with self._lock:
            if self._host_form is None:
                self._host_form = self._collect(ctx)
            return self._host_form

    def broadcast_batch(self, ctx: ExecContext) -> ColumnarBatch:
        """Device view of the broadcast value; lazy re-upload, spillable."""
        with self._lock:
            if self._host_form is None:
                self._host_form = self._collect(ctx)
            leaves, meta = self._host_form
            if ctx.runtime is not None:
                if self._buffer_id is not None:
                    try:
                        return ctx.runtime.get_batch(self._buffer_id)
                    except KeyError:
                        self._buffer_id = None
                batch = host_to_batch(leaves, meta)
                self._buffer_id = ctx.runtime.add_batch(
                    batch, SpillPriorities.ACTIVE_ON_DECK_PRIORITY)
                return batch
            return host_to_batch(leaves, meta)

    def execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        yield self.broadcast_batch(ctx)


class CpuBroadcastExchangeExec(CpuExec):
    """Host fallback: collect once, replay the cached arrow table."""

    def __init__(self, child: ExecNode):
        super().__init__(child)
        self._table = None
        self._lock = threading.Lock()

    @property
    def schema(self):
        return self.children[0].schema

    def execute_cpu(self, ctx: ExecContext):
        import pyarrow as pa
        with self._lock:
            if self._table is None:
                tables = list(self.children[0].execute_cpu(ctx))
                if tables:
                    self._table = pa.concat_tables(tables)
                else:
                    from ..types import to_arrow
                    self._table = pa.table(
                        {f.name: pa.array([], type=to_arrow(f.dtype))
                         for f in self.schema})
        yield self._table


class TpuBroadcastHashJoinExec(TpuHashJoinExec):
    """Hash join whose build side is a broadcast exchange
    (GpuBroadcastHashJoinExec.scala:115-151).  The probe kernels are
    identical to the shuffled hash join; only the build-side source
    differs."""

    def describe(self):
        return (f"TpuBroadcastHashJoinExec[{self.join_type}, "
                f"keys={len(self.left_keys)}]")
